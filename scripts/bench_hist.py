"""Microbenchmark of histogram-kernel variants on the live backend.

Run on the TPU (ambient axon backend):  python scripts/bench_hist.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import load_obs  # noqa: E402

LOG = load_obs().EventLog.default(echo=True)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def time_fn(fn, *args, iters=10):
    """Time `iters` on-device repetitions inside ONE dispatch: the remote
    tunnel adds ~90ms per host round-trip, so per-call host timing is useless.
    A data dependence (g perturbed by the loop index) defeats CSE."""
    bins, g, h, m = args

    @jax.jit
    def many(bins, g, h, m):
        def body(acc, i):
            hh = fn(bins, g + i * 1e-12, h, m)
            return acc + jnp.sum(hh), None
        acc, _ = jax.lax.scan(body, jnp.float32(0),
                              jnp.arange(iters, dtype=jnp.float32))
        return acc

    float(many(bins, g, h, m))          # compile + warm
    t0 = time.perf_counter()
    s = float(many(bins, g, h, m))
    total = time.perf_counter() - t0
    return (total - 0.09) / iters       # subtract one tunnel round-trip


def make_data(n, f, b, seed=0):
    rng = np.random.default_rng(seed)
    bins = jnp.asarray(rng.integers(0, b, size=(n, f), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=n).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, size=n).astype(np.float32))
    m = jnp.ones(n, jnp.float32)
    return bins, g, h, m


def hist_onehot_old(bins, g, h, m, B, chunk):
    from lightgbm_tpu.ops.histogram import _hist_onehot
    return _hist_onehot(bins, g, h, m, B, chunk)


def hist_onehot_swapped(bins, g, h, m, B, chunk):
    """gh on the left: [3, chunk] @ [chunk, F*B] -> [3, F*B]."""
    n, f = bins.shape
    gh = jnp.stack([g * m, h * m, m], axis=0).astype(jnp.float32)   # [3, N]
    pad = (-n) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, 0), (0, pad)))
    nc = (n + pad) // chunk
    bins_c = bins.reshape(nc, chunk, f)
    gh_c = gh.reshape(3, nc, chunk).transpose(1, 0, 2)              # [nc, 3, chunk]

    def body(acc, xs):
        b, gh_ = xs
        flat = b.astype(jnp.int32) + B * jnp.arange(f, dtype=jnp.int32)[None, :]
        onehot = (flat[:, :, None] ==
                  jnp.arange(f * B, dtype=jnp.int32)[None, None, :])
        # wait: this makes [chunk, F, F*B] - wrong. build per-feature block
        return acc, None

    # correct: one-hot per feature over B, reshaped to [chunk, F*B]
    def body2(acc, xs):
        b, gh_ = xs                                                  # [chunk,F],[3,chunk]
        onehot = (b.astype(jnp.int32)[:, :, None] ==
                  jnp.arange(B, dtype=jnp.int32)[None, None, :])
        onehot = onehot.astype(jnp.float32).reshape(chunk, f * B)
        hpart = jax.lax.dot_general(
            gh_, onehot, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                      # [3, F*B]
        return acc + hpart, None

    init = jnp.zeros((3, f * B), jnp.float32)
    if nc == 1:
        out, _ = body2(init, (bins_c[0], gh_c[0]))
    else:
        out, _ = jax.lax.scan(body2, init, (bins_c, gh_c))
    return out.reshape(3, f, B).transpose(1, 2, 0)


def hist_scatter(bins, g, h, m, B, chunk):
    from lightgbm_tpu.ops.histogram import _hist_scatter
    return _hist_scatter(bins, g, h, m, B)


def main():
    print("backend:", jax.default_backend(), jax.devices()[0])
    N, F, B = 1_000_000, 28, 256
    bins, g, h, m = make_data(N, F, B)
    ref = None
    results, failed = {}, 0
    for name, fn, chunk in [
        ("onehot_old c64k", hist_onehot_old, 65536),
        ("onehot_old c8k", hist_onehot_old, 8192),
        ("onehot_swap c64k", hist_onehot_swapped, 65536),
        ("onehot_swap c8k", hist_onehot_swapped, 8192),
        ("onehot_swap c128k", hist_onehot_swapped, 131072),
        ("scatter", hist_scatter, 0),
    ]:
        try:
            jf = jax.jit(lambda b_, g_, h_, m_, fn=fn, c=chunk: fn(b_, g_, h_, m_, B, c))
            t = time_fn(lambda b_, g_, h_, m_, fn=fn, c=chunk: fn(b_, g_, h_, m_, B, c),
                        bins, g, h, m, iters=20)
            out = jf(bins, g, h, m)
            if ref is None:
                ref = np.asarray(out)
                err = 0.0
            else:
                err = float(np.max(np.abs(np.asarray(out) - ref)))
            rows_per_s = N / t
            results[name] = {"ms": round(t * 1e3, 3), "maxerr": err}
            print(f"{name:20s} {t*1e3:8.2f} ms  {rows_per_s/1e6:8.1f} Mrows/s  maxerr={err:.2e}")
        except Exception as e:
            failed += 1
            print(f"{name:20s} FAILED: {type(e).__name__} {str(e)[:120]}")
    best = min(results, key=lambda k: results[k]["ms"]) if results else None
    # one-JSON-line contract: the LAST stdout line is the schema summary
    LOG.summary(bench="hist_variants", rows=N, features=F, max_bins=B,
                backend=jax.default_backend(), ok=len(results), failed=failed,
                best=best, results=results)


if __name__ == "__main__":
    main()
