"""Generate docs/Parameters.md from the Config dataclass + alias table.

The reference generates its Parameters.rst from config.h field comments via
``helpers/parameter_generator.py`` and CI-diffs the two (SURVEY §2.2 item
"generated accessors/docs").  Here the single source of truth is
``lightgbm_tpu/config.py``: this script renders every field with its type,
default and aliases, grouped by the section comments in the dataclass
source, and ``tests/test_param_docs.py`` asserts the rendered doc stays in
sync with the dataclass (the CI-diff analog).
"""
from __future__ import annotations

import dataclasses
import inspect
import os
import re
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lightgbm_tpu.config import PARAM_ALIASES, Config  # noqa: E402


def field_sections():
    """Map field name -> section title, parsed from ``# -- section --``
    comments in the dataclass source."""
    src = inspect.getsource(Config)
    section = "core"
    out = {}
    for line in src.splitlines():
        m = re.match(r"\s*# -- (.+?) \(", line)
        if m:
            section = m.group(1)
            continue
        m = re.match(r"\s*(\w+)\s*:", line)
        if m and not line.strip().startswith("#"):
            out[m.group(1)] = section
    return out


def aliases_by_field():
    rev = defaultdict(list)
    for alias, canonical in PARAM_ALIASES.items():
        rev[canonical].append(alias)
    return {k: sorted(v) for k, v in rev.items()}


def _fmt_default(v):
    if isinstance(v, str):
        return f'``"{v}"``' if v else "``\"\"``"
    if isinstance(v, (list, tuple)):
        return "``[]``" if not v else f"``{list(v)}``"
    return f"``{v}``"


def render() -> str:
    sections = field_sections()
    rev = aliases_by_field()
    by_section = defaultdict(list)
    for f in dataclasses.fields(Config):
        by_section[sections.get(f.name, "other")].append(f)

    lines = [
        "# Parameters",
        "",
        "Generated from `lightgbm_tpu/config.py` by"
        " `scripts/gen_param_docs.py` — do not edit by hand"
        " (`python scripts/gen_param_docs.py` regenerates;"
        " `tests/test_param_docs.py` keeps it in sync, the analog of the"
        " reference's `helpers/parameter_generator.py` + CI diff).",
        "",
        "Aliases follow the reference's `Parameters.rst`; unrecognized"
        " parameters are warned about and ignored, as in the reference.",
        "",
    ]
    # CLI-level pseudo-parameters: consumed by application.py before
    # Config.from_params ever sees them (reference: config= on the CLI)
    lines.append("## CLI-level")
    lines.append("")
    lines.append("| parameter | type | default | aliases |")
    lines.append("|---|---|---|---|")
    lines.append("| `config` | str | ``\"\"`` | `config_file` |")
    lines.append("")
    for section, fs in by_section.items():
        lines.append(f"## {section}")
        lines.append("")
        lines.append("| parameter | type | default | aliases |")
        lines.append("|---|---|---|---|")
        for f in fs:
            ftype = (f.type if isinstance(f.type, str)
                     else getattr(f.type, "__name__", str(f.type)))
            if f.default is not dataclasses.MISSING:
                d = _fmt_default(f.default)
            else:
                d = _fmt_default(f.default_factory())
            al = ", ".join(f"`{a}`" for a in rev.get(f.name, [])) or "—"
            lines.append(f"| `{f.name}` | {ftype} | {d} | {al} |")
        lines.append("")
    return "\n".join(lines) + "\n"


def main() -> int:
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "Parameters.md")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    text = render()
    with open(out, "w") as fh:
        fh.write(text)
    print(f"wrote {out} ({len(text.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
