"""Microbench the grower's per-split primitives on the live backend.

Isolates: row gather (both layouts), u8 transpose, partition scatter,
cumsum, and the pallas histogram at ladder cap sizes.

usage: PYTHONPATH=/root/.axon_site:/root/repo python scripts/bench_micro.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import load_obs  # noqa: E402

LOG = load_obs().EventLog.default(echo=True)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

N, F, B = 1_000_000, 28, 256
rng = np.random.default_rng(0)
bins = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
bins_t = jnp.asarray(np.asarray(bins).T.copy())
g = jnp.asarray(rng.normal(size=N).astype(np.float32))
h = jnp.asarray(np.full(N, 0.25, np.float32))


RESULTS_MS = {}


def timed(name, fn, *args, iters=20):
    r = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn(*args)
    jax.block_until_ready(r)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:44s} {dt*1e3:8.3f} ms")
    RESULTS_MS[name] = round(dt * 1e3, 4)
    return dt


for cap in (16384, 131072, 1_000_000):
    seg = jnp.asarray(rng.integers(0, N, size=cap, dtype=np.int32))

    timed(f"gather rows [cap={cap},F] axis0",
          jax.jit(lambda s: jnp.take(bins, s, axis=0)), seg)
    timed(f"gather cols [F,cap={cap}] axis1 (bins_t)",
          jax.jit(lambda s: jnp.take(bins_t, s, axis=1)), seg)
    timed(f"gather rows+transpose [F,cap={cap}]",
          jax.jit(lambda s: jnp.take(bins, s, axis=0).T.copy()), seg)
    timed(f"gather gh [cap={cap}]",
          jax.jit(lambda s: (jnp.take(g, s), jnp.take(h, s))), seg)
    timed(f"cumsum i32 [cap={cap}]",
          jax.jit(lambda s: jnp.cumsum(s)), seg)
    pos = jnp.asarray(rng.permutation(cap).astype(np.int32))
    timed(f"scatter set [cap={cap}]",
          jax.jit(lambda p_, s: jnp.zeros(cap, jnp.int32).at[p_].set(s)),
          pos, seg)

    from lightgbm_tpu.ops.histogram import _hist_pallas
    bc = jnp.take(bins, seg, axis=0)
    gc, hc = jnp.take(g, seg), jnp.take(h, seg)
    mc = jnp.ones(cap, jnp.float32)
    timed(f"pallas hist [cap={cap}]",
          jax.jit(lambda b_, g_, h_, m_: _hist_pallas(b_, g_, h_, m_, B)),
          bc, gc, hc, mc)
    print()

# --- combined-payload and physical-partition primitives ------------------
print("=== combined payload / physical partition ===")
gh_bytes = jax.lax.bitcast_convert_type(
    jnp.stack([g, h, jnp.ones(N, jnp.float32)], axis=1), jnp.uint8
).reshape(N, 12)
comb = jnp.concatenate([bins, gh_bytes], axis=1)        # [N, 40] u8
comb = jax.block_until_ready(comb)
for cap in (16384, 131072, 524288):
    seg = jnp.asarray(rng.integers(0, N, size=cap, dtype=np.int32))
    timed(f"gather comb rows [cap={cap},40]",
          jax.jit(lambda s: jnp.take(comb, s, axis=0)), seg)
    pos = jnp.asarray(rng.permutation(cap).astype(np.int32))
    block = jnp.take(comb, seg, axis=0)
    timed(f"scatter comb rows [cap={cap},40]",
          jax.jit(lambda p_, b_: jnp.zeros((cap, 40), jnp.uint8).at[p_].set(b_)),
          pos, block)
    timed(f"gather-by-invperm comb rows [cap={cap},40]",
          jax.jit(lambda p_, b_: jnp.take(b_, p_, axis=0)), pos, block)
    timed(f"contiguous read+sum comb [cap={cap},40]",
          jax.jit(lambda b_: b_.astype(jnp.float32).sum()), block)
    # monotonic (sorted) index gather — compaction-style access
    mono = jnp.sort(seg)
    timed(f"gather comb rows SORTED idx [cap={cap}]",
          jax.jit(lambda s: jnp.take(comb, s, axis=0)), mono)

# one-JSON-line contract: the LAST stdout line is the schema summary
LOG.summary(bench="micro_primitives", rows=N, features=F, max_bins=B,
            backend=jax.default_backend(), entries=len(RESULTS_MS),
            results_ms=RESULTS_MS)
