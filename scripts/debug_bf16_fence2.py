"""Confirm: the parity 'reference' (_hist_onehot) runs at bf16 matmul
precision on TPU by default; against a truly-f32 reference the fenced
split-precision kernels are accurate."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def emit(**kv):
    kv["ts"] = time.time()
    print(json.dumps(kv), flush=True)


def main():
    import bench
    if not bench.probe_backend(300):
        emit(stage="abort", reason="tpu_unreachable")
        return 1
    import jax
    import jax.numpy as jnp
    import numpy as np
    from lightgbm_tpu.ops import histogram as H

    emit(stage="sanity", backend=jax.default_backend())
    rng = np.random.default_rng(3)
    n, f, b = 200_000, 28, 255
    bins = jnp.asarray(rng.integers(0, b, size=(n, f), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=n).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=n).astype(np.float32))
    m = jnp.asarray((rng.uniform(size=n) < 0.8).astype(np.float32))

    def relerr(a, bb):
        return float(jnp.max(jnp.abs(a - bb) / (jnp.abs(bb) + 1.0)))

    # truly-f32 references: scatter-add, and onehot at 'highest' precision
    ref_sc = jax.jit(lambda *x: H._hist_scatter(*x, b))(bins, g, h, m)
    with jax.default_matmul_precision("highest"):
        ref_oh = jax.jit(lambda *x: H._hist_onehot(*x, b, 65536))(bins, g, h, m)
    emit(stage="scatter_vs_onehot_highest", relerr=relerr(ref_oh, ref_sc))

    ref_oh_default = jax.jit(lambda *x: H._hist_onehot(*x, b, 65536))(
        bins, g, h, m)
    emit(stage="onehot_default_vs_scatter", relerr=relerr(ref_oh_default, ref_sc))

    got = jax.jit(lambda *x: H._hist_pallas(*x, b))(bins, g, h, m)
    emit(stage="pallas_fenced_vs_scatter", relerr=relerr(got, ref_sc))

    # batched-leaf kernel vs scatter ref (the gate that caught the collapse)
    BR, NB, NC, B, k = 512, 24, 32, 255, 6
    C = BR * NB
    comb = jnp.asarray(rng.integers(0, B, size=(C, NC), dtype=np.uint8))
    g2 = jnp.asarray(rng.normal(size=C).astype(np.float32))
    h2 = jnp.asarray(rng.uniform(0.1, 1.0, size=C).astype(np.float32))
    m2 = jnp.asarray((rng.uniform(size=C) < 0.8).astype(np.float32))
    bl = np.sort(rng.integers(0, k, size=NB)).astype(np.int32)
    bl = jnp.asarray(np.where(bl == k - 2, k - 1, bl))
    got = jax.jit(lambda *x: H._hist_leaves_pallas(*x, k, B, BR, 28))(
        comb, g2, h2, m2, bl)
    ref = jax.jit(lambda *x: H.build_histogram_leaves(
        *x, k, B, method="scatter", block_rows=BR, f_limit=28))(
        comb, g2, h2, m2, bl)
    emit(stage="batched_leaves_vs_scatter", relerr=relerr(got, ref[:, :28]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
