"""Serving latency/throughput bench: p50/p99 + rows/s per request size.

Trains a small synthetic model, freezes it into a
``serve.PredictorArtifact`` (AOT bucket programs), then measures:

- **direct path**: per-request latency (p50/p99/mean) and rows/s at each
  request size in ``--rows-list`` (default 1k -> 1M rows/request — the
  1k-row end prices the interactive case, the 1M end the bulk-scoring
  case);
- **micro-batched path**: many small concurrent requests pushed through a
  ``MicroBatcher`` by client threads — achieved request rate, rows/s and
  per-request p50/p99 (the "millions of users" shape: tiny requests,
  shared buckets).

CPU-runnable today; on a TPU backend the same script prices the hardware.
One jsonl record per measurement is appended to ``WATCHER_PERF_LOG`` (or
``perf_results.jsonl``) as it lands, and the LAST stdout line is a single
JSON summary (the bench one-JSON-line contract, extracted by
``supervise.extract_json_line`` in the suite/watcher).

Run:
    python scripts/bench_serve.py [--rows-list 1024,16384,262144,1048576]
                                  [--iters 10] [--quick]
"""
import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import load_obs  # noqa: E402

# the single perf-journal writer (obs.events resolves WATCHER_PERF_LOG or
# the repo default).  Loaded WITHOUT lightgbm_tpu/jax: the serve_abort
# record must land even when importing jax would wedge the process.
LOG = load_obs().EventLog.default(echo=True)


def emit(**kv):
    LOG.emit(kv.pop("stage", "bench_record"), **kv)


def _pctl(xs, q):
    xs = sorted(xs)
    if not xs:
        return None           # json null, never a non-strict NaN token
    i = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[i]


def _ms(v):
    return None if v is None else round(v * 1e3, 3)


def build_model(rows: int, feats: int, trees: int, leaves: int):
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(0)
    X = rng.normal(size=(rows, feats)).astype(np.float32)
    logit = (X[:, 0] + np.sin(2 * X[:, 1]) + 0.5 * X[:, 2] * X[:, 3]
             + 0.3 * rng.normal(size=rows))
    y = (logit > 0).astype(np.float64)
    p = {"objective": "binary", "num_leaves": leaves, "verbose": -1,
         "learning_rate": 0.1}
    t0 = time.perf_counter()
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p),
                    num_boost_round=trees)
    emit(stage="serve_train", rows=rows, feats=feats, trees=trees,
         secs=round(time.perf_counter() - t0, 2))
    return bst, rng


def bench_direct(art, rng, feats: int, rows_list, iters: int):
    import numpy as np
    best_rps = 0.0
    for req in rows_list:
        X = rng.normal(size=(req, feats)).astype(np.float32)
        art.predict(X[: min(req, 256)])          # warm transfer paths
        art.predict(X)                           # warm the request bucket
        lat = []
        for _ in range(iters):
            t0 = time.perf_counter()
            art.predict(X)
            lat.append(time.perf_counter() - t0)
        rps = req / (sum(lat) / len(lat))
        best_rps = max(best_rps, rps)
        emit(stage="serve_direct", rows_per_request=req, iters=iters,
             p50_ms=_ms(_pctl(lat, 0.50)), p99_ms=_ms(_pctl(lat, 0.99)),
             mean_ms=round(sum(lat) / len(lat) * 1e3, 3),
             rows_per_sec=round(rps, 1),
             bucket=art._bucket_for(min(req, art.buckets[-1])))
    return best_rps


def bench_batched(art, rng, feats: int, *, req_rows: int, clients: int,
                  seconds: float, deadline_ms: float, queue_depth: int):
    import threading

    import numpy as np
    from lightgbm_tpu.serve import MicroBatcher, QueueSaturatedError
    mb = MicroBatcher(art.predict, max_batch_rows=art.buckets[-1],
                      deadline_ms=deadline_ms, queue_depth=queue_depth,
                      name="bench")
    X = rng.normal(size=(req_rows, feats)).astype(np.float32)
    art.predict(X)                               # warm the smallest bucket
    lat, shed, errs = [], [0], []
    lock = threading.Lock()
    stop = time.monotonic() + seconds

    def client():
        while time.monotonic() < stop:
            t0 = time.perf_counter()
            try:
                mb.predict(X, timeout=30)
            except QueueSaturatedError:
                with lock:
                    shed[0] += 1
                time.sleep(deadline_ms / 1e3)    # backoff, like a real client
                continue
            except Exception as e:
                # a timeout/crash must not silently kill the client thread
                # and leave the record undercounting — say so and stop
                with lock:
                    errs.append(f"{type(e).__name__}: {e}"[:120])
                return
            dt = time.perf_counter() - t0
            with lock:
                lat.append(dt)

    threads = [threading.Thread(target=client) for _ in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    mb.close()
    served = len(lat)
    emit(stage="serve_batched", rows_per_request=req_rows, clients=clients,
         wall_secs=round(wall, 2), requests=served, shed=shed[0],
         qps=round(served / wall, 1),
         rows_per_sec=round(served * req_rows / wall, 1),
         p50_ms=_ms(_pctl(lat, 0.50)), p99_ms=_ms(_pctl(lat, 0.99)),
         coalesced_batches=mb.stats["batches"],
         max_batch_requests=mb.stats["max_batch_requests"],
         **({"client_errors": errs[:4]} if errs else {}))
    return served * req_rows / wall if wall > 0 else 0.0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="serving latency/throughput bench")
    ap.add_argument("--rows-list", default="1024,16384,262144,1048576",
                    help="request sizes for the direct path")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--train-rows", type=int, default=50000)
    ap.add_argument("--feats", type=int, default=20)
    ap.add_argument("--trees", type=int, default=30)
    ap.add_argument("--leaves", type=int, default=63)
    ap.add_argument("--buckets", default=None,
                    help="comma-separated AOT bucket row counts (default: "
                         "lightgbm_tpu.config.SERVE_DEFAULT_BUCKETS)")
    ap.add_argument("--batch-seconds", type=float, default=3.0)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--req-rows", type=int, default=128,
                    help="rows per request on the micro-batched path")
    ap.add_argument("--quick", action="store_true",
                    help="small shapes for CI/smoke (seconds, not minutes)")
    args = ap.parse_args(argv)
    if args.quick:
        args.rows_list = "256,4096"
        args.buckets = "256,4096"
        args.train_rows, args.trees, args.iters = 5000, 10, 3
        args.batch_seconds = 1.0

    # wedge-safe on remote backends: prove the backend live in a guarded
    # subprocess before this process commits to importing jax against it
    import bench
    if "axon" in os.environ.get("JAX_PLATFORMS", "axon") \
            and not os.environ.get("BENCH_SKIP_PROBE") \
            and not bench.probe_backend(
                float(os.environ.get("BENCH_PROBE_TIMEOUT", 300))):
        emit(stage="serve_abort", reason="tpu_unreachable")
        return 1

    import jax
    backend = jax.default_backend()
    rows_list = [int(r) for r in args.rows_list.split(",") if r.strip()]
    if args.buckets is None:
        # resolved AFTER the probe: importing the package pulls in jax
        from lightgbm_tpu.config import SERVE_DEFAULT_BUCKETS
        buckets = list(SERVE_DEFAULT_BUCKETS)
    else:
        buckets = [int(b) for b in args.buckets.split(",") if b.strip()]

    bst, rng = build_model(args.train_rows, args.feats, args.trees,
                           args.leaves)
    from lightgbm_tpu.serve import PredictorArtifact
    t0 = time.perf_counter()
    art = PredictorArtifact.freeze(bst, buckets=buckets)
    compile_secs = time.perf_counter() - t0
    emit(stage="serve_freeze", backend=backend, buckets=buckets,
         trees=args.trees, compiles=art.compile_count,
         secs=round(compile_secs, 2))

    direct_rps = bench_direct(art, rng, args.feats, rows_list, args.iters)
    batched_rps = bench_batched(
        art, rng, args.feats, req_rows=args.req_rows, clients=args.clients,
        seconds=args.batch_seconds,
        deadline_ms=bst._gbdt.config.serve_batch_deadline_ms,
        queue_depth=bst._gbdt.config.serve_queue_depth)

    # one-JSON-line contract: summary() appends to the journal AND prints
    # the schema-stamped record as the LAST stdout line
    LOG.summary(
        metric="serve_throughput", unit="rows/sec",
        value=round(max(direct_rps, batched_rps), 1),
        backend=backend,
        detail={"direct_rows_per_sec": round(direct_rps, 1),
                "batched_rows_per_sec": round(batched_rps, 1),
                "trees": args.trees, "feats": args.feats,
                "buckets": buckets,
                "aot_compile_secs": round(compile_secs, 2)})
    return 0


if __name__ == "__main__":
    sys.exit(main())
