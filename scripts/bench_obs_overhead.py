"""Telemetry overhead bench: boosting-loop cost with obs on vs off.

The acceptance bar for the observability subsystem is that telemetry
OFF (the default) costs nothing measurable — the boosting loop holds a
``None`` and pays one attribute check per iteration — and telemetry ON
stays under a few percent, because iteration events ride host phase-timer
deltas instead of forcing device syncs (models/gbdt.py keeps its lazy
``_pending`` drain).

Trials are INTERLEAVED (off, on, off, on, ...) so machine drift —
thermal, other tenants, allocator state — lands on both arms, and each
arm reports median ± MAD over the repeats.  A few-percent overhead is
near the noise floor of a shared CPU box, so the summary carries a
``sign_ambiguous`` verdict: when the arms' MAD bands overlap the
measured delta, the sign of the overhead is not resolved by this run
and the number must not be read as a regression (or an improvement).

Usage:
    JAX_PLATFORMS=cpu python scripts/bench_obs_overhead.py \
        [--rows 100000] [--rounds 8] [--repeats 5]
"""
import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import load_obs  # noqa: E402

LOG = load_obs().EventLog.default(echo=True)


def emit(**kv):
    LOG.emit(kv.pop("stage", "bench_record"), **kv)


def median(xs):
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def mad(xs):
    """Median absolute deviation — the robust spread for tiny samples
    where one GC pause would wreck a standard deviation."""
    m = median(xs)
    return median([abs(x - m) for x in xs])


def train_secs(params, X, y, rounds):
    import lightgbm_tpu as lgb
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params=params, train_set=ds)
    bst.update()                                  # compile outside the clock
    bst._gbdt._train_score.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(rounds):
        bst.update()
    bst._gbdt._train_score.block_until_ready()
    return time.perf_counter() - t0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--feats", type=int, default=16)
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--leaves", type=int, default=63)
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args(argv)

    import bench
    if "axon" in os.environ.get("JAX_PLATFORMS", "axon") \
            and not os.environ.get("BENCH_SKIP_PROBE") \
            and not bench.probe_backend(
                float(os.environ.get("BENCH_PROBE_TIMEOUT", 300))):
        emit(stage="obs_overhead_abort", reason="tpu_unreachable")
        return 1

    import numpy as np
    import jax
    backend = jax.default_backend()
    rng = np.random.default_rng(0)
    X = rng.normal(size=(args.rows, args.feats)).astype(np.float32)
    y = (X[:, 0] + np.sin(X[:, 1] * 3) + X[:, 2] * X[:, 3]
         + 0.1 * rng.normal(size=args.rows)).astype(np.float64)
    base = {"objective": "regression", "num_leaves": args.leaves,
            "max_bin": 63, "verbose": -1, "seed": 7}

    import tempfile
    evpath = os.path.join(tempfile.mkdtemp(prefix="obs_overhead_"),
                          "events.jsonl")
    configs = {"off": dict(base),
               "on": dict(base, obs_telemetry=True, obs_events_path=evpath)}
    # interleave repeats so drift (thermal, other tenants) hits both arms
    times = {k: [] for k in configs}
    for _ in range(max(1, args.repeats)):
        for name, params in configs.items():
            times[name].append(train_secs(params, X, y, args.rounds))
    med = {k: median(v) for k, v in times.items()}
    spread = {k: mad(v) for k, v in times.items()}
    overhead_on = (med["on"] - med["off"]) / med["off"] * 100.0
    # propagate each arm's MAD into the delta (conservative: sum, not
    # quadrature — MADs of 3-5 samples are too coarse for quadrature)
    noise_s = spread["on"] + spread["off"]
    overhead_mad = noise_s / med["off"] * 100.0
    # when the noise band covers the measured delta, this run cannot even
    # resolve WHICH arm was faster — say so instead of printing a signed
    # percentage that a reader (or the regression sentinel) would trust
    sign_ambiguous = abs(med["on"] - med["off"]) <= noise_s

    for name in configs:
        emit(stage="obs_overhead_arm", arm=name, backend=backend,
             median_s=round(med[name], 4), mad_s=round(spread[name], 4),
             all_s=[round(t, 4) for t in times[name]])

    note = (f"overhead {overhead_on:+.2f}% ± {overhead_mad:.2f}% (MAD); "
            + ("sign NOT resolved at this repeat count"
               if sign_ambiguous else "sign resolved"))
    # one-JSON-line contract: summary() appends to the journal AND prints
    # the schema-stamped record as the LAST stdout line
    LOG.summary(
        metric="obs_telemetry_overhead", unit="pct",
        value=round(overhead_on, 2), backend=backend,
        detail={"rows": args.rows, "rounds": args.rounds,
                "repeats": args.repeats,
                "median_off_s": round(med["off"], 4),
                "median_on_s": round(med["on"], 4),
                "mad_off_s": round(spread["off"], 4),
                "mad_on_s": round(spread["on"], 4),
                "overhead_mad_pct": round(overhead_mad, 2),
                "sign_ambiguous": sign_ambiguous,
                "note": note,
                "events_path": evpath})
    return 0


if __name__ == "__main__":
    sys.exit(main())
