"""Time the individual per-split ops 254x inside one dispatch."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu.ops.histogram import build_histogram, gather_rows
from lightgbm_tpu.ops.split import SplitParams, find_best_split

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
F, B, REP = 28, 256, 254
rng = np.random.default_rng(0)
bins = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
g = jnp.asarray(rng.normal(size=N).astype(np.float32))
h = jnp.asarray(np.full(N, 0.25, np.float32))
na = jnp.asarray(rng.integers(0, 255, size=N, dtype=np.int32))
hist = jnp.asarray(rng.normal(size=(F, B, 3)).astype(np.float32))


def timed(name, fn, *args):
    @jax.jit
    def many(*a):
        def body(acc, i):
            out = fn(i, *a)
            return acc + out, None
        acc, _ = jax.lax.scan(body, jnp.float32(0),
                              jnp.arange(REP, dtype=jnp.int32))
        return acc
    float(many(*args))
    t0 = time.perf_counter()
    float(many(*args))
    dt = time.perf_counter() - t0 - 0.09
    print(f"{name:28s} {dt/REP*1e3:8.3f} ms/iter")


# 1. column take + decision chain + node_assign update
def col_chain(i, bins, na):
    feat = i % F
    col = jnp.take(bins, feat, axis=1).astype(jnp.int32)
    in_leaf = na == (i % 255)
    goes_left = col <= (i % B)
    na2 = jnp.where(in_leaf & ~goes_left, 255 + i, na)
    mask = jnp.where(in_leaf & goes_left, 1.0, 0.0)
    return jnp.sum(mask) + jnp.sum(na2)


timed("col+decide+assign", col_chain, bins, na)


# 2. compaction gather at cap 8192
def compact(i, bins, g, h, na):
    mask = jnp.where(na == (i % 255), 1.0, 0.0)
    bc, gc, hc, mc = gather_rows(bins, g, h, mask, 8192)
    return jnp.sum(gc) + jnp.sum(bc.astype(jnp.float32)[:, 0])


timed("gather_rows cap=8k", compact, bins, g, h, na)


# 3. histogram of 8k compacted rows
bins8 = bins[:8192]
g8, h8 = g[:8192], h[:8192]
m8 = jnp.ones(8192, jnp.float32)


def hist8(i, bins8, g8, h8, m8):
    hh = build_histogram(bins8, g8 + i * 1e-12, h8, m8, B, method="onehot",
                         chunk_rows=8192)
    return jnp.sum(hh)


timed("hist 8k rows", hist8, bins8, g8, h8, m8)

# 4. find_best_split x2
sp = SplitParams(lambda_l1=0.0, lambda_l2=0.0, min_data_in_leaf=100,
                 min_sum_hessian_in_leaf=100.0, min_gain_to_split=0.0,
                 max_delta_step=0.0, path_smooth=0.0, cat_smooth=10.0,
                 cat_l2=10.0, max_cat_to_onehot=4)
meta = dict(num_bins=jnp.full(F, B, jnp.int32),
            default_bins=jnp.zeros(F, jnp.int32),
            nan_bins=jnp.full(F, -1, jnp.int32),
            is_categorical=jnp.zeros(F, bool),
            monotone=jnp.zeros(F, jnp.int8))
fm = jnp.ones(F, jnp.float32)


def fbs(i, hist):
    s1 = find_best_split(hist + i * 1e-12, meta["num_bins"], meta["default_bins"],
                         meta["nan_bins"], meta["is_categorical"],
                         meta["monotone"], 0.0, 1000.0, 4000.0, sp, fm)
    s2 = find_best_split(hist * (1 + i * 1e-12), meta["num_bins"], meta["default_bins"],
                         meta["nan_bins"], meta["is_categorical"],
                         meta["monotone"], 0.0, 1000.0, 4000.0, sp, fm)
    return s1.gain + s2.gain


timed("find_best_split x2", fbs, hist)

# 5. hist store slice update (simulating [L,F,B,3] in-place writes)
store = jnp.zeros((255, F, B, 3), jnp.float32)


def store_upd(i, store, hist):
    s2 = store.at[i % 255].set(hist * i).at[(i + 1) % 255].set(hist)
    return jnp.sum(s2[i % 255, 0, 0])


timed("hist store 2x slice set", store_upd, store, hist)

# 6. full hist at N rows (for comparison)
def histN(i, bins, g, h):
    hh = build_histogram(bins, g + i * 1e-12, h, jnp.ones(N, jnp.float32), B,
                         method="onehot", chunk_rows=8192)
    return jnp.sum(hh)


REP = 10
timed(f"hist {N} rows", histN, bins, g, h)
