"""Decompose gather_rows cost; test scatter-free variants."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
F, CAP, REP = 28, 8192, 100
rng = np.random.default_rng(0)
bins = jnp.asarray(rng.integers(0, 256, size=(N, F), dtype=np.uint8))
na = jnp.asarray(rng.integers(0, 255, size=N, dtype=np.int32))
g = jnp.asarray(rng.normal(size=N).astype(np.float32))


def timed(name, fn, *args):
    @jax.jit
    def many(*a):
        def body(acc, i):
            return acc + fn(i, *a), None
        acc, _ = jax.lax.scan(body, jnp.float32(0),
                              jnp.arange(REP, dtype=jnp.int32))
        return acc
    float(many(*args))
    t0 = time.perf_counter()
    float(many(*args))
    print(f"{name:30s} {(time.perf_counter()-t0-0.09)/REP*1e3:8.3f} ms/iter")


def cumsum_only(i, na):
    active = (na == (i % 255))
    return jnp.sum(jnp.cumsum(active.astype(jnp.int32))).astype(jnp.float32) * 1e-9


timed("cumsum", cumsum_only, na)


def scatter_ids(i, na):
    active = na == (i % 255)
    pos = jnp.cumsum(active.astype(jnp.int32)) - 1
    slot = jnp.where(active, pos, CAP)
    row_ids = jnp.zeros(CAP, jnp.int32).at[slot].set(
        jnp.arange(N, dtype=jnp.int32), mode="drop")
    return jnp.sum(row_ids).astype(jnp.float32) * 1e-9


timed("cumsum+scatter", scatter_ids, na)


def searchsorted_ids(i, na):
    active = na == (i % 255)
    cs = jnp.cumsum(active.astype(jnp.int32))
    row_ids = jnp.searchsorted(cs, jnp.arange(1, CAP + 1, dtype=jnp.int32),
                               side="left")
    return jnp.sum(row_ids).astype(jnp.float32) * 1e-9


timed("cumsum+searchsorted", searchsorted_ids, na)


row_ids_const = jnp.asarray(rng.integers(0, N, size=CAP, dtype=np.int32))


def row_gather(i, bins, g):
    ids = (row_ids_const + i) % N
    bc = jnp.take(bins, ids, axis=0)
    gc = jnp.take(g, ids)
    return jnp.sum(gc) + jnp.sum(bc[:, 0].astype(jnp.float32)) * 1e-9


timed("row gather cap=8k", row_gather, bins, g)


def nonzero_ids(i, na):
    active = na == (i % 255)
    ids = jnp.nonzero(active, size=CAP, fill_value=N - 1)[0]
    return jnp.sum(ids).astype(jnp.float32) * 1e-9


timed("jnp.nonzero size=8k", nonzero_ids, na)


def unrolled_ids(i, na):
    active = na == (i % 255)
    cs = jnp.cumsum(active.astype(jnp.int32))
    targets = jnp.arange(1, CAP + 1, dtype=jnp.int32)
    lo = jnp.zeros(CAP, jnp.int32)
    span = 1 << max(0, (N - 1).bit_length())
    while span >= 1:
        mid = jnp.minimum(lo + span, N) - 1
        lo = jnp.where(jnp.take(cs, mid) < targets, lo + span, lo)
        span >>= 1
    return jnp.sum(lo).astype(jnp.float32) * 1e-9


timed("unrolled binsearch", unrolled_ids, na)


def twolevel_ids(i, na):
    S = 1024
    nb = -(-N // S)
    active = na == (i % 255)
    act_i = jnp.pad(active.astype(jnp.int32), (0, nb * S - N))
    blk_cnt = jnp.sum(act_i.reshape(nb, S), axis=1)          # [nb]
    blk_cs = jnp.cumsum(blk_cnt)                              # [nb]
    targets = jnp.arange(1, CAP + 1, dtype=jnp.int32)
    # level 1: find block (search in [nb], VMEM-resident)
    lo = jnp.zeros(CAP, jnp.int32)
    span = 1 << max(0, (nb - 1).bit_length())
    while span >= 1:
        mid = jnp.minimum(lo + span, nb) - 1
        lo = jnp.where(jnp.take(blk_cs, mid) < targets, lo + span, lo)
        span >>= 1
    blk = jnp.minimum(lo, nb - 1)
    prev = jnp.where(blk > 0, jnp.take(blk_cs, blk - 1), 0)
    t_in = targets - prev                                     # 1-based in block
    # level 2: in-block cumsum gathered rows: gather the S-length block rows
    # for each target and cumsum? instead gather in-block prefix via binary
    # search over the original cs restricted to the block
    cs = jnp.cumsum(act_i)
    base = blk * S
    lo2 = jnp.zeros(CAP, jnp.int32)
    span = S
    while span >= 1:
        mid = jnp.minimum(lo2 + span, S) - 1
        v = jnp.take(cs, base + mid) - prev
        lo2 = jnp.where(v < t_in, lo2 + span, lo2)
        span >>= 1
    return jnp.sum(base + lo2).astype(jnp.float32) * 1e-9


timed("twolevel binsearch", twolevel_ids, na)
