"""Isolate grow_tree cost on the live backend with config toggles.

usage: python scripts/profile_grow.py [rows] [leaves] [compact(0/1)] [chunk]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu.ops.grower import GrowerConfig, grow_tree
from lightgbm_tpu.ops.split import SplitParams

rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
leaves = int(sys.argv[2]) if len(sys.argv) > 2 else 255
compact = bool(int(sys.argv[3])) if len(sys.argv) > 3 else True
chunk = int(sys.argv[4]) if len(sys.argv) > 4 else 8192

F, B = 28, 256
rng = np.random.default_rng(0)
bins = jnp.asarray(rng.integers(0, B, size=(rows, F), dtype=np.uint8))
g = jnp.asarray(rng.normal(size=rows).astype(np.float32))
h = jnp.asarray(np.full(rows, 0.25, np.float32))
rw = jnp.ones(rows, jnp.float32)
fm = jnp.ones(F, jnp.float32)
meta = dict(num_bins=jnp.full(F, B, jnp.int32),
            default_bins=jnp.zeros(F, jnp.int32),
            nan_bins=jnp.full(F, -1, jnp.int32),
            is_categorical=jnp.zeros(F, bool),
            monotone=jnp.zeros(F, jnp.int8))
sp = SplitParams(lambda_l1=0.0, lambda_l2=0.0, min_data_in_leaf=100,
                 min_sum_hessian_in_leaf=100.0, min_gain_to_split=0.0,
                 max_delta_step=0.0, path_smooth=0.0, cat_smooth=10.0,
                 cat_l2=10.0, max_cat_to_onehot=4)
import os
cfg = GrowerConfig(num_leaves=leaves, max_depth=-1, max_bin=B, split=sp,
                   feature_fraction_bynode=1.0,
                   hist_method=("pallas" if jax.default_backend() == "tpu"
                                else "scatter"),
                   hist_chunk_rows=chunk, hist_compact=compact,
                   sorted_cat=bool(int(os.environ.get("PROF_SORTED_CAT", "0"))),
                   hist_compact_ladder=float(os.environ.get("PROF_LADDER",
                                                            "1.41")),
                   grower_mode=os.environ.get("PROF_GROWER", "serial"),
                   frontier_k=int(os.environ.get("PROF_K", "32")),
                   frontier_block_rows=int(os.environ.get("PROF_BR", "512")))


@jax.jit
def run(bins, g, h, rw, fm, key):
    t, na = grow_tree(bins, g, h, rw, fm, **meta, key=key, cfg=cfg)
    return t.num_leaves, t.leaf_value.sum()


key = jax.random.PRNGKey(0)
t0 = time.perf_counter()
nl, s = run(bins, g, h, rw, fm, key)
nl = int(nl)
print(f"compile+first: {time.perf_counter()-t0:.2f}s num_leaves={nl}")
for trial in range(3):
    t0 = time.perf_counter()
    nl, s = run(bins, g, h, rw, fm, jax.random.PRNGKey(trial))
    float(s)
    dt = time.perf_counter() - t0
    print(f"grow: {dt*1e3:.0f} ms  ({dt/max(int(nl)-1,1)*1e3:.2f} ms/split, {int(nl)} leaves)")

# optional: one profiled iteration (PROF_TRACE=/tmp/trace writes a
# jax.profiler trace attributing per-round cost: gather vs kernel vs
# cumsum/partition vs split search)
trace_dir = os.environ.get("PROF_TRACE")
if trace_dir:
    with jax.profiler.trace(trace_dir):
        nl, s = run(bins, g, h, rw, fm, jax.random.PRNGKey(9))
        float(s)
    print(f"trace written to {trace_dir}")
