"""Out-of-core streaming bench: rows/s + H2D-overlap efficiency vs in-HBM.

Trains the same synthetic workload (a) fully device-resident (the
baseline) and (b) streamed from host RAM under a synthetic HBM cap at
three block sizes, and reports per configuration:

- **rows/s** (train rows x boosting rounds / wall time) and the slowdown
  vs the in-HBM baseline (streaming re-reads the matrix once per split —
  the out-of-core price; on TPU the H2D sits off the critical path, on
  CPU this bench mostly prices the re-read);
- **H2D-overlap efficiency**: 1 - max(0, t_stream - t_baseline) /
  t_pure_transfer, where t_pure_transfer is a timed transfer-only sweep
  moving the same bytes — 1.0 means every copied byte hid behind compute,
  0 means every byte was paid on the critical path.  Also measured
  directly as the prefetch=1 vs prefetch=2 wall-time delta at the middle
  block size;
- **peak device bytes** of in-flight blocks vs the cap (must stay below —
  the synthetic-HBM acceptance gate), plus transferred bytes/pass counts.

One jsonl record per measurement is appended to ``WATCHER_PERF_LOG`` (or
``perf_results.jsonl``), and the LAST stdout line is a single JSON summary
(the bench one-JSON-line contract, ``supervise.extract_json_line``).

Run:
    python scripts/bench_stream.py [--rows 200000] [--feats 16]
                                   [--rounds 5] [--quick]
"""
import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import load_obs  # noqa: E402

# the single perf-journal writer (obs.events resolves WATCHER_PERF_LOG or
# the repo default); echo keeps the one-record-per-line stdout mirror
LOG = load_obs().EventLog.default(echo=True)


def emit(**kv):
    LOG.emit(kv.pop("stage", "bench_record"), **kv)


def make_data(rows: int, feats: int):
    import numpy as np
    rng = np.random.default_rng(0)
    X = rng.normal(size=(rows, feats))
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 3) + X[:, 2] * X[:, 3]
         + 0.1 * rng.normal(size=rows)).astype(np.float64)
    return X, y


def train_once(params, X, y, rounds):
    import lightgbm_tpu as lgb
    ds = lgb.Dataset(X, label=y, params=params)
    ds.construct()
    t0 = time.perf_counter()
    bst = lgb.train(params, ds, num_boost_round=rounds)
    return bst, time.perf_counter() - t0, ds


def pure_transfer_time(matrix, prefetch):
    """Timed transfer-only sweep: the H2D cost with zero compute."""
    import jax
    from lightgbm_tpu.stream.pipeline import RowBlockPipeline
    pipe = RowBlockPipeline(matrix, prefetch)
    t0 = time.perf_counter()
    last = None
    for blk in pipe.blocks():
        last = blk.bins
    if last is not None:
        jax.block_until_ready(last)
    return time.perf_counter() - t0, pipe.stats


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--feats", type=int, default=16)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--leaves", type=int, default=15)
    ap.add_argument("--quick", action="store_true",
                    help="small shape for CI/tier-1 (~100k x 10, 3 rounds)")
    args = ap.parse_args()
    if args.quick:
        args.rows, args.feats, args.rounds = 100_000, 10, 3

    import jax
    import numpy as np
    backend = jax.default_backend()
    X, y = make_data(args.rows, args.feats)
    base_params = {"objective": "regression", "num_leaves": args.leaves,
                   "max_bin": 63, "verbose": -1, "seed": 7,
                   "tree_grower": "serial"}

    # --- in-HBM baseline ------------------------------------------------
    bst_ref, t_ref, _ = train_once(dict(base_params), X, y, args.rounds)
    ref_rows_s = args.rows * args.rounds / t_ref
    emit(stage="stream_baseline", backend=backend, rows=args.rows,
         feats=args.feats, rounds=args.rounds, wall_s=round(t_ref, 3),
         rows_per_s=round(ref_rows_s, 1))

    # synthetic cap small enough to force >= 4 blocks at the LARGEST
    # tested block size
    row_bytes = args.feats + 16                 # u8 bins + f32 sidecars
    cap = (args.rows // 4) * row_bytes * 3      # prefetch+1 = 3 resident
    ref_pred = bst_ref.predict(X[:4096])

    results = []
    block_sizes = sorted({max(128, (args.rows // k) // 128 * 128)
                          for k in (16, 8, 4)})
    for i, br in enumerate(block_sizes):
        os.environ["STREAM_FAKE_HBM_BYTES"] = str(cap)
        params = dict(base_params, stream_rows=br)
        bst, t_s, ds = train_once(params, X, y, args.rounds)
        os.environ.pop("STREAM_FAKE_HBM_BYTES", None)
        gb = bst._gbdt
        stats = gb.stream_stats.as_dict()
        matrix = gb._matrix
        t_xfer, xstats = pure_transfer_time(matrix, gb._plan.prefetch)
        # transfer time the training run actually paid: scale the measured
        # full-sweep time by the TRUE bytes moved (per-split passes skip
        # blocks via the count table, so passes * t_xfer would overstate
        # the denominator and flatter the overlap number)
        t_xfer_train = t_xfer * stats["bytes_h2d"] / max(
            xstats.bytes_h2d, 1)
        # fraction of that transfer time hidden behind compute
        overlap = max(0.0, min(1.0, 1.0 - max(0.0, t_s - t_ref)
                               / max(t_xfer_train, 1e-9)))
        pred_diff = float(np.abs(bst.predict(X[:4096]) - ref_pred).max())
        rec = dict(stage="stream_block", backend=backend, block_rows=br,
                   num_blocks=matrix.num_blocks, wall_s=round(t_s, 3),
                   rows_per_s=round(args.rows * args.rounds / t_s, 1),
                   vs_inhbm=round(t_ref / t_s, 4),
                   overlap_efficiency=round(overlap, 4),
                   peak_block_bytes=stats["peak_block_bytes"],
                   fake_hbm_cap=cap,
                   under_cap=bool(stats["peak_block_bytes"] <= cap),
                   bytes_h2d=stats["bytes_h2d"], passes=stats["passes"],
                   blocks_skipped=stats["blocks_skipped"],
                   max_pred_diff=pred_diff)
        emit(**rec)
        results.append(rec)

    # --- direct prefetch-depth comparison at the middle block size ------
    mid = block_sizes[len(block_sizes) // 2]
    times = {}
    for pf in (1, 2):
        params = dict(base_params, stream_rows=mid, stream_prefetch=pf)
        _, t_pf, _ = train_once(params, X, y, max(1, args.rounds // 2))
        times[pf] = t_pf
    emit(stage="stream_prefetch_depth", block_rows=mid,
         wall_s_prefetch1=round(times[1], 3),
         wall_s_prefetch2=round(times[2], 3),
         speedup_2_vs_1=round(times[1] / times[2], 4))

    ok = all(r["under_cap"] for r in results) and \
        all(r["max_pred_diff"] < 1e-4 for r in results)
    summary = dict(bench="stream", backend=backend, rows=args.rows,
                   feats=args.feats, rounds=args.rounds,
                   baseline_rows_per_s=round(ref_rows_s, 1),
                   fake_hbm_cap=cap,
                   blocks=[{k: r[k] for k in
                            ("block_rows", "num_blocks", "rows_per_s",
                             "vs_inhbm", "overlap_efficiency",
                             "peak_block_bytes", "under_cap")}
                           for r in results],
                   prefetch_speedup=round(times[1] / times[2], 4),
                   ok=bool(ok))
    # one-JSON-line contract: summary() appends to the journal AND prints
    # the schema-stamped record as the LAST stdout line
    LOG.summary(**summary)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
