"""Dual-kernel / dual-grower parity on the AMBIENT backend (the TPU).

The hardware half of ``tests/test_dual.py``: the CPU CI backend cannot lower
the Pallas kernels, so the r02-class failure (a lowering crash only a real
TPU invocation surfaces) is caught here.  Wedge-safe: probes the backend in
a subprocess before committing this process to it (see bench.probe_backend).

Checks, in order (each emits one JSON line; first failure exits nonzero):
  1. pallas row-major one-hot kernel vs XLA one-hot         (both layouts)
  2. pallas feature-major blocked kernel vs XLA one-hot     (wide features)
  3. pallas batched-leaf kernel vs scatter fallback         (frontier path)
  4. frontier-vs-serial grower: identical trees on the TPU

Run (the ONLY process touching the TPU):
    python scripts/bench_dual.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import load_obs  # noqa: E402

LOG = load_obs().EventLog.default(echo=True)


def emit(**kv):
    LOG.emit(kv.pop("stage", "bench_record"), **kv)


def main() -> int:
    import bench
    if (not os.environ.get("BENCH_SKIP_PROBE")
            and "axon" in os.environ.get("JAX_PLATFORMS", "axon")
            and not bench.probe_backend(
                float(os.environ.get("BENCH_PROBE_TIMEOUT", 300)))):
        # abort without importing jax (the probe said the TPU would wedge us)
        LOG.summary(bench="dual_parity", ok=False, reason="tpu_unreachable")
        return 1
    import jax
    backend = jax.default_backend()
    emit(stage="sanity", backend=backend)
    rc = run_checks(emit)
    # one-JSON-line contract: the LAST stdout line is the schema summary
    LOG.summary(bench="dual_parity", ok=rc == 0, rc=rc, backend=backend)
    return rc


def run_checks(emit) -> int:
    """All dual checks, in-process (importable by tpu_perf_suite so only ONE
    process ever touches the TPU).  Returns 0 when every check passes."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from lightgbm_tpu.ops.histogram import (_hist_onehot, _hist_pallas,
                                            build_histogram_leaves,
                                            _hist_leaves_pallas)
    rng = np.random.default_rng(3)

    def data(n, f, b):
        bins = jnp.asarray(rng.integers(0, b, size=(n, f), dtype=np.uint8))
        g = jnp.asarray(rng.normal(size=n).astype(np.float32))
        h = jnp.asarray(rng.uniform(0.1, 1.0, size=n).astype(np.float32))
        m = jnp.asarray((rng.uniform(size=n) < 0.8).astype(np.float32))
        return bins, g, h, m

    def relerr(a, b):
        return float(jnp.max(jnp.abs(a - b) / (jnp.abs(b) + 1.0)))

    rc = 0

    # Parity threshold: the shared lo-residual-floor constant from
    # ops/histogram.py (its derivation lives on the constant) — ONE number
    # for every kernel parity gate, hardware or interpret.
    from lightgbm_tpu.ops.histogram import HIST_PARITY_TOL as TOL

    # 1/2: one-hot kernel, both layouts (rowmajor is bench-opt-in but must
    # stay numerically correct while it exists)
    for name, (n, f, b) in (("rowmajor", (200_000, 28, 255)),
                            ("featmajor", (100_000, 200, 255))):
        bins, g, h, m = data(n, f, b)
        try:
            a = jax.jit(lambda *x: _hist_pallas(*x, b, layout=name))(
                bins, g, h, m)
            ref = jax.jit(lambda *x: _hist_onehot(*x, b, 65536))(bins, g, h, m)
            err = relerr(a, ref)
            ok = err < TOL
            emit(stage=f"pallas_{name}", ok=ok, relerr=err)
            rc |= 0 if ok else 1
        except Exception as e:
            emit(stage=f"pallas_{name}", ok=False, error=str(e)[:300])
            rc |= 1

    # 3: batched-leaf kernel (scalar-prefetched output block index)
    BR, NB, NC, B, k = 512, 24, 32, 255, 6
    C = BR * NB
    comb = jnp.asarray(rng.integers(0, B, size=(C, NC), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=C).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=C).astype(np.float32))
    m = jnp.asarray((rng.uniform(size=C) < 0.8).astype(np.float32))
    # deliberately leave slot k-2 empty: a slot with no row blocks must
    # come back as zeros (the kernel zero-inits its whole VMEM-resident
    # accumulator at grid step 0), not stale HBM
    bl = np.sort(rng.integers(0, k, size=NB)).astype(np.int32)
    bl = jnp.asarray(np.where(bl == k - 2, k - 1, bl))
    try:
        got = jax.jit(lambda *x: _hist_leaves_pallas(*x, k, B, BR, 28))(
            comb, g, h, m, bl)
        ref = jax.jit(lambda *x: build_histogram_leaves(
            *x, k, B, method="scatter", block_rows=BR, f_limit=28))(
            comb, g, h, m, bl)
        err = relerr(got, ref[:, :28])
        ok = err < TOL
        emit(stage="pallas_batched_leaves", ok=ok, relerr=err)
        rc |= 0 if ok else 1
    except Exception as e:
        emit(stage="pallas_batched_leaves", ok=False, error=str(e)[:300])
        rc |= 1

    # 4: frontier-vs-serial grower on hardware — identical trees
    try:
        from sklearn.datasets import make_classification
        import lightgbm_tpu as lgb
        X, y = make_classification(n_samples=20000, n_features=12,
                                   n_informative=7, random_state=7)
        X = X.astype(np.float32)
        out = {}
        for grower in ("serial", "frontier"):
            p = {"objective": "binary", "num_leaves": 63, "verbose": -1,
                 "tree_grower": grower, "min_data_in_leaf": 20}
            ds = lgb.Dataset(X, label=y, params=p)
            out[grower] = lgb.train(p, ds, num_boost_round=3)
        d = float(np.abs(out["serial"].predict(X)
                         - out["frontier"].predict(X)).max())
        ok = d < 1e-4
        emit(stage="grower_dual", ok=ok, max_pred_diff=d)
        rc |= 0 if ok else 1
    except Exception as e:
        emit(stage="grower_dual", ok=False, error=str(e)[:300])
        rc |= 1

    emit(stage="done", rc=rc)
    return rc


if __name__ == "__main__":
    sys.exit(main())
