#!/bin/bash
# Build the reference LightGBM CLI + lib out-of-tree for the interop tests
# (tests/test_interop.py).  The reference mount is read-only and its
# external_libs submodules are empty, so this stages a patched copy:
#   - fmt: the spdlog-bundled copy shipped inside the tensorflow wheel
#   - eigen: the Eigen headers shipped inside the tensorflow wheel
#   - fast_double_parser: a strtod-backed stand-in (correctly rounded)
#   - C++17 (the tensorflow Eigen needs >= C++14)
# Produces /tmp/lgbm_src/lightgbm and /tmp/lgbm_src/lib_lightgbm.so
# (~10 min).  Re-entrant: skips everything if the binary already runs.
set -euo pipefail

REF=${1:-/root/reference}
SRC=/tmp/lgbm_src
TF_INC=$(python - <<'EOF'
import pathlib, tensorflow
print(pathlib.Path(tensorflow.__file__).parent / "include")
EOF
)

if [ -x "$SRC/lightgbm" ]; then
    echo "reference binary already built: $SRC/lightgbm"
    exit 0
fi

rm -rf "$SRC" /tmp/lgbm_build
cp -r "$REF" "$SRC"
rm -rf "$SRC/.git"

mkdir -p "$SRC/external_libs/fmt/include/fmt"
cp "$TF_INC"/external/spdlog/include/spdlog/fmt/bundled/*.h \
   "$SRC/external_libs/fmt/include/fmt/"
mkdir -p "$SRC/external_libs/eigen"
cp -r "$TF_INC/Eigen" "$SRC/external_libs/eigen/Eigen"
mkdir -p "$SRC/external_libs/fast_double_parser/include"
cat > "$SRC/external_libs/fast_double_parser/include/fast_double_parser.h" <<'EOF'
// Minimal stand-in for fast_double_parser used by the offline reference
// build: parse via strtod (correctly rounded, just slower).
#pragma once
#include <cstdlib>
namespace fast_double_parser {
inline const char *parse_number(const char *p, double *outDouble) {
  char *end = nullptr;
  *outDouble = std::strtod(p, &end);
  return end == p ? nullptr : end;
}
}  // namespace fast_double_parser
EOF

sed -i 's/-std=c++11 -pthread/-std=c++17 -pthread/' "$SRC/CMakeLists.txt"
cmake -S "$SRC" -B /tmp/lgbm_build -DCMAKE_BUILD_TYPE=Release
cmake --build /tmp/lgbm_build -j "$(nproc)"
echo "built: $SRC/lightgbm"
