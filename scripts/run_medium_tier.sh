#!/usr/bin/env bash
# Medium validation tier (VERDICT "Next round" #6): the <15-min CPU
# cross-section — parallel (8-device virtual mesh), frontier grower
# parity, reference-binary interop, compute-op units — run before a
# hardware window so a broken tree never burns TPU time.  Appends one
# green/red record with the wall time to PROGRESS.jsonl so pre-window
# validation is cheap AND recorded.
#
# After the tests, the perf-regression sentinel gate runs over the
# journal (obs-report --regressions --gate, jax-free): a perf
# regression blocks the tier exactly like a failing test, and the
# verdict counts land in the same PROGRESS.jsonl record.
#
# Usage: scripts/run_medium_tier.sh [extra pytest args...]
set -u
cd "$(dirname "$0")/.."

START=$(date +%s)
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" timeout -k 10 900 \
    python -m pytest tests/ -q -m 'medium and not slow' \
    -p no:cacheprovider --continue-on-collection-errors "$@"
RC=$?
WALL=$(( $(date +%s) - START ))

python - "$RC" "$WALL" <<'EOF'
import json, sys, time
rc, wall = int(sys.argv[1]), int(sys.argv[2])

# perf-regression sentinel: jax-free load, gate rc folded into the
# tier verdict (a sentinel crash must not mask a green/red test run,
# so failures of the GATE ITSELF are recorded but non-fatal)
gate = {"gate_rc": None, "regressed": None, "verdicts": None}
try:
    import io, contextlib
    import bench
    obs = bench.load_obs()
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        gate_rc = obs.report.main(["--regressions", "--gate",
                                   "--format", "json"])
    res = json.loads(buf.getvalue())["regressions"]
    gate = {"gate_rc": gate_rc, "regressed": bool(res["regressed"]),
            "verdicts": res["counts"]}
except Exception as e:   # noqa: BLE001 - record, don't mask the tests
    gate["gate_error"] = f"{type(e).__name__}: {e}"

final_rc = rc if rc != 0 else (gate["gate_rc"] or 0)
rec = {"ts": round(time.time(), 3), "event": "medium_tier",
       "green": final_rc == 0, "rc": rc, "wall_secs": wall,
       "timed_out": rc == 124, "perf_gate": gate}
with open("PROGRESS.jsonl", "a") as f:
    f.write(json.dumps(rec) + "\n")
print(json.dumps(rec))
sys.exit(final_rc)
EOF
exit $?
