#!/usr/bin/env bash
# Medium validation tier (VERDICT "Next round" #6): the <15-min CPU
# cross-section — parallel (8-device virtual mesh), frontier grower
# parity, reference-binary interop, compute-op units — run before a
# hardware window so a broken tree never burns TPU time.  Appends one
# green/red record with the wall time to PROGRESS.jsonl so pre-window
# validation is cheap AND recorded.
#
# Usage: scripts/run_medium_tier.sh [extra pytest args...]
set -u
cd "$(dirname "$0")/.."

START=$(date +%s)
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" timeout -k 10 900 \
    python -m pytest tests/ -q -m 'medium and not slow' \
    -p no:cacheprovider --continue-on-collection-errors "$@"
RC=$?
WALL=$(( $(date +%s) - START ))

python - "$RC" "$WALL" <<'EOF'
import json, sys, time
rc, wall = int(sys.argv[1]), int(sys.argv[2])
rec = {"ts": round(time.time(), 3), "event": "medium_tier",
       "green": rc == 0, "rc": rc, "wall_secs": wall,
       "timed_out": rc == 124}
with open("PROGRESS.jsonl", "a") as f:
    f.write(json.dumps(rec) + "\n")
print(json.dumps(rec))
EOF
exit $RC
