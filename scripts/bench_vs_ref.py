"""Head-to-head vs the compiled reference binary, same data, same machine.

Trains ``/tmp/lgbm_src/lightgbm`` (reference CLI, ``docs/Experiments.rst:
110-135`` methodology) on the exact dataset ``bench.py`` uses
(``make_higgs_like``) with the exact bench params, times it from the
reference's own per-iteration log lines (``src/boosting/gbdt.cpp:275``
prints cumulative elapsed per iteration), and scores held-out AUC on a
fresh 200k-row split via ``task=predict``.

Results land in ``docs/ref_headtohead.json`` keyed by row count —
``bench.py`` reads that file to derive its held-out-AUC floor and to emit
``ref_auc`` / ``ref_sec_per_tree_local`` / ``auc_delta`` in the bench
detail — and are appended to ``perf_results.jsonl``.

Run: ``python scripts/bench_vs_ref.py [--rows 1000000] [--iters 22]``
(iters defaults to bench.py's warmup+timed = 22 so the AUC comparison is
between same-size ensembles).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
from bench import make_higgs_like  # noqa: E402

from bench import load_obs  # noqa: E402

REF_BIN = os.environ.get("REF_LGBM_BIN", "/tmp/lgbm_src/lightgbm")
OUT_JSON = os.path.join(REPO, "docs", "ref_headtohead.json")
# the single perf-journal writer (obs.events): honors WATCHER_PERF_LOG,
# which the bare perf_results.jsonl path here previously ignored
LOG = load_obs().EventLog.default(echo=True)

# one row per line, label first (the reference default: label=column 0).
# %.9g round-trips float32 bit-exactly (9 significant digits uniquely
# identify any binary32; %.7g did NOT, so the reference trained on data
# that differed from ours in the last ulps — weakening the "identical
# data" head-to-head claim).  tests/test_bench.py locks the round trip.
def _write_csv(path: str, X: np.ndarray, y: np.ndarray | None) -> None:
    cols = X if y is None else np.column_stack([y, X])
    np.savetxt(path, cols, delimiter=",", fmt="%.9g")


def _run(cmd, **kw):
    p = subprocess.run(cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                       text=True, **kw)
    if p.returncode != 0:
        sys.exit(f"reference binary failed ({p.returncode}):\n{p.stdout[-3000:]}")
    return p.stdout


def _auc(y_true: np.ndarray, score: np.ndarray) -> float:
    order = np.argsort(score, kind="mergesort")
    y = y_true[order]
    # tie-corrected rank AUC
    ranks = np.empty(len(y), np.float64)
    s = score[order]
    i = 0
    while i < len(s):
        j = i
        while j + 1 < len(s) and s[j + 1] == s[i]:
            j += 1
        ranks[i:j + 1] = 0.5 * (i + j) + 1.0
        i = j + 1
    npos = y.sum()
    nneg = len(y) - npos
    return float((ranks[y > 0].sum() - npos * (npos + 1) / 2) / (npos * nneg))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--iters", type=int, default=22)
    ap.add_argument("--valid-rows", type=int, default=200_000)
    ap.add_argument("--warmup", type=int, default=2,
                    help="iterations excluded from sec/tree (compile/cache"
                         " warmup analog; the reference has none, but this"
                         " matches how bench.py times ours)")
    args = ap.parse_args()

    if not os.path.exists(REF_BIN):
        sys.exit(f"reference binary not found at {REF_BIN}")

    Xtr, ytr = make_higgs_like(args.rows)
    Xva, yva = make_higgs_like(args.valid_rows, seed=43)

    tmp = tempfile.mkdtemp(prefix="ref_h2h_")
    train_csv = os.path.join(tmp, "train.csv")
    valid_csv = os.path.join(tmp, "valid.csv")
    model_txt = os.path.join(tmp, "model.txt")
    pred_txt = os.path.join(tmp, "pred.txt")
    print(f"writing CSVs to {tmp} ...", flush=True)
    _write_csv(train_csv, Xtr, ytr)
    _write_csv(valid_csv, Xva, yva)

    nthreads = os.cpu_count() or 1
    conf = {
        "task": "train", "objective": "binary",
        "data": train_csv, "output_model": model_txt,
        "num_iterations": args.iters, "num_leaves": 255,
        "learning_rate": 0.1, "max_bin": 255,
        "min_data_in_leaf": 100, "min_sum_hessian_in_leaf": 100.0,
        "num_threads": nthreads, "verbosity": 1, "header": "false",
    }
    cmd = [REF_BIN] + [f"{k}={v}" for k, v in conf.items()]
    print("training reference ...", flush=True)
    t0 = time.perf_counter()
    out = _run(cmd)
    wall = time.perf_counter() - t0

    elapsed = {int(m.group(2)): float(m.group(1)) for m in re.finditer(
        r"([0-9.]+) seconds elapsed, finished iteration (\d+)", out)}
    load = re.search(r"Finished loading data in ([0-9.]+) seconds", out)
    if args.iters not in elapsed:
        sys.exit(f"could not parse reference timing from log:\n{out[-2000:]}")
    w = min(args.warmup, args.iters - 1)
    sec_per_tree = (elapsed[args.iters] - elapsed.get(w, 0.0)) / (args.iters - w)

    print("predicting held-out ...", flush=True)
    _run([REF_BIN, "task=predict", f"data={valid_csv}",
          f"input_model={model_txt}", f"output_result={pred_txt}",
          "header=false", f"num_threads={nthreads}"])
    pred = np.loadtxt(pred_txt)
    ref_auc = _auc(yva.astype(np.float64), pred)

    import shutil
    shutil.rmtree(tmp, ignore_errors=True)

    entry = {
        "rows": args.rows, "iters": args.iters, "valid_rows": args.valid_rows,
        "num_leaves": conf["num_leaves"],
        "ref_sec_per_tree": round(sec_per_tree, 4),
        "ref_train_sec": round(elapsed[args.iters], 3),
        "ref_load_sec": round(float(load.group(1)), 3) if load else None,
        "ref_wall_sec": round(wall, 3),
        "ref_auc_holdout": round(ref_auc, 6),
        "threads": nthreads,
        "ref_version": "LightGBM v3.1.1.99 (compiled on this VM)",
    }
    print(json.dumps(entry))

    table = {}
    if os.path.exists(OUT_JSON):
        with open(OUT_JSON) as f:
            table = json.load(f)
    table[str(args.rows)] = entry
    os.makedirs(os.path.dirname(OUT_JSON), exist_ok=True)
    with open(OUT_JSON, "w") as f:
        json.dump(table, f, indent=1)
    print(f"recorded -> {OUT_JSON}")
    # one-JSON-line contract: summary() appends to the journal AND prints
    # the schema-stamped record as the LAST stdout line
    LOG.summary(bench="ref_headtohead", **entry)


if __name__ == "__main__":
    main()
