"""Unattended TPU-window watcher: poll for a live backend, then spend the
window on the full perf story with zero human attention.

After three wedged rounds the headline claim is still unmeasured on
hardware (ROADMAP item 1); this daemon converts "hope someone is at the
keyboard when the tunnel recovers" into infrastructure.  It is a state
machine journaled to ``watcher_state.json``:

  POLL      probe the backend (``bench.probe_backend``: subprocess +
            process group + killpg, ~10 min cadence) with jittered
            exponential backoff on repeated failure.
  PIPELINE  on the first live probe, run the staged capture — each stage
            its OWN subprocess under a wall-clock budget:
              parity           scripts/bench_dual.py
              perf_suite       scripts/tpu_perf_suite.py
              onehot_shootout  scripts/bench_onehot_variants.py
              headline         bench.py
            A stage crash or hang records a failure and DEGRADES to the
            remaining stages (window time is precious; one broken kernel
            must not cost the headline number).  After any stage failure
            the backend is re-probed: a dead probe means the window
            re-wedged mid-run — the watcher returns to POLL and, on the
            next window, RESUMES from the first incomplete stage instead
            of restarting (completed and deliberately-failed stages are
            never re-run within a window).
  DONE      after ``--max-windows`` captured windows.

Every stage result is appended to ``perf_results.jsonl`` as it lands; a
heartbeat jsonl (``watcher_heartbeat.jsonl``) records every poll, attempt,
backoff, and kill so a dead watcher leaves a legible trail.  A
single-owner pid-checked lock file (``watcher.lock``) guarantees only one
process ever touches the TPU: a second invocation refuses to start with a
clear message and exit code 2.

Fault-injection seam (CPU-testable, no TPU required): setting
``WATCHER_FAKE_BACKEND=ok|fail|hang|flaky`` swaps the probe and every
stage command for scripted fakes (re-invocations of this file with
``--fake-probe`` / ``--fake-stage``).  Finer scripting for tests:
``WATCHER_FAKE_PROBE_PLAN`` (file of ok/fail/hang lines, popped one per
probe) and ``WATCHER_FAKE_STAGE_PLAN`` (JSON file {stage: [behavior,...]},
popped one per invocation).  See docs/WATCHER.md.

Run unattended (the ONLY process touching the TPU):
    nohup python scripts/tpu_window_watcher.py >/dev/null 2>&1 &
Exit codes: 0 captured/stepped, 2 lock held, 3 --max-polls exhausted.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STAGE_NAMES = ("parity", "perf_suite", "onehot_shootout", "headline",
               "bench_serve", "bench_stream")
JOURNAL_VERSION = 1


# --------------------------------------------------------------------------
# scripted fakes (run FIRST: the fake subprocesses must not import numpy/
# jax or take the argparse path)
# --------------------------------------------------------------------------

def _perf_log_path() -> str:
    return os.environ.get("WATCHER_PERF_LOG",
                          os.path.join(REPO, "perf_results.jsonl"))


def _append_perf(rec: dict) -> None:
    rec.setdefault("ts", round(time.time(), 3))
    with open(_perf_log_path(), "a") as f:
        f.write(json.dumps(rec) + "\n")


def _append_regress_verdict(stage: str, window_id) -> None:
    """Post-stage self-judgment: classify the numbers the stage just
    appended against the journal + BENCH_r* history via the jax-free
    regression sentinel (obs.regress), so a slower-than-last-window
    result flags WHILE the window is still open instead of after it
    closes.  Never fatal — a verdict bug must not cost a captured
    stage."""
    try:
        regress = bench.load_obs().regress
        res = regress.scan(journal_path=_perf_log_path())
        _append_perf({"stage": "watcher_regress", "after_stage": stage,
                      "window_id": window_id, "counts": res["counts"],
                      "regressed": res["regressed"],
                      "worst": [v for v in res["verdicts"]
                                if v["verdict"] == "regressed"][:5]})
    except Exception as e:
        _append_perf({"stage": "watcher_regress", "after_stage": stage,
                      "window_id": window_id,
                      "error": f"{type(e).__name__}: {e}"[:200]})


def _pop_plan_line(path: str) -> "str | None":
    """Pop the first nonempty line of a plan file (test scripting).  The
    watcher runs fakes strictly one at a time, so read-modify-write is
    race-free."""
    try:
        with open(path) as f:
            lines = [l.strip() for l in f.read().splitlines()]
    except OSError:
        return None
    lines = [l for l in lines if l]
    if not lines:
        return None
    with open(path, "w") as f:
        f.write("\n".join(lines[1:]) + ("\n" if len(lines) > 1 else ""))
    return lines[0]


def _hang_with_grandchild() -> None:
    """Fork a grandchild and hang both — the supervisor's killpg must reap
    the whole tree.  Pids go to WATCHER_GRANDCHILD_PIDFILE so tests can
    assert neither survives.  Sleeps are finite (a failed kill must not
    leak a truly immortal process into CI)."""
    child = os.fork()
    if child == 0:
        time.sleep(120)
        os._exit(0)
    pidfile = os.environ.get("WATCHER_GRANDCHILD_PIDFILE")
    if pidfile:
        with open(pidfile, "w") as f:
            json.dump({"child": os.getpid(), "grandchild": child}, f)
    print("hanging", flush=True)
    time.sleep(120)


def _fake_probe() -> int:
    plan = os.environ.get("WATCHER_FAKE_PROBE_PLAN")
    behavior = _pop_plan_line(plan) if plan else None
    if behavior is None:
        mode = os.environ.get("WATCHER_FAKE_BACKEND", "ok")
        if mode == "flaky":
            # fail twice, succeed on every third probe (counter on disk —
            # each probe is a fresh subprocess)
            cnt_path = os.path.join(
                os.environ.get("WATCHER_STATE_DIR", "."), "fake_probe_count")
            try:
                with open(cnt_path) as f:
                    n = int(f.read().strip() or 0)
            except (OSError, ValueError):
                n = 0
            with open(cnt_path, "w") as f:
                f.write(str(n + 1))
            behavior = "ok" if (n + 1) % 3 == 0 else "fail"
        else:
            behavior = mode
    if behavior == "hang":
        _hang_with_grandchild()
        return 1
    if behavior == "ok":
        print("ndev=1")
        return 0
    print("ndev=0")
    return 1


def _arm_fake_flight(name: str):
    """Arm a flight recorder inside a fake stage when the supervisor
    exported ``LGBM_FLIGHT_DIR`` (run_stage's flight_dir seam).  Loads the
    stdlib-only obs package standalone — fake subprocesses must not import
    bench/numpy/jax.  flush_every=1 so even a SIGKILLed hang leaves its
    eager flush on disk."""
    if not os.environ.get("LGBM_FLIGHT_DIR"):
        return None
    try:
        import importlib.util
        pkg_dir = os.path.join(REPO, "lightgbm_tpu", "obs")
        spec = importlib.util.spec_from_file_location(
            "_watcher_fake_obs", os.path.join(pkg_dir, "__init__.py"),
            submodule_search_locations=[pkg_dir])
        mod = importlib.util.module_from_spec(spec)
        sys.modules["_watcher_fake_obs"] = mod
        spec.loader.exec_module(mod)
        rec = mod.flight.install(flush_every=1)
        rec.note("fake_stage_start", stage=name, pid=os.getpid())
        return rec
    except Exception:
        return None      # forensics must never break the fake itself


def _fake_stage(name: str) -> int:
    flight_rec = _arm_fake_flight(name)
    behavior = None
    plan = os.environ.get("WATCHER_FAKE_STAGE_PLAN")
    if plan:
        table = {}
        try:
            with open(plan) as f:
                table = json.load(f)
        except (OSError, ValueError):
            pass
        seq = table.get(name) or []
        if seq:
            behavior = seq.pop(0)
            with open(plan, "w") as f:
                json.dump(table, f)
    if behavior is None:
        behavior = "ok"
    if flight_rec is not None:
        flight_rec.note("fake_stage_behavior", stage=name,
                        behavior=behavior)
    if behavior == "hang":
        _hang_with_grandchild()
        return 1
    if behavior in ("crash", "fail"):
        return 1
    _append_perf({"stage": name, "fake": True})
    if name == "headline":
        # mimic bench.py's one-JSON-line contract so the parent's
        # extraction path is exercised end to end
        print(json.dumps({"metric": "higgs_1m_train_throughput",
                          "value": 1.0, "unit": "Mrow_iters/sec",
                          "vs_baseline": 0.0248, "detail": {"fake": True}}))
    return 0


if "--fake-probe" in sys.argv[1:2]:
    sys.exit(_fake_probe())
if "--fake-stage" in sys.argv[1:2]:
    sys.exit(_fake_stage(sys.argv[2]))


# --------------------------------------------------------------------------
# watcher proper
# --------------------------------------------------------------------------

import bench                                                    # noqa: E402

sup = bench._load_supervise()


def stage_table(args) -> list:
    """(name, argv, timeout_sec, env_overrides) in pipeline order.  Stages
    skip their own backend probe (the watcher just proved it live; a
    mid-stage re-wedge is caught by the stage's wall-clock budget)."""
    py = sys.executable
    fake = bool(os.environ.get("WATCHER_FAKE_BACKEND"))
    me = os.path.abspath(__file__)
    t = {"parity": args.stage_timeout or 1800,
         "perf_suite": args.stage_timeout or 7200,
         "onehot_shootout": args.stage_timeout or 3600,
         "headline": args.stage_timeout or 3600,
         "bench_serve": args.stage_timeout or 1800,
         "bench_stream": args.stage_timeout or 1800}
    if fake:
        return [(n, [py, me, "--fake-stage", n], t[n], {})
                for n in STAGE_NAMES]
    return [
        ("parity", [py, os.path.join(REPO, "scripts", "bench_dual.py")],
         t["parity"], {"BENCH_SKIP_PROBE": "1"}),
        ("perf_suite", [py, os.path.join(REPO, "scripts",
                                         "tpu_perf_suite.py")],
         t["perf_suite"], {"BENCH_SKIP_PROBE": "1"}),
        # the shootout sweeps every registry variant family at the bench
        # width AND max_bin=64 (exercising the lane-packing variant); the
        # flag mirrors the script default so the sweep is explicit in the
        # journal's argv without changing watcher_state.json semantics
        ("onehot_shootout", [py, os.path.join(REPO, "scripts",
                                              "bench_onehot_variants.py"),
                             "--max-bin", "255,64"],
         t["onehot_shootout"], {"BENCH_SKIP_PROBE": "1"}),
        ("headline", [py, os.path.join(REPO, "bench.py")],
         t["headline"], {"BENCH_SKIP_PROBE": "1"}),
        # serving p50/p99 + rows/s (docs/SERVING.md); the suite's OWN
        # bench_serve phase is skipped when the watcher drives it (below),
        # so a window prices serving exactly once
        ("bench_serve", [py, os.path.join(REPO, "scripts",
                                          "bench_serve.py")],
         t["bench_serve"], {"BENCH_SKIP_PROBE": "1"}),
        # out-of-core streaming rows/s + H2D-overlap efficiency
        # (docs/STREAMING.md): on hardware the overlap numbers become the
        # real double-buffering measurement; the suite's own bench_stream
        # phase is skipped when the watcher drives it (below)
        ("bench_stream", [py, os.path.join(REPO, "scripts",
                                           "bench_stream.py"), "--quick"],
         t["bench_stream"], {"BENCH_SKIP_PROBE": "1"}),
    ]


def probe(args, hb) -> bool:
    argv = None
    if os.environ.get("WATCHER_FAKE_BACKEND"):
        argv = [sys.executable, os.path.abspath(__file__), "--fake-probe"]
    t0 = time.monotonic()
    live = bench.probe_backend(args.probe_timeout, argv=argv)
    hb("probe", live=bool(live), secs=round(time.monotonic() - t0, 3))
    return bool(live)


# ---- journal --------------------------------------------------------------

def fresh_stages() -> list:
    return [{"name": n, "status": "pending"} for n in STAGE_NAMES]


def fresh_journal() -> dict:
    return {"version": JOURNAL_VERSION, "state": "poll", "window_id": 1,
            "probe_failures": 0, "window_failures": 0, "polls": 0,
            "windows_captured": 0, "stages": fresh_stages()}


def load_journal(path: str) -> dict:
    j = sup.read_json(path, default=None)
    if not isinstance(j, dict) or j.get("version") != JOURNAL_VERSION:
        j = fresh_journal()
    # reconcile against the current stage table: renames/additions get a
    # pending entry, vanished stages are dropped, order is canonical
    by_name = {s.get("name"): s for s in j.get("stages", [])}
    j["stages"] = [by_name.get(n, {"name": n, "status": "pending"})
                   for n in STAGE_NAMES]
    # a stage left "running" means the WATCHER died mid-stage: incomplete
    for s in j["stages"]:
        if s.get("status") == "running":
            s["status"] = "interrupted"
    return j


def save_journal(path: str, j: dict) -> None:
    j["updated"] = round(time.time(), 3)
    sup.write_json_atomic(path, j)


def incomplete(j: dict) -> list:
    """Stages still owed to the CURRENT window (resume set): everything not
    terminally ok/failed."""
    return [s for s in j["stages"] if s["status"] not in ("ok", "failed")]


# ---- pipeline -------------------------------------------------------------

def run_pipeline(args, j: dict, hb) -> str:
    """Run every incomplete stage in order; returns "complete" (all stages
    terminal) or "wedged" (backend died mid-window; journal holds the
    resume point)."""
    table = stage_table(args)
    for name, argv, timeout, env_over in table:
        ent = next(s for s in j["stages"] if s["name"] == name)
        if ent["status"] in ("ok", "failed"):
            continue
        resumed = ent["status"] == "interrupted"
        ent["status"] = "running"
        save_journal(args.journal, j)
        env = dict(os.environ)
        env["WATCHER_PERF_LOG"] = _perf_log_path()
        if args.health_port:
            # stages run strictly one at a time, so a single port serves
            # whichever stage is live; each stage's loops call
            # obs.health.maybe_start off this env var
            env["LGBM_OBS_HEALTH_PORT"] = str(args.health_port)
        env.update(env_over)
        parity_ok = next(s for s in j["stages"]
                         if s["name"] == "parity")["status"] == "ok"
        if name == "perf_suite":
            if resumed:
                # a suite killed mid-phase left suite_phase_done markers
                # in perf_results.jsonl; let it skip what already landed
                env["TPU_SUITE_RESUME"] = "1"
            # the watcher has its OWN bench_serve/bench_stream stages (last
            # in the pipeline): skip the suite's copies so a window prices
            # each exactly once — unlike the parity skip this is
            # unconditional, because the watcher's stages run regardless of
            # the suite's outcome
            env["TPU_SUITE_SKIP_PHASES"] = ",".join(filter(None, [
                env.get("TPU_SUITE_SKIP_PHASES", ""), "bench_serve",
                "bench_stream"]))
            if parity_ok:
                # the watcher's parity stage IS bench_dual: don't burn
                # window time re-running the same checks in the suite's
                # parity phase.  But ONLY when our parity actually passed
                # — on a parity failure the suite must keep its own
                # "abort before recording numbers off a wrong kernel"
                # invariant.  (The suite's internal headline stays: it is
                # the grow_sweep-tuned measurement, distinct from the
                # watcher's default-knob headline stage.)
                env["TPU_SUITE_SKIP_PHASES"] = ",".join(filter(None, [
                    env.get("TPU_SUITE_SKIP_PHASES", ""), "parity"]))
        res = sup.run_stage(name, argv, timeout=timeout,
                            retries=args.stage_retries,
                            backoff=args.stage_backoff,
                            heartbeat=hb, env=env, cwd=REPO,
                            # crashed/hung stages leave their flight
                            # recorder dumps beside the journal
                            flight_dir=args.state_dir)
        ent["detail"] = {**res.to_record(), "window_id": j["window_id"],
                         **({"resumed": True} if resumed else {}),
                         # numbers recorded after a parity failure are
                         # suspect: say so ON the record, not just in the
                         # window summary
                         **({} if parity_ok or name == "parity"
                            else {"parity_failed": True})}
        if res.ok:
            ent["status"] = "ok"
            rec = {**ent["detail"], "stage": f"watcher_{name}"}
            if name == "headline":
                payload = sup.extract_json_line(res.output_tail)
                if payload:
                    rec["result"] = payload
            _append_perf(rec)
            _append_regress_verdict(name, j["window_id"])
            save_journal(args.journal, j)
            continue
        # crash or hang: distinguish "this stage is broken" from "the
        # whole window re-wedged" by re-probing the backend
        if probe(args, hb):
            ent["status"] = "failed"
            _append_perf({**ent["detail"], "stage": f"watcher_{name}",
                          "output_tail": res.output_tail[-500:]})
            hb("stage_degraded", stage=name, status=res.status)
            save_journal(args.journal, j)
            continue
        ent["status"] = "interrupted"
        _append_perf({"stage": "watcher_rewedge", "during": name,
                      "window_id": j["window_id"]})
        hb("rewedge", during=name)
        j["state"] = "poll"
        j["probe_failures"] = 1
        save_journal(args.journal, j)
        return "wedged"
    return "complete"


def finish_window(args, j: dict, hb) -> None:
    """Close out a window whose stages are all terminal.  A window where
    NOTHING succeeded is not a capture: a persistent stage defect on a
    live backend (e.g. an import error crashing every stage in seconds)
    must not let the daemon report success and stop polling — it retries
    from scratch on the poll cadence, with backoff, leaving a
    ``captured: false`` trail."""
    statuses = {s["name"]: s["status"] for s in j["stages"]}
    captured = any(v == "ok" for v in statuses.values())
    _append_perf({"stage": "watcher_window", "window_id": j["window_id"],
                  "stages": statuses, "captured": captured})
    # per-window observability artifact: render the perf journal through
    # obs.report (jax-free loader — this process must never touch the
    # backend) into a markdown digest beside the log.  Never fatal: a
    # render bug must not cost the captured window.
    try:
        report = bench.load_obs().report
        loaded = report.load_perf_log(_perf_log_path())
        md = report.render_markdown(report.summarize(loaded))
        art_path = os.path.join(
            os.path.dirname(os.path.abspath(_perf_log_path())),
            f"obs_report_window_{j['window_id']}.md")
        with open(art_path, "w") as f:
            f.write(md)
        _append_perf({"stage": "watcher_obs_report",
                      "window_id": j["window_id"], "path": art_path,
                      "events": loaded["total"], "bad": loaded["bad"]})
    except Exception as e:
        _append_perf({"stage": "watcher_obs_report",
                      "window_id": j["window_id"],
                      "error": f"{type(e).__name__}: {e}"[:300]})
    hb("window_complete", window_id=j["window_id"], stages=statuses,
       captured=captured)
    if captured:
        j["windows_captured"] += 1
        j["window_failures"] = 0
    else:
        # its own backoff counter (probe_failures is reset by every live
        # probe, so it cannot carry this): the backend is live but the
        # pipeline is broken — a hot retry loop would burn the window
        j["window_failures"] = j.get("window_failures", 0) + 1
    if not captured or j["windows_captured"] < args.max_windows:
        j["window_id"] += 1
        j["stages"] = fresh_stages()
        j["state"] = "poll"
    else:
        j["state"] = "done"
    save_journal(args.journal, j)
    return captured


def poll_delay(args, failures: int, rng: random.Random) -> float:
    """Backoff the POLL cadence on consecutive dead probes: base interval
    doubling per failure (after the first) up to ``--poll-cap``, jittered
    ±25% so restarted watchers don't synchronize against the tunnel."""
    d = min(args.poll_cap,
            args.poll_interval * (2.0 ** min(max(failures - 1, 0), 16)))
    return d * (1.0 + 0.25 * (2.0 * rng.random() - 1.0))


def watch(args, hb) -> int:
    rng = random.Random()
    j = load_journal(args.journal)
    if j["state"] == "done":
        # ANY finished journal restarts fresh (rerun later for another
        # window — including with a raised --max-windows: the old all-ok
        # stages must not skip straight to a phantom 'captured' window)
        j = fresh_journal()
    polls = 0          # consecutive polls WITHOUT a capture (exit-3 gauge)
    while True:
        live = probe(args, hb)
        polls += 1
        j["polls"] = j.get("polls", 0) + 1
        if live:
            j["probe_failures"] = 0
            j["state"] = "pipeline"
            save_journal(args.journal, j)
            hb("window_open", window_id=j["window_id"],
               resume=[s["name"] for s in incomplete(j)])
            if run_pipeline(args, j, hb) == "complete":
                if finish_window(args, j, hb):
                    polls = 0          # captured: the give-up clock restarts
                if j["state"] == "done":
                    return 0
        else:
            j["probe_failures"] = j.get("probe_failures", 0) + 1
            j["state"] = "poll"
            save_journal(args.journal, j)
        if args.once:
            return 0
        if args.max_polls and polls >= args.max_polls:
            hb("give_up", polls=polls)
            return 3
        # either trouble source backs the cadence off: dead probes, or
        # live-but-broken pipelines (window_failures)
        failures = j["probe_failures"] + j.get("window_failures", 0)
        d = poll_delay(args, failures, rng)
        hb("sleep", delay_sec=round(d, 3), probe_failures=j["probe_failures"],
           window_failures=j.get("window_failures", 0))
        time.sleep(d)


def parse_args(argv=None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(
        description="Unattended TPU-window perf-capture watcher")
    ap.add_argument("--state-dir",
                    default=os.environ.get("WATCHER_STATE_DIR", REPO),
                    help="directory for journal/lock/heartbeat files")
    ap.add_argument("--poll-interval", type=float,
                    default=float(os.environ.get("WATCHER_POLL_INTERVAL",
                                                 600)),
                    help="seconds between backend probes (default 600)")
    ap.add_argument("--poll-cap", type=float,
                    default=float(os.environ.get("WATCHER_POLL_CAP", 3600)),
                    help="max backed-off poll interval (default 3600)")
    ap.add_argument("--probe-timeout", type=float,
                    default=float(os.environ.get("WATCHER_PROBE_TIMEOUT",
                                                 300)))
    ap.add_argument("--stage-timeout", type=float,
                    default=float(os.environ.get("WATCHER_STAGE_TIMEOUT", 0))
                    or None,
                    help="override EVERY stage's wall-clock budget (tests)")
    ap.add_argument("--stage-retries", type=int,
                    default=int(os.environ.get("WATCHER_STAGE_RETRIES", 0)))
    ap.add_argument("--stage-backoff", type=float,
                    default=float(os.environ.get("WATCHER_STAGE_BACKOFF", 5)))
    ap.add_argument("--max-windows", type=int, default=1,
                    help="exit 0 after this many captured windows")
    ap.add_argument("--max-polls", type=int, default=0,
                    help="exit 3 after this many polls without capture "
                         "(0 = poll forever)")
    ap.add_argument("--once", action="store_true",
                    help="one poll step (and pipeline, if live) then exit")
    ap.add_argument("--health-port", type=int,
                    default=int(os.environ.get("WATCHER_HEALTH_PORT", 0)),
                    help="export LGBM_OBS_HEALTH_PORT to stages so the "
                         "live stage serves /metrics //healthz here "
                         "(0 = off)")
    args = ap.parse_args(argv)
    os.makedirs(args.state_dir, exist_ok=True)
    args.journal = os.path.join(args.state_dir, "watcher_state.json")
    args.lock = os.path.join(args.state_dir, "watcher.lock")
    args.heartbeat = os.path.join(args.state_dir, "watcher_heartbeat.jsonl")
    os.environ["WATCHER_STATE_DIR"] = args.state_dir
    return args


def main(argv=None) -> int:
    args = parse_args(argv)
    hb = sup.Heartbeat(args.heartbeat)
    lock = sup.SingleOwnerLock(args.lock)
    try:
        lock.acquire()
    except sup.LockHeldError as e:
        print(f"tpu_window_watcher: {e}", file=sys.stderr)
        return 2
    hb("start", argv=sys.argv,
       fake=os.environ.get("WATCHER_FAKE_BACKEND", ""))
    try:
        return watch(args, hb)
    finally:
        hb("stop")
        lock.release()


if __name__ == "__main__":
    sys.exit(main())
