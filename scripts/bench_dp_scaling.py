"""Data-parallel scaling curve on the virtual CPU mesh.

The virtual mesh shares one host's cores, so this measures the COMM/compute
structure (and that more shards do not regress the program), not real ICI
speedup — the reference's real-cluster curve is its Criteo 1->16-machine
table (``docs/Experiments.rst:231-239``); ours on real chips awaits a
multi-chip window.

Per split, the data-parallel learner moves one histogram reduction:
``psum_scatter`` leaves each shard owning F*B/ndev bins of [grad,hess,count]
f32, i.e. bytes_on_wire ~= F*B*3*4*(ndev-1)/ndev per shard (ring), vs the
reference's Reduce-Scatter over the same F*B*3 payload
(``src/treelearner/data_parallel_tree_learner.cpp:155-173``) — identical
asymptotic volume; XLA owns the schedule.

usage: python scripts/bench_dp_scaling.py [rows] [features] [leaves]
Appends one JSON line per shard count to perf_results.jsonl.
"""
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import numpy as np   # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

rows = int(sys.argv[1]) if len(sys.argv) > 1 else 200_000
feats = int(sys.argv[2]) if len(sys.argv) > 2 else 28
leaves = int(sys.argv[3]) if len(sys.argv) > 3 else 63
max_bin = 255

sys.path.insert(0, REPO)
from bench import load_obs   # noqa: E402

# the single perf-journal writer (obs.events): honors WATCHER_PERF_LOG,
# which the bare perf_results.jsonl path here previously ignored
LOG = load_obs().EventLog.default(echo=True)

import lightgbm_tpu as lgb   # noqa: E402

rng = np.random.default_rng(0)
X = rng.normal(size=(rows, feats)).astype(np.float32)
y = (X[:, 0] + X[:, 1] * X[:, 2] + rng.logistic(size=rows) > 0).astype(np.float32)

results = []
for ndev in (1, 2, 4, 8):
    params = {"objective": "binary", "num_leaves": leaves, "verbose": -1,
              "max_bin": max_bin,
              "tree_learner": "data" if ndev > 1 else "serial",
              "mesh_shape": [ndev] if ndev > 1 else None,
              "min_data_in_leaf": 50}
    train = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params=params, train_set=train)
    bst.update()                                # compile
    bst._gbdt._train_score.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(5):
        bst.update()
    bst._gbdt._train_score.block_until_ready()
    dt = (time.perf_counter() - t0) / 5
    # per-shard wire bytes for ONE histogram reduce at this width (ring)
    wire_mb = feats * max_bin * 3 * 4 * (ndev - 1) / ndev / 1e6
    results.append({"shards": ndev, "ms_per_tree": round(dt * 1e3, 1),
                    "reduce_mb_per_split_per_shard": round(wire_mb, 3)})
    print(f"shards={ndev}:  {dt*1e3:8.1f} ms/tree   "
          f"(~{wire_mb:.2f} MB/shard on the wire per split reduce)")

print("recorded -> perf journal")
# one-JSON-line contract (previously violated here: the last line was
# prose): summary() appends to the journal AND prints the schema-stamped
# record as the LAST stdout line
LOG.summary(bench="dp_scaling_virtual_mesh", rows=rows, features=feats,
            leaves=leaves, max_bin=max_bin, host_cores=os.cpu_count(),
            results=results)
