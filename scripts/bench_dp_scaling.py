"""Data-parallel scaling curve on the virtual CPU mesh.

The virtual mesh shares one host's cores, so this measures the COMM/compute
structure (and that more shards do not regress the program), not real ICI
speedup — the reference's real-cluster curve is BASELINE.md's Criteo table.

usage: python scripts/bench_dp_scaling.py [rows] [features] [leaves]
"""
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import numpy as np   # noqa: E402

rows = int(sys.argv[1]) if len(sys.argv) > 1 else 200_000
feats = int(sys.argv[2]) if len(sys.argv) > 2 else 28
leaves = int(sys.argv[3]) if len(sys.argv) > 3 else 63

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import lightgbm_tpu as lgb   # noqa: E402

rng = np.random.default_rng(0)
X = rng.normal(size=(rows, feats)).astype(np.float32)
y = (X[:, 0] + X[:, 1] * X[:, 2] + rng.logistic(size=rows) > 0).astype(np.float32)

for ndev in (1, 2, 4, 8):
    params = {"objective": "binary", "num_leaves": leaves, "verbose": -1,
              "tree_learner": "data" if ndev > 1 else "serial",
              "mesh_shape": [ndev] if ndev > 1 else None,
              "min_data_in_leaf": 50}
    train = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params=params, train_set=train)
    bst.update()                                # compile
    bst._gbdt._train_score.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(5):
        bst.update()
    bst._gbdt._train_score.block_until_ready()
    dt = (time.perf_counter() - t0) / 5
    print(f"shards={ndev}:  {dt*1e3:8.1f} ms/tree")
