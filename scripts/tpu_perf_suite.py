"""One-shot TPU perf diagnosis: sanity → kernel micro → headline bench.

The axon tunnel can wedge for hours (see README round-3 notes); when a
recovery window appears, this packs the whole perf story into ONE process
so nothing is wasted: (1) device sanity, (2) Pallas-vs-onehot histogram
microbench at the bench shape, (3) grow_tree isolation, (4) the headline
bench. Results append to ``perf_results.jsonl`` as they land, so a
mid-run re-wedge still leaves everything completed so far on disk.

Run (ONLY process touching the TPU):
    python scripts/tpu_perf_suite.py [rows]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "perf_results.jsonl")
ROWS = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000


def emit(**kv):
    kv["ts"] = time.time()
    with open(OUT, "a") as f:
        f.write(json.dumps(kv) + "\n")
    print(json.dumps(kv), flush=True)


def main():
    # wedge-safe: prove the backend live in a TIMEOUT-GUARDED subprocess
    # before this process commits to it (a wedged tunnel hangs forever)
    import bench
    if "axon" in os.environ.get("JAX_PLATFORMS", "axon") \
            and not bench.probe_backend(
                float(os.environ.get("BENCH_PROBE_TIMEOUT", 300))):
        emit(stage="abort", reason="tpu_unreachable")
        return 1

    import jax
    import jax.numpy as jnp
    import numpy as np

    t0 = time.perf_counter()
    x = jnp.ones((512, 512))
    (x @ x).block_until_ready()
    emit(stage="sanity", backend=jax.default_backend(),
         secs=round(time.perf_counter() - t0, 2))

    # --- kernel parity FIRST (the r02 lowering crash was only visible on
    # hardware): both one-hot layouts + the frontier batched-leaf kernel +
    # grower dual.  A parity failure aborts before any perf number could be
    # recorded off a wrong kernel.
    if jax.default_backend() == "tpu":
        import bench_dual

        def emit_dual(**kv):
            emit(stage="dual_" + kv.pop("stage", "?"), **kv)
        if bench_dual.run_checks(emit_dual) != 0:
            emit(stage="abort", reason="kernel_parity_failed")
            return 1

    # --- histogram kernels at the bench shape ---------------------------
    from lightgbm_tpu.ops.histogram import _hist_onehot, _hist_pallas
    rng = np.random.default_rng(0)
    N, F, B = ROWS, 28, 255
    bins = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(np.full(N, 0.25, np.float32))
    m = jnp.ones(N, jnp.float32)

    def timed_jfn(jfn, mk_args, iters=10):
        """Warm once, then average ``iters`` timed calls; ``mk_args(eps)``
        builds the call args with a gradient cache-buster perturbation."""
        float(jfn(*mk_args(0.0)))
        t = time.perf_counter()
        for _ in range(iters):
            float(jfn(*mk_args(1e-12)))
        return (time.perf_counter() - t) / iters

    def timed(fn, iters=10):
        jfn = jax.jit(lambda b_, g_: jnp.sum(fn(b_, g_, h, m, B)))
        return timed_jfn(jfn, lambda eps: (bins, g + eps), iters)

    if jax.default_backend() == "tpu":
        try:
            t_pallas = timed(_hist_pallas)
            Bp = -(-B // 128) * 128
            peak = bench._PEAK_BF16_FLOPS.get(
                jax.devices()[0].device_kind.lower(), 197e12)
            emit(stage="hist_pallas", ms=round(t_pallas * 1e3, 3),
                 grows_per_sec=round(N / t_pallas / 1e9, 3),
                 mfu=round(2.0 * 6 * N * F * Bp / t_pallas / peak, 4))
        except Exception as e:        # lowering failure must be visible
            emit(stage="hist_pallas", error=str(e)[:300])
        # batched-leaf kernel at the frontier shape: same rows split over
        # 16 slots in 512-row blocks (the per-round frontier workload)
        try:
            from lightgbm_tpu.ops.histogram import _hist_leaves_pallas
            BRL, KSL = 512, 16
            nbl = N // BRL
            bl = jnp.asarray((np.arange(nbl) * KSL // nbl).astype(np.int32))
            # slice ONCE outside the timed loop so the number is comparable
            # to hist_pallas (a per-call 28MB device copy would skew it)
            bins_l, g_l = bins[:nbl * BRL], g[:nbl * BRL]
            h_l, m_l = h[:nbl * BRL], m[:nbl * BRL]
            jfn = jax.jit(lambda b_, g_: jnp.sum(_hist_leaves_pallas(
                b_, g_, h_l, m_l, bl, KSL, B, BRL, F)))
            t_leaves = timed_jfn(jfn, lambda eps: (bins_l, g_l + eps))
            emit(stage="hist_leaves_pallas", ms=round(t_leaves * 1e3, 3),
                 slots=KSL, block_rows=BRL)
        except Exception as e:
            emit(stage="hist_leaves_pallas", error=str(e)[:300])
    t_onehot = timed(lambda b_, g_, h_, m_, B_: _hist_onehot(
        b_, g_, h_, m_, B_, 65536))
    emit(stage="hist_onehot", ms=round(t_onehot * 1e3, 3))

    # --- grow_tree isolation at bench shape (255 leaves) ----------------
    from lightgbm_tpu.ops.grower import GrowerConfig, grow_tree
    from lightgbm_tpu.ops.split import SplitParams
    sp = SplitParams(lambda_l1=0.0, lambda_l2=0.0, min_data_in_leaf=100,
                     min_sum_hessian_in_leaf=100.0, min_gain_to_split=0.0,
                     max_delta_step=0.0, path_smooth=0.0, cat_smooth=10.0,
                     cat_l2=10.0, max_cat_to_onehot=4)
    hist_method = "pallas" if jax.default_backend() == "tpu" else "onehot"
    cfg = GrowerConfig(num_leaves=255, max_depth=-1, max_bin=256, split=sp,
                       feature_fraction_bynode=1.0, hist_method=hist_method,
                       hist_chunk_rows=65536, sorted_cat=False)
    meta = dict(num_bins=jnp.full(F, 256, jnp.int32),
                default_bins=jnp.zeros(F, jnp.int32),
                nan_bins=jnp.full(F, -1, jnp.int32),
                is_categorical=jnp.zeros(F, bool),
                monotone=jnp.zeros(F, jnp.int32))
    rw = jnp.ones(N, jnp.float32)
    fm = jnp.ones(F, jnp.float32)
    key = jax.random.PRNGKey(0)

    def time_grow(cfg_m, tag, iters):
        grow = jax.jit(lambda b_, g_, h_, rw_, fm_, k_, c=cfg_m: grow_tree(
            b_, g_, h_, rw_, fm_, **meta, key=k_, cfg=c))
        t = time.perf_counter()
        tree, _ = grow(bins, g, h, rw, fm, key)
        tree.leaf_value.block_until_ready()
        emit(stage=f"grow_{tag}_compile_plus_first",
             secs=round(time.perf_counter() - t, 1))
        t = time.perf_counter()
        for _ in range(iters):
            tree, _ = grow(bins, g + 1e-12, h, rw, fm, key)
        tree.leaf_value.block_until_ready()
        ms = (time.perf_counter() - t) / iters * 1e3
        emit(stage=f"grow_{tag}_steady", ms_per_tree=round(ms, 1))
        return ms

    best = (None, float("inf"))
    # frontier_k sweep: the batch width trades per-round fixed cost against
    # block-padding waste — pick the winner for the headline bench
    for fk, br in ((32, 512), (16, 512), (64, 512), (32, 1024)):
        cfg_m = cfg._replace(grower_mode="frontier", frontier_k=fk,
                             frontier_block_rows=br)
        ms = time_grow(cfg_m, f"frontier_k{fk}_br{br}", iters=4)
        if ms < best[1]:
            best = ((fk, br), ms)
    emit(stage="frontier_best", k=best[0][0], block_rows=best[0][1],
         ms_per_tree=round(best[1], 1))
    time_grow(cfg._replace(grower_mode="serial"), "serial", iters=2)
    # merge the sweep winner UNDER any user-provided knobs (theirs win)
    os.environ["BENCH_PARAMS_EXTRA"] = json.dumps(
        {"frontier_k": best[0][0], "frontier_block_rows": best[0][1],
         **json.loads(os.environ.get("BENCH_PARAMS_EXTRA", "{}"))})

    # --- headline bench (in-process, same params as bench.py) ----------
    # one coherent shape for the whole story (a leftover BENCH_ROWS env
    # var must not decouple the headline from the micro stages); probe
    # already done above
    os.environ["BENCH_ROWS"] = str(ROWS)
    os.environ["BENCH_SKIP_PROBE"] = "1"
    import contextlib, io
    import bench

    def run_headline(tag):
        buf = io.StringIO()
        try:
            with contextlib.redirect_stdout(buf):
                bench.main()
        except SystemExit:
            pass          # auc-floor exit: the JSON line is already in buf
        except Exception as e:
            # a 10.5M OOM/lowering failure must still leave a record —
            # the suite's contract is append-as-they-land
            emit(stage=tag, error=f"{type(e).__name__}: {e}"[:300])
            return
        line = [l for l in buf.getvalue().splitlines() if l.startswith("{")]
        emit(stage=tag,
             **(json.loads(line[-1]) if line else
                {"error": buf.getvalue()[-300:]}))

    run_headline("headline_bench")

    # --- real-Higgs scale: one 10.5M-row single-chip run (VERDICT r4
    # item 4; ~0.3 GB of bins) with the device-memory high-water in the
    # detail.  TPU-only and opt-out-able: on a slow backend it would burn
    # the window.
    if (jax.default_backend() == "tpu"
            and not os.environ.get("TPU_SUITE_SKIP_BIG")):
        os.environ["BENCH_ROWS"] = "10500000"
        run_headline("headline_bench_10p5M")


if __name__ == "__main__":
    sys.exit(main())
