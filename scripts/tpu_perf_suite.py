"""One-shot TPU perf diagnosis: sanity → kernel micro → headline bench.

The axon tunnel can wedge for hours (see README round-3 notes); when a
recovery window appears, this packs the whole perf story into ONE process
so nothing is wasted.  The suite is a sequence of NAMED PHASES —

    sanity → parity → hist_micro → grow_sweep → headline → bench_serve
    → bench_stream → headline_big

— each wrapped so a crash records an error and degrades to the next phase
(parity is the exception: a wrong kernel must abort before any perf number
is recorded off it).  Results append to ``perf_results.jsonl`` as they
land, bracketed by resumable markers: ``suite_start`` at entry and one
``suite_phase_done`` per completed phase, so a mid-run re-wedge leaves an
exact record of what is still owed.

Resume knobs (used by scripts/tpu_window_watcher.py and by hand):
  TPU_SUITE_RESUME=1        skip phases with a ``suite_phase_done`` marker
                            (same row count) since the last ``suite_start``
  TPU_SUITE_SKIP_PHASES=a,b explicit skip list (wins over resume)
  TPU_SUITE_ONLY_PHASES=a,b run only these phases
  TPU_SUITE_SKIP_BIG=1      legacy alias for skipping ``headline_big``

The 10.5M-row headline runs in its OWN subprocess under a wall-clock
budget (``supervise.run_stage``): an OOM, lowering hang, or wedge there
must not take down the phases already captured.

Run (ONLY process touching the TPU):
    python scripts/tpu_perf_suite.py [rows]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import load_obs  # noqa: E402

# the watcher points every stage at one results file (WATCHER_PERF_LOG);
# obs.events owns that resolution now — one writer for every bench.
# Loaded WITHOUT lightgbm_tpu/jax: the suite supervises subprocesses and
# must never touch a possibly-wedged backend itself.
OBS = load_obs()
LOG = OBS.EventLog.default(echo=True)
# achieved/peak math: obs.costs is the ONE peak table + MFU formula
# (tests/test_obs.py greps the tree to keep peak constants out of here)
COSTS = OBS.costs
OUT = LOG.path
ROWS = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000

PHASES = ("sanity", "parity", "hist_micro", "grow_sweep",
          "headline", "bench_serve", "bench_stream", "headline_big",
          "regress")


def emit(**kv):
    LOG.emit(kv.pop("stage", "suite_record"), **kv)


class SuiteAbort(RuntimeError):
    """Raised by a phase whose failure poisons everything downstream."""


def _completed_phases_since_last_start():
    """(done, saved): phase names with a ``suite_phase_done`` marker (same
    row count) since the most recent ``suite_start`` — the resume set —
    plus any side state a completed phase recorded into its marker (the
    grow_sweep tuning).  ``resumed_done`` on a suite_start seeds ``done``
    so a SECOND re-wedge still remembers phases captured two runs ago
    (deliberate user skips are NOT in that field: a phase skipped by
    TPU_SUITE_ONLY_PHASES never ran and must not count as landed)."""
    done, saved = set(), {}
    try:
        with open(OUT) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("rows") != ROWS:
                    continue
                if rec.get("stage") == "suite_start":
                    done = set(rec.get("resumed_done") or [])
                elif rec.get("stage") == "suite_end":
                    # that run finished: nothing to resume
                    done, saved = set(), {}
                elif rec.get("stage") == "suite_phase_done":
                    done.add(rec.get("phase"))
                    if rec.get("bench_params_extra") is not None:
                        saved["bench_params_extra"] = \
                            rec["bench_params_extra"]
    except OSError:
        pass
    return done, saved


def _phases_to_skip(resume_done: set) -> set:
    skip = set(resume_done)
    if os.environ.get("TPU_SUITE_SKIP_PHASES"):
        skip |= {p.strip() for p in
                 os.environ["TPU_SUITE_SKIP_PHASES"].split(",") if p.strip()}
    if os.environ.get("TPU_SUITE_SKIP_BIG"):
        skip.add("headline_big")
    only = os.environ.get("TPU_SUITE_ONLY_PHASES")
    if only:
        keep = {p.strip() for p in only.split(",") if p.strip()}
        skip |= set(PHASES) - keep
    return skip


# --------------------------------------------------------------------------
# phases (each takes the shared mutable context dict)
# --------------------------------------------------------------------------

def phase_sanity(ctx):
    import jax
    import jax.numpy as jnp
    t0 = time.perf_counter()
    x = jnp.ones((512, 512))
    (x @ x).block_until_ready()
    emit(stage="sanity", backend=jax.default_backend(),
         secs=round(time.perf_counter() - t0, 2))


def phase_parity(ctx):
    # kernel parity FIRST (the r02 lowering crash was only visible on
    # hardware): both one-hot layouts + the frontier batched-leaf kernel +
    # grower dual.  A parity failure aborts before any perf number could
    # be recorded off a wrong kernel.
    import jax
    if jax.default_backend() != "tpu":
        emit(stage="dual_skip", reason="cpu backend")
        return
    import bench_dual

    def emit_dual(**kv):
        emit(stage="dual_" + kv.pop("stage", "?"), **kv)
    if bench_dual.run_checks(emit_dual) != 0:
        raise SuiteAbort("kernel_parity_failed")


def phase_hist_micro(ctx):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import bench
    from lightgbm_tpu.ops.histogram import _hist_onehot, _hist_pallas
    rng = np.random.default_rng(0)
    N, F, B = ROWS, 28, 255
    bins = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(np.full(N, 0.25, np.float32))
    m = jnp.ones(N, jnp.float32)
    ctx.update(bins=bins, g=g, h=h, m=m, N=N, F=F, B=B)

    def timed_jfn(jfn, mk_args, iters=10):
        """Warm once, then average ``iters`` timed calls; ``mk_args(eps)``
        builds the call args with a gradient cache-buster perturbation."""
        float(jfn(*mk_args(0.0)))
        t = time.perf_counter()
        for _ in range(iters):
            float(jfn(*mk_args(1e-12)))
        return (time.perf_counter() - t) / iters

    def timed(fn, iters=10):
        jfn = jax.jit(lambda b_, g_: jnp.sum(fn(b_, g_, h, m, B)))
        return timed_jfn(jfn, lambda eps: (bins, g + eps), iters)

    if jax.default_backend() == "tpu":
        chip = COSTS.current_chip()
        try:
            t_pallas = timed(_hist_pallas)
            Bp = -(-B // 128) * 128
            emit(stage="hist_pallas", ms=round(t_pallas * 1e3, 3),
                 grows_per_sec=round(N / t_pallas / 1e9, 3),
                 mfu=round(COSTS.mfu(2.0 * 6 * N * F * Bp, t_pallas,
                                     chip), 4),
                 chip=chip)
        except Exception as e:        # lowering failure must be visible
            emit(stage="hist_pallas", error=str(e)[:300])
        # production-kernel variant sweep from the SHARED registry
        # (ops/onehot_variants.py) at the bench width AND max_bin=64 (the
        # lane-packing width): these numbers price exactly what
        # hist_variant=<name> would ship, because _hist_pallas and the
        # shootout run the same registry bodies.  The full (variant, BR)
        # grid lives in scripts/bench_onehot_variants.py (the watcher's
        # onehot_shootout stage sweeps --max-bin the same way).
        from lightgbm_tpu.ops import onehot_variants as ov
        rng_v = np.random.default_rng(1)
        for vb in (B, 64):
            vbins = bins if vb == B else jnp.asarray(
                rng_v.integers(0, vb, size=(N, F), dtype=np.uint8))
            for vname in ov.AUTO_CANDIDATES:
                if not ov.VARIANTS[vname].supports(vb):
                    continue
                try:
                    jv = jax.jit(lambda b_, g_, v=vname, bb=vb: jnp.sum(
                        _hist_pallas(b_, g_, h, m, bb, variant=v)))
                    t_v = timed_jfn(jv, lambda eps: (vbins, g + eps))
                    lanes = ov.total_lanes(vname, F, vb)
                    emit(stage="hist_pallas_variant", variant=vname,
                         max_bin=vb, ms=round(t_v * 1e3, 3),
                         mxu_lanes=lanes,
                         mfu=round(COSTS.mfu(2.0 * 6 * N * lanes, t_v,
                                             chip), 4),
                         # the VPU-work-model bound next to the achieved
                         # figure prices each variant's remaining headroom
                         predicted_mfu=round(
                             ov.predicted_mfu(vname, F, vb), 4))
                except Exception as e:
                    emit(stage="hist_pallas_variant", variant=vname,
                         max_bin=vb, error=str(e)[:250])
        # batched-leaf kernel at the frontier shape: same rows split over
        # 16 slots in 512-row blocks (the per-round frontier workload)
        try:
            from lightgbm_tpu.ops.histogram import _hist_leaves_pallas
            BRL, KSL = 512, 16
            nbl = N // BRL
            bl = jnp.asarray((np.arange(nbl) * KSL // nbl).astype(np.int32))
            # slice ONCE outside the timed loop so the number is comparable
            # to hist_pallas (a per-call 28MB device copy would skew it)
            bins_l, g_l = bins[:nbl * BRL], g[:nbl * BRL]
            h_l, m_l = h[:nbl * BRL], m[:nbl * BRL]
            jfn = jax.jit(lambda b_, g_: jnp.sum(_hist_leaves_pallas(
                b_, g_, h_l, m_l, bl, KSL, B, BRL, F)))
            t_leaves = timed_jfn(jfn, lambda eps: (bins_l, g_l + eps))
            emit(stage="hist_leaves_pallas", ms=round(t_leaves * 1e3, 3),
                 slots=KSL, block_rows=BRL)
        except Exception as e:
            emit(stage="hist_leaves_pallas", error=str(e)[:300])
    t_onehot = timed(lambda b_, g_, h_, m_, B_: _hist_onehot(
        b_, g_, h_, m_, B_, 65536))
    emit(stage="hist_onehot", ms=round(t_onehot * 1e3, 3))


def phase_grow_sweep(ctx):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from lightgbm_tpu.ops.grower import GrowerConfig, grow_tree
    from lightgbm_tpu.ops.split import SplitParams
    if "bins" not in ctx:             # hist_micro skipped: rebuild inputs
        rng = np.random.default_rng(0)
        N, F, B = ROWS, 28, 255
        ctx.update(
            bins=jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8)),
            g=jnp.asarray(rng.normal(size=N).astype(np.float32)),
            h=jnp.asarray(np.full(N, 0.25, np.float32)),
            m=jnp.ones(N, jnp.float32), N=N, F=F, B=B)
    bins, g, h = ctx["bins"], ctx["g"], ctx["h"]
    N, F = ctx["N"], ctx["F"]
    sp = SplitParams(lambda_l1=0.0, lambda_l2=0.0, min_data_in_leaf=100,
                     min_sum_hessian_in_leaf=100.0, min_gain_to_split=0.0,
                     max_delta_step=0.0, path_smooth=0.0, cat_smooth=10.0,
                     cat_l2=10.0, max_cat_to_onehot=4)
    hist_method = "pallas" if jax.default_backend() == "tpu" else "onehot"
    cfg = GrowerConfig(num_leaves=255, max_depth=-1, max_bin=256, split=sp,
                       feature_fraction_bynode=1.0, hist_method=hist_method,
                       hist_chunk_rows=65536, sorted_cat=False)
    meta = dict(num_bins=jnp.full(F, 256, jnp.int32),
                default_bins=jnp.zeros(F, jnp.int32),
                nan_bins=jnp.full(F, -1, jnp.int32),
                is_categorical=jnp.zeros(F, bool),
                monotone=jnp.zeros(F, jnp.int32))
    rw = jnp.ones(N, jnp.float32)
    fm = jnp.ones(F, jnp.float32)
    key = jax.random.PRNGKey(0)

    def time_grow(cfg_m, tag, iters):
        grow = jax.jit(lambda b_, g_, h_, rw_, fm_, k_, c=cfg_m: grow_tree(
            b_, g_, h_, rw_, fm_, **meta, key=k_, cfg=c))
        t = time.perf_counter()
        tree, _ = grow(bins, g, h, rw, fm, key)
        tree.leaf_value.block_until_ready()
        emit(stage=f"grow_{tag}_compile_plus_first",
             secs=round(time.perf_counter() - t, 1))
        t = time.perf_counter()
        for _ in range(iters):
            tree, _ = grow(bins, g + 1e-12, h, rw, fm, key)
        tree.leaf_value.block_until_ready()
        ms = (time.perf_counter() - t) / iters * 1e3
        emit(stage=f"grow_{tag}_steady", ms_per_tree=round(ms, 1))
        return ms

    best = (None, float("inf"))
    # frontier_k sweep: the batch width trades per-round fixed cost against
    # block-padding waste — pick the winner for the headline bench
    for fk, br in ((32, 512), (16, 512), (64, 512), (32, 1024)):
        cfg_m = cfg._replace(grower_mode="frontier", frontier_k=fk,
                             frontier_block_rows=br)
        ms = time_grow(cfg_m, f"frontier_k{fk}_br{br}", iters=4)
        if ms < best[1]:
            best = ((fk, br), ms)
    emit(stage="frontier_best", k=best[0][0], block_rows=best[0][1],
         ms_per_tree=round(best[1], 1))
    time_grow(cfg._replace(grower_mode="serial"), "serial", iters=2)
    # merge the sweep winner UNDER any user-provided knobs (theirs win);
    # returning it records the tuning in this phase's suite_phase_done
    # marker, so a RESUMED run that skips grow_sweep still benches the
    # headline with the same knobs instead of silently reverting
    extra = {"frontier_k": best[0][0], "frontier_block_rows": best[0][1],
             **json.loads(os.environ.get("BENCH_PARAMS_EXTRA", "{}"))}
    os.environ["BENCH_PARAMS_EXTRA"] = json.dumps(extra)
    return {"bench_params_extra": extra}


def phase_headline(ctx):
    # in-process, same params as bench.py; one coherent shape for the
    # whole story (a leftover BENCH_ROWS env var must not decouple the
    # headline from the micro stages); probe already done at entry
    os.environ["BENCH_ROWS"] = str(ROWS)
    os.environ["BENCH_SKIP_PROBE"] = "1"
    import contextlib
    import io
    import bench

    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    except SystemExit:
        pass          # auc-floor exit: the JSON line is already in buf
    except Exception as e:
        # a lowering/OOM failure must still leave a record — the suite's
        # contract is append-as-they-land
        emit(stage="headline_bench", error=f"{type(e).__name__}: {e}"[:300])
        return
    payload = bench._load_supervise().extract_json_line(buf.getvalue())
    emit(stage="headline_bench",
         **(payload if payload is not None
            else {"error": buf.getvalue()[-300:]}))


def phase_bench_serve(ctx):
    # serving p50/p99 + rows/s (scripts/bench_serve.py, docs/SERVING.md):
    # FAULT-ISOLATED in its own budgeted subprocess — an AOT-lowering crash
    # or hang in the serving path must not cost the already-captured
    # training numbers (nor the 10.5M headline still owed after it)
    import bench
    sup = bench._load_supervise()
    env = dict(os.environ)
    env["BENCH_SKIP_PROBE"] = "1"          # the suite already proved it live
    res = sup.run_stage(
        "bench_serve",
        [sys.executable, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "bench_serve.py")],
        timeout=float(os.environ.get("TPU_SUITE_SERVE_TIMEOUT", 1200)),
        env=env)
    payload = sup.extract_json_line(res.output_tail)
    if payload is not None:
        # nest, don't splat: a crash mid-bench leaves one of bench_serve's
        # OWN stage-keyed progress records as the last json line, and
        # **payload would collide with stage= (the watcher nests too)
        emit(stage="bench_serve", subprocess_status=res.status,
             result=payload)
    else:
        emit(stage="bench_serve", subprocess_status=res.status,
             error=res.output_tail[-300:])


def phase_bench_stream(ctx):
    # out-of-core streaming rows/s + H2D-overlap efficiency vs in-HBM
    # (scripts/bench_stream.py, docs/STREAMING.md): FAULT-ISOLATED like
    # bench_serve — a wedge in the host-paced streaming loop must not cost
    # the captured training numbers.  --quick keeps the phase under its
    # budget; the full sweep belongs to a dedicated window.
    import bench
    sup = bench._load_supervise()
    env = dict(os.environ)
    res = sup.run_stage(
        "bench_stream",
        [sys.executable, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "bench_stream.py"), "--quick"],
        timeout=float(os.environ.get("TPU_SUITE_STREAM_TIMEOUT", 1200)),
        env=env)
    payload = sup.extract_json_line(res.output_tail)
    if payload is not None:
        # nest, don't splat (same stage=-collision reason as bench_serve)
        emit(stage="bench_stream", subprocess_status=res.status,
             result=payload)
    else:
        emit(stage="bench_stream", subprocess_status=res.status,
             error=res.output_tail[-300:])


def phase_headline_big(ctx):
    # real-Higgs scale: one 10.5M-row single-chip run (VERDICT r4 item 4;
    # ~0.3 GB of bins) with the device-memory high-water in the detail.
    # TPU-only, and FAULT-ISOLATED in its own subprocess under a
    # wall-clock budget: an OOM or lowering hang at this scale must not
    # take down a suite that already captured everything else.
    import jax
    import bench
    if jax.default_backend() != "tpu":
        emit(stage="headline_bench_10p5M", skipped="cpu backend")
        return
    sup = bench._load_supervise()
    env = dict(os.environ)
    env.update(BENCH_ROWS="10500000", BENCH_SKIP_PROBE="1")
    res = sup.run_stage(
        "headline_bench_10p5M",
        [sys.executable, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench.py")],
        timeout=float(os.environ.get("TPU_SUITE_BIG_TIMEOUT", 2400)),
        env=env)
    payload = sup.extract_json_line(res.output_tail)
    if payload is not None:
        emit(stage="headline_bench_10p5M", subprocess_status=res.status,
             **payload)
    else:
        emit(stage="headline_bench_10p5M", subprocess_status=res.status,
             error=res.output_tail[-300:])


def phase_regress(ctx):
    # CLOSING self-judgment (jax-free: obs.regress loaded via load_obs):
    # every number this suite just appended is classified against the
    # accumulated journal + BENCH_r* history, so a slower-than-last-window
    # result flags loudly WHILE the window is still open.  Degrade-only by
    # construction — the phase loop already records an error and moves on,
    # and a verdict never aborts: the captured numbers are the product.
    res = OBS.regress.scan(journal_path=OUT)
    worst = [v for v in res["verdicts"]
             if v["verdict"] in ("regressed", "improved")][:10]
    emit(stage="regress_verdict", rows=ROWS, counts=res["counts"],
         regressed=res["regressed"], worst=worst)


PHASE_FNS = {"sanity": phase_sanity, "parity": phase_parity,
             "regress": phase_regress,
             "hist_micro": phase_hist_micro, "grow_sweep": phase_grow_sweep,
             "headline": phase_headline, "bench_serve": phase_bench_serve,
             "bench_stream": phase_bench_stream,
             "headline_big": phase_headline_big}


def main():
    # wedge-safe: prove the backend live in a TIMEOUT-GUARDED subprocess
    # before this process commits to it (a wedged tunnel hangs forever)
    import bench
    if "axon" in os.environ.get("JAX_PLATFORMS", "axon") \
            and not os.environ.get("BENCH_SKIP_PROBE") \
            and not bench.probe_backend(
                float(os.environ.get("BENCH_PROBE_TIMEOUT", 300))):
        emit(stage="abort", reason="tpu_unreachable")
        return 1

    resume_done, saved = (set(), {})
    if os.environ.get("TPU_SUITE_RESUME"):
        resume_done, saved = _completed_phases_since_last_start()
    skip = _phases_to_skip(resume_done)
    if "grow_sweep" in skip and saved.get("bench_params_extra"):
        # resuming past a completed sweep: restore its tuning (any
        # user-provided knobs still win)
        os.environ["BENCH_PARAMS_EXTRA"] = json.dumps(
            {**saved["bench_params_extra"],
             **json.loads(os.environ.get("BENCH_PARAMS_EXTRA", "{}"))})
    emit(stage="suite_start", rows=ROWS, skipped=sorted(skip),
         resumed_done=sorted(resume_done))
    ctx = {}
    rc = 0
    for name in PHASES:
        if name in skip:
            continue
        try:
            marker_extra = PHASE_FNS[name](ctx) or {}
        except SuiteAbort as e:
            emit(stage="abort", reason=str(e), phase=name, rows=ROWS)
            return 1
        except Exception as e:       # degrade: later phases still run
            emit(stage="suite_phase_error", phase=name, rows=ROWS,
                 error=f"{type(e).__name__}: {e}"[:300])
            rc = 1
            continue
        emit(stage="suite_phase_done", phase=name, rows=ROWS, **marker_extra)
    emit(stage="suite_end", rows=ROWS, rc=rc)
    return rc


if __name__ == "__main__":
    sys.exit(main())
