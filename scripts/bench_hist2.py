"""Bench + verify the bf16 split-precision Pallas histogram vs the f32 paths.

Run on the TPU (ambient axon backend):
    PYTHONPATH=/root/.axon_site:/root/repo python scripts/bench_hist2.py [rows]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import load_obs  # noqa: E402

LOG = load_obs().EventLog.default(echo=True)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from lightgbm_tpu.ops.histogram import _hist_onehot, _hist_pallas  # noqa: E402

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
F = int(sys.argv[2]) if len(sys.argv) > 2 else 28
B = int(sys.argv[3]) if len(sys.argv) > 3 else 255

rng = np.random.default_rng(0)
bins = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
g = jnp.asarray(rng.normal(size=N).astype(np.float32))
h = jnp.asarray(rng.uniform(0.1, 1, size=N).astype(np.float32))
m = jnp.ones(N, jnp.float32)


def timed(name, fn, iters=10):
    @jax.jit
    def many(bins, g, h, m):
        def body(acc, i):
            hh = fn(bins, g + i * 1e-12, h, m)
            return acc + jnp.sum(hh), None
        acc, _ = jax.lax.scan(body, jnp.float32(0),
                              jnp.arange(iters, dtype=jnp.float32))
        return acc

    float(many(bins, g, h, m))
    t0 = time.perf_counter()
    float(many(bins, g, h, m))
    dt = (time.perf_counter() - t0 - 0.09) / iters
    rate = N / dt / 1e9
    print(f"{name:28s} {dt*1e3:8.2f} ms  {rate:6.2f} Grow/s")
    return dt


ref = jax.jit(lambda b, g, h, m: _hist_onehot(b, g, h, m, B, 65536))(
    bins[:65536], g[:65536], h[:65536], m[:65536])
got = jax.jit(lambda b, g, h, m: _hist_pallas(b, g, h, m, B))(
    bins[:65536], g[:65536], h[:65536], m[:65536])
err = float(jnp.max(jnp.abs(ref - got) / (jnp.abs(ref) + 1.0)))
print(f"pallas-vs-onehot max rel err: {err:.2e}")
assert err < 1e-4, err

results = {}
for br in (512, 1024, 2048):
    results[f"pallas_bf16_br{br}"] = round(timed(
        f"pallas bf16 BR={br}",
        lambda b, g, h, m, br=br: _hist_pallas(b, g, h, m, B, block_rows=br)
    ) * 1e3, 3)
results["onehot_f32_xla"] = round(timed(
    "onehot f32 (xla)",
    lambda b, g, h, m: _hist_onehot(b, g, h, m, B, 65536)) * 1e3, 3)
# one-JSON-line contract: the LAST stdout line is the schema summary
LOG.summary(bench="hist_bf16_parity", rows=N, features=F, max_bins=B,
            backend=jax.default_backend(), parity_relerr=err,
            results_ms=results)
