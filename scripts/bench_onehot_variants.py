"""One-hot histogram kernel variants — timing shootout on the TPU.

The production kernel (ops/histogram.py:_hist_pallas) is VPU-bound building
the one-hot (iota-compare-select over f*Bp*BR elements per block; measured
~12% MFU at the bench shape).  Each variant here changes ONE aspect of the
one-hot build so the winner can be folded back into the production kernel:

  base      int32 iota compare -> bf16 select (current production shape)
  bf16cmp   bf16 iota + bf16 bins compare (2-byte lanes may pack 2x)
  i16cmp    int16 iota + int16 bins compare
  sub1abs   onehot = max(0, 1 - |b - j|) in bf16 (no select, all-arith)
  brN       base at BR in {256, 1024, 2048} (VMEM one-hot budget sweep)

Every variant is parity-checked against the XLA one-hot before timing.
Results append to perf_results.jsonl (stage "onehot_variant").

Run (the ONLY process touching the TPU):
    python scripts/bench_onehot_variants.py [rows]
"""
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the watcher points every stage at one results file; standalone runs use
# the repo default
OUT = os.environ.get("WATCHER_PERF_LOG") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "perf_results.jsonl")
ROWS = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000


def emit(**kv):
    kv["ts"] = time.time()
    with open(OUT, "a") as f:
        f.write(json.dumps(kv) + "\n")
    print(json.dumps(kv), flush=True)


def make_kernel(f, Bp, BR, onehot_fn):
    """Feature-major single-block kernel (bins pre-transposed OUTSIDE —
    the production layout; the in-kernel transpose benched 35x slower) with
    a pluggable one-hot builder."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(bins_ref, gh_ref, out_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        b = bins_ref[:]                                       # [f, BR] u8
        onehot = onehot_fn(b, f, Bp, BR).reshape(f * Bp, BR)
        out_ref[:] += jax.lax.dot_general(
            gh_ref[:], onehot,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)

    def run(bins_t, gh6):
        n = bins_t.shape[1]
        assert n % BR == 0
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((6, f * Bp), jnp.float32),
            grid=(n // BR,),
            in_specs=[pl.BlockSpec((f, BR), lambda i: (0, i)),
                      pl.BlockSpec((6, BR), lambda i: (0, i))],
            out_specs=pl.BlockSpec((6, f * Bp), lambda i: (0, 0)),
            interpret=bool(os.environ.get("ONEHOT_INTERPRET")),
        )(bins_t, gh6)
    return run


def onehot_base(b, f, Bp, BR):
    import jax
    import jax.numpy as jnp
    bi = b.astype(jnp.int32)
    bin_id = jax.lax.broadcasted_iota(jnp.int32, (f, Bp, BR), 1)
    return (bi[:, None, :] == bin_id).astype(jnp.bfloat16)


def onehot_bf16cmp(b, f, Bp, BR):
    import jax
    import jax.numpy as jnp
    bb = b.astype(jnp.bfloat16)                  # bins < 256: exact in bf16
    bin_id = jax.lax.broadcasted_iota(jnp.bfloat16, (f, Bp, BR), 1)
    return (bb[:, None, :] == bin_id).astype(jnp.bfloat16)


def onehot_i16cmp(b, f, Bp, BR):
    import jax
    import jax.numpy as jnp
    bi = b.astype(jnp.int16)
    bin_id = jax.lax.broadcasted_iota(jnp.int16, (f, Bp, BR), 1)
    return (bi[:, None, :] == bin_id).astype(jnp.bfloat16)


def onehot_u8cmp(b, f, Bp, BR):
    # 1-byte compare domain (VERDICT r4 item 2: "u8-domain compares upcast
    # in the dot"): u8 lanes pack 4x vs i32, and Bp=256 exactly spans u8
    import jax
    import jax.numpy as jnp
    bin_id = jax.lax.broadcasted_iota(jnp.uint8, (f, Bp, BR), 1)
    return (b[:, None, :] == bin_id).astype(jnp.bfloat16)


def onehot_sub1abs(b, f, Bp, BR):
    import jax
    import jax.numpy as jnp
    bb = b.astype(jnp.bfloat16)
    bin_id = jax.lax.broadcasted_iota(jnp.bfloat16, (f, Bp, BR), 1)
    d = bb[:, None, :] - bin_id
    return jnp.maximum(jnp.bfloat16(1.0) - jnp.abs(d), jnp.bfloat16(0.0))


def main():
    import bench
    if "axon" in os.environ.get("JAX_PLATFORMS", "axon") \
            and not os.environ.get("BENCH_SKIP_PROBE") \
            and not bench.probe_backend(
                float(os.environ.get("BENCH_PROBE_TIMEOUT", 300))):
        emit(stage="abort", reason="tpu_unreachable")
        return 1

    import jax
    import jax.numpy as jnp
    import numpy as np
    from lightgbm_tpu.ops.histogram import _hist_onehot

    N, F, B = ROWS, 28, 255
    Bp = 256
    rng = np.random.default_rng(0)
    bins = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(np.full(N, 0.25, np.float32))
    m = jnp.ones(N, jnp.float32)
    from lightgbm_tpu.ops.histogram import _gh6
    gh6 = _gh6(g, h, m)                     # fenced split-precision pair
    bins_t = jnp.asarray(np.ascontiguousarray(
        np.asarray(bins).T))                # [F, N] u8, transposed ONCE

    ref = jax.jit(lambda b_, g_: _hist_onehot(b_, g_, h, m, B, 65536))(bins, g)
    ref = ref.block_until_ready()

    peak = bench._PEAK_BF16_FLOPS.get(
        jax.devices()[0].device_kind.lower(), 197e12)
    variants = [("base_br512", onehot_base, 512),
                ("bf16cmp_br512", onehot_bf16cmp, 512),
                ("i16cmp_br512", onehot_i16cmp, 512),
                ("u8cmp_br512", onehot_u8cmp, 512),
                ("sub1abs_br512", onehot_sub1abs, 512),
                ("base_br256", onehot_base, 256),
                ("base_br1024", onehot_base, 1024),
                ("base_br2048", onehot_base, 2048),
                ("u8cmp_br1024", onehot_u8cmp, 1024),
                ("u8cmp_br2048", onehot_u8cmp, 2048)]
    for name, fn, BR in variants:
        try:
            run = make_kernel(F, Bp, BR, fn)
            jfn = jax.jit(run)
            out = jfn(bins_t, gh6).block_until_ready()
            hist = (out.reshape(2, 3, F, Bp)[0]
                    + out.reshape(2, 3, F, Bp)[1])[:, :, :B].transpose(1, 2, 0)
            # same tolerance derivation as scripts/bench_dual.py TOL
            err = float(jnp.max(jnp.abs(hist - ref) / (jnp.abs(ref) + 1.0)))
            if err > 5e-4:
                emit(stage="onehot_variant", name=name, ok=False, relerr=err)
                continue
            t0 = time.perf_counter()
            for _ in range(10):
                r = jfn(bins_t, gh6)
            r.block_until_ready()
            dt = (time.perf_counter() - t0) / 10
            emit(stage="onehot_variant", name=name, ok=True,
                 ms=round(dt * 1e3, 3),
                 mfu=round(2.0 * 6 * N * F * Bp / dt / peak, 4))
        except Exception as e:
            emit(stage="onehot_variant", name=name, ok=False,
                 error=str(e)[:250])
    return 0


if __name__ == "__main__":
    sys.exit(main())
