"""One-hot histogram kernel variants — timing shootout on the TPU.

The production kernel (ops/histogram.py:_hist_pallas) is VPU-bound building
the one-hot (iota-compare-select over f*Bp*BR elements per block; measured
~12% MFU at the bench shape).  Every candidate build lives in the SHARED
variant registry (lightgbm_tpu/ops/onehot_variants.py) — the same kernel
bodies the production kernels run — so the shootout prices exactly what
training would ship and nothing can drift between the two (the pre-registry
shootout duplicated kernel code by hand).

Per (variant, BR, max_bin) entry: parity vs the true-f32 XLA one-hot at the
shared tolerance (HIST_PARITY_TOL), then a 10-iteration timing.  Results
append to perf_results.jsonl (stage "onehot_variant") with the structural
work model alongside the wall-clock: ``mxu_lanes`` (the dot's N-dim) and
``onehot_elems_per_row`` (VPU compare count) — see docs/PERF.md "ceiling
attack" for how to read them.

Run (the ONLY process touching the TPU):
    python scripts/bench_onehot_variants.py [rows] [--max-bin 255,64]

``--max-bin`` takes a comma list; the default sweeps 255 (the Higgs bench
width) and 64 (exercising the lane-packing variant).  The watcher's
onehot_shootout stage runs this unchanged.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import load_obs  # noqa: E402

# the watcher points every stage at one results file (WATCHER_PERF_LOG);
# obs.events owns that resolution now — one writer for every bench
OBS = load_obs()
LOG = OBS.EventLog.default(echo=True)
# achieved/peak math: obs.costs is the ONE peak table + MFU formula
COSTS = OBS.costs


def emit(**kv):
    LOG.emit(kv.pop("stage", "bench_record"), **kv)


# (variant, BR) grid: every registry family at the production BR, plus a
# BR sweep for the families whose VMEM one-hot budget trade-off moved the
# needle in earlier rounds
def entry_grid(variant_names):
    entries = [(name, 512) for name in variant_names]
    entries += [("base", 256), ("base", 1024), ("base", 2048),
                ("u8cmp", 1024), ("u8cmp", 2048),
                ("staged", 1024), ("packed", 1024), ("int8", 1024)]
    return entries


def run_shootout(rows, max_bins, emit=emit, interpret=False):
    """All (variant, BR) entries at each requested max_bin; importable so
    the perf suite / tests can drive the same sweep in-process."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from lightgbm_tpu.ops import onehot_variants as ov
    from lightgbm_tpu.ops.histogram import HIST_PARITY_TOL, _hist_onehot

    F = 28
    chip = COSTS.current_chip()
    # Per-entry failures (parity or lowering) are fully recorded as their
    # own ok:false jsonl entries and must NOT fail the stage: a nonzero
    # exit would make the watcher mark the whole onehot_shootout stage
    # failed — and re-run the entire 60-min sweep under stage retries —
    # because ONE experimental variant refused to lower, discarding every
    # valid timing already captured.  Nonzero is reserved for the sweep
    # itself crashing (main's probe abort / an unhandled error).
    tally = {"ok": 0, "failed": 0, "skipped": 0, "best": None}
    for B in max_bins:
        rng = np.random.default_rng(0)
        # pad rows to a multiple of the largest BR so every entry divides
        N = -(-rows // 2048) * 2048
        bins = rng.integers(0, B, size=(N, F), dtype=np.uint8)
        g_np = rng.normal(size=N).astype(np.float32)
        g_np[rows:] = 0.0
        g = jnp.asarray(g_np)
        h = jnp.asarray(np.full(N, 0.25, np.float32))
        m = jnp.asarray((np.arange(N) < rows).astype(np.float32))
        bins_t = jnp.asarray(np.ascontiguousarray(bins.T))  # [F, N] u8, once
        bins_d = jnp.asarray(bins)

        ref = jax.jit(lambda b_, g_: _hist_onehot(b_, g_, h, m, B, 65536))(
            bins_d, g)
        ref = ref.block_until_ready()

        for name, BR in entry_grid(ov.VARIANT_NAMES):
            spec = ov.VARIANTS[name]
            tag = f"{name}_br{BR}"
            if not spec.supports(B):
                emit(stage="onehot_variant", name=tag, max_bin=B,
                     skipped="unsupported_max_bin")
                tally["skipped"] += 1
                continue
            try:
                prep, run = ov.make_bench_kernel(name, F, B, BR,
                                                 interpret=interpret)
                rows_arr = jax.jit(prep)(g, h, m).block_until_ready()
                jfn = jax.jit(run)
                hist = jfn(bins_t, rows_arr).block_until_ready()
                err = float(jnp.max(jnp.abs(hist - ref)
                                    / (jnp.abs(ref) + 1.0)))
                if err > HIST_PARITY_TOL:
                    emit(stage="onehot_variant", name=tag, max_bin=B,
                         ok=False, relerr=err)
                    tally["failed"] += 1
                    continue
                t0 = time.perf_counter()
                for _ in range(10):
                    r = jfn(bins_t, rows_arr)
                r.block_until_ready()
                dt = (time.perf_counter() - t0) / 10
                lanes = ov.total_lanes(name, F, B)
                emit(stage="onehot_variant", name=tag, variant=name, br=BR,
                     max_bin=B, ok=True, relerr=err,
                     ms=round(dt * 1e3, 3),
                     # useful-FLOPs MFU vs the bf16 peak: 2 * 6 rows * N *
                     # the dot's actual N-dim (lane packing SHRINKS it)
                     mfu=round(COSTS.mfu(2.0 * 6 * rows * lanes, dt,
                                         chip), 4),
                     # analytical VPU-work-model bound (docs/PERF.md):
                     # predicted-vs-achieved prices the ceiling attack
                     predicted_mfu=round(ov.predicted_mfu(name, F, B), 4),
                     chip=chip, mxu_lanes=lanes,
                     onehot_elems_per_row=spec.vpu_compares(F, B, 1))
                tally["ok"] += 1
                if (tally["best"] is None
                        or dt * 1e3 < tally["best"]["ms"]):
                    tally["best"] = {"name": tag, "max_bin": B,
                                     "ms": round(dt * 1e3, 3)}
            except Exception as e:
                emit(stage="onehot_variant", name=tag, max_bin=B, ok=False,
                     error=str(e)[:250])
                tally["failed"] += 1
    return tally


def parse_args(argv):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("rows", nargs="?", type=int, default=1_000_000)
    ap.add_argument("--max-bin", default="255,64",
                    help="comma list of histogram widths to sweep")
    return ap.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    max_bins = [int(b) for b in str(args.max_bin).split(",") if b.strip()]

    import bench
    if "axon" in os.environ.get("JAX_PLATFORMS", "axon") \
            and not os.environ.get("BENCH_SKIP_PROBE") \
            and not bench.probe_backend(
                float(os.environ.get("BENCH_PROBE_TIMEOUT", 300))):
        emit(stage="abort", reason="tpu_unreachable")
        return 1

    tally = run_shootout(args.rows, max_bins,
                         interpret=bool(os.environ.get("ONEHOT_INTERPRET")))
    # one-JSON-line contract: summary() appends to the journal AND prints
    # the schema-stamped record as the LAST stdout line.  Per-entry
    # failures are informational (see run_shootout) — exit 0 regardless.
    LOG.summary(bench="onehot_variants", rows=args.rows, max_bins=max_bins,
                **tally)
    return 0


if __name__ == "__main__":
    sys.exit(main())
