"""Two-process data-parallel training over localhost — the analog of the
reference's parallel_learning recipe (machine list + one lightgbm run per
machine; its README.md).  The coordinator host and the machine count come
from ``mlist.txt`` (the reference machine-list grammar); the port is
re-picked free at launch so concurrent runs don't collide.  Each process
holds HALF the training rows and ``train_distributed`` produces the
identical Booster on both.

On REAL multi-machine setups use ``parallel.set_network(machines)`` (one
process per machine, rank resolved from the local address) or
``parallel.mesh.init_distributed`` directly; on one host two ranks share
every interface address, so the rank must be passed explicitly.

Run:  python run_distributed.py
"""
import os
import socket
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

_WORKER = r"""
import os, sys
import numpy as np

sys.path.insert(0, sys.argv[5])        # repo root: works uninstalled
proc_id = int(sys.argv[1])
coord = sys.argv[2]
num_machines = int(sys.argv[3])
os.chdir(sys.argv[4])

from lightgbm_tpu.parallel.mesh import init_distributed
init_distributed(coordinator_address=coord, num_processes=num_machines,
                 process_id=proc_id)
from lightgbm_tpu.parallel import train_distributed
from lightgbm_tpu.application import parse_config_file

params = dict(parse_config_file("train.conf"))
raw = np.loadtxt(params["data"], delimiter="\t")
X, y = raw[:, 1:], raw[:, 0]
half = len(y) // 2
lo, hi = (0, half) if proc_id == 0 else (half, len(y))
vraw = np.loadtxt(params["valid_data"], delimiter="\t")
n_trees = int(params.pop("num_trees"))
for k in ("task", "data", "valid_data", "output_model", "machine_list_file",
          "is_training_metric", "metric_freq"):
    params.pop(k, None)
params["verbose"] = -1
bst = train_distributed(params, X[lo:hi], y[lo:hi], num_boost_round=n_trees,
                        valid_data=(vraw[:, 1:], vraw[:, 0]))
if proc_id == 0:
    bst.save_model("LightGBM_model.txt")
print("proc%d trained %d trees" % (proc_id, bst.num_trees()))
"""


def main():
    # machine list: first entry is the coordinator (reference rank-0 hub)
    with open(os.path.join(HERE, "mlist.txt")) as f:
        machines = [ln.split() for ln in f if ln.strip()]
    coord_host = machines[0][0]
    with socket.socket() as s:          # fresh port: no cross-run collision
        s.bind((coord_host, 0))
        coord = f"{coord_host}:{s.getsockname()[1]}"

    procs = []
    for pid in range(len(machines)):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(pid), coord,
             str(len(machines)), HERE,
             os.path.dirname(os.path.dirname(HERE))], env=env))
    rc = sum(p.wait() for p in procs)
    if rc == 0:
        print("distributed training complete -> LightGBM_model.txt")
    return rc


if __name__ == "__main__":
    sys.exit(main())
