"""Advanced workflows (reference analog: examples/python-guide/
advanced_example.py): sample weights, categorical features, missing values,
JSON model dump, continued training from ``init_model``, and resetting
parameters between training stages.
"""
import _bootstrap  # noqa: F401  (repo path + CPU backend for direct runs)
import json
import os
import tempfile

import numpy as np
from sklearn.datasets import make_classification

import lightgbm_tpu as lgb


def main():
    rng = np.random.default_rng(3)
    X, y = make_classification(n_samples=4000, n_features=12, n_informative=7,
                               random_state=3)
    X = X.astype(np.float64)
    # feature 0 becomes categorical with 6 levels; feature 1 gets missing rows
    X[:, 0] = rng.integers(0, 6, size=len(X))
    X[rng.uniform(size=len(X)) < 0.05, 1] = np.nan
    w = rng.uniform(0.5, 1.5, size=len(X)).astype(np.float64)

    params = {"objective": "binary", "metric": "auc", "num_leaves": 31,
              "verbose": -1}
    train_set = lgb.Dataset(X[:3000], label=y[:3000], weight=w[:3000],
                            categorical_feature=[0], params=params)
    valid_set = train_set.create_valid(X[3000:], label=y[3000:],
                                       weight=w[3000:])

    # stage 1: 20 rounds
    booster = lgb.train(params, train_set, num_boost_round=20,
                        valid_sets=[valid_set], verbose_eval=False)
    auc1 = booster.eval_valid()[0][2]
    print(f"Stage-1 valid AUC after 20 rounds: {auc1:.4f}")

    # inspect the model: JSON dump + per-feature importance
    dump = booster.dump_model()
    print(f"Model dump carries {len(dump['tree_info'])} trees; "
          f"gain importance: {booster.feature_importance('gain')[:4].round(2)}")

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "stage1.txt")
        booster.save_model(path)
        json_path = os.path.join(tmp, "stage1.json")
        with open(json_path, "w") as f:
            json.dump(dump, f)

        # stage 2: continue training 20 more rounds from the saved model,
        # with a smaller learning rate via reset_parameter
        params2 = dict(params, learning_rate=0.05)
        booster2 = lgb.train(
            params2, train_set, num_boost_round=20, init_model=path,
            valid_sets=[valid_set],
            callbacks=[lgb.reset_parameter(
                learning_rate=lambda it: 0.05 * (0.99 ** it))],
            verbose_eval=False)
        auc2 = booster2.eval_valid()[0][2]
        print(f"Stage-2 valid AUC after 40 total rounds: {auc2:.4f} "
              f"({booster2.num_trees()} trees)")
        assert booster2.num_trees() == 40
        assert auc2 >= auc1 - 0.01


if __name__ == "__main__":
    main()
