"""The sklearn estimator surface (reference analog: examples/python-guide/
sklearn_example.py): fit with eval sets + early stopping, inspect feature
importances, and run a hyper-parameter grid search with the stock sklearn
machinery (the wrappers are sklearn-compatible estimators).
"""
import _bootstrap  # noqa: F401  (repo path + CPU backend for direct runs)
import numpy as np
from sklearn.datasets import make_regression
from sklearn.model_selection import GridSearchCV, train_test_split

from lightgbm_tpu.sklearn import LGBMRegressor


def main():
    X, y = make_regression(n_samples=3000, n_features=15, n_informative=8,
                           noise=10.0, random_state=1)
    X_train, X_test, y_train, y_test = train_test_split(
        X.astype(np.float32), y.astype(np.float32), random_state=1)

    model = LGBMRegressor(num_leaves=31, learning_rate=0.08,
                          n_estimators=50, verbose=-1)
    model.fit(X_train, y_train,
              eval_set=[(X_test, y_test)], eval_metric="l1",
              early_stopping_rounds=8, verbose=False)
    pred = model.predict(X_test, num_iteration=model.best_iteration_)
    rmse = float(np.sqrt(np.mean((pred - y_test) ** 2)))
    print(f"RMSE: {rmse:.4f} (best iteration {model.best_iteration_})")

    order = np.argsort(model.feature_importances_)[::-1][:5]
    print("Top-5 features by split importance:", order.tolist())

    search = GridSearchCV(
        LGBMRegressor(n_estimators=25, verbose=-1),
        {"learning_rate": [0.05, 0.1], "num_leaves": [15, 31]},
        cv=3)
    search.fit(X_train, y_train)
    print("Best grid-search params:", search.best_params_)


if __name__ == "__main__":
    main()
