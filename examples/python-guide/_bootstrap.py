"""Direct-run bootstrap shared by the python-guide examples.

Makes ``python examples/python-guide/<script>.py`` work from a source
checkout with no install: puts the repo root on ``sys.path`` and pins the
CPU backend (these are tiny demo datasets; set ``LGBM_GUIDE_BACKEND=tpu``
to opt into an accelerator).  Under pytest this is a no-op repeat of what
``tests/conftest.py`` already did.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir, os.pardir))

if os.environ.get("LGBM_GUIDE_BACKEND", "cpu") == "cpu":
    # the ambient env may pre-register a remote accelerator backend whose
    # factory has already read JAX_PLATFORMS; pin the imported config and
    # drop non-cpu factories so a demo run can never touch hardware
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    import jax._src.xla_bridge as _xb
    jax.config.update("jax_platforms", "cpu")
    for _plat in list(_xb._backend_factories):
        if _plat != "cpu":
            _xb._backend_factories.pop(_plat, None)
