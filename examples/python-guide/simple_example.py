"""Train/validate/predict round trip through the core Python API.

The entry-level workflow (reference analog: examples/python-guide/
simple_example.py): build ``Dataset``s, train with early stopping against a
validation set, predict, and persist the model as LightGBM-format text.
"""
import _bootstrap  # noqa: F401  (repo path + CPU backend for direct runs)
import os
import tempfile

import numpy as np
from sklearn.datasets import make_regression
from sklearn.model_selection import train_test_split

import lightgbm_tpu as lgb


def main():
    X, y = make_regression(n_samples=4000, n_features=20, n_informative=12,
                           noise=8.0, random_state=7)
    X_train, X_test, y_train, y_test = train_test_split(
        X.astype(np.float32), y.astype(np.float32), random_state=7)

    params = {
        "objective": "regression",
        "metric": {"l2", "l1"},
        "num_leaves": 31,
        "learning_rate": 0.08,
        "feature_fraction": 0.9,
        "bagging_fraction": 0.8,
        "bagging_freq": 5,
        "verbose": -1,
    }
    train_set = lgb.Dataset(X_train, label=y_train, params=params)
    valid_set = train_set.create_valid(X_test, label=y_test)

    print("Starting training...")
    evals = {}
    booster = lgb.train(
        params, train_set, num_boost_round=60,
        valid_sets=[valid_set], valid_names=["valid"],
        callbacks=[lgb.early_stopping(stopping_rounds=8),
                   lgb.record_evaluation(evals)],
        verbose_eval=False)
    print(f"Best iteration: {booster.best_iteration}; "
          f"valid l2 history tail: {evals['valid']['l2'][-3:]}")

    pred = booster.predict(X_test, num_iteration=booster.best_iteration)
    rmse = float(np.sqrt(np.mean((pred - y_test) ** 2)))
    print(f"RMSE on held-out data: {rmse:.4f}")

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "model.txt")
        booster.save_model(path)
        reloaded = lgb.Booster(model_file=path)
        assert np.allclose(reloaded.predict(X_test), pred, atol=1e-6)
        print(f"Model round-trips through {os.path.basename(path)}")


if __name__ == "__main__":
    main()
