"""Plotting module walkthrough (reference analog: examples/python-guide/
plot_example.py): record eval history during training, then render metric
curves, feature importances, a split-value histogram, and one tree, saving
all figures as PNGs (Agg backend; no display needed).
"""
import _bootstrap  # noqa: F401  (repo path + CPU backend for direct runs)
import os
import shutil
import tempfile

import matplotlib
matplotlib.use("Agg")

import numpy as np
from sklearn.datasets import make_classification

import lightgbm_tpu as lgb
from lightgbm_tpu import plotting


def main():
    X, y = make_classification(n_samples=3000, n_features=10,
                               n_informative=6, random_state=5)
    X = X.astype(np.float32)
    params = {"objective": "binary", "metric": {"binary_logloss", "auc"},
              "num_leaves": 15, "verbose": -1}
    train_set = lgb.Dataset(X[:2200], label=y[:2200], params=params)
    valid_set = train_set.create_valid(X[2200:], label=y[2200:])

    evals = {}
    booster = lgb.train(params, train_set, num_boost_round=30,
                        valid_sets=[train_set, valid_set],
                        valid_names=["train", "valid"],
                        callbacks=[lgb.record_evaluation(evals)],
                        verbose_eval=False)

    with tempfile.TemporaryDirectory(prefix="lgb_plots_") as out:
        ax = plotting.plot_metric(evals, metric="auc")
        ax.figure.savefig(os.path.join(out, "metric.png"))
        ax = plotting.plot_importance(booster, max_num_features=8)
        ax.figure.savefig(os.path.join(out, "importance.png"))
        ax = plotting.plot_split_value_histogram(booster, feature=0)
        ax.figure.savefig(os.path.join(out, "split_hist.png"))
        expected = 3
        if shutil.which("dot"):   # tree rendering needs graphviz installed
            ax = plotting.plot_tree(booster, tree_index=0)
            ax.figure.savefig(os.path.join(out, "tree0.png"))
            expected = 4
        made = sorted(os.listdir(out))
        print(f"Wrote {len(made)} figures: {made}")
        assert len(made) == expected


if __name__ == "__main__":
    main()
