"""Built-in ``binary`` objective vs a custom sigmoid-cross-entropy
``fobj``/``feval`` pair (reference analog: examples/python-guide/
logistic_regression.py): both train the same task and converge to the same
AUC, demonstrating the custom-gradient path end to end.
"""
import _bootstrap  # noqa: F401  (repo path + CPU backend for direct runs)
import numpy as np
from sklearn.datasets import make_classification
from sklearn.metrics import roc_auc_score

import lightgbm_tpu as lgb


def sigmoid(z):
    return 1.0 / (1.0 + np.exp(-z))


def logloss_fobj(preds, train_data):
    """Gradient/hessian of sigmoid cross-entropy on raw scores."""
    y = train_data.get_label()
    p = sigmoid(preds)
    return p - y, p * (1.0 - p)


def logloss_feval(preds, train_data):
    y = train_data.get_label()
    p = np.clip(sigmoid(preds), 1e-15, 1.0 - 1e-15)
    loss = -np.mean(y * np.log(p) + (1.0 - y) * np.log(1.0 - p))
    return "custom_logloss", float(loss), False


def main():
    X, y = make_classification(n_samples=4000, n_features=12, n_informative=8,
                               random_state=11)
    X = X.astype(np.float32)
    y = y.astype(np.float64)
    Xtr, ytr, Xte, yte = X[:3000], y[:3000], X[3000:], y[3000:]
    base = {"num_leaves": 31, "learning_rate": 0.1, "verbose": -1}

    built_in = lgb.train({**base, "objective": "binary"},
                         lgb.Dataset(Xtr, label=ytr), num_boost_round=30)
    auc_builtin = roc_auc_score(yte, built_in.predict(Xte))

    custom_set = lgb.Dataset(Xtr, label=ytr)
    custom = lgb.train({**base, "objective": "none"}, custom_set,
                       num_boost_round=30, fobj=logloss_fobj,
                       feval=logloss_feval, verbose_eval=False)
    # custom-objective models emit raw scores; apply the sigmoid ourselves
    auc_custom = roc_auc_score(yte, sigmoid(custom.predict(Xte)))

    print(f"AUC built-in objective: {auc_builtin:.4f}")
    print(f"AUC custom fobj:        {auc_custom:.4f}")
    assert abs(auc_builtin - auc_custom) < 0.02


if __name__ == "__main__":
    main()
