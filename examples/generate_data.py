"""Generate the example datasets (synthetic stand-ins with the reference's
file formats: TSV with the label in column 0; `.query` files for ranking;
`.weight` files for weighted training).

Run from the repo root or the examples dir:
    python examples/generate_data.py
"""
import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def _write_tsv(path, y, X):
    with open(path, "w") as f:
        for yi, row in zip(y, X):
            f.write("\t".join([f"{yi:g}"] + [f"{v:.6g}" for v in row]) + "\n")


def binary(n_train=7000, n_test=500, n_feat=28, seed=7):
    rng = np.random.default_rng(seed)
    n = n_train + n_test
    X = rng.normal(size=(n, n_feat))
    logit = 1.3 * X[:, 0] - 0.9 * X[:, 1] + X[:, 2] * X[:, 3] + 0.4 * X[:, 4] ** 2
    y = (logit + rng.logistic(size=n) > 0).astype(np.int64)
    d = os.path.join(HERE, "binary_classification")
    _write_tsv(os.path.join(d, "binary.train"), y[:n_train], X[:n_train])
    _write_tsv(os.path.join(d, "binary.test"), y[n_train:], X[n_train:])
    w = rng.uniform(0.5, 1.5, size=n)
    np.savetxt(os.path.join(d, "binary.train.weight"), w[:n_train], fmt="%.4f")
    np.savetxt(os.path.join(d, "binary.test.weight"), w[n_train:], fmt="%.4f")


def regression(n_train=7000, n_test=500, n_feat=20, seed=11):
    rng = np.random.default_rng(seed)
    n = n_train + n_test
    X = rng.normal(size=(n, n_feat))
    y = (2.0 * X[:, 0] + X[:, 1] ** 2 - 1.5 * X[:, 2] * X[:, 3]
         + rng.normal(scale=0.3, size=n))
    d = os.path.join(HERE, "regression")
    _write_tsv(os.path.join(d, "regression.train"), y[:n_train], X[:n_train])
    _write_tsv(os.path.join(d, "regression.test"), y[n_train:], X[n_train:])


def lambdarank(n_queries=200, seed=13, n_feat=16):
    rng = np.random.default_rng(seed)
    d = os.path.join(HERE, "lambdarank")

    def make(nq, fname, qname):
        rows, labels, qsizes = [], [], []
        for _ in range(nq):
            sz = int(rng.integers(5, 25))
            qsizes.append(sz)
            X = rng.normal(size=(sz, n_feat))
            rel = X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.7, size=sz)
            lab = np.clip(np.digitize(rel, [-0.5, 0.5, 1.5]), 0, 4)
            rows.append(X)
            labels.append(lab)
        _write_tsv(fname, np.concatenate(labels), np.concatenate(rows))
        np.savetxt(qname, np.asarray(qsizes, np.int64), fmt="%d")

    make(n_queries, os.path.join(d, "rank.train"),
         os.path.join(d, "rank.train.query"))
    make(max(20, n_queries // 5), os.path.join(d, "rank.test"),
         os.path.join(d, "rank.test.query"))


def multiclass(n_train=6000, n_test=500, n_feat=12, n_class=5, seed=17):
    rng = np.random.default_rng(seed)
    n = n_train + n_test
    X = rng.normal(size=(n, n_feat))
    centers = rng.normal(scale=2.0, size=(n_class, n_feat))
    y = np.argmin(
        ((X[:, None, :4] - centers[None, :, :4]) ** 2).sum(-1)
        + rng.gumbel(scale=1.5, size=(n, n_class)), axis=1)
    d = os.path.join(HERE, "multiclass_classification")
    _write_tsv(os.path.join(d, "multiclass.train"), y[:n_train], X[:n_train])
    _write_tsv(os.path.join(d, "multiclass.test"), y[n_train:], X[n_train:])


def xendcg(n_queries=150, seed=19, n_feat=14):
    # same ranking file format as lambdarank, different draw
    rng = np.random.default_rng(seed)
    d = os.path.join(HERE, "xendcg")

    def make(nq, fname, qname):
        rows, labels, qsizes = [], [], []
        for _ in range(nq):
            sz = int(rng.integers(6, 30))
            qsizes.append(sz)
            X = rng.normal(size=(sz, n_feat))
            rel = 0.8 * X[:, 0] - 0.6 * X[:, 1] + rng.normal(scale=0.8, size=sz)
            lab = np.clip(np.digitize(rel, [-0.6, 0.4, 1.4]), 0, 4)
            rows.append(X)
            labels.append(lab)
        _write_tsv(fname, np.concatenate(labels), np.concatenate(rows))
        np.savetxt(qname, np.asarray(qsizes, np.int64), fmt="%d")

    make(n_queries, os.path.join(d, "rank.train"),
         os.path.join(d, "rank.train.query"))
    make(max(20, n_queries // 5), os.path.join(d, "rank.test"),
         os.path.join(d, "rank.test.query"))


def parallel_learning(n_train=4000, n_test=400, n_feat=10, seed=23):
    rng = np.random.default_rng(seed)
    n = n_train + n_test
    X = rng.normal(size=(n, n_feat))
    logit = 2.2 * X[:, 0] - 1.6 * X[:, 1] + 1.2 * X[:, 2] * X[:, 3]
    y = (logit + rng.logistic(size=n) > 0).astype(np.int64)
    d = os.path.join(HERE, "parallel_learning")
    _write_tsv(os.path.join(d, "binary.train"), y[:n_train], X[:n_train])
    _write_tsv(os.path.join(d, "binary.test"), y[n_train:], X[n_train:])


if __name__ == "__main__":
    for sub in ("binary_classification", "regression", "lambdarank",
                "multiclass_classification", "xendcg", "parallel_learning"):
        os.makedirs(os.path.join(HERE, sub), exist_ok=True)
    binary()
    regression()
    lambdarank()
    multiclass()
    xendcg()
    parallel_learning()
    print("example datasets written under", HERE)
