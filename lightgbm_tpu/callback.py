"""Training callbacks (reference ``python-package/lightgbm/callback.py``):
``print_evaluation``/``log_evaluation``, ``record_evaluation``,
``reset_parameter``, ``early_stopping`` — same env-closure protocol."""
from __future__ import annotations

import collections
from typing import Callable, Dict, List

from .utils.log import Log


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list and \
                (env.iteration + 1) % period == 0:
            def fmt(entry):
                # cv passes 5-tuples carrying the across-fold stdv
                if len(entry) == 5 and show_stdv:
                    n, m, v, _, sd = entry
                    return f"{n}'s {m}: {v:g} + {sd:g}"
                n, m, v = entry[0], entry[1], entry[2]
                return f"{n}'s {m}: {v:g}"
            result = "\t".join(
                fmt(e) for e in env.evaluation_result_list)
            Log.info("[%d]\t%s", env.iteration + 1, result)
    _callback.order = 10
    return _callback


log_evaluation = print_evaluation


def record_evaluation(eval_result: Dict) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _init(env: CallbackEnv) -> None:
        eval_result.clear()
        for name, metric, *_ in env.evaluation_result_list:
            eval_result.setdefault(name, collections.OrderedDict())
            eval_result[name].setdefault(metric, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for name, metric, value, *_ in env.evaluation_result_list:
            eval_result.setdefault(name, collections.OrderedDict()).setdefault(
                metric, []).append(value)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(f"Length of list {key} has to equal to 'num_boost_round'.")
                new_params[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_params[key] = value(env.iteration - env.begin_iteration)
            else:
                # reference callback.reset_parameter: anything else is a
                # user error, not a silent no-op
                raise ValueError(
                    "Only list and callable values are supported "
                    f"as a mapping from boosting round index to new "
                    f"parameter value (got {type(value).__name__} for "
                    f"{key!r}).")
        if new_params:
            # route through Booster.reset_parameter so compile-time grower
            # params (num_leaves, min_data_in_leaf, ...) genuinely re-apply
            # (reference model.reset_parameter(new_parameters))
            env.model.reset_parameter(new_params)
            for k, v in new_params.items():
                env.params[k] = v
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[list] = []
    cmp_op: List[Callable] = []
    enabled = [True]
    first_metric = [""]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = not any(env.params.get(alias, "") == "dart"
                             for alias in ("boosting", "boosting_type", "boost"))
        if not enabled[0]:
            Log.warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError("For early stopping, at least one dataset and eval metric is required for evaluation")
        if verbose:
            Log.info("Training until validation scores don't improve for %d rounds", stopping_rounds)
        # cv entries carry composite "<set> <metric>" keys; compare
        # bare metric names (reference .split(" ")[-1])
        first_metric[0] = env.evaluation_result_list[0][1].split(" ")[-1]
        for entry in env.evaluation_result_list:
            name, metric, higher_better = entry[0], entry[1], entry[3]
            best_iter.append(0)
            best_score_list.append(None)
            if higher_better:
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y)

    def _callback(env: CallbackEnv) -> None:
        if not cmp_op:
            _init(env)
        if not enabled[0]:
            return
        # CVBooster's __getattr__ fabricates a handler for any attribute, so
        # only trust a real string here (cv's train rows are the cv_agg case)
        train_name = getattr(env.model, "_train_data_name", "training")
        if not isinstance(train_name, str):
            train_name = "training"
        for i, entry in enumerate(env.evaluation_result_list):
            name, metric, score = entry[0], entry[1], entry[2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            if first_metric_only and first_metric[0] != metric.split(" ")[-1]:
                continue
            if (name == "training" or name == train_name
                    or (name == "cv_agg" and metric.startswith("train"))):
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    Log.info("Early stopping, best iteration is: [%d]", best_iter[i] + 1)
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    Log.info("Did not meet early stopping. Best iteration is: [%d]", best_iter[i] + 1)
                raise EarlyStopException(best_iter[i], best_score_list[i])
    _callback.order = 30
    return _callback
