// Native data-ingest runtime for lightgbm_tpu.
//
// TPU-native analog of the reference's C++ ingest pipeline: the text parsers
// (src/io/parser.cpp — CSV/TSV/LibSVM), the pipelined file reader
// (include/LightGBM/utils/pipeline_reader.h) and the feature-extraction hot
// loop (DatasetLoader::ExtractFeaturesFromFile, src/io/dataset_loader.cpp:1254),
// re-designed as a flat C ABI for ctypes: the host parses + bins with
// std::thread row-block parallelism, then hands dense arrays straight to
// device upload (no per-row virtual dispatch, no FeatureGroup push path).
//
// Exposed entry points (all extern "C"):
//   ParseDelimited  — CSV/TSV -> dense double matrix (+count pass)
//   ParseLibSVM     — sparse text -> dense double matrix
//   BinValues       — raw doubles -> per-feature bin ids (uint16) via
//                     upper-bound binary search (BinMapper::ValueToBin,
//                     include/LightGBM/bin.h:464-502)
#include <atomic>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

int hardware_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n == 0 ? 4 : static_cast<int>(n);
}

// fast strtod-compatible float parse; falls back to strtod for exotic forms
inline double fast_atof(const char* p, const char** end) {
  while (*p == ' ' || *p == '\t') ++p;
  bool neg = false;
  if (*p == '-') { neg = true; ++p; }
  else if (*p == '+') { ++p; }
  if ((p[0] == 'n' || p[0] == 'N') && (p[1] == 'a' || p[1] == 'A')) {
    *end = p + 3;
    return std::nan("");
  }
  double value = 0.0;
  int digits = 0;
  while (*p >= '0' && *p <= '9') {
    value = value * 10.0 + (*p - '0');
    ++p; ++digits;
  }
  if (*p == '.') {
    ++p;
    double frac = 0.1;
    while (*p >= '0' && *p <= '9') {
      value += (*p - '0') * frac;
      frac *= 0.1;
      ++p; ++digits;
    }
  }
  if (digits == 0) {  // not a plain number; delegate
    char* e;
    double v = std::strtod(p, &e);
    *end = e;
    return neg ? -v : v;
  }
  if (*p == 'e' || *p == 'E') {
    ++p;
    bool eneg = false;
    if (*p == '-') { eneg = true; ++p; }
    else if (*p == '+') { ++p; }
    int ex = 0;
    while (*p >= '0' && *p <= '9') { ex = ex * 10 + (*p - '0'); ++p; }
    value *= std::pow(10.0, eneg ? -ex : ex);
  }
  *end = p;
  return neg ? -value : value;
}

// read whole file into memory (the reference double-buffers via
// PipelineReader; a single read keeps the ABI simple and saturates page
// cache for benchmark-sized files)
bool read_file(const char* path, std::vector<char>* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  out->resize(static_cast<size_t>(size) + 1);
  size_t got = std::fread(out->data(), 1, static_cast<size_t>(size), f);
  std::fclose(f);
  if (got != static_cast<size_t>(size)) return false;
  (*out)[got] = '\0';
  return true;
}

// ---- PipelineReader: double-buffered read-ahead --------------------------
// Analog of the reference's PipelineReader
// (include/LightGBM/utils/pipeline_reader.h): a background thread reads
// section k+1 while the caller parses section k, so IO and parsing overlap
// and peak memory is two sections, not the whole file.
class PipelineReader {
 public:
  PipelineReader(const char* path, size_t section_bytes)
      : f_(std::fopen(path, "rb")), section_(section_bytes) {}
  ~PipelineReader() {
    if (io_.joinable()) io_.join();
    if (f_) std::fclose(f_);
  }
  bool ok() const { return f_ != nullptr; }
  // true if any fread failed mid-stream (EOF is not an error)
  bool io_error() const { return error_; }

  // Hand the caller the next section; the following section's read is
  // already in flight when this returns.  False at EOF.  The returned
  // pointer stays valid until the next acquire() call.
  bool acquire(const char** data, size_t* n) {
    if (!started_) {
      fill(front_);
      started_ = true;
    } else {
      if (io_.joinable()) io_.join();   // no thread after a short-read skip
      front_ ^= 1;              // the prefetched buffer becomes current
    }
    if (len_[front_] == 0) {
      len_[front_ ^ 1] = 0;     // EOF is sticky: further acquires stay false
      return false;
    }
    *data = buf_[front_].data();
    *n = len_[front_];
    // a short read means EOF was reached: the prefetch would only perform a
    // guaranteed zero-byte fread, so don't spawn it
    if (len_[front_] == section_) {
      int back = front_ ^ 1;
      io_ = std::thread([this, back] { fill(back); });
    } else {
      len_[front_ ^ 1] = 0;
    }
    return true;
  }

 private:
  void fill(int idx) {
    buf_[idx].resize(section_);
    len_[idx] = f_ ? std::fread(buf_[idx].data(), 1, section_, f_) : 0;
    if (f_ && len_[idx] < section_ && std::ferror(f_)) error_ = true;
  }
  FILE* f_;
  size_t section_;
  std::vector<char> buf_[2];
  size_t len_[2] = {0, 0};
  int front_ = 0;
  bool started_ = false;
  bool error_ = false;
  std::thread io_;
};

// mutable: tests shrink it via SetParserSectionBytes to stress boundaries
size_t g_section_bytes = 64 << 20;           // two in flight -> 128MB peak

// newline-aligned split of [0, len) into nt chunks
std::vector<size_t> chunk_starts(const char* buf, size_t len, int nt) {
  std::vector<size_t> starts{0};
  for (int t = 1; t < nt; ++t) {
    size_t pos = len * static_cast<size_t>(t) / nt;
    while (pos < len && buf[pos] != '\n') ++pos;
    if (pos < len) ++pos;
    starts.push_back(pos);
  }
  starts.push_back(len);
  return starts;
}

}  // namespace

extern "C" {

// Test hook: override the pipeline section size (0 restores the default).
void SetParserSectionBytes(int64_t n) {
  g_section_bytes = n > 0 ? static_cast<size_t>(n) : (64 << 20);
}

// First pass: count data rows and columns, streamed through the pipelined
// reader (no whole-file buffer).  Returns 0 on success.
int CountDelimited(const char* path, char delim, int skip_rows,
                   int64_t* out_rows, int64_t* out_cols) {
  PipelineReader reader(path, g_section_bytes);
  if (!reader.ok()) return 1;
  int64_t rows = 0, cols = 0;
  int skipped = 0;
  std::vector<char> carry;
  const char* data;
  size_t n;
  auto count_line = [&](const char* p, const char* line_end) {
    if (line_end <= p) return;               // empty line
    if (skipped < skip_rows) {
      ++skipped;
      return;
    }
    if (rows == 0) {
      cols = 1;
      for (const char* q = p; q < line_end; ++q)
        if (*q == delim) ++cols;
    }
    ++rows;
  };
  while (reader.acquire(&data, &n)) {
    const char* p = data;
    const char* end = data + n;
    if (!carry.empty()) {
      // finish the line split across the section boundary
      const char* nl = static_cast<const char*>(std::memchr(p, '\n', n));
      size_t take = nl ? static_cast<size_t>(nl - p) : n;
      carry.insert(carry.end(), p, p + take);
      if (!nl) continue;                     // line still not complete
      count_line(carry.data(), carry.data() + carry.size());
      carry.clear();
      p = nl + 1;
    }
    while (p < end) {
      const char* nl = static_cast<const char*>(
          std::memchr(p, '\n', static_cast<size_t>(end - p)));
      if (!nl) {
        carry.assign(p, end);
        break;
      }
      count_line(p, nl);
      p = nl + 1;
    }
  }
  if (reader.io_error()) return 1;
  if (!carry.empty())
    count_line(carry.data(), carry.data() + carry.size());
  *out_rows = rows;
  *out_cols = cols;
  return 0;
}

namespace {

// Parse the newline-terminated region [base, base+len) into out rows
// starting at row_off; thread-parallel over newline-aligned chunks.
// Returns the number of rows parsed.
int64_t parse_region(const char* base, size_t len, char delim, int64_t rows,
                     int64_t cols, int64_t row_off, double* out) {
  if (len == 0) return 0;
  int nt = hardware_threads();
  auto starts = chunk_starts(base, len, nt);
  std::vector<int64_t> row_at(nt + 1, 0);
  for (int t = 0; t < nt; ++t) {
    // count NON-BLANK lines only — the parse loop skips blank lines, so
    // counting raw newlines would drift every later row's offset
    int64_t cnt = 0;
    const char* p = base + starts[t];
    const char* cend = base + starts[t + 1];
    while (p < cend) {
      const char* nl = static_cast<const char*>(
          std::memchr(p, '\n', static_cast<size_t>(cend - p)));
      if (!nl) nl = cend;
      if (nl > p) ++cnt;
      p = nl + 1;
    }
    row_at[t + 1] = row_at[t] + cnt;
  }
  std::vector<std::thread> ths;
  for (int t = 0; t < nt; ++t) {
    ths.emplace_back([&, t]() {
      const char* p = base + starts[t];
      const char* chunk_end = base + starts[t + 1];
      int64_t r = row_off + row_at[t];
      while (p < chunk_end && r < rows) {
        const char* line_end = static_cast<const char*>(
            std::memchr(p, '\n', static_cast<size_t>(chunk_end - p)));
        if (!line_end) line_end = chunk_end;
        if (line_end > p) {
          double* dst = out + r * cols;
          const char* q = p;
          for (int64_t c = 0; c < cols; ++c) {
            const char* e;
            dst[c] = fast_atof(q, &e);
            q = e;
            while (q < line_end && *q != delim) ++q;
            if (q < line_end) ++q;
          }
          ++r;
        }
        p = line_end + 1;
      }
    });
  }
  for (auto& th : ths) th.join();
  return row_at[nt];
}

}  // namespace

// Second pass: parse into the caller-allocated [rows, cols] matrix.
// Sections stream through the PipelineReader (IO overlapped with parsing);
// within a section, parsing is thread-parallel over newline-aligned chunks.
int ParseDelimited(const char* path, char delim, int skip_rows,
                   int64_t rows, int64_t cols, double* out) {
  PipelineReader reader(path, g_section_bytes);
  if (!reader.ok()) return 1;
  int to_skip = skip_rows;
  int64_t row_off = 0;
  std::vector<char> carry;                    // partial tail line
  const char* data;
  size_t n;
  while (reader.acquire(&data, &n)) {
    const char* p = data;
    const char* end = data + n;
    // skip header rows (may span sections)
    while (to_skip > 0 && p < end) {
      const char* nl = static_cast<const char*>(
          std::memchr(p, '\n', static_cast<size_t>(end - p)));
      if (!nl) { p = end; break; }
      p = nl + 1;
      --to_skip;
    }
    if (p >= end) continue;
    if (!carry.empty()) {
      const char* nl = static_cast<const char*>(
          std::memchr(p, '\n', static_cast<size_t>(end - p)));
      size_t take = nl ? static_cast<size_t>(nl - p) + 1
                       : static_cast<size_t>(end - p);
      carry.insert(carry.end(), p, p + take);
      if (!nl) continue;                      // line still incomplete
      row_off += parse_region(carry.data(), carry.size(), delim, rows, cols,
                              row_off, out);
      carry.clear();
      p += take;
    }
    // parse up to the last complete line; keep the tail for the next section
    const char* last_nl = nullptr;
    for (const char* q = end; q > p; --q) {
      if (q[-1] == '\n') { last_nl = q; break; }
    }
    if (!last_nl) {
      carry.assign(p, end);
      continue;
    }
    row_off += parse_region(p, static_cast<size_t>(last_nl - p), delim, rows,
                            cols, row_off, out);
    if (last_nl < end) carry.assign(last_nl, end);
  }
  if (reader.io_error()) return 1;
  if (!carry.empty())
    parse_region(carry.data(), carry.size(), delim, rows, cols, row_off, out);
  return 0;
}

// LibSVM: "label idx:val idx:val ...".  Single pass to find dims, then
// parallel fill.  out must be [rows, max_feature+1] zero-initialised by the
// caller after calling CountLibSVM.
int CountLibSVM(const char* path, int64_t* out_rows, int64_t* out_cols) {
  std::vector<char> buf;
  if (!read_file(path, &buf)) return 1;
  const char* p = buf.data();
  const char* end = p + buf.size() - 1;
  int64_t rows = 0, max_feat = -1;
  while (p < end) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(end - p)));
    if (!line_end) line_end = end;
    if (line_end > p) {
      ++rows;
      for (const char* q = p; q < line_end; ++q) {
        if (*q == ':') {
          const char* d = q;
          while (d > p && std::isdigit(*(d - 1))) --d;
          int64_t idx = std::strtoll(d, nullptr, 10);
          if (idx > max_feat) max_feat = idx;
        }
      }
    }
    p = line_end + 1;
  }
  *out_rows = rows;
  *out_cols = max_feat + 1;
  return 0;
}

int ParseLibSVM(const char* path, int64_t rows, int64_t cols,
                double* out, double* labels) {
  std::vector<char> buf;
  if (!read_file(path, &buf)) return 1;
  const char* base = buf.data();
  size_t len = buf.size() - 1;
  int nt = hardware_threads();
  auto starts = chunk_starts(base, len, nt);
  std::vector<int64_t> row_at(nt + 1, 0);
  for (int t = 0; t < nt; ++t) {
    int64_t cnt = 0;
    for (size_t p = starts[t]; p < starts[t + 1]; ++p)
      if (base[p] == '\n') ++cnt;
    if (t == nt - 1 && starts[t + 1] > starts[t] &&
        base[starts[t + 1] - 1] != '\n')
      ++cnt;
    row_at[t + 1] = row_at[t] + cnt;
  }
  std::vector<std::thread> ths;
  for (int t = 0; t < nt; ++t) {
    ths.emplace_back([&, t]() {
      const char* p = base + starts[t];
      const char* chunk_end = base + starts[t + 1];
      int64_t r = row_at[t];
      while (p < chunk_end && r < rows) {
        const char* line_end = static_cast<const char*>(
            std::memchr(p, '\n', static_cast<size_t>(chunk_end - p)));
        if (!line_end) line_end = chunk_end;
        if (line_end > p) {
          const char* e;
          labels[r] = fast_atof(p, &e);
          const char* q = e;
          double* dst = out + r * cols;
          while (q < line_end) {
            while (q < line_end && (*q == ' ' || *q == '\t')) ++q;
            if (q >= line_end) break;
            char* colon_end;
            int64_t idx = std::strtoll(q, &colon_end, 10);
            if (*colon_end != ':') break;
            const char* v = colon_end + 1;
            double val = fast_atof(v, &e);
            if (idx >= 0 && idx < cols) dst[idx] = val;
            q = e;
          }
          ++r;
        }
        p = line_end + 1;
      }
    });
  }
  for (auto& th : ths) th.join();
  return 0;
}

// raw values -> bin ids.  Per feature: upper-bound binary search over
// bin_uppers[offsets[f] : offsets[f+1]] (BinMapper::ValueToBin semantics:
// first bin whose upper bound >= value); NaN maps to nan_bin[f] when >= 0,
// else to default_bin[f].  Categorical features (is_cat[f]) map value v to
// cat_bin via a per-feature hash-free table lookup is done Python-side —
// here cat features use the same searchsorted over sorted category values
// encoded in bin_uppers with bin ids in cat_perm.
int BinValues(const double* data, int64_t rows, int64_t cols,
              const double* bin_uppers, const int64_t* offsets,
              const int32_t* nan_bins, const int32_t* default_bins,
              const uint8_t* is_cat, const int32_t* cat_perm,
              uint16_t* out) {
  int nt = hardware_threads();
  std::vector<std::thread> ths;
  int64_t block = (rows + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    int64_t r0 = t * block;
    int64_t r1 = std::min(rows, r0 + block);
    if (r0 >= r1) break;
    ths.emplace_back([=]() {
      for (int64_t r = r0; r < r1; ++r) {
        const double* row = data + r * cols;
        uint16_t* dst = out + r * cols;
        for (int64_t f = 0; f < cols; ++f) {
          double v = row[f];
          int64_t lo = offsets[f], hi = offsets[f + 1];
          int64_t nb = hi - lo;
          if (std::isnan(v)) {
            dst[f] = static_cast<uint16_t>(
                nan_bins[f] >= 0 ? nan_bins[f] : default_bins[f]);
            continue;
          }
          if (is_cat[f]) {
            // binary search for exact category among sorted values
            int64_t a = 0, b = nb;
            int32_t bin = default_bins[f];
            while (a < b) {
              int64_t m = (a + b) / 2;
              double cv = bin_uppers[lo + m];
              if (cv < v) a = m + 1;
              else if (cv > v) b = m;
              else { bin = cat_perm[lo + m]; break; }
            }
            dst[f] = static_cast<uint16_t>(bin < 0 ? 0 : bin);
            continue;
          }
          // first bin whose upper bound >= v (searchsorted 'left')
          int64_t a = 0, b = nb - 1;
          while (a < b) {
            int64_t m = (a + b) / 2;
            if (bin_uppers[lo + m] < v) a = m + 1;
            else b = m;
          }
          dst[f] = static_cast<uint16_t>(a);
        }
      }
    });
  }
  for (auto& th : ths) th.join();
  return 0;
}

}  // extern "C"
