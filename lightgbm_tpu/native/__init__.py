"""Native (C++) ingest runtime: fast parallel text parsing and binning.

Loads ``parser.cpp`` as a shared object via ctypes, building it with g++ on
first use (cached beside the source; rebuilt when the source is newer).
Every entry point has a pure-numpy fallback in ``io/`` — the native path is
an accelerator, not a dependency (the reference's equivalent machinery is
``src/io/parser.cpp`` + ``DatasetLoader::ExtractFeatures*``, which is
mandatory C++; here Python remains the source of truth for semantics and
the C++ is held to byte-identical outputs by tests).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "parser.cpp")
_SO = os.path.join(_DIR, "_parser.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", _SO]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None when unavailable."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    with _lib_lock:
        if _lib is not None:
            return _lib
        need_build = (not os.path.exists(_SO) or
                      os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        if need_build and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _build_failed = True
            return None
        i64p = ctypes.POINTER(ctypes.c_int64)
        f64p = ctypes.POINTER(ctypes.c_double)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u16p = ctypes.POINTER(ctypes.c_uint16)
        lib.SetParserSectionBytes.argtypes = [ctypes.c_int64]
        lib.SetParserSectionBytes.restype = None
        lib.CountDelimited.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                       ctypes.c_int, i64p, i64p]
        lib.ParseDelimited.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                       ctypes.c_int, ctypes.c_int64,
                                       ctypes.c_int64, f64p]
        lib.CountLibSVM.argtypes = [ctypes.c_char_p, i64p, i64p]
        lib.ParseLibSVM.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_int64, f64p, f64p]
        lib.BinValues.argtypes = [f64p, ctypes.c_int64, ctypes.c_int64,
                                  f64p, i64p, i32p, i32p, u8p, i32p, u16p]
        for fn in ("CountDelimited", "ParseDelimited", "CountLibSVM",
                   "ParseLibSVM", "BinValues"):
            getattr(lib, fn).restype = ctypes.c_int
        _lib = lib
        return _lib


def _ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def parse_delimited(path: str, delim: str, skip_rows: int = 0
                    ) -> Optional[np.ndarray]:
    """CSV/TSV -> dense [rows, cols] float64, or None if native unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    pb = path.encode()
    if lib.CountDelimited(pb, delim.encode(), skip_rows,
                          ctypes.byref(rows), ctypes.byref(cols)):
        return None
    out = np.empty((rows.value, cols.value), np.float64)
    if lib.ParseDelimited(pb, delim.encode(), skip_rows, rows.value,
                          cols.value, _ptr(out, ctypes.c_double)):
        return None
    return out


def parse_libsvm(path: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """LibSVM -> (features [rows, cols], labels [rows]) or None."""
    lib = get_lib()
    if lib is None:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    pb = path.encode()
    if lib.CountLibSVM(pb, ctypes.byref(rows), ctypes.byref(cols)):
        return None
    out = np.zeros((rows.value, cols.value), np.float64)
    labels = np.empty(rows.value, np.float64)
    if lib.ParseLibSVM(pb, rows.value, cols.value,
                       _ptr(out, ctypes.c_double), _ptr(labels, ctypes.c_double)):
        return None
    return out, labels


def bin_values(data: np.ndarray, mappers, used_features) -> Optional[np.ndarray]:
    """Raw [n, F_total] float64 -> binned [n, F_used] uint16 using the
    per-feature BinMappers; None if native unavailable.  Semantics match
    ``BinMapper.value_to_bin`` exactly (tests enforce equality)."""
    lib = get_lib()
    if lib is None:
        return None
    from ..io.bin import BinType, MissingType
    cols = len(used_features)
    n = data.shape[0]
    uppers, offsets, nan_bins, default_bins, is_cat, cat_perm = \
        [], [0], [], [], [], []
    for f in used_features:
        m = mappers[f]
        if m.bin_type == BinType.CATEGORICAL:
            cats = np.asarray(m.bin_2_categorical, np.float64)
            order = np.argsort(cats)
            uppers.append(cats[order])
            cat_perm.append(order.astype(np.int32) + 1)
            nan_bins.append(-1)
            default_bins.append(0)
            is_cat.append(1)
        else:
            ub = np.asarray(m.bin_upper_bound, np.float64)
            uppers.append(ub)
            cat_perm.append(np.zeros(len(ub), np.int32))
            nan_bins.append(m.num_bin - 1
                            if m.missing_type == MissingType.NAN else -1)
            default_bins.append(int(np.searchsorted(ub, 0.0, side="left"))
                                if len(ub) else 0)
            is_cat.append(0)
        offsets.append(offsets[-1] + len(uppers[-1]))
    uppers_c = (np.concatenate(uppers) if uppers else np.zeros(0)).astype(np.float64)
    cat_perm_c = (np.concatenate(cat_perm) if cat_perm else
                  np.zeros(0, np.int32)).astype(np.int32)
    offsets_c = np.asarray(offsets, np.int64)
    nan_c = np.asarray(nan_bins, np.int32)
    def_c = np.asarray(default_bins, np.int32)
    cat_c = np.asarray(is_cat, np.uint8)

    sub = np.ascontiguousarray(data[:, list(used_features)], np.float64)
    out = np.empty((n, cols), np.uint16)
    if lib.BinValues(_ptr(sub, ctypes.c_double), n, cols,
                     _ptr(uppers_c, ctypes.c_double),
                     _ptr(offsets_c, ctypes.c_int64),
                     _ptr(nan_c, ctypes.c_int32), _ptr(def_c, ctypes.c_int32),
                     _ptr(cat_c, ctypes.c_uint8),
                     _ptr(cat_perm_c, ctypes.c_int32),
                     _ptr(out, ctypes.c_uint16)):
        return None
    return out


__all__ = ["get_lib", "parse_delimited", "parse_libsvm", "bin_values"]
