"""``python -m lightgbm_tpu`` — the CLI entry point (reference
``src/main.cpp``)."""
import sys

from .application import main

sys.exit(main())
