"""scikit-learn API wrappers.

Analog of the reference ``python-package/lightgbm/sklearn.py`` —
``LGBMModel`` (:180), ``LGBMRegressor`` (:780), ``LGBMClassifier`` (:806),
``LGBMRanker`` (:958) plus the custom objective/eval wrappers (:19,103) —
re-hosted on the TPU engine.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .io.dataset import _is_dataframe, _is_sparse
from .basic import Booster, Dataset
from .engine import train
from .utils.log import LightGBMError

try:
    # real sklearn bases when available: estimator tags (__sklearn_tags__,
    # required by sklearn>=1.6 meta-estimators like GridSearchCV), clone()
    # and repr support all ride the official protocol
    from sklearn.base import BaseEstimator as _SKLBase
    from sklearn.base import ClassifierMixin as _SKLClassifierMixin
    from sklearn.base import RegressorMixin as _SKLRegressorMixin
except ImportError:                                  # sklearn is optional
    _SKLBase = object

    class _SKLClassifierMixin:
        pass

    class _SKLRegressorMixin:
        pass

__all__ = ["LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"]


class _ObjectiveFunctionWrapper:
    """Adapt sklearn-style ``fobj(y_true, y_pred) -> grad, hess`` to the
    engine's ``fobj(preds, dataset)`` (reference ``sklearn.py:19``)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds, dataset.get_group())
        else:
            raise TypeError(f"Self-defined objective should have 2 or 3 arguments, got {argc}")
        return grad, hess


class _EvalFunctionWrapper:
    """Adapt ``feval(y_true, y_pred) -> name, value, higher_better``
    (reference ``sklearn.py:103``)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        elif argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        elif argc == 4:
            return self.func(labels, preds, dataset.get_weight(), dataset.get_group())
        raise TypeError(f"Self-defined eval function should have 2-4 arguments, got {argc}")


class LGBMModel(_SKLBase):
    """Base sklearn estimator (reference ``sklearn.py:180``)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[str] = None, class_weight=None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 silent: bool = True,
                 importance_type: str = "split", **kwargs):
        # ``silent`` sits at the reference's position (sklearn.py:180) so
        # positional callers bind identically; it is estimator state, not a
        # booster param
        self.silent = silent
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params: Dict[str, Any] = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_iteration: int = -1
        self._n_features: int = -1
        self._objective = objective
        self.fitted_ = False

    # -- sklearn protocol ----------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {k: getattr(self, k) for k in (
            "boosting_type", "num_leaves", "max_depth", "learning_rate",
            "n_estimators", "subsample_for_bin", "objective", "class_weight",
            "min_split_gain", "min_child_weight", "min_child_samples",
            "subsample", "subsample_freq", "colsample_bytree", "reg_alpha",
            "reg_lambda", "random_state", "n_jobs", "silent",
            "importance_type")}
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for k, v in params.items():
            if hasattr(self, k) and not k.startswith("_"):
                setattr(self, k, v)
            else:
                self._other_params[k] = v
        return self

    # -- param assembly -------------------------------------------------
    def _lgb_params(self) -> Dict[str, Any]:
        p = {
            "boosting": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            # reference sklearn wrapper: silent picks the verbosity (an
            # explicit verbose/verbosity kwarg in _other_params overrides)
            "verbose": -1 if self.silent else 1,
        }
        if self.random_state is not None:
            p["seed"] = int(self.random_state)
        p.update(self._other_params)
        if callable(self._objective):
            p["objective"] = "none"
        elif self._objective is not None:
            p["objective"] = self._objective
        return p

    def _class_weight_to_sample_weight(self, y, sample_weight):
        if self.class_weight is None:
            return sample_weight
        from sklearn.utils.class_weight import compute_sample_weight
        cw = compute_sample_weight(self.class_weight, y)
        return cw if sample_weight is None else cw * sample_weight

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_group=None, eval_metric=None, early_stopping_rounds=None,
            feature_name="auto", categorical_feature="auto", callbacks=None,
            init_model=None, verbose: Any = False):
        if not _is_sparse(X) and not _is_dataframe(X):
            # DataFrames pass through untouched so Dataset's pandas path
            # (category-dtype -> codes, auto feature names) applies;
            # non-pandas frame look-alikes contribute their .values
            X = np.asarray(getattr(X, "values", X), dtype=np.float64)
        y = np.asarray(y).ravel()
        self._n_features = X.shape[1]
        params = self._lgb_params()
        if eval_metric is not None and not callable(eval_metric):
            metrics = eval_metric if isinstance(eval_metric, list) else [eval_metric]
            existing = params.get("metric")
            if existing:
                existing = existing if isinstance(existing, list) else [existing]
                metrics = existing + [m for m in metrics if m not in existing]
            params["metric"] = metrics

        fobj = _ObjectiveFunctionWrapper(self._objective) if callable(self._objective) else None
        feval = _EvalFunctionWrapper(eval_metric) if callable(eval_metric) else None

        sample_weight = self._class_weight_to_sample_weight(y, sample_weight)
        train_set = Dataset(X, label=y, weight=sample_weight, group=group,
                            init_score=init_score, params=params,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature)
        valid_sets, valid_names = [], []
        if eval_set is not None:
            for i, (vX, vy) in enumerate(eval_set):
                if not _is_sparse(vX) and not _is_dataframe(vX):
                    # DataFrames stay intact: Dataset(reference=train_set)
                    # re-codes category dtypes against the training mapping
                    vX = np.asarray(getattr(vX, "values", vX), dtype=np.float64)
                vy = np.asarray(vy).ravel()
                same_X = vX is X or (not _is_sparse(vX) and not _is_dataframe(vX)
                                     and not _is_sparse(X) and not _is_dataframe(X)
                                     and vX.shape == X.shape
                                     and np.array_equal(vX, X))
                # the reference wrapper reuses the train set only when BOTH
                # X and y match (same X with held-out labels is a distinct
                # eval set); compare in encoded space, y is already encoded.
                # A caller-supplied eval weight/group also forces a real
                # eval Dataset — reusing train_set would drop them.
                vy_enc = np.asarray(self._prep_eval_label(vy)).ravel()
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vg = eval_group[i] if eval_group else None
                if (same_X and np.array_equal(vy_enc, y)
                        and vw is None and vg is None):
                    valid_sets.append(train_set)
                else:
                    valid_sets.append(Dataset(vX, label=vy_enc,
                                              weight=vw, group=vg,
                                              reference=train_set))
                valid_names.append(eval_names[i] if eval_names else f"valid_{i}")

        if isinstance(init_model, LGBMModel):
            # reference sklearn wrapper: continued training accepts a
            # filename, a Booster, or another fitted estimator
            init_model = init_model.booster_

        self._evals_result = {}
        self._Booster = train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None, valid_names=valid_names or None,
            fobj=fobj, feval=feval, init_model=init_model,
            early_stopping_rounds=early_stopping_rounds,
            verbose_eval=verbose, evals_result=self._evals_result,
            callbacks=callbacks)
        self._best_iteration = self._Booster.best_iteration
        self.fitted_ = True
        return self

    def _prep_eval_label(self, y):
        return y

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        self._check_fitted()
        if not _is_sparse(X) and not _is_dataframe(X):
            X = np.asarray(getattr(X, "values", X), dtype=np.float64)
        if X.shape[1] != self._n_features:
            raise LightGBMError(
                f"Number of features of the model must match the input. Model "
                f"n_features_ is {self._n_features} and input n_features is {X.shape[1]}")
        ni = num_iteration if num_iteration is not None else (
            self._best_iteration if self._best_iteration > 0 else -1)
        return self._Booster.predict(X, raw_score=raw_score,
                                     start_iteration=start_iteration,
                                     num_iteration=ni, pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib, **kwargs)

    def _check_fitted(self):
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, call fit before exploiting the model.")

    # -- fitted attributes ---------------------------------------------
    @property
    def booster_(self) -> Booster:
        self._check_fitted()
        return self._Booster

    @property
    def evals_result_(self) -> Dict:
        self._check_fitted()
        return self._evals_result

    @property
    def best_iteration_(self) -> int:
        self._check_fitted()
        return self._best_iteration

    @property
    def best_score_(self) -> Dict:
        self._check_fitted()
        return self._Booster.best_score

    @property
    def n_features_(self) -> int:
        self._check_fitted()
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        return self.n_features_

    @property
    def feature_importances_(self) -> np.ndarray:
        self._check_fitted()
        return self._Booster.feature_importance(importance_type=self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        self._check_fitted()
        return self._Booster.feature_name()

    @property
    def objective_(self):
        self._check_fitted()
        return self._objective if self._objective is not None else self._default_objective()

    def _default_objective(self) -> str:
        return "regression"

    def __sklearn_is_fitted__(self) -> bool:
        return self.fitted_


class LGBMRegressor(_SKLRegressorMixin, LGBMModel):
    """LightGBM regressor (reference ``sklearn.py:780``)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        if self._objective is None:
            self._objective = "regression"

    def _default_objective(self):
        return "regression"

    def score(self, X, y, sample_weight=None):
        from sklearn.metrics import r2_score
        return r2_score(y, self.predict(X), sample_weight=sample_weight)


class LGBMClassifier(_SKLClassifierMixin, LGBMModel):
    """LightGBM classifier (reference ``sklearn.py:806``)."""

    def fit(self, X, y, **kwargs):
        y = np.asarray(y).ravel()
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        self._class_map = {c: i for i, c in enumerate(self._classes)}
        y_enc = np.searchsorted(self._classes, y).astype(np.float64)
        self._resolve_classification_objective()
        return super().fit(X, y_enc, **kwargs)

    def _resolve_classification_objective(self) -> None:
        """Default/upgrade the objective from ``_n_classes`` (binary vs
        multiclass + ``num_class``).  ONE copy, shared with the
        distributed ``DistLGBMClassifier`` so the two fits cannot resolve
        the same data to different objectives."""
        if self._objective is None or (isinstance(self._objective, str)
                                       and self._objective in ("binary", "multiclass", "multiclassova")):
            if self._n_classes > 2:
                if not isinstance(self._objective, str) or self._objective == "binary":
                    self._objective = "multiclass"
                self._other_params["num_class"] = self._n_classes
            elif self._objective is None:
                self._objective = "binary"

    def _prep_eval_label(self, y):
        return np.searchsorted(self._classes, np.asarray(y).ravel()).astype(np.float64)

    def _default_objective(self):
        return "binary"

    def predict(self, X, raw_score: bool = False, **kwargs):
        result = self.predict_proba(X, raw_score=raw_score, **kwargs)
        if raw_score or kwargs.get("pred_leaf") or kwargs.get("pred_contrib"):
            return result
        if result.ndim > 1 and result.shape[1] > 1:
            return self._classes[np.argmax(result, axis=1)]
        return self._classes[(result > 0.5).astype(np.int64)]

    def predict_proba(self, X, raw_score: bool = False, **kwargs):
        self._check_fitted()
        result = super().predict(X, raw_score=raw_score, **kwargs)
        if raw_score or kwargs.get("pred_leaf") or kwargs.get("pred_contrib"):
            return result
        if result.ndim == 1:
            return np.vstack([1.0 - result, result]).T
        return result

    def score(self, X, y, sample_weight=None):
        from sklearn.metrics import accuracy_score
        return accuracy_score(y, self.predict(X), sample_weight=sample_weight)

    @property
    def classes_(self) -> np.ndarray:
        self._check_fitted()
        return self._classes

    @property
    def n_classes_(self) -> int:
        self._check_fitted()
        return self._n_classes


class LGBMRanker(LGBMModel):
    """LightGBM ranker (reference ``sklearn.py:958``)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        if self._objective is None:
            self._objective = "lambdarank"

    def _default_objective(self):
        return "lambdarank"

    def fit(self, X, y, group=None, eval_set=None, eval_group=None, **kwargs):
        if group is None:
            raise LightGBMError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise LightGBMError("Eval_group cannot be None when eval_set is not None")
        return super().fit(X, y, group=group, eval_set=eval_set,
                           eval_group=eval_group, **kwargs)
