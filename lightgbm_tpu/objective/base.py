"""Objective function interface.

Analog of the reference ``ObjectiveFunction``
(``include/LightGBM/objective_function.h``): per-row gradients/hessians from
scores, automatic initial score (``BoostFromScore``), output transform
(``ConvertOutput``) and optional leaf-output renewal for L1-style objectives
(``RenewTreeOutput``).  Gradient math is pure ``jax.numpy`` so it fuses into
the boosting step's compiled program.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config


class ObjectiveFunction:
    name: str = "base"

    def __init__(self, config: Config):
        self.config = config
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None

    # -- lifecycle ------------------------------------------------------
    def init(self, metadata, num_data: int) -> None:
        """Bind dataset metadata (reference ``ObjectiveFunction::Init``)."""
        self.num_data = num_data
        self.label = metadata.label
        self.weight = metadata.weight
        self.query_boundaries = metadata.query_boundaries

    # -- core -----------------------------------------------------------
    def get_gradients(self, score: jax.Array, label: jax.Array,
                      weight: Optional[jax.Array]) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def boost_from_score(self, class_id: int = 0) -> float:
        """Initial constant score (reference ``BoostFromScore``); 0 if the
        objective does not support boosting from average."""
        return 0.0

    def convert_output(self, score):
        return score

    @property
    def num_model_per_iteration(self) -> int:
        return 1

    @property
    def is_constant_hessian(self) -> bool:
        return False

    def need_renew_tree_output(self) -> bool:
        return False

    def renew_leaf_values(self, leaf_pred: np.ndarray, score: np.ndarray,
                          leaf_values: np.ndarray, num_leaves: int) -> np.ndarray:
        """Percentile re-fit of leaf outputs (reference ``RenewTreeOutput``,
        used by L1/quantile/MAPE)."""
        return leaf_values

    def _weights(self, n: int):
        return self.weight if self.weight is not None else None


def _percentile_of(values: np.ndarray, weights: Optional[np.ndarray], alpha: float) -> float:
    """Weighted percentile (reference ``PercentileFun``/``WeightedPercentileFun``,
    ``regression_objective.hpp:23-70``)."""
    if len(values) == 0:
        return 0.0
    order = np.argsort(values)
    v = values[order]
    if weights is None:
        # reference PercentileFun: linear interpolation on positions
        pos = alpha * (len(v) - 1)
        lo = int(np.floor(pos))
        hi = min(lo + 1, len(v) - 1)
        return float(v[lo] + (pos - lo) * (v[hi] - v[lo]))
    w = weights[order]
    cw = np.cumsum(w)
    threshold = alpha * cw[-1]
    idx = int(np.searchsorted(cw, threshold))
    return float(v[min(idx, len(v) - 1)])
