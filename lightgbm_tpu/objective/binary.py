"""Binary classification objective (reference
``src/objective/binary_objective.hpp``): sigmoid-parameterized logloss with
class weighting (``scale_pos_weight`` / ``is_unbalance``)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import ObjectiveFunction
from ..utils.log import Log


class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def __init__(self, config, is_unbalance=None):
        super().__init__(config)
        self.sigmoid = config.sigmoid
        self.is_unbalance = config.is_unbalance if is_unbalance is None else is_unbalance
        self.scale_pos_weight = config.scale_pos_weight
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid parameter %f should be greater than zero", self.sigmoid)
        self.label_weights = (1.0, 1.0)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = self.label
        if lbl is None:
            return
        cnt_pos = float(np.sum(lbl > 0))
        cnt_neg = float(len(lbl) - cnt_pos)
        if cnt_pos == 0 or cnt_neg == 0:
            Log.warning("Contains only one class")
        # is_unbalance: weight classes inversely to frequency (binary_objective.hpp:70)
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            # the MINORITY class is weighted up (binary_objective.hpp:82-89:
            # label_weights_[1] is the positive-class weight)
            if cnt_pos > cnt_neg:
                self.label_weights = (cnt_pos / cnt_neg, 1.0)
            else:
                self.label_weights = (1.0, cnt_neg / cnt_pos)
        else:
            self.label_weights = (1.0, self.scale_pos_weight)
        self.cnt_pos, self.cnt_neg = cnt_pos, cnt_neg

    def get_gradients(self, score, label, weight):
        is_pos = label > 0
        y = jnp.where(is_pos, 1.0, -1.0)
        lw = jnp.where(is_pos, self.label_weights[1], self.label_weights[0])
        response = -y * self.sigmoid / (1.0 + jnp.exp(y * self.sigmoid * score))
        abs_response = jnp.abs(response)
        grad = response * lw
        hess = abs_response * (self.sigmoid - abs_response) * lw
        if weight is not None:
            grad, hess = grad * weight, hess * weight
        return grad, hess

    def boost_from_score(self, class_id=0):
        if self.label is None:
            return 0.0
        if self.weight is not None:
            pavg = float(np.sum(self.weight * (self.label > 0)) / np.sum(self.weight))
        else:
            pavg = self.cnt_pos / max(1.0, self.cnt_pos + self.cnt_neg)
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        init = np.log(pavg / (1.0 - pavg)) / self.sigmoid
        Log.info("[%s:BoostFromScore]: pavg=%f -> initscore=%f", self.name, pavg, init)
        return float(init)

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * score))
