"""Objective factory (reference ``src/objective/objective_function.cpp:16-48``)."""
from __future__ import annotations

from typing import Optional

from ..config import Config
from ..utils.log import Log
from .base import ObjectiveFunction
from .binary import BinaryLogloss
from .multiclass import MulticlassSoftmax, MulticlassOVA
from .regression import (RegressionL2Loss, RegressionL1Loss, HuberLoss,
                         FairLoss, PoissonLoss, QuantileLoss, MAPELoss,
                         GammaLoss, TweedieLoss)

_REGISTRY = {
    "regression": RegressionL2Loss,
    "regression_l1": RegressionL1Loss,
    "huber": HuberLoss,
    "fair": FairLoss,
    "poisson": PoissonLoss,
    "quantile": QuantileLoss,
    "mape": MAPELoss,
    "gamma": GammaLoss,
    "tweedie": TweedieLoss,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
}


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    name = config.objective
    if name == "none":
        return None
    # ranking / xentropy objectives register themselves on import
    if name in ("lambdarank", "rank_xendcg"):
        from . import rank  # noqa: F401
    if name in ("cross_entropy", "cross_entropy_lambda"):
        from . import xentropy  # noqa: F401
    if name not in _REGISTRY:
        Log.fatal("Unknown objective type name: %s", name)
    return _REGISTRY[name](config)


def register_objective(name: str, cls) -> None:
    _REGISTRY[name] = cls


__all__ = ["ObjectiveFunction", "create_objective", "register_objective"]
