"""Regression objectives (reference ``src/objective/regression_objective.hpp``).

Each class mirrors one reference objective's gradient/hessian closed forms:
L2 ``:93``, L1 ``:207``, Huber ``:293``, Fair ``:351``, Poisson ``:398``,
Quantile ``:478``, MAPE ``:576``, Gamma ``:677``, Tweedie ``:712``.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import ObjectiveFunction, _percentile_of


class RegressionL2Loss(ObjectiveFunction):
    name = "regression"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = config.reg_sqrt

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt and self.label is not None:
            self.trans_label = np.sign(self.label) * np.sqrt(np.abs(self.label))
        else:
            self.trans_label = self.label

    def get_gradients(self, score, label, weight):
        grad = score - label
        hess = jnp.ones_like(score)
        if weight is not None:
            grad, hess = grad * weight, hess * weight
        return grad, hess

    def boost_from_score(self, class_id=0):
        lbl = self.trans_label
        if lbl is None:
            return 0.0
        if self.weight is not None:
            return float(np.sum(lbl * self.weight) / np.sum(self.weight))
        return float(np.mean(lbl))

    def convert_output(self, score):
        if self.sqrt:
            return jnp.sign(score) * score * score
        return score

    @property
    def is_constant_hessian(self):
        return self.weight is None


class RegressionL1Loss(RegressionL2Loss):
    name = "regression_l1"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False

    def get_gradients(self, score, label, weight):
        diff = score - label
        grad = jnp.sign(diff)
        hess = jnp.ones_like(score)
        if weight is not None:
            grad, hess = grad * weight, hess * weight
        return grad, hess

    def boost_from_score(self, class_id=0):
        if self.label is None:
            return 0.0
        return _percentile_of(self.label.astype(np.float64), self.weight, 0.5)

    def convert_output(self, score):
        return score

    def need_renew_tree_output(self):
        return True

    def renew_leaf_values(self, leaf_pred, score, leaf_values, num_leaves):
        # median of residuals per leaf (RenewTreeOutput, regression_objective.hpp:254)
        out = leaf_values.copy()
        resid = self.label - score
        for leaf in range(num_leaves):
            rows = leaf_pred == leaf
            if rows.any():
                w = self.weight[rows] if self.weight is not None else None
                out[leaf] = _percentile_of(resid[rows].astype(np.float64), w, 0.5)
        return out

    @property
    def is_constant_hessian(self):
        return self.weight is None


class HuberLoss(RegressionL2Loss):
    name = "huber"

    def __init__(self, config):
        super().__init__(config)
        self.alpha = config.alpha
        self.sqrt = False

    def get_gradients(self, score, label, weight):
        diff = score - label
        grad = jnp.clip(diff, -self.alpha, self.alpha)
        hess = jnp.ones_like(score)
        if weight is not None:
            grad, hess = grad * weight, hess * weight
        return grad, hess

    @property
    def is_constant_hessian(self):
        return self.weight is None


class FairLoss(RegressionL2Loss):
    name = "fair"

    def __init__(self, config):
        super().__init__(config)
        self.c = config.fair_c
        self.sqrt = False

    def get_gradients(self, score, label, weight):
        diff = score - label
        grad = self.c * diff / (jnp.abs(diff) + self.c)
        hess = self.c * self.c / (jnp.abs(diff) + self.c) ** 2
        if weight is not None:
            grad, hess = grad * weight, hess * weight
        return grad, hess

    def boost_from_score(self, class_id=0):
        return 0.0

    @property
    def is_constant_hessian(self):
        return False


class PoissonLoss(RegressionL2Loss):
    name = "poisson"

    def __init__(self, config):
        super().__init__(config)
        self.max_delta_step = config.poisson_max_delta_step
        self.sqrt = False

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.label is not None and np.any(self.label < 0):
            from ..utils.log import Log
            Log.fatal("[poisson]: at least one target label is negative")

    def get_gradients(self, score, label, weight):
        exp_s = jnp.exp(score)
        grad = exp_s - label
        hess = jnp.exp(score + self.max_delta_step)
        if weight is not None:
            grad, hess = grad * weight, hess * weight
        return grad, hess

    def boost_from_score(self, class_id=0):
        mean = super().boost_from_score(class_id)
        return float(np.log(max(mean, 1e-20)))

    def convert_output(self, score):
        return jnp.exp(score)


class QuantileLoss(RegressionL2Loss):
    name = "quantile"

    def __init__(self, config):
        super().__init__(config)
        self.alpha = config.alpha
        self.sqrt = False

    def get_gradients(self, score, label, weight):
        delta = score - label
        grad = jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
        hess = jnp.ones_like(score)
        if weight is not None:
            grad, hess = grad * weight, hess * weight
        return grad, hess

    def boost_from_score(self, class_id=0):
        if self.label is None:
            return 0.0
        return _percentile_of(self.label.astype(np.float64), self.weight, self.alpha)

    def need_renew_tree_output(self):
        return True

    def renew_leaf_values(self, leaf_pred, score, leaf_values, num_leaves):
        out = leaf_values.copy()
        resid = self.label - score
        for leaf in range(num_leaves):
            rows = leaf_pred == leaf
            if rows.any():
                w = self.weight[rows] if self.weight is not None else None
                out[leaf] = _percentile_of(resid[rows].astype(np.float64), w, self.alpha)
        return out

    @property
    def is_constant_hessian(self):
        return self.weight is None


class MAPELoss(RegressionL2Loss):
    name = "mape"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        # per-row 1/|label| factors folded into weights (mape hpp:585)
        lbl = np.abs(self.label.astype(np.float64)) if self.label is not None else None
        base = self.weight if self.weight is not None else 1.0
        self.label_weight = (base / np.maximum(1.0, lbl)) if lbl is not None else None

    def get_gradients(self, score, label, weight):
        lw = jnp.asarray(self.label_weight)
        diff = score - label
        grad = jnp.sign(diff) * lw
        hess = lw
        return grad, hess

    def boost_from_score(self, class_id=0):
        if self.label is None:
            return 0.0
        return _percentile_of(self.label.astype(np.float64),
                              self.label_weight, 0.5)

    def need_renew_tree_output(self):
        return True

    def renew_leaf_values(self, leaf_pred, score, leaf_values, num_leaves):
        out = leaf_values.copy()
        resid = self.label - score
        for leaf in range(num_leaves):
            rows = leaf_pred == leaf
            if rows.any():
                out[leaf] = _percentile_of(resid[rows].astype(np.float64),
                                           self.label_weight[rows], 0.5)
        return out

    @property
    def is_constant_hessian(self):
        return False


class GammaLoss(PoissonLoss):
    name = "gamma"

    def get_gradients(self, score, label, weight):
        grad = 1.0 - label * jnp.exp(-score)
        hess = label * jnp.exp(-score)
        if weight is not None:
            grad, hess = grad * weight, hess * weight
        return grad, hess


class TweedieLoss(PoissonLoss):
    name = "tweedie"

    def __init__(self, config):
        super().__init__(config)
        self.rho = config.tweedie_variance_power

    def get_gradients(self, score, label, weight):
        exp_1 = jnp.exp((1.0 - self.rho) * score)
        exp_2 = jnp.exp((2.0 - self.rho) * score)
        grad = -label * exp_1 + exp_2
        hess = -label * (1.0 - self.rho) * exp_1 + (2.0 - self.rho) * exp_2
        if weight is not None:
            grad, hess = grad * weight, hess * weight
        return grad, hess
