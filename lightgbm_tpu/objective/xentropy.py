"""Cross-entropy objectives over probabilistic labels in [0, 1].

Analog of the reference ``src/objective/xentropy_objective.hpp``:
``CrossEntropy`` (:44) — standard logistic cross-entropy with linear
weights — and ``CrossEntropyLambda`` (:152) — the alternative
parameterisation where the score maps to an intensity
``lambda = log(1 + e^f)`` and weights enter as ``p = 1 - (1-z)^w``.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import ObjectiveFunction
from . import register_objective
from ..utils.log import Log


def _check_unit_interval(label: np.ndarray, name: str) -> None:
    if np.any(label < 0.0) or np.any(label > 1.0):
        Log.fatal("[%s]: label must be in the interval [0, 1]", name)


class CrossEntropy(ObjectiveFunction):
    name = "cross_entropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        _check_unit_interval(self.label, self.name)
        if self.weight is not None:
            if np.min(self.weight) < 0.0:
                Log.fatal("[%s]: at least one weight is negative", self.name)
            if np.sum(self.weight) == 0.0:
                Log.fatal("[%s]: sum of weights is zero", self.name)

    def get_gradients(self, score, label, weight):
        z = 1.0 / (1.0 + jnp.exp(-score))
        grad = z - label
        hess = z * (1.0 - z)
        if weight is not None:
            grad, hess = grad * weight, hess * weight
        return grad, hess

    def boost_from_score(self, class_id=0):
        if self.weight is not None:
            pavg = float(np.sum(self.label * self.weight) / np.sum(self.weight))
        else:
            pavg = float(np.mean(self.label))
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        init = np.log(pavg / (1.0 - pavg))
        Log.info("[%s:BoostFromScore]: pavg=%f -> initscore=%f",
                 self.name, pavg, init)
        return float(init)

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-score))


class CrossEntropyLambda(ObjectiveFunction):
    name = "cross_entropy_lambda"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        _check_unit_interval(self.label, self.name)
        if self.weight is not None and np.min(self.weight) <= 0.0:
            Log.fatal("[%s]: at least one weight is non-positive", self.name)

    def get_gradients(self, score, label, weight):
        if weight is None:
            z = 1.0 / (1.0 + jnp.exp(-score))
            return z - label, z * (1.0 - z)
        # weighted case (xentropy_objective.hpp:199-216)
        w, y = weight, label
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = jnp.exp(-score)
        grad = (1.0 - y / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (d * d)
        d = c - 1.0
        b = (c / (d * d)) * (1.0 + w * epf - c)
        hess = a * (1.0 + y * b)
        return grad, hess

    def boost_from_score(self, class_id=0):
        if self.weight is not None:
            havg = float(np.sum(self.label * self.weight) / np.sum(self.weight))
        else:
            havg = float(np.mean(self.label))
        init = np.log(max(np.exp(havg) - 1.0, 1e-15))
        Log.info("[%s:BoostFromScore]: havg=%f -> initscore=%f",
                 self.name, havg, init)
        return float(init)

    def convert_output(self, score):
        # output is the intensity lambda > 0, NOT a probability
        # (xentropy_objective.hpp:222-234)
        return jnp.log1p(jnp.exp(score))


register_objective("cross_entropy", CrossEntropy)
register_objective("cross_entropy_lambda", CrossEntropyLambda)
register_objective("xentropy", CrossEntropy)
register_objective("xentlambda", CrossEntropyLambda)

__all__ = ["CrossEntropy", "CrossEntropyLambda"]
