"""Learning-to-rank objectives: LambdarankNDCG and RankXENDCG.

TPU-native re-design of the reference ranking objectives
(``src/objective/rank_objective.hpp``; LambdarankNDCG at :98, RankXENDCG at
:284).  The reference iterates queries with OpenMP and runs an O(n^2)
pairwise loop per query; here queries are packed into a fixed ``[Q, L]``
padded layout (L = longest query, rounded up) and the pairwise lambda
accumulation is computed as masked ``[C, L, L]`` broadcast algebra inside an
``lax.map`` over query chunks — all static shapes, one compiled program.

Semantics kept from the reference:
- label gains default ``2^label - 1`` (``dcg_calculator.cpp:33-41``),
  position discount ``1/log2(2+rank)`` (``dcg_calculator.cpp:48-51``).
- per-pair |ΔNDCG| weighting with inverse-max-DCG per query, optional
  score-distance regularisation and total-lambda normalisation when
  ``lambdarank_norm`` (``rank_objective.hpp:164-226``).
- pairs restricted to differing labels with the higher-sorted document above
  ``lambdarank_truncation_level``.
- the sigmoid is computed exactly instead of via the reference's 1M-entry
  lookup table (a CPU-only optimisation, ``rank_objective.hpp:230-259``).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import ObjectiveFunction
from . import register_objective
from ..utils.log import Log, check

#: cap on ranked positions contributing discount (dcg_calculator.cpp:17)
K_MAX_POSITION = 10000


def default_label_gain(max_label: int = 31) -> np.ndarray:
    """``2^i - 1`` gains (reference ``DCGCalculator::DefaultLabelGain``)."""
    g = np.zeros(max_label, np.float64)
    for i in range(1, max_label):
        g[i] = float((1 << i) - 1)
    return g


def check_rank_labels(label: np.ndarray, num_gains: int) -> None:
    """Reference ``DCGCalculator::CheckLabel``."""
    if np.any(np.abs(label - np.round(label)) > 1e-10):
        Log.fatal("label should be int type for ranking task")
    if np.any(label < 0):
        Log.fatal("Label should be non-negative for ranking task")
    if np.any(label >= num_gains):
        Log.fatal("Label is not less than the number of label mappings (%d)",
                  num_gains)


def max_dcg_at_k(k: int, labels: np.ndarray, gains: np.ndarray) -> float:
    """Reference ``DCGCalculator::CalMaxDCGAtK``: ideal DCG using the best-k
    labels in descending order."""
    k = min(k, len(labels))
    if k <= 0:
        return 0.0
    top = np.sort(labels.astype(np.int64))[::-1][:k]
    disc = 1.0 / np.log2(2.0 + np.arange(k))
    return float(np.sum(gains[top] * disc))


def _pad_queries(boundaries: np.ndarray, lane: int = 8):
    """Build the padded [Q, L] gather layout for a query-boundary array."""
    counts = np.diff(boundaries).astype(np.int64)
    Q = len(counts)
    L = int(max(1, counts.max()))
    L = -(-L // lane) * lane                       # round to TPU lane multiple
    # gather index [Q, L] into the flat row space; padded slots point at the
    # query's first row and are masked out
    idx = boundaries[:-1, None] + np.minimum(np.arange(L)[None, :],
                                             np.maximum(counts[:, None] - 1, 0))
    mask = np.arange(L)[None, :] < counts[:, None]
    return idx.astype(np.int32), mask, Q, L, counts


class RankingObjective(ObjectiveFunction):
    """Shared query machinery (reference ``RankingObjective``,
    ``rank_objective.hpp:25``)."""

    def __init__(self, config):
        super().__init__(config)
        self.seed = config.objective_seed

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        check(self.query_boundaries is not None,
              "Ranking tasks require query information")
        bounds = np.asarray(self.query_boundaries, np.int64)
        self._qidx, self._qmask, self.num_queries, self.L, self._counts = \
            _pad_queries(bounds)
        self._qidx_dev = jnp.asarray(self._qidx)
        self._qmask_dev = jnp.asarray(self._qmask)
        # pairwise chunk size bounded so a [C, L, L] f32 block stays ~64MB
        self._chunk = int(min(self.num_queries,
                              max(1, (16 << 20) // (self.L * self.L))))

    def _to_padded(self, flat: jax.Array) -> jax.Array:
        return flat[self._qidx_dev]

    def _scatter_back(self, padded: jax.Array, fill: float = 0.0) -> jax.Array:
        """[Q, L] padded → [N] flat (padded slots dropped via mask)."""
        flat = jnp.zeros(self.num_data, padded.dtype)
        vals = jnp.where(self._qmask_dev, padded, 0.0)
        return flat.at[self._qidx_dev.ravel()].add(vals.ravel())

    @property
    def is_ranking(self) -> bool:
        return True


class LambdarankNDCG(RankingObjective):
    name = "lambdarank"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = config.sigmoid
        self.norm = config.lambdarank_norm
        self.truncation_level = config.lambdarank_truncation_level
        gains = (np.asarray(config.label_gain, np.float64)
                 if config.label_gain else default_label_gain())
        self.label_gain = gains
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid param %f should be greater than zero", self.sigmoid)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        check_rank_labels(self.label, len(self.label_gain))
        # inverse max DCG per query at the truncation level
        # (rank_objective.hpp:124-136)
        inv = np.zeros(self.num_queries, np.float64)
        b = np.asarray(self.query_boundaries)
        for i in range(self.num_queries):
            m = max_dcg_at_k(self.truncation_level, self.label[b[i]:b[i + 1]],
                             self.label_gain)
            inv[i] = 1.0 / m if m > 0 else 0.0
        self._inv_max_dcg = jnp.asarray(inv, jnp.float32)
        self._gain_dev = jnp.asarray(self.label_gain, jnp.float32)
        L = self.L
        disc = np.zeros(L, np.float64)
        upto = min(L, K_MAX_POSITION)
        disc[:upto] = 1.0 / np.log2(2.0 + np.arange(upto))
        self._discount = jnp.asarray(disc, jnp.float32)
        self._grad_fn = jax.jit(functools.partial(_lambdarank_padded,
                                                  sigmoid=float(self.sigmoid),
                                                  norm=bool(self.norm),
                                                  trunc=int(self.truncation_level),
                                                  chunk=self._chunk))

    def get_gradients(self, score, label, weight):
        ps = self._to_padded(score.astype(jnp.float32))
        pl = self._to_padded(label.astype(jnp.float32))
        g_pad, h_pad = self._grad_fn(ps, pl, self._qmask_dev, self._gain_dev,
                                     self._discount, self._inv_max_dcg)
        g = self._scatter_back(g_pad)
        h = self._scatter_back(h_pad)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h


def _lambdarank_padded(ps, pl, mask, gain_table, discount, inv_max_dcg, *,
                       sigmoid: float, norm: bool, trunc: int, chunk: int):
    """Padded-layout lambdarank gradients.

    ps/pl/mask: [Q, L]; returns ([Q, L], [Q, L]) lambdas/hessians in the
    original (unsorted) within-query positions.
    """
    Q, L = ps.shape
    # stable descending sort by score within each query; invalid slots sink
    sort_key = jnp.where(mask, -ps, jnp.inf)
    order = jnp.argsort(sort_key, axis=1, stable=True)          # [Q, L]
    ss = jnp.take_along_axis(ps, order, axis=1)
    sl = jnp.take_along_axis(pl, order, axis=1)
    sm = jnp.take_along_axis(mask, order, axis=1)
    sgain = gain_table[sl.astype(jnp.int32)]

    # best/worst real scores per query, for the norm regulariser
    best = jnp.max(jnp.where(sm, ss, -jnp.inf), axis=1)
    worst = jnp.min(jnp.where(sm, ss, jnp.inf), axis=1)

    n_chunks = -(-Q // chunk)
    pad_q = n_chunks * chunk - Q
    def padq(x, fill=0.0):
        return jnp.concatenate(
            [x, jnp.full((pad_q,) + x.shape[1:], fill, x.dtype)], 0) \
            .reshape(n_chunks, chunk, *x.shape[1:])

    args = (padq(ss), padq(sl), padq(sm.astype(jnp.float32)), padq(sgain),
            padq(inv_max_dcg), padq(best), padq(worst))

    trunc_ok = (jnp.minimum(jnp.arange(L)[:, None], jnp.arange(L)[None, :])
                < trunc)                                          # [L, L]

    def one_chunk(a):
        css, csl, csm, csg, cinv, cbest, cworst = a
        # pair tensors [C, L, L]; axis1 = "a", axis2 = "b"
        delta_s = css[:, :, None] - css[:, None, :]               # s_a - s_b
        high = (csl[:, :, None] > csl[:, None, :])                # a outranks b
        valid = (csm[:, :, None] * csm[:, None, :]) * trunc_ok[None]
        dcg_gap = jnp.abs(csg[:, :, None] - csg[:, None, :])
        pair_disc = jnp.abs(discount[None, :, None] - discount[None, None, :])
        delta_ndcg = dcg_gap * pair_disc * cinv[:, None, None]
        if norm:
            has_range = (cbest != cworst)[:, None, None]
            delta_ndcg = jnp.where(has_range,
                                   delta_ndcg / (0.01 + jnp.abs(delta_s)),
                                   delta_ndcg)
        # p_ab = sigma(s_a - s_b) in the reference's table convention
        p = jax.nn.sigmoid(-sigmoid * delta_s)                    # 1/(1+e^{σΔ})
        lam = sigmoid * delta_ndcg * p                            # ≥ 0
        hes = sigmoid * sigmoid * delta_ndcg * p * (1.0 - p)
        w_high = jnp.where(high, valid, 0.0)                      # a is high
        w_low = jnp.where(high.transpose(0, 2, 1), valid, 0.0)    # a is low
        # high doc pushed up ⇒ negative gradient (rank_objective.hpp:208-213)
        lam_a = -jnp.sum(w_high * lam, 2) + \
            jnp.sum(w_low * lam.transpose(0, 2, 1), 2)
        hes_a = jnp.sum((w_high + w_low) * hes, 2)
        sum_lambdas = jnp.sum(w_high * lam, (1, 2)) * 2.0
        if norm:
            nf = jnp.where(sum_lambdas > 0,
                           jnp.log2(1.0 + sum_lambdas) / jnp.maximum(sum_lambdas, 1e-20),
                           1.0)[:, None]
            lam_a, hes_a = lam_a * nf, hes_a * nf
        return lam_a, hes_a

    lam_s, hes_s = jax.lax.map(one_chunk, args)
    lam_s = lam_s.reshape(n_chunks * chunk, L)[:Q]
    hes_s = hes_s.reshape(n_chunks * chunk, L)[:Q]
    # un-sort back to original within-query positions
    inv_order = jnp.argsort(order, axis=1, stable=True)
    lam = jnp.take_along_axis(lam_s, inv_order, axis=1)
    hes = jnp.take_along_axis(hes_s, inv_order, axis=1)
    return lam, hes


class RankXENDCG(RankingObjective):
    """Cross-entropy surrogate for NDCG, arxiv.org/abs/1911.09798
    (reference ``rank_objective.hpp:284``)."""

    name = "rank_xendcg"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._iter = 0
        self._grad_fn = jax.jit(_xendcg_padded)

    def get_gradients(self, score, label, weight):
        key = jax.random.PRNGKey(self.seed + self._iter * 7919)
        self._iter += 1
        ps = self._to_padded(score.astype(jnp.float32))
        pl = self._to_padded(label.astype(jnp.float32))
        g_pad, h_pad = self._grad_fn(ps, pl, self._qmask_dev, key)
        g = self._scatter_back(g_pad)
        h = self._scatter_back(h_pad)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h


def _xendcg_padded(ps, pl, mask, key):
    """Padded XE-NDCG gradients (reference per-query loop at
    ``rank_objective.hpp:303-357``), vectorised over queries."""
    Q, L = ps.shape
    neg_inf = jnp.float32(-1e30)
    logits = jnp.where(mask, ps, neg_inf)
    rho = jax.nn.softmax(logits, axis=1)
    rho = jnp.where(mask, rho, 0.0)
    # ground-truth distribution terms phi(l, u) = 2^l - u
    u = jax.random.uniform(key, (Q, L))
    params = jnp.where(mask, jnp.exp2(pl) - u, 0.0)
    denom = jnp.maximum(jnp.sum(params, 1, keepdims=True), 1e-10)
    # first-order terms
    t1 = -params / denom + rho
    p1 = jnp.where(mask, t1 / jnp.maximum(1.0 - rho, 1e-10), 0.0)
    s1 = jnp.sum(p1, 1, keepdims=True)
    t2 = rho * (s1 - p1)
    p2 = jnp.where(mask, t2 / jnp.maximum(1.0 - rho, 1e-10), 0.0)
    s2 = jnp.sum(p2, 1, keepdims=True)
    lam = t1 + t2 + rho * (s2 - p2)
    hes = rho * (1.0 - rho)
    # queries with <= 1 document produce zero gradients
    few = (jnp.sum(mask, 1, keepdims=True) <= 1)
    lam = jnp.where(mask & ~few, lam, 0.0)
    hes = jnp.where(mask & ~few, hes, 0.0)
    return lam, hes


register_objective("lambdarank", LambdarankNDCG)
register_objective("rank_xendcg", RankXENDCG)

__all__ = ["LambdarankNDCG", "RankXENDCG", "RankingObjective",
           "default_label_gain", "max_dcg_at_k"]
