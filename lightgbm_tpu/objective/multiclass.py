"""Multiclass objectives (reference ``src/objective/multiclass_objective.hpp``):
softmax (K coupled trees per iteration) and one-vs-all."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import ObjectiveFunction
from .binary import BinaryLogloss
from ..utils.log import Log


class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.label is not None:
            lbl = self.label.astype(np.int32)
            if lbl.min() < 0 or lbl.max() >= self.num_class:
                Log.fatal("Label must be in [0, %d) for multiclass objective", self.num_class)

    @property
    def num_model_per_iteration(self):
        return self.num_class

    def get_gradients_multi(self, score, label, weight):
        """score: [K, N]; returns ([K, N], [K, N])."""
        p = jnp.exp(score - jnp.max(score, axis=0, keepdims=True))
        p = p / jnp.sum(p, axis=0, keepdims=True)                   # [K, N]
        onehot = (jnp.arange(self.num_class)[:, None] == label[None, :].astype(jnp.int32))
        grad = p - onehot
        factor = self.num_class / (self.num_class - 1.0)
        hess = factor * p * (1.0 - p)
        if weight is not None:
            grad = grad * weight[None, :]
            hess = hess * weight[None, :]
        return grad, hess

    def boost_from_score(self, class_id=0):
        if self.label is None:
            return 0.0
        w = self.weight if self.weight is not None else np.ones_like(self.label)
        pavg = float(np.sum(w * (self.label.astype(np.int32) == class_id)) / np.sum(w))
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        return float(np.log(pavg))

    def convert_output(self, score):
        """score: [K, N] raw -> softmax probabilities."""
        p = jnp.exp(score - jnp.max(score, axis=0, keepdims=True))
        return p / jnp.sum(p, axis=0, keepdims=True)


class MulticlassOVA(ObjectiveFunction):
    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = config.num_class
        self.sigmoid = config.sigmoid
        self._binary = [BinaryLogloss(config) for _ in range(self.num_class)]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        for k, b in enumerate(self._binary):
            class Meta:  # per-class binarized view
                pass
            m = Meta()
            m.label = (self.label.astype(np.int32) == k).astype(np.float32) \
                if self.label is not None else None
            m.weight = self.weight
            m.query_boundaries = None
            b.init(m, num_data)

    @property
    def num_model_per_iteration(self):
        return self.num_class

    def get_gradients_multi(self, score, label, weight):
        grads, hesss = [], []
        for k, b in enumerate(self._binary):
            lbl_k = (label.astype(jnp.int32) == k).astype(jnp.float32)
            g, h = b.get_gradients(score[k], lbl_k, weight)
            grads.append(g)
            hesss.append(h)
        return jnp.stack(grads), jnp.stack(hesss)

    def boost_from_score(self, class_id=0):
        return self._binary[class_id].boost_from_score()

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * score))
