"""GBDT: the boosting engine.

TPU-native re-design of the reference ``GBDT`` (``src/boosting/gbdt.cpp``):
same training-loop semantics — boost-from-average (``gbdt.cpp:344``),
per-iteration gradients (``:170``), bagging (``:228``), one tree per class per
iteration, shrinkage, score-cache updates (``:491``), early stopping
(``:517-575``), model text IO (``gbdt_model_text.cpp``) — but each boosting
iteration's compute (gradients → bagging mask → tree growth → score update)
runs as compiled JAX programs with device-resident scores, and the tree
learner is the single-program grower in ``ops/grower.py``.
"""
from __future__ import annotations

import functools
import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.dataset import Dataset, DeviceData
from ..obs import TrainTelemetry
from ..obs import health as obs_health
from ..metric import create_metrics
from ..objective import ObjectiveFunction, create_objective
from ..ops.grower import GrowerConfig, TreeArrays, grow_tree
from ..ops.predict import predict_leaf_binned
from ..ops.split import SplitParams
from ..utils.log import Log, check, LightGBMError
from ..utils.random_gen import key_for_iteration
from ..utils.timer import global_timer
from .tree import Tree

# rows per densified block when predicting on scipy.sparse input: bounds
# peak host memory at block_rows * F floats (reference predicts CSR rows
# one at a time; here a block feeds the device ensemble predictor)
_SPARSE_PREDICT_BLOCK = 65536


from ..io.dataset import _is_sparse as _is_sparse_mat


def _blockwise_sparse(X, fn):
    """Apply ``fn`` (a dense-matrix predict) over densified row blocks of a
    scipy.sparse matrix and concatenate the results."""
    X = X.tocsr()
    if X.shape[0] == 0:
        return fn(np.zeros((0, X.shape[1]), np.float64))
    outs = [fn(np.asarray(X[s:s + _SPARSE_PREDICT_BLOCK].toarray(), np.float64))
            for s in range(0, X.shape[0], _SPARSE_PREDICT_BLOCK)]
    return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)


class GBDT:
    """Gradient Boosting Decision Tree engine (reference ``gbdt.h:35``)."""

    def __init__(self, config: Config, train_data: Optional[Dataset] = None,
                 objective: Optional[ObjectiveFunction] = None):
        self.config = config
        self.train_data: Optional[Dataset] = None
        self.objective = objective
        # telemetry hook (obs_telemetry): None keeps the off path at one
        # attribute check per iteration (<2% overhead budget)
        self._obs = TrainTelemetry(config) if config.obs_telemetry else None
        # live health plane: numeric sentinels every N rounds + the
        # /metrics //healthz exposition server (obs_health_port or the
        # LGBM_OBS_HEALTH_PORT env var the watcher exports to stages)
        self._health_every = int(
            getattr(config, "obs_health_check_iters", 0) or 0)
        server = obs_health.maybe_start(
            getattr(config, "obs_health_port", 0))
        self._health_enabled = bool(server is not None or self._health_every)
        if self._health_enabled and os.environ.get("LGBM_FLIGHT_DIR"):
            # supervised stage (run_stage exports the dir): arm the flight
            # recorder so a divergence or kill leaves forensics even when
            # obs_telemetry is off
            from ..obs import flight as obs_flight
            obs_flight.install()
        self._health_jit = None
        self._grow_cost_recorded = False
        self._models: List[Tree] = []
        # deferred host trees: (tree_arrays, shrinkage, bias, iter,
        # health_stats-or-None) tuples whose device->host copies are in
        # flight (see `models` property)
        self._pending: List[tuple] = []
        self._stop_flag = False
        self._empty_by_iter: Dict[int, int] = {}
        self.valid_sets: List[Dataset] = []
        self.valid_names: List[str] = []
        self.iter_ = 0
        self.num_class = config.num_class
        self.num_tree_per_iteration = 1
        self.max_feature_idx = 0
        self.best_score: Dict[str, Dict[str, float]] = {}
        self.init_scores: List[float] = []
        self.shrinkage_rate = config.learning_rate
        self._train_score = None       # [K, N] device
        self._valid_scores: List = []
        self._eval_history: Dict[str, Dict[str, List[float]]] = {}
        self._early_stop_counter = 0
        self._best_iter: Dict[str, int] = {}
        self._prev_scores = None
        self._device_trees: List = []        # per-model device TreeArrays
        self._tree_weights: List[float] = []  # current scale of each model
        self.train_data_name = "training"    # Booster.set_train_data_name
        if train_data is not None:
            self.init_train(train_data)

    # ------------------------------------------------------------------
    # Deferred host-tree materialization.  Over a remote-tunnel backend every
    # synchronous device fetch stalls the host for a round-trip, so the fast
    # training path (no leaf renewal / linear trees / CEGB) keeps the whole
    # iteration on device, starts an async device->host copy of the tree
    # arrays, and only builds the host-side ``Tree`` when someone actually
    # reads ``self.models`` — by which time the copy has long landed.
    @property
    def models(self) -> List[Tree]:
        self._drain_pending()
        return self._models

    @models.setter
    def models(self, value: List[Tree]) -> None:
        self._pending.clear()
        self._models = value

    def _drain_pending(self, keep: int = 0) -> None:
        """Materialize pending device trees (oldest first), leaving at most
        ``keep`` in flight."""
        while len(self._pending) > keep:
            arrs, shrink, bias, _it, health_dev = self._pending.pop(0)
            host = jax.device_get(arrs)
            if health_dev is not None:
                # sentinel scalars rode the same async materialization —
                # by now they are computed+copied, so this is a cheap host
                # read, not a new device sync
                self._run_numeric_check(_it, health_dev)
            nl = int(host.num_leaves)
            if self._obs is not None:
                self._obs.tree_event(_it, num_leaves=nl, split_gains=[
                    float(v) for v in
                    np.asarray(host.split_gain)[:max(0, nl - 1)]])
            tree = Tree.from_arrays(host, self.train_data, learning_rate=1.0)
            tree.shrink(shrink)
            if bias:
                if nl > 1:
                    tree.add_bias(bias)
                else:
                    tree.leaf_value = np.full_like(tree.leaf_value, bias)
            self._models.append(tree)
            if nl <= 1:
                # when ALL trees of an iteration are split-less, report stop
                # on the next update (one iteration late vs the reference's
                # synchronous check, gbdt.cpp:375-388)
                cnt = self._empty_by_iter.get(_it, 0) + 1
                self._empty_by_iter[_it] = cnt
                if cnt >= self.num_tree_per_iteration:
                    self._stop_flag = True

    # ------------------------------------------------------------------
    def init_train(self, train_data: Dataset) -> None:
        cfg = self.config
        self.train_data = train_data
        if self.objective is None:
            self.objective = create_objective(cfg)
        if self.objective is not None:
            self.objective.init(train_data.metadata, train_data.num_data)
            self.num_tree_per_iteration = self.objective.num_model_per_iteration
        else:
            self.num_tree_per_iteration = max(1, cfg.num_class)
        self.max_feature_idx = train_data.num_total_features - 1
        self.train_metrics = create_metrics(cfg)
        for m in self.train_metrics:
            m.init(train_data.metadata, train_data.num_data)
        self._dd = train_data.device_data()
        self._label_dev = (jnp.asarray(train_data.metadata.label)
                          if train_data.metadata.label is not None else None)
        self._weight_dev = (jnp.asarray(train_data.metadata.weight)
                           if train_data.metadata.weight is not None else None)
        K = self.num_tree_per_iteration
        n = train_data.num_data

        # boost from average / init_score (gbdt.cpp:338-368)
        init = np.zeros((K, n), dtype=np.float32)
        md_init = train_data.metadata.init_score
        self.init_scores = [0.0] * K
        if md_init is not None:
            init += md_init.reshape(-1, n).astype(np.float32)
        elif cfg.boost_from_average and self.objective is not None:
            for k in range(K):
                s = self.objective.boost_from_score(k)
                self.init_scores[k] = s
                init[k] += s
        self._train_score = jnp.asarray(init)
        self._grower_cfg = self._make_grower_cfg()
        self._setup_parallel()

    def _setup_parallel(self) -> None:
        """Route ``tree_learner=data|feature|voting`` through a device mesh
        (the analog of the reference's learner×device ``CreateTreeLearner``
        factory, ``tree_learner.cpp:15-53``).  Falls back to serial with a
        warning when only one device is available."""
        from ..parallel.mesh import DATA_AXIS, FEATURE_AXIS, default_mesh
        cfg = self.config
        self._mesh = None
        tl = cfg.tree_learner or "serial"
        if tl == "serial":
            return
        n_dev = cfg.mesh_shape[0] if cfg.mesh_shape else len(jax.devices())
        if n_dev < 2:
            Log.warning(
                "tree_learner=%s requested but only one device is available; "
                "training serially", tl)
            return
        if tl in ("feature", "voting") and self._dd.efb is not None:
            # the Dataset disables bundling when its params request these
            # learners; a dataset constructed for serial/data training and
            # then reused here would silently misalign per-feature metadata
            # against bundle columns
            raise LightGBMError(
                f"tree_learner={tl} cannot train on an EFB-bundled Dataset; "
                "construct the Dataset with tree_learner=%s or "
                "enable_bundle=false in its params" % tl)
        axis = FEATURE_AXIS if tl == "feature" else DATA_AXIS
        self._mesh = default_mesh(n_dev, axis_name=axis)
        self._grower_cfg = self._grower_cfg._replace(
            axis_name=axis, parallel_mode=tl, num_shards=n_dev,
            top_k=cfg.top_k)

    def _make_grower_cfg(self) -> GrowerConfig:
        cfg = self.config
        max_bin = int(max((self.train_data.num_bin(i)
                           for i in range(self.train_data.num_features)), default=2))
        # round up to a TPU-friendly lane width
        max_bin = max(4, min(cfg.max_bin + 1, -(-max_bin // 4) * 4))
        sp = SplitParams(
            lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
            min_data_in_leaf=cfg.min_data_in_leaf,
            min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
            min_gain_to_split=cfg.min_gain_to_split,
            max_delta_step=cfg.max_delta_step,
            path_smooth=cfg.path_smooth,
            cat_smooth=cfg.cat_smooth, cat_l2=cfg.cat_l2,
            max_cat_to_onehot=cfg.max_cat_to_onehot,
            max_cat_threshold=cfg.max_cat_threshold,
            min_data_per_group=cfg.min_data_per_group)
        # static: does any feature take the sorted many-category scan?
        # (num_bin > max_cat_to_onehot categorical, feature_histogram.hpp:316)
        ds = self.train_data
        from ..io.bin import BinType
        sorted_cat = any(
            ds.bin_mappers[r].bin_type == BinType.CATEGORICAL
            and ds.num_bin(i) > cfg.max_cat_to_onehot
            for i, r in enumerate(ds.used_features))
        # histogram layout: auto-picked by backend (the analog of the
        # reference's TrainingShareStates timed row/col-wise autotune,
        # train_share_states.h — here the winner per backend is known:
        # pallas one-hot on TPU, scatter-add on CPU, so the pick is static
        # and the first-iteration timing run is saved); force_col_wise/
        # force_row_wise override it like the reference's flags
        # (col-wise = per-column scatter adds, row-wise = each row pushed
        # into all feature histograms at once = the one-hot matmul)
        if cfg.force_col_wise:
            hist_method = "scatter"
        elif cfg.force_row_wise:
            hist_method = ("pallas" if jax.default_backend() == "tpu"
                           else "onehot")
        else:
            hist_method = {"tpu": "pallas", "cpu": "scatter"}.get(
                jax.default_backend(), "onehot")
        if cfg.force_col_wise and jax.default_backend() == "tpu":
            Log.warning("force_col_wise maps to the scatter histogram "
                        "kernel, which is much slower than the default "
                        "one-hot MXU kernel on TPU")
        # one-hot build strategy for the pallas kernels: 'auto' runs the
        # one-time cached on-device micro-bench (ops/onehot_variants.pick_
        # variant — the reference train_share_states auto-tuner's TPU
        # analog); an explicit name is validated against the KERNEL bin
        # width (the EFB bundle width when bundling is on).  Resolved to a
        # concrete static string HERE, before GrowerConfig exists, so the
        # compiled tree program never retraces over it.
        if hist_method == "pallas":
            from ..ops import onehot_variants as _ov
            kernel_bins = self._dd.bundle_bins or max_bin
            if cfg.hist_variant == "auto":
                hist_variant = _ov.pick_variant(
                    kernel_bins, self.train_data.num_features)
            else:
                hist_variant = _ov.resolve(cfg.hist_variant, kernel_bins)
        else:
            hist_variant = "base"           # XLA fallbacks ignore it
        return GrowerConfig(
            num_leaves=cfg.num_leaves, max_depth=cfg.max_depth, max_bin=max_bin,
            split=sp, feature_fraction_bynode=cfg.feature_fraction_bynode,
            hist_method=hist_method, hist_variant=hist_variant,
            hist_chunk_rows=cfg.hist_chunk_rows,
            cegb_split_penalty=cfg.cegb_tradeoff * cfg.cegb_penalty_split,
            hist_compact=cfg.hist_compact,
            hist_compact_min_cap=cfg.hist_compact_min_cap,
            hist_compact_ladder=cfg.hist_compact_ladder,
            extra_trees=cfg.extra_trees,
            extra_seed=cfg.extra_seed,
            sorted_cat=sorted_cat,
            bundle_bins=self._dd.bundle_bins,
            monotone_penalty=cfg.monotone_penalty,
            monotone_mode=cfg.monotone_constraints_method,
            has_monotone=any(v != 0 for v in cfg.monotone_constraints),
            grower_mode=cfg.tree_grower,
            frontier_k=cfg.frontier_k,
            frontier_block_rows=cfg.frontier_block_rows)

    # ------------------------------------------------------------------
    # feature-gating state: interaction constraints + CEGB (SURVEY.md §2.4)
    def _interaction_sets(self):
        """[C, F_inner] 0/1 matrix of interaction-constraint groups over inner
        feature ids, or None (``col_sampler.hpp:74``)."""
        groups = self.config.interaction_constraints
        if not groups:
            return None
        used = list(self.train_data.used_features)
        real2inner = {r: i for i, r in enumerate(used)}
        mat = np.zeros((len(groups), len(used)), np.float32)
        for c, grp in enumerate(groups):
            for real in grp:
                if real in real2inner:
                    mat[c, real2inner[real]] = 1.0
        return jnp.asarray(mat)

    def _forced_splits(self):
        """Parse ``forcedsplits_filename`` into the grower's static BFS tuple
        (side, inner_feature, threshold_bin, parent_forced_idx); the grower
        resolves target leaf ids at runtime (a forced split that fails its
        gates must not shift its siblings' numbering)."""
        fname = self.config.forcedsplits_filename
        if not fname:
            return ()
        import json
        with open(fname) as fh:
            root = json.load(fh)
        ds = self.train_data
        real2inner = {r: i for i, r in enumerate(ds.used_features)}
        out = []
        queue = [(root, 0, -1)]
        while queue and len(out) < self.config.num_leaves - 1:
            node, side, par = queue.pop(0)
            if not node:
                continue
            real_f = int(node["feature"])
            if real_f not in real2inner:
                Log.warning("forced split on unused feature %d ignored", real_f)
                continue
            mapper = ds.bin_mappers[real_f]
            thr_bin = int(np.asarray(
                mapper.value_to_bin(np.array([float(node["threshold"])])))[0])
            idx = len(out)
            out.append((side, real2inner[real_f], thr_bin, par))
            if node.get("left"):
                queue.append((node["left"], 0, idx))
            if node.get("right"):
                queue.append((node["right"], 1, idx))
        return tuple(out)

    # ------------------------------------------------------------------
    # linear trees (linear_tree=true; LinearTreeLearner, SURVEY.md §2.4)
    @functools.cached_property
    def _raw_dev(self):
        if self.train_data.raw_data is None:
            raise LightGBMError(
                "linear_tree=true requires the Dataset to keep raw values; "
                "pass linear_tree in the Dataset params")
        return jnp.asarray(self.train_data.raw_data)

    def _branch_features(self, tree) -> list:
        """Per-leaf sorted unique NUMERICAL real feature ids on the
        root->leaf path (linear_tree_learner.cpp:195-215)."""
        from ..io.bin import BinType
        mappers = self.train_data.bin_mappers
        paths = [[] for _ in range(tree.num_leaves)]
        stack = [(0, [])]
        while stack:
            node, fs = stack.pop()
            if node < 0:
                paths[~node] = sorted({
                    f for f in fs
                    if mappers[f].bin_type != BinType.CATEGORICAL})
                continue
            fs2 = fs + [int(tree.split_feature[node])]
            stack.append((int(tree.left_child[node]), fs2))
            stack.append((int(tree.right_child[node]), fs2))
        return paths

    def _fit_linear_tree(self, tree, node_assign, g, h,
                         row_weight, is_first_tree: bool):
        """Fit per-leaf linear models and return device arrays for the score
        update, or None when constants suffice (first tree)."""
        nl = tree.num_leaves
        tree.is_linear = True
        if is_first_tree:
            # first tree: constants only (linear_tree_learner.cpp:175-181)
            tree.leaf_const = np.asarray(tree.leaf_value, np.float64).copy()
            tree.leaf_coeff = [[] for _ in range(nl)]
            tree.leaf_features = [[] for _ in range(nl)]
            return None
        paths = self._branch_features(tree)
        L = self._grower_cfg.num_leaves
        k_raw = max(1, max((len(p) for p in paths), default=1))
        K = 1 << (k_raw - 1).bit_length()          # pad: fewer recompiles
        feat_mat = np.full((L, K), -1, np.int32)
        for i, p in enumerate(paths):
            feat_mat[i, :len(p)] = p
        feat_dev = jnp.asarray(feat_mat)
        coeffs, consts, oks = self._fit_linear_jit(
            self._raw_dev, g, h, node_assign, row_weight, feat_dev)
        coeffs = np.asarray(coeffs, np.float64)
        consts = np.asarray(consts, np.float64)
        oks = np.asarray(oks)
        leaf_value = np.asarray(tree.leaf_value, np.float64)
        tree.leaf_const = np.where(oks[:nl], consts[:nl], leaf_value[:nl])
        tree.leaf_coeff, tree.leaf_features = [], []
        for i in range(nl):
            cs, fs = [], []
            if oks[i]:
                for jx, f in enumerate(paths[i]):
                    c = coeffs[i, jx]
                    if abs(c) > 1e-35:            # kZeroThreshold prune
                        cs.append(float(c))
                        fs.append(int(f))
            tree.leaf_coeff.append(cs)
            tree.leaf_features.append(fs)
        # device views for the score update: failed leaves behave as constants
        coeff_dev = jnp.asarray(np.where(oks[:, None], coeffs, 0.0), jnp.float32)
        const_dev = jnp.zeros(L, jnp.float32).at[:nl].set(
            jnp.asarray(tree.leaf_const, jnp.float32))
        return coeff_dev, const_dev, feat_dev

    def _valid_raw_dev(self, vi: int):
        if not hasattr(self, "_vraw_cache"):
            self._vraw_cache = {}
        if vi not in self._vraw_cache:
            vset = self.valid_sets[vi]
            if vset.raw_data is None:
                raise LightGBMError(
                    "linear_tree validation sets must keep raw values")
            self._vraw_cache[vi] = jnp.asarray(vset.raw_data)
        return self._vraw_cache[vi]

    @functools.cached_property
    def _fit_linear_jit(self):
        from ..ops.linear import fit_leaf_linear
        lam = self.config.linear_lambda
        L = self._grower_cfg.num_leaves

        @jax.jit    # retraces per feat_mat width K (power-of-2 padded)
        def fn(raw, g, h, na, rw, feat_mat):
            return fit_leaf_linear(raw, g, h, na, rw, feat_mat, L, lam)
        return fn

    def _feature_contri_vec(self):
        """[F_inner] per-feature gain multipliers (reference
        feature_contri -> FeatureMetainfo::penalty), or None."""
        fc = self.config.feature_contri
        if not fc:
            return None
        used = list(self.train_data.used_features)
        if len(fc) != self.train_data.num_total_features:
            raise LightGBMError(
                "feature_contri should be the same size as feature number")
        return jnp.asarray([fc[r] for r in used], jnp.float32)

    def _cegb_vectors(self):
        """(coupled[F_inner]|None, lazy[F_inner]|None), tradeoff-premultiplied."""
        cfg = self.config
        used = list(self.train_data.used_features)

        def vec(pen):
            if not pen:
                return None
            if len(pen) < self.train_data.num_total_features:
                raise LightGBMError(
                    "cegb_penalty_feature_* should be the same size as feature number")
            return jnp.asarray([cfg.cegb_tradeoff * pen[r] for r in used],
                               jnp.float32)
        return vec(cfg.cegb_penalty_feature_coupled), vec(cfg.cegb_penalty_feature_lazy)

    def add_valid_data(self, valid_data: Dataset, name: str) -> None:
        check(valid_data.reference is self.train_data or
              valid_data.bin_mappers is self.train_data.bin_mappers,
              "validation set must be constructed with reference=train_set")
        self.valid_sets.append(valid_data)
        self.valid_names.append(name)
        metrics = create_metrics(self.config)
        for m in metrics:
            m.init(valid_data.metadata, valid_data.num_data)
        if not hasattr(self, "valid_metrics"):
            self.valid_metrics = []
        self.valid_metrics.append(metrics)
        K = self.num_tree_per_iteration
        n = valid_data.num_data
        init = np.zeros((K, n), dtype=np.float32)
        md_init = valid_data.metadata.init_score
        if md_init is not None:
            init += md_init.reshape(-1, n).astype(np.float32)
        else:
            for k in range(K):
                init[k] += self.init_scores[k]
        self._valid_scores.append(jnp.asarray(init))

    # ------------------------------------------------------------------
    # bagging (gbdt.cpp:182-262); subclasses (GOSS) override
    def _bagging_weights(self, iteration: int, grad, hess):
        cfg = self.config
        n = self.train_data.num_data
        need = cfg.bagging_freq > 0 and (cfg.bagging_fraction < 1.0 or
                                         cfg.pos_bagging_fraction < 1.0 or
                                         cfg.neg_bagging_fraction < 1.0)
        if not need:
            return None, grad, hess
        if iteration % cfg.bagging_freq == 0:
            key = key_for_iteration(cfg.bagging_seed, iteration // cfg.bagging_freq)
            self._bag_mask = bag_mask_from_uniform(
                cfg, jax.random.uniform(key, (n,)), self._label_dev)
        mask = self._bag_mask
        return mask, grad * mask, hess * mask

    # -- bagging subset (reference CopySubrow, gbdt.cpp:256): when bagging
    # drops a material fraction of rows, compact the survivors into a
    # fixed-capacity buffer so every grower pass costs O(cap), not O(N).
    # The MASK still decides membership (identical trees to the masked
    # path — the compaction is exact as long as count <= cap, and cap
    # carries a >6-sigma margin over the Bernoulli mean), so serial,
    # data-parallel and masked runs stay in exact parity.
    _BAG_SUBSET_MAX_FRACTION = 0.8

    def _bag_subset_capacity(self) -> Optional[int]:
        cfg = self.config
        n = self.train_data.num_data
        if (cfg.bagging_freq <= 0 or not (0.0 < cfg.bagging_fraction
                                          < self._BAG_SUBSET_MAX_FRACTION)
                or cfg.pos_bagging_fraction < 1.0
                or cfg.neg_bagging_fraction < 1.0
                or getattr(self, "_mesh", None) is not None
                or type(self)._bagging_weights is not GBDT._bagging_weights):
            return None
        return self._capacity_with_margin(n * cfg.bagging_fraction, n)

    @staticmethod
    def _capacity_with_margin(expected_k: float, n: int) -> Optional[int]:
        """Bag buffer capacity: expected count + a >6-sigma Bernoulli
        margin, rounded up to 1024; None when it wouldn't beat full width.
        Shared by every booster that compacts its bag (GBDT, GOSS)."""
        cap = int(expected_k + max(64.0, 6.0 * float(np.sqrt(max(1.0, expected_k)))))
        cap = -(-cap // 1024) * 1024
        return cap if cap < n else None

    def _bag_subset_refresh(self, iteration: int) -> bool:
        """True when the bag membership changed this iteration (subclasses
        that re-bag every iteration override)."""
        return iteration % self.config.bagging_freq == 0

    @functools.cached_property
    def _bag_compact_jit(self):
        from ..ops.histogram import unrolled_rank
        n = self.train_data.num_data

        @functools.partial(jax.jit, static_argnums=2)
        def fn(mask, bins, cap):
            cs = jnp.cumsum((mask > 0).astype(jnp.int32))
            targets = jnp.arange(1, cap + 1, dtype=jnp.int32)
            row_ids = jnp.minimum(unrolled_rank(cs, targets, strict=True),
                                  n - 1)
            filled = targets <= cs[-1]
            rw = jnp.where(filled, jnp.take(mask, row_ids), 0.0)
            return row_ids, rw, jnp.take(bins, row_ids, axis=0)
        return fn

    def _feature_mask(self, iteration: int) -> jnp.ndarray:
        cfg = self.config
        f = self.train_data.num_features
        if cfg.feature_fraction >= 1.0:
            return jnp.ones(f, jnp.float32)
        # per-tree column sampling (ColSampler::ResetByTree, col_sampler.hpp:74)
        rng = np.random.default_rng(cfg.feature_fraction_seed + iteration)
        k = max(1, int(round(cfg.feature_fraction * f)))
        mask = np.zeros(f, np.float32)
        mask[rng.choice(f, size=k, replace=False)] = 1.0
        return jnp.asarray(mask)

    # ------------------------------------------------------------------
    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration (reference ``GBDT::TrainOneIter``,
        ``gbdt.cpp:369``).  Returns True if training should stop (no splits)."""
        cfg = self.config
        K = self.num_tree_per_iteration
        n = self.train_data.num_data
        it = self.iter_

        if self._stop_flag:
            Log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            return True

        obs = self._obs
        if obs is not None:
            obs.phase_mark()
            # the global_timer scopes below nest under this span (the
            # timer->tracer bridge), giving Perfetto the train-loop tree
            obs.tracer.begin("train/iteration", step=it)

        with global_timer.scope("GBDT::gradients"):
            if grad is None or hess is None:
                g, h = self._compute_gradients(self._train_score)
            else:
                g = jnp.asarray(np.asarray(grad, np.float32).reshape(K, n))
                h = jnp.asarray(np.asarray(hess, np.float32).reshape(K, n))

        bag_mask, g, h = self._bagging_weights(it, g, h)
        row_weight = bag_mask if bag_mask is not None else jnp.ones(n, jnp.float32)
        fmask = self._feature_mask(it)
        self._prev_scores = (self._train_score, list(self._valid_scores))

        cegb_coupled0, cegb_used0 = self._cegb_state()
        _, cegb_lazy0 = self._cegb_vectors()
        fast = ((self.objective is None
                 or not self.objective.need_renew_tree_output())
                and not cfg.linear_tree
                and cegb_coupled0 is None and cegb_lazy0 is None)
        if fast:
            return self._train_one_iter_fast(g, h, row_weight, fmask, it, K,
                                             bag_mask=bag_mask)

        should_stop = True
        for k in range(K):
            with global_timer.scope("GBDT::grow_tree"):
                cegb_coupled, cegb_used = self._cegb_state()
                tree_arrays, node_assign = self._grow_jit(
                    self._dd.bins, g[k], h[k], row_weight, fmask,
                    key_for_iteration(cfg.seed, it, salt=k + 1),
                    cegb_coupled, cegb_used)
            if obs is not None and not self._grow_cost_recorded:
                self._ledger_grow_cost(
                    self._dd.bins, g[k], h[k], row_weight, fmask,
                    key_for_iteration(cfg.seed, it, salt=k + 1),
                    cegb_coupled, cegb_used)
            # ONE host fetch for the whole tree: over a remote-tunnel backend
            # each np.asarray is a ~90ms round-trip, so per-field pulls
            # dominate training time
            tree_host = jax.device_get(tree_arrays)
            if self._health_due(it, k):
                # the slow path already syncs per tree; check in line
                self._run_numeric_check(it, self._health_stats_fn()(
                    g[k], h[k], tree_arrays.leaf_value))
            self._cegb_update(tree_host, node_assign, bag_mask)
            nl = int(tree_host.num_leaves)
            if obs is not None:
                obs.tree_event(it, num_leaves=nl, split_gains=[
                    float(v) for v in
                    np.asarray(tree_host.split_gain)[:max(0, nl - 1)]])
            if nl > 1:
                should_stop = False
            tree = Tree.from_arrays(tree_host, self.train_data, learning_rate=1.0)

            # leaf renewal for L1-style objectives (RenewTreeOutput,
            # serial_tree_learner.cpp:684)
            if self.objective is not None and self.objective.need_renew_tree_output() and nl > 1:
                leaf_pred = np.asarray(node_assign)
                score_host = np.asarray(self._train_score[k], np.float64)
                new_vals = self.objective.renew_leaf_values(
                    leaf_pred, score_host, tree.leaf_value.copy(), nl)
                tree.leaf_value = np.asarray(new_vals, np.float64)
                tree_arrays = tree_arrays._replace(
                    leaf_value=jnp.asarray(tree.leaf_value, jnp.float32))

            linear_dev = None
            if cfg.linear_tree and nl > 1:
                linear_dev = self._fit_linear_tree(
                    tree, node_assign, g[k], h[k], row_weight,
                    is_first_tree=(it == 0))
            elif cfg.linear_tree:
                tree.is_linear = True
                tree.leaf_const = np.asarray(tree.leaf_value, np.float64).copy()
                tree.leaf_coeff = [[] for _ in range(max(1, nl))]
                tree.leaf_features = [[] for _ in range(max(1, nl))]

            tree.shrink(self.shrinkage_rate)
            # first tree carries the boost-from-average bias (Tree::AddBias);
            # a split-less first tree becomes a constant tree holding the bias
            if it == 0 and self.init_scores[k] != 0.0:
                if nl > 1:
                    tree.add_bias(self.init_scores[k])
                else:
                    tree.leaf_value = np.full_like(tree.leaf_value, self.init_scores[k])
                    if tree.is_linear:
                        tree.leaf_const = np.asarray(tree.leaf_value, np.float64).copy()

            with global_timer.scope("GBDT::update_score"):
                delta = tree_arrays.leaf_value * self.shrinkage_rate
                if linear_dev is not None:
                    from ..ops.linear import linear_leaf_delta
                    coeff_dev, const_dev, feat_dev = linear_dev
                    row_delta = linear_leaf_delta(
                        self._raw_dev, node_assign, coeff_dev, const_dev,
                        feat_dev, tree_arrays.leaf_value) * self.shrinkage_rate
                    self._train_score = self._train_score.at[k].add(row_delta)
                else:
                    self._train_score = self._train_score.at[k].add(
                        jnp.where(nl > 1, delta[node_assign], 0.0))
                for vi, vset in enumerate(self.valid_sets):
                    vleaf = self._predict_leaf_jit(tree_arrays, vset.device_data().bins)
                    if linear_dev is not None:
                        vraw = self._valid_raw_dev(vi)
                        vdelta = linear_leaf_delta(
                            vraw, vleaf, coeff_dev, const_dev, feat_dev,
                            tree_arrays.leaf_value) * self.shrinkage_rate
                        self._valid_scores[vi] = self._valid_scores[vi].at[k].add(vdelta)
                    else:
                        self._valid_scores[vi] = self._valid_scores[vi].at[k].add(
                            jnp.where(nl > 1, delta[vleaf], 0.0))
            self.models.append(tree)
            self._device_trees.append(tree_arrays)
            self._tree_weights.append(self.shrinkage_rate)

        self.iter_ += 1
        if obs is not None:
            obs.tracer.end("train/iteration")
            obs.iteration_event(it, trees=K)
        elif self._health_enabled:
            obs_health.set_status(stage="train", iteration=it)
        if should_stop:
            Log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
        return should_stop

    def _train_one_iter_fast(self, g, h, row_weight, fmask, it: int,
                             K: int, bag_mask=None) -> bool:
        """Device-resident iteration: grow, score-update and valid-update all
        stay on device; the host tree materializes lazily (``models``
        property), so the boosting loop issues work without ever blocking on
        the device — the per-tree host round-trip of the synchronous path
        disappears from the critical path."""
        cfg = self.config
        cap = self._bag_subset_capacity() if bag_mask is not None else None
        if cap is not None:
            if (self._bag_subset_refresh(it)
                    or getattr(self, "_bag_sub", None) is None):
                self._bag_sub = self._bag_compact_jit(bag_mask, self._dd.bins,
                                                      cap)
            bag_rows, bag_rw, bag_bins = self._bag_sub
        for k in range(K):
            with global_timer.scope("GBDT::grow_tree"):
                if cap is not None:
                    # grow over the compacted bag; leaf assignment for the
                    # FULL training set comes from one binned traversal
                    tree_arrays, _ = self._grow_jit(
                        bag_bins, jnp.take(g[k], bag_rows),
                        jnp.take(h[k], bag_rows), bag_rw, fmask,
                        key_for_iteration(cfg.seed, it, salt=k + 1),
                        None, None)
                    node_assign = self._predict_leaf_jit(tree_arrays,
                                                         self._dd.bins)
                else:
                    tree_arrays, node_assign = self._grow_jit(
                        self._dd.bins, g[k], h[k], row_weight, fmask,
                        key_for_iteration(cfg.seed, it, salt=k + 1), None, None)
            if (self._obs is not None and not self._grow_cost_recorded
                    and cap is None):
                self._ledger_grow_cost(
                    self._dd.bins, g[k], h[k], row_weight, fmask,
                    key_for_iteration(cfg.seed, it, salt=k + 1), None, None)
            jax.tree.map(lambda a: a.copy_to_host_async(), tree_arrays)
            health_dev = None
            if self._health_due(it, k):
                # sentinel reductions ride the same async materialization:
                # dispatched now, judged at drain time — no new device sync
                health_dev = self._health_stats_fn()(
                    g[k], h[k], tree_arrays.leaf_value)
                jax.tree.map(lambda a: a.copy_to_host_async(), health_dev)
            bias = (self.init_scores[k]
                    if it == 0 and self.init_scores[k] != 0.0 else 0.0)
            self._pending.append((tree_arrays, self.shrinkage_rate, bias, it,
                                  health_dev))
            with global_timer.scope("GBDT::update_score"):
                gate = tree_arrays.num_leaves > 1
                delta = tree_arrays.leaf_value * self.shrinkage_rate
                self._train_score = self._train_score.at[k].add(
                    jnp.where(gate, delta[node_assign], 0.0))
                for vi, vset in enumerate(self.valid_sets):
                    vleaf = self._predict_leaf_jit(tree_arrays,
                                                   vset.device_data().bins)
                    self._valid_scores[vi] = self._valid_scores[vi].at[k].add(
                        jnp.where(gate, delta[vleaf], 0.0))
            self._device_trees.append(tree_arrays)
            self._tree_weights.append(self.shrinkage_rate)
        self.iter_ += 1
        if self._obs is not None:
            # iteration event here, per-tree split-gain events from
            # _drain_pending when the async host copies land — telemetry
            # must not add a device sync to the fast path
            self._obs.tracer.end("train/iteration")
            self._obs.iteration_event(it, trees=K)
        elif self._health_enabled:
            obs_health.set_status(stage="train", iteration=it)
        # keep one iteration in flight: draining then blocks only on the
        # PREVIOUS iteration's device work (host stays a full iteration
        # ahead) and its async device->host copy has typically landed, so
        # the device_get is a cache read, not a round-trip.  The stop check
        # is therefore one iteration late (at most K extra constant trees).
        self._drain_pending(keep=K)
        return self._stop_flag

    # ------------------------------------------------------------------
    # numeric health sentinels (obs_health_check_iters): tiny device-side
    # isfinite/max-abs reductions over gradients, hessians and leaf values
    def _health_stats_fn(self):
        if self._health_jit is None:
            @jax.jit
            def stats(g, h, leaf):
                def s(x):
                    xf = jnp.asarray(x, jnp.float32).ravel()
                    finite = jnp.isfinite(xf)
                    return jnp.stack([
                        jnp.mean(finite.astype(jnp.float32)),
                        jnp.max(jnp.where(finite, jnp.abs(xf), 0.0))])
                return s(g), s(h), s(leaf)
            self._health_jit = stats
        return self._health_jit

    def _health_due(self, it: int, k: int) -> bool:
        """Sample one tree (k==0) every ``obs_health_check_iters`` rounds."""
        return bool(self._health_every and k == 0
                    and it % self._health_every == 0)

    def _run_numeric_check(self, it: int, health_dev) -> None:
        """Judge fetched sentinel scalars; raises DivergenceError on
        NaN/Inf (with a flight dump) via ``obs.health.check_numeric``."""
        g_s, h_s, l_s = jax.device_get(health_dev)
        stats = {
            "grad": {"finite_frac": float(g_s[0]),
                     "max_abs": float(g_s[1])},
            "hess": {"finite_frac": float(h_s[0]),
                     "max_abs": float(h_s[1])},
            "leaf_value": {"finite_frac": float(l_s[0]),
                           "max_abs": float(l_s[1])},
        }
        obs_health.check_numeric(
            stats, iteration=it, kind="train",
            log=self._obs.log if self._obs is not None else None)

    def _compute_gradients(self, score):
        obj = self.objective
        if obj is None:
            raise LightGBMError("objective is None; provide custom grad/hess")
        if self.num_tree_per_iteration > 1:
            return obj.get_gradients_multi(score, self._label_dev, self._weight_dev)
        g, h = obj.get_gradients(score[0], self._label_dev, self._weight_dev)
        return g[None, :], h[None, :]

    def _ledger_grow_cost(self, *args) -> None:
        """One-time XLA cost/memory capture of the compiled grow program
        into the obs cost ledger (``train.grow_tree``): re-lowering costs
        one retrace, ``compile()`` hits the executable cache, and the
        telemetry loop joins per-iteration grow seconds against it.
        Never fatal — attribution must not break training."""
        self._grow_cost_recorded = True
        try:
            from ..obs import costs as obs_costs
            bins = args[0]
            obs_costs.analyze_jitted(
                "train.grow_tree", self._grow_jit, *args,
                rows=int(bins.shape[0]), features=int(bins.shape[1]))
        except Exception:
            pass

    @functools.cached_property
    def _grow_jit(self):
        dd = self._dd
        cfg = self._grower_cfg
        inter = self._interaction_sets()
        _, lazy = self._cegb_vectors()
        forced = self._forced_splits()
        contri = self._feature_contri_vec()
        mesh = getattr(self, "_mesh", None)

        if mesh is None:
            @jax.jit
            def fn(bins, g, h, rw, fmask, key, cegb_coupled, cegb_used):
                return grow_tree(bins, g, h, rw, fmask, dd.num_bins,
                                 dd.default_bins, dd.nan_bins,
                                 dd.is_categorical, dd.monotone, key, cfg,
                                 interaction_sets=inter,
                                 cegb_coupled=cegb_coupled,
                                 cegb_lazy=lazy, cegb_used_data=cegb_used,
                                 forced=forced, efb=dd.efb,
                                 feature_contri=contri)
            return fn

        # parallel learners: the same grow_tree program under shard_map, with
        # rows (data/voting) or features (feature) sharded over the mesh and
        # the grower's psum/pmax collectives joining the shards (reference
        # learner dataflows: data_parallel_tree_learner.cpp:155-251,
        # feature_parallel_tree_learner.cpp:38-57,
        # voting_parallel_tree_learner.cpp:151-345)
        from jax.sharding import PartitionSpec as P
        axis = cfg.axis_name
        ns = cfg.num_shards
        n = self.train_data.num_data
        f = self.train_data.num_features

        if cfg.parallel_mode == "feature":
            f_pad = (-f) % ns
            pad_i = lambda a, v: jnp.pad(a, (0, f_pad), constant_values=v)
            num_bins = pad_i(dd.num_bins, 1)
            default_bins = pad_i(dd.default_bins, 0)
            nan_bins = pad_i(dd.nan_bins, -1)
            is_cat = pad_i(dd.is_categorical, False)
            mono = pad_i(dd.monotone, 0)
            inter_p = (jnp.pad(inter, ((0, 0), (0, f_pad)))
                       if inter is not None else None)
            lazy_p = pad_i(lazy, 0.0) if lazy is not None else None

            contri_p = (pad_i(contri, 1.0) if contri is not None else None)

            def grow(bins, g, h, rw, fmask, key, cc, cu):
                return grow_tree(bins, g, h, rw, fmask, num_bins, default_bins,
                                 nan_bins, is_cat, mono, key, cfg,
                                 interaction_sets=inter_p, cegb_coupled=cc,
                                 cegb_lazy=lazy_p, cegb_used_data=cu,
                                 forced=forced, feature_contri=contri_p)

            from ..parallel.mesh import shard_map as _shard_map
            sharded = _shard_map(
                grow, mesh=mesh,
                in_specs=(P(None, axis), P(), P(), P(), P(), P(), P(), P()),
                out_specs=(P(), P()), check_vma=False)

            @jax.jit
            def fn(bins, g, h, rw, fmask, key, cegb_coupled, cegb_used):
                if f_pad:
                    bins = jnp.pad(bins, ((0, 0), (0, f_pad)))
                    fmask = jnp.pad(fmask, (0, f_pad))
                    if cegb_coupled is not None:
                        cegb_coupled = jnp.pad(cegb_coupled, (0, f_pad))
                    if cegb_used is not None:
                        cegb_used = jnp.pad(cegb_used, ((0, 0), (0, f_pad)))
                return sharded(bins, g, h, rw, fmask, key,
                               cegb_coupled, cegb_used)
            return fn

        # data / voting: rows sharded
        n_pad = (-n) % ns

        def grow(bins, g, h, rw, fmask, key, cc, cu):
            return grow_tree(bins, g, h, rw, fmask, dd.num_bins,
                             dd.default_bins, dd.nan_bins, dd.is_categorical,
                             dd.monotone, key, cfg, interaction_sets=inter,
                             cegb_coupled=cc, cegb_lazy=lazy,
                             cegb_used_data=cu, forced=forced, efb=dd.efb,
                             feature_contri=contri)

        from ..parallel.mesh import shard_map as _shard_map
        sharded = _shard_map(
            grow, mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis), P(), P(), P(),
                      P(axis)),
            out_specs=(P(), P(axis)), check_vma=False)

        @jax.jit
        def fn(bins, g, h, rw, fmask, key, cegb_coupled, cegb_used):
            if n_pad:
                # pad rows to a mesh multiple; zero weight excludes them from
                # every histogram/sum, so results match serial exactly
                bins = jnp.pad(bins, ((0, n_pad), (0, 0)))
                g = jnp.pad(g, (0, n_pad))
                h = jnp.pad(h, (0, n_pad))
                rw = jnp.pad(rw, (0, n_pad))
                if cegb_used is not None:
                    cegb_used = jnp.pad(cegb_used, ((0, n_pad), (0, 0)))
            tree, na = sharded(bins, g, h, rw, fmask, key,
                               cegb_coupled, cegb_used)
            return tree, (na[:n] if n_pad else na)
        return fn

    def _cegb_state(self):
        """Per-model CEGB accumulators, created lazily on first use."""
        coupled, lazy = self._cegb_vectors()
        if coupled is not None and not hasattr(self, "_cegb_feat_used"):
            self._cegb_feat_used = np.zeros(self.train_data.num_features, bool)
        if lazy is not None and not hasattr(self, "_cegb_used_data"):
            self._cegb_used_data = jnp.zeros(
                (self.train_data.num_data, self.train_data.num_features), bool)
        coupled_arg = None
        if coupled is not None:
            coupled_arg = jnp.where(jnp.asarray(self._cegb_feat_used), 0.0, coupled)
        used_arg = self._cegb_used_data if lazy is not None else None
        return coupled_arg, used_arg

    def _cegb_update(self, tree_arrays, node_assign, bag_mask):
        """Fold one finished tree into the model-level CEGB state.

        Rows were in a node at split time iff that node is an ancestor of the
        row's final leaf, so the per-row feature costs paid by this tree are
        exactly the features on each row's root->leaf path."""
        nl = int(tree_arrays.num_leaves)
        if nl <= 1:
            return
        if hasattr(self, "_cegb_feat_used"):
            feats = np.asarray(tree_arrays.split_feature[:nl - 1], np.int64)
            self._cegb_feat_used[feats[feats >= 0]] = True
        if hasattr(self, "_cegb_used_data"):
            L = self._grower_cfg.num_leaves
            path = np.zeros((L, self.train_data.num_features), bool)
            left = np.asarray(tree_arrays.left_child)
            right = np.asarray(tree_arrays.right_child)
            feat = np.asarray(tree_arrays.split_feature)
            stack = [(0, [])]
            while stack:
                node, fs = stack.pop()
                if node < 0:           # ~leaf_id
                    path[~node, fs] = True
                    continue
                if feat[node] < 0:
                    continue
                fs2 = fs + [feat[node]]
                stack.append((int(left[node]), fs2))
                stack.append((int(right[node]), fs2))
            paid = jnp.asarray(path)[node_assign]
            if bag_mask is not None:
                paid = paid & (bag_mask > 0)[:, None]
            self._cegb_used_data = self._cegb_used_data | paid

    @functools.cached_property
    def _predict_leaf_jit(self):
        dd = self._dd

        @jax.jit
        def fn(tree_arrays, bins):
            return predict_leaf_binned(tree_arrays, bins, dd.nan_bins,
                                       efb=dd.efb)
        return fn

    # ------------------------------------------------------------------
    def eval_current(self) -> List[Tuple[str, str, float, bool]]:
        """Evaluate all metrics on train (if enabled) + valid sets.
        Returns (dataset_name, metric_name, value, higher_better)."""
        out = []
        if self.config.is_provide_training_metric and self.train_metrics:
            score = np.asarray(self._train_score, np.float64)
            s = score[0] if self.num_tree_per_iteration == 1 else score
            for m in self.train_metrics:
                for name, val, hib in m.eval(s, self.objective):
                    out.append((self.train_data_name, name, val, hib))
        for vi, vset in enumerate(self.valid_sets):
            score = np.asarray(self._valid_scores[vi], np.float64)
            s = score[0] if self.num_tree_per_iteration == 1 else score
            for m in self.valid_metrics[vi]:
                for name, val, hib in m.eval(s, self.objective):
                    out.append((self.valid_names[vi], name, val, hib))
        return out

    # ------------------------------------------------------------------
    # row*tree volume above which the stacked device traversal beats the
    # host loop (compile cost amortizes); overridable via config.pred_device
    _DEVICE_PREDICT_MIN_WORK = 2_000_000

    def predict_raw(self, X: np.ndarray, num_iteration: int = -1,
                    start_iteration: int = 0) -> np.ndarray:
        """Raw scores [N] or [N, K] (reference ``GBDT::PredictRaw``).

        Large requests run as ONE compiled device program over the stacked
        ensemble (``ops/ensemble.py``) instead of a per-tree host loop —
        the TPU analog of the reference's OpenMP block predictor
        (``gbdt_prediction.cpp:20-72``)."""
        if _is_sparse_mat(X):
            return _blockwise_sparse(
                X, lambda d: self.predict_raw(d, num_iteration, start_iteration))
        X = np.asarray(X, dtype=np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        K = self.num_tree_per_iteration
        n_iters = len(self.models) // K
        if num_iteration is not None and num_iteration > 0:
            n_iters = min(n_iters, num_iteration)
        models = self.models[start_iteration * K:(start_iteration + n_iters) * K]

        mode = getattr(self.config, "pred_device", "auto")
        early_stop = (self.config.pred_early_stop
                      and self.objective is not None
                      and getattr(self.objective, "name", "") in
                      ("binary", "multiclass", "multiclassova"))
        use_device = models and not early_stop and mode != "host" and (
            mode == "device"
            or X.shape[0] * len(models) >= self._DEVICE_PREDICT_MIN_WORK)
        if use_device:
            out = self._predict_raw_device(models, start_iteration, X)
        elif early_stop:
            out = self._predict_raw_early_stop(models, X, K)
        else:
            out = np.zeros((X.shape[0], K))
            for ti, t in enumerate(models):
                out[:, ti % K] += t.predict(X)
        return out[:, 0] if K == 1 else out

    def _predict_raw_early_stop(self, models, X: np.ndarray, K: int):
        """Margin-based per-row prediction early termination (reference
        ``prediction_early_stop.cpp``): every ``pred_early_stop_freq`` trees,
        rows whose margin — ``2*|score|`` for binary, top1−top2 for
        multiclass — exceeds ``pred_early_stop_margin`` stop accumulating
        further trees."""
        cfg = self.config
        # round the check period up to an iteration boundary: freezing a row
        # mid-iteration would leave unequal per-class tree counts
        freq = max(1, cfg.pred_early_stop_freq) * K
        thresh = cfg.pred_early_stop_margin
        n = X.shape[0]
        out = np.zeros((n, K))
        active = np.ones(n, bool)
        for ti, t in enumerate(models):
            out[active, ti % K] += t.predict(X[active])
            if (ti + 1) % freq == 0 and ti + 1 < len(models):
                if K == 1:
                    margin = 2.0 * np.abs(out[:, 0])
                else:
                    part = np.partition(out, K - 2, axis=1)
                    margin = part[:, K - 1] - part[:, K - 2]
                active &= margin <= thresh
                if not active.any():
                    break
        return out

    def _predict_raw_device(self, models, start_iteration: int,
                            X: np.ndarray) -> np.ndarray:
        from ..ops.ensemble import predict_raw_ensemble, stack_trees
        key = (start_iteration, len(models), len(self.models))
        cache = getattr(self, "_ens_cache", None)
        if cache is None or cache[0] != key:
            self._ens_cache = (key, stack_trees(models))
        ens = self._ens_cache[1]
        K = self.num_tree_per_iteration
        any_linear = any(getattr(t, "is_linear", False) for t in models)
        fn = jax.jit(predict_raw_ensemble, static_argnums=(2, 3))
        out = np.zeros((X.shape[0], K))
        step = 1 << 22                      # bound device residency of X
        for s in range(0, X.shape[0], step):
            chunk = jnp.asarray(X[s:s + step], jnp.float32)
            out[s:s + step] = np.asarray(fn(ens, chunk, K, any_linear),
                                         np.float64).T
        return out

    def predict(self, X: np.ndarray, num_iteration: int = -1,
                start_iteration: int = 0, raw_score: bool = False) -> np.ndarray:
        raw = self.predict_raw(X, num_iteration, start_iteration)
        if raw_score or self.objective is None:
            return raw
        if self.num_tree_per_iteration > 1:
            return np.asarray(self.objective.convert_output(raw.T)).T
        return np.asarray(self.objective.convert_output(raw))

    def predict_contrib(self, X: np.ndarray, num_iteration: int = -1,
                        start_iteration: int = 0, sparse: bool = False,
                        sparse_format: "str | None" = None):
        """TreeSHAP feature contributions (reference ``GBDT::PredictContrib``
        via ``Tree::TreeSHAP``, ``tree.cpp:887``): per row, per class,
        ``[num_features + 1]`` with the bias (expected value) last.

        ``sparse=True`` returns scipy CSR (one matrix, or a list of K for
        multiclass) built block by block, so a wide-sparse input never
        materializes the full dense contribution matrix — the analog of the
        reference's ``LGBM_BoosterPredictSparseOutput``
        (``src/c_api.cpp:1900``) and the python package's sparse-in →
        sparse-out contract."""
        from ..ops.shap import tree_shap, expected_value
        if any(getattr(t, "is_linear", False) for t in self.models):
            raise LightGBMError(
                "pred_contrib (TreeSHAP) is not supported for linear trees")
        if sparse:
            return self._predict_contrib_sparse(X, num_iteration,
                                                start_iteration,
                                                sparse_format)
        if _is_sparse_mat(X):
            return _blockwise_sparse(
                X, lambda d: self.predict_contrib(d, num_iteration,
                                                  start_iteration))
        X = np.asarray(X, dtype=np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        n, F = X.shape
        K = self.num_tree_per_iteration
        n_iters = len(self.models) // K
        if num_iteration is not None and num_iteration > 0:
            n_iters = min(n_iters, num_iteration)
        out = np.zeros((n, K, F + 1))
        for i in range(start_iteration, start_iteration + n_iters):
            for k in range(K):
                ti = i * K + k
                if ti < len(self.models):
                    t = self.models[ti]
                    out[:, k, :F] += tree_shap(t, X)
                    out[:, k, F] += expected_value(t)
        return out[:, 0, :] if K == 1 else out.reshape(n, K * (F + 1))

    def _predict_contrib_sparse(self, X, num_iteration: int,
                                start_iteration: int,
                                sparse_format: "str | None" = None):
        """Blockwise sparse TreeSHAP: CSR per block, stacked — peak memory
        is one dense block, not the [n, F+1] matrix.  The block row count
        is capped by total ELEMENTS, so a wide-sparse input (the case this
        path exists for) still bounds the dense scratch."""
        import scipy.sparse as sp
        K = self.num_tree_per_iteration
        Xc = X.tocsr() if _is_sparse_mat(X) else np.asarray(X, np.float64)
        n, F = Xc.shape
        block = max(1, min(_SPARSE_PREDICT_BLOCK,
                           (64 << 20) // max(1, (F + 1) * K)))
        blocks: List[list] = [[] for _ in range(K)]
        for s in range(0, max(n, 1), block):
            xb = Xc[s:s + block]
            if _is_sparse_mat(xb):
                xb = np.asarray(xb.toarray(), np.float64)
            dense = self.predict_contrib(xb, num_iteration, start_iteration)
            if K == 1:
                blocks[0].append(sp.csr_matrix(dense))
            else:
                F1 = dense.shape[1] // K
                for k in range(K):
                    blocks[k].append(
                        sp.csr_matrix(dense[:, k * F1:(k + 1) * F1]))
        # format-preserving like the reference python package: CSC in ->
        # CSC out (LGBM_BoosterPredictSparseOutput handles both layouts);
        # the caller passes the ORIGINAL input format (Booster.predict
        # normalizes the matrix to CSR before the blocks are cut)
        fmt = sparse_format or (getattr(X, "format", "csr")
                                if _is_sparse_mat(X) else "csr")
        fmt = fmt if fmt in ("csr", "csc") else "csr"
        mats = [sp.vstack(b, format=fmt) if len(b) > 1
                else (b[0] if fmt == "csr" else b[0].tocsc())
                for b in blocks]
        return mats[0] if K == 1 else mats

    def predict_leaf_index(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        if _is_sparse_mat(X):
            return _blockwise_sparse(
                X, lambda d: self.predict_leaf_index(d, num_iteration))
        X = np.asarray(X, dtype=np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        K = self.num_tree_per_iteration
        n_iters = len(self.models) // K
        if num_iteration is not None and num_iteration > 0:
            n_iters = min(n_iters, num_iteration)
        out = np.zeros((X.shape[0], n_iters * K), np.int32)
        for i in range(n_iters * K):
            out[:, i] = self.models[i].predict_leaf_index(X)
        return out

    # ------------------------------------------------------------------
    def continue_from(self, prev: "GBDT") -> None:
        """Continued training from an existing model (reference CLI
        ``input_model`` / Python ``init_model``: ``boosting.cpp:35-60``,
        ``engine.py:15``): adopt the previous ensemble and warm up the
        cached train/valid scores with its predictions over the binned data."""
        import copy
        check(prev.num_tree_per_iteration == self.num_tree_per_iteration,
              "init_model has a different number of tree per iteration")
        self.models = [copy.deepcopy(t) for t in prev.models]
        self._tree_weights = list(prev._tree_weights) or [1.0] * len(self.models)
        self._device_trees = []
        self._ens_cache = None
        K = self.num_tree_per_iteration
        self.iter_ = len(self.models) // K

        has_linear = any(getattr(t, "is_linear", False) for t in self.models)

        def warm(ds, dd, score, raw):
            # host-side binned traversal wants per-feature bins: decode any
            # EFB bundle columns (io/efb.py)
            bins_np = ds.unbundled_bins()
            nan_np = np.asarray(dd.nan_bins)
            s = np.array(score, np.float64)
            for t in self.models:
                if len(t.cat_boundaries) > 1:
                    # text-loaded trees carry VALUE bitsets only; binned
                    # traversal needs the bin-space ones
                    t.bin_cat_bitsets(self.train_data.bin_mappers)
                # ... and VALUE thresholds only: without this, a file-based
                # init_model warmed the scores with all-zero bin thresholds
                t.bin_numeric_thresholds(self.train_data.bin_mappers)
            for i, t in enumerate(self.models):
                if getattr(t, "is_linear", False):
                    # linear leaves need raw values (binned midpoints would
                    # warm the scores away from the model's true predictions)
                    s[i % K] = s[i % K] + t.predict(raw)
                else:
                    s[i % K] = s[i % K] + t.predict_binned(bins_np, nan_np)
            return jnp.asarray(s.astype(np.float32))

        def raw_of(ds):
            if not has_linear:
                return None
            if ds.raw_data is None:
                raise LightGBMError(
                    "continued training from a linear-tree model requires "
                    "the Dataset to keep raw values (pass linear_tree=true)")
            return np.asarray(ds.raw_data, np.float64)

        # the first tree of the previous model already carries its bias;
        # drop this model's own boost-from-average init
        self._train_score = warm(self.train_data, self._dd,
                                 jnp.zeros_like(self._train_score),
                                 raw_of(self.train_data))
        for vi, vset in enumerate(self.valid_sets):
            # device_meta, not device_data: warm() only reads nan_bins, and
            # under the streaming engine a full device_data() here would
            # materialize (and cache) a valid bin matrix the budget says
            # does not fit
            self._valid_scores[vi] = warm(vset, vset.device_meta(),
                                          jnp.zeros_like(self._valid_scores[vi]),
                                          raw_of(vset))

    # ------------------------------------------------------------------
    def refit(self, X: np.ndarray, y: np.ndarray, decay_rate: float = 0.9) -> None:
        """Refit the existing tree structures on new data (reference
        ``GBDT::RefitTree`` (``gbdt.cpp:285``) + ``FitByExistingTree``
        (``serial_tree_learner.cpp:211-250``)): per iteration, gradients at
        the progressive score are re-aggregated per leaf and
        ``new = output*shrinkage``, ``leaf = decay*old + (1-decay)*new``."""
        from ..objective import create_objective
        from ..io.dataset import Metadata
        if any(getattr(t, "is_linear", False) for t in self.models):
            raise LightGBMError(
                "refit is not supported for linear-tree models yet")
        cfg = self.config
        X = np.asarray(X, np.float64)
        n = X.shape[0]
        obj = self.objective
        if obj is None:
            obj = create_objective(cfg)
        if obj is None:
            raise LightGBMError("cannot refit without an objective")
        md = Metadata(n)
        md.set_field("label", y)
        obj.init(md, n)
        K = self.num_tree_per_iteration
        n_iters = len(self.models) // K
        label_dev = jnp.asarray(md.label)
        score = np.zeros((K, n), np.float32)
        leaf_idx = [t.predict_leaf_index(X) for t in self.models]
        lam1, lam2, mds = cfg.lambda_l1, cfg.lambda_l2, cfg.max_delta_step

        def out_of(sg, sh):
            thr = np.sign(sg) * np.maximum(np.abs(sg) - lam1, 0.0)
            o = -thr / (sh + lam2 + 1e-35)
            if mds > 0:
                o = np.clip(o, -mds, mds)
            return o

        for it in range(n_iters):
            sc = jnp.asarray(score)
            if K > 1:
                g, h = obj.get_gradients_multi(sc, label_dev, None)
            else:
                g0, h0 = obj.get_gradients(sc[0], label_dev, None)
                g, h = g0[None, :], h0[None, :]
            g, h = np.asarray(g, np.float64), np.asarray(h, np.float64)
            for k in range(K):
                t = self.models[it * K + k]
                lp = leaf_idx[it * K + k]
                nl = t.num_leaves
                sg = np.bincount(lp, weights=g[k], minlength=nl)[:nl]
                sh = np.bincount(lp, weights=h[k], minlength=nl)[:nl] + 1e-15
                new_out = out_of(sg, sh) * t.shrinkage
                t.leaf_value = (decay_rate * t.leaf_value
                                + (1.0 - decay_rate) * new_out)
                score[k] += t.leaf_value[lp].astype(np.float32)
        self._device_trees = []            # host trees changed; drop caches
        self._ens_cache = None

    # ------------------------------------------------------------------
    def rollback_one_iter(self) -> None:
        """Reference ``GBDT::RollbackOneIter`` (``gbdt.cpp:454``): undo the
        last iteration's trees and restore cached scores (one-step history)."""
        if self.iter_ <= 0:
            return
        if self._prev_scores is None:
            raise LightGBMError("rollback history exhausted (only one step kept)")
        K = self.num_tree_per_iteration
        self.models = self.models[:-K]
        self._device_trees = self._device_trees[:-K]
        self._tree_weights = self._tree_weights[:-K]
        self._ens_cache = None
        self.iter_ -= 1
        # the rolled-back iteration's empty-tree accounting must not leak
        # into a retrain of the same iteration (or pin _stop_flag)
        self._empty_by_iter.pop(self.iter_, None)
        self._stop_flag = False
        self._train_score, self._valid_scores = self._prev_scores
        self._prev_scores = None

    @property
    def num_trees(self) -> int:
        return len(self.models)

    def feature_importance(self, importance_type: str = "split",
                           iteration: int = -1) -> np.ndarray:
        """split/gain importance (reference ``GBDT::FeatureImportance``,
        ``gbdt.cpp:606``)."""
        n_feat = self.max_feature_idx + 1
        imp = np.zeros(n_feat)
        models = self.models
        if iteration is not None and iteration > 0:
            models = models[:iteration * self.num_tree_per_iteration]
        for tree in models:
            for j in range(tree.num_internal):
                if tree.num_leaves > 1 and tree.split_gain[j] > 0:
                    f = tree.split_feature[j]
                    if importance_type == "split":
                        imp[f] += 1
                    else:
                        imp[f] += tree.split_gain[j]
        return imp


def bag_mask_from_uniform(cfg: Config, u, label):
    """Bernoulli bagging mask from a per-row uniform draw (the shared math
    of GBDT._bagging_weights and the distributed trainer — the two paths
    must stay byte-identical for multi-process parity, so the formula
    lives ONCE here; reference gbdt.cpp:182-262)."""
    if cfg.pos_bagging_fraction < 1.0 or cfg.neg_bagging_fraction < 1.0:
        frac = jnp.where(label > 0, cfg.pos_bagging_fraction,
                         cfg.neg_bagging_fraction)
    else:
        frac = cfg.bagging_fraction
    return (u < frac).astype(jnp.float32)
