"""Random Forest mode (reference ``src/boosting/rf.hpp``): bagging required,
no shrinkage, gradients always computed at the initial score, predictions are
the average over trees (``average_output``)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..utils.log import check
from .gbdt import GBDT


class RF(GBDT):
    average_output = True

    def init_train(self, train_data):
        cfg = self.config
        check(cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0,
              "Random forest requires bagging_freq > 0 and bagging_fraction < 1.0")
        super().init_train(train_data)
        self.shrinkage_rate = 1.0        # no shrinkage (rf.hpp:48)
        self._init_score_const = self._train_score

    def _compute_gradients(self, score):
        # gradients at the constant init score (rf.hpp:82 Boosting override)
        return super()._compute_gradients(self._init_score_const)

    def predict_raw(self, X, num_iteration=-1, start_iteration=0):
        raw = super().predict_raw(X, num_iteration, start_iteration)
        K = self.num_tree_per_iteration
        n_iters = len(self.models) // max(1, K)
        if num_iteration is not None and num_iteration > 0:
            n_iters = min(n_iters, num_iteration)
        return raw / max(1, n_iters)

    def eval_current(self):
        # metrics see averaged scores
        n_iters = max(1, self.iter_)
        saved_t, saved_v = self._train_score, self._valid_scores
        try:
            self._train_score = self._train_score / n_iters
            self._valid_scores = [s / n_iters for s in self._valid_scores]
            return super().eval_current()
        finally:
            self._train_score, self._valid_scores = saved_t, saved_v
