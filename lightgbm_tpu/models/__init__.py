from .tree import Tree
from .gbdt import GBDT
from .dart import DART
from .goss import GOSS
from .rf import RF

__all__ = ["Tree", "GBDT", "DART", "GOSS", "RF"]
