"""Decision tree model: flat arrays + traversal + serialization.

Re-design of the reference ``Tree`` (``include/LightGBM/tree.h:25``,
``src/io/tree.cpp``): same flat-array layout (split feature / threshold /
children with ``~leaf`` negative encoding / leaf values), same
``decision_type`` bit semantics (categorical, default-left, missing type) and
the same text-serialization grammar (``Tree::ToString``, ``tree.cpp:333``) so
models interoperate with the reference's model files.

Prediction here is vectorized over rows (numpy on host, ``lax.while_loop``
pointer-chasing on device) instead of the reference's per-row recursive
traversal (``tree.h:133``).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..io.bin import BinMapper, BinType, MissingType
from ..utils.common import K_ZERO_THRESHOLD

_CAT_MASK = 1        # decision_type bit 0 (tree.h kCategoricalMask)
_DEFAULT_LEFT_MASK = 2   # bit 1 (kDefaultLeftMask)


class Tree:
    """Host-side tree (arrays indexed by internal node / leaf)."""

    def __init__(self, num_leaves: int):
        m = max(1, num_leaves - 1)
        self.num_leaves = num_leaves
        self.split_feature: np.ndarray = np.zeros(m, np.int32)   # real feature idx
        self.split_feature_inner: np.ndarray = np.zeros(m, np.int32)
        self.threshold: np.ndarray = np.zeros(m, np.float64)     # real threshold
        self.threshold_bin: np.ndarray = np.zeros(m, np.int32)
        self.decision_type: np.ndarray = np.zeros(m, np.int8)
        self.split_gain: np.ndarray = np.zeros(m, np.float32)
        self.left_child: np.ndarray = np.full(m, -1, np.int32)
        self.right_child: np.ndarray = np.full(m, -1, np.int32)
        self.leaf_value: np.ndarray = np.zeros(num_leaves, np.float64)
        self.leaf_weight: np.ndarray = np.zeros(num_leaves, np.float64)
        self.leaf_count: np.ndarray = np.zeros(num_leaves, np.int64)
        self.internal_value: np.ndarray = np.zeros(m, np.float64)
        self.internal_weight: np.ndarray = np.zeros(m, np.float64)
        self.internal_count: np.ndarray = np.zeros(m, np.int64)
        # categorical split support: threshold indexes into cat bitset arrays
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []
        # BIN-space bitsets per cat node (for binned traversal); rebuilt from
        # the value bitsets via bin_cat_bitsets() for text-loaded models
        self.cat_bits_bin: dict = {}
        # text-loaded trees carry VALUE thresholds only; binned traversal
        # must rebuild threshold_bin first (bin_numeric_thresholds)
        self._has_bin_thresholds: bool = True
        self.shrinkage: float = 1.0
        # linear trees (reference tree.h:49-54): per-leaf linear models
        self.is_linear: bool = False
        self.leaf_const: np.ndarray = np.zeros(0, np.float64)     # [L]
        self.leaf_coeff: List[List[float]] = []                   # per leaf
        self.leaf_features: List[List[int]] = []                  # real ids

    # ------------------------------------------------------------------
    @property
    def num_internal(self) -> int:
        return self.num_leaves - 1

    def is_categorical_split(self, node: int) -> bool:
        return bool(self.decision_type[node] & _CAT_MASK)

    def default_left(self, node: int) -> bool:
        return bool(self.decision_type[node] & _DEFAULT_LEFT_MASK)

    def missing_type(self, node: int) -> int:
        return (int(self.decision_type[node]) >> 2) & 3

    # ------------------------------------------------------------------
    @classmethod
    def from_arrays(cls, arrays, dataset, learning_rate: float = 1.0) -> "Tree":
        """Build from device ``TreeArrays`` + the Dataset (for real feature
        indices and real-valued thresholds)."""
        nl = int(arrays.num_leaves)
        t = cls(nl)
        if nl <= 1:
            return t
        m = nl - 1
        sf_inner = np.asarray(arrays.split_feature[:m], np.int32)
        t.split_feature_inner = sf_inner
        t.split_feature = np.array([dataset.used_features[i] for i in sf_inner], np.int32)
        t.threshold_bin = np.asarray(arrays.threshold[:m], np.int32)
        t.split_gain = np.asarray(arrays.split_gain[:m], np.float32)
        t.left_child = np.asarray(arrays.left_child[:m], np.int32)
        t.right_child = np.asarray(arrays.right_child[:m], np.int32)
        t.leaf_value = np.asarray(arrays.leaf_value[:nl], np.float64) * learning_rate
        t.leaf_weight = np.asarray(arrays.leaf_weight[:nl], np.float64)
        t.leaf_count = np.asarray(arrays.leaf_count[:nl], np.int64)
        t.internal_value = np.asarray(arrays.internal_value[:m], np.float64)
        t.internal_count = np.asarray(arrays.internal_count[:m], np.int64)
        t.shrinkage = learning_rate

        is_cat = np.asarray(arrays.is_cat_split[:m], bool)
        dleft = np.asarray(arrays.default_left[:m], bool)
        cat_bits = np.asarray(arrays.cat_bits[:m], np.int32).view(np.uint32)
        t.threshold = np.zeros(m, np.float64)
        t.decision_type = np.zeros(m, np.int8)
        for j in range(m):
            mapper: BinMapper = dataset.bin_mappers[t.split_feature[j]]
            dt = 0
            if is_cat[j]:
                dt |= _CAT_MASK
                # bin bitset -> category-VALUE bitset (reference
                # Tree::SplitCategorical stores cat_threshold over raw values)
                words = cat_bits[j]
                bins_set = [bi for bi in range(32 * len(words))
                            if (words[bi // 32] >> (bi % 32)) & 1]
                t.cat_bits_bin[j] = words.copy()
                cats = sorted(int(mapper.bin_to_value(bi)) for bi in bins_set)
                t.threshold[j] = float(len(t.cat_boundaries) - 1)  # cat index
                word_cnt = (max(cats) // 32 + 1) if cats else 1
                bits = [0] * word_cnt
                for cat in cats:
                    bits[cat // 32] |= 1 << (cat % 32)
                t.cat_threshold.extend(bits)
                t.cat_boundaries.append(len(t.cat_threshold))
            else:
                if dleft[j]:
                    dt |= _DEFAULT_LEFT_MASK
                dt |= int(mapper.missing_type) << 2
                t.threshold[j] = mapper.bin_to_value(int(t.threshold_bin[j]))
            t.decision_type[j] = dt
        return t

    # ------------------------------------------------------------------
    def _decide(self, node: int, values: np.ndarray) -> np.ndarray:
        """Vectorized decision for raw feature values -> goes-left bool."""
        if self.is_categorical_split(node):
            ci = int(self.threshold[node])
            lo, hi = self.cat_boundaries[ci], self.cat_boundaries[ci + 1]
            words = np.array(self.cat_threshold[lo:hi], dtype=np.uint32)
            iv = np.where(np.isfinite(values) & (values >= 0), values, -1).astype(np.int64)
            wi = iv // 32
            in_range = (iv >= 0) & (wi < len(words))
            wi_safe = np.clip(wi, 0, max(0, len(words) - 1))
            bit = (words[wi_safe] >> (iv % 32).astype(np.uint32)) & 1
            return in_range & (bit == 1)
        mt = self.missing_type(node)
        th = self.threshold[node]
        dl = self.default_left(node)
        nan_mask = np.isnan(values)
        if mt == int(MissingType.NONE):
            values = np.where(nan_mask, 0.0, values)
            return values <= th
        if mt == int(MissingType.ZERO):
            is_miss = nan_mask | (np.abs(values) <= K_ZERO_THRESHOLD)
        else:
            is_miss = nan_mask
        base = np.where(nan_mask, 0.0, values) <= th
        return np.where(is_miss, dl, base)

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Raw-value batch prediction (reference ``Tree::Predict``)."""
        n = X.shape[0]
        if self.num_leaves <= 1 and not self.is_linear:
            return np.full(n, self.leaf_value[0] if len(self.leaf_value) else 0.0)
        leaf = self.predict_leaf_index(X)
        if not self.is_linear:
            return self.leaf_value[leaf]
        # linear leaves: const + coeff·x; NaN in any leaf feature falls back
        # to the constant leaf value (reference PredictionFunLinear,
        # tree.cpp:127-136)
        out = np.zeros(n, np.float64)
        for l in np.unique(leaf):
            sel = leaf == l
            feats = self.leaf_features[l] if l < len(self.leaf_features) else []
            if not feats:
                out[sel] = self.leaf_const[l] if len(self.leaf_const) > l else self.leaf_value[l]
                continue
            vals = X[np.ix_(sel, feats)]
            nan_found = np.isnan(vals).any(axis=1)
            lin = self.leaf_const[l] + np.nan_to_num(vals) @ np.asarray(
                self.leaf_coeff[l], np.float64)
            out[sel] = np.where(nan_found, self.leaf_value[l], lin)
        return out

    def bin_cat_bitsets(self, mappers) -> None:
        """Rebuild BIN-space bitsets from the value bitsets so binned
        traversal works for text-loaded models (inverse of the
        ``from_arrays`` bin->value mapping)."""
        for j in range(self.num_internal):
            if not self.is_categorical_split(j) or j in self.cat_bits_bin:
                continue
            mapper = mappers[self.split_feature[j]]
            ci = int(self.threshold[j])
            lo, hi = self.cat_boundaries[ci], self.cat_boundaries[ci + 1]
            words_vals = np.array(self.cat_threshold[lo:hi], np.uint32)
            nb = mapper.num_bin
            out = np.zeros((nb + 31) // 32, np.uint32)
            for bi in range(nb):
                v = int(mapper.bin_to_value(bi))
                if 0 <= v < 32 * len(words_vals) and \
                        (int(words_vals[v // 32]) >> (v % 32)) & 1:
                    out[bi // 32] |= np.uint32(1 << (bi % 32))
            self.cat_bits_bin[j] = out

    def bin_numeric_thresholds(self, mappers) -> None:
        """Rebuild BIN-space numeric thresholds from the value thresholds
        so binned traversal works for text-loaded models (the numeric
        analog of ``bin_cat_bitsets``; ``from_text`` leaves
        ``threshold_bin`` unset because the reference grammar stores only
        real values).  Exact for same-data continuation: model thresholds
        are bin upper bounds, and ``value_to_bin`` maps a bound back to
        its own bin."""
        if self._has_bin_thresholds:
            return
        by_feat: dict = {}
        for j in range(self.num_internal):
            if not self.is_categorical_split(j):
                by_feat.setdefault(int(self.split_feature[j]), []).append(j)
        for fi, nodes in by_feat.items():
            # one vectorized call per feature, not one per node: a warm
            # start from a big ensemble rebuilds ~leaves x trees thresholds
            bins = np.asarray(mappers[fi].value_to_bin(
                np.array([float(self.threshold[j]) for j in nodes])))
            for j, b in zip(nodes, bins):
                self.threshold_bin[j] = int(b)
        self._has_bin_thresholds = True

    def predict_binned(self, bins: np.ndarray, nan_bins: np.ndarray) -> np.ndarray:
        """Batch prediction over BINNED columns (inner feature space), using
        the grower's decision convention (``ops/grower.py`` partition step).
        Used for continued-training score warm-up where only the binned
        matrix is resident."""
        n = bins.shape[0]
        if self.num_leaves <= 1:
            return np.full(n, self.leaf_value[0] if len(self.leaf_value) else 0.0)
        out = np.zeros(n, np.float64)
        node = np.zeros(n, np.int64)
        active = np.ones(n, bool)
        idx = np.arange(n)
        while active.any():
            cur = node[active]
            rows = idx[active]
            goes_left = np.zeros(len(rows), bool)
            for j in np.unique(cur):
                sel = cur == j
                fi = int(self.split_feature_inner[j])
                col = bins[rows[sel], fi].astype(np.int64)
                thr = int(self.threshold_bin[j])
                if self.is_categorical_split(j):
                    words = self.cat_bits_bin.get(j)
                    if words is None:
                        goes_left[sel] = col == thr      # legacy one-hot
                    else:
                        wi = (col >> 5).astype(np.int64)
                        ok_w = wi < len(words)
                        w = words[np.clip(wi, 0, len(words) - 1)]
                        goes_left[sel] = ok_w & (
                            ((w >> (col % 32).astype(np.uint32)) & 1) == 1)
                else:
                    nb = int(nan_bins[fi])
                    is_miss = (col == nb) & (nb >= 0)
                    goes_left[sel] = np.where(is_miss, self.default_left(j),
                                              col <= thr)
            nxt = np.where(goes_left, self.left_child[cur], self.right_child[cur])
            node[active] = nxt
            done = nxt < 0
            out[rows[done]] = self.leaf_value[~nxt[done]]
            active[rows[done]] = False
        return out

    def predict_leaf_index(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, np.int32)
        node = np.zeros(n, np.int64)
        active = np.ones(n, bool)
        idx = np.arange(n)
        leaf = np.zeros(n, np.int32)
        while active.any():
            cur = node[active]
            rows = idx[active]
            goes_left = np.zeros(len(rows), bool)
            for j in np.unique(cur):
                sel = cur == j
                goes_left[sel] = self._decide(int(j), X[rows[sel], self.split_feature[j]])
            nxt = np.where(goes_left, self.left_child[cur], self.right_child[cur])
            node[active] = nxt
            done = nxt < 0
            leaf[rows[done]] = ~nxt[done].astype(np.int32)
            active[rows[done]] = False
        return leaf

    # ------------------------------------------------------------------
    def shrink(self, rate: float) -> None:
        """Reference ``Tree::Shrinkage`` (``tree.h:187``)."""
        self.leaf_value *= rate
        self.internal_value *= rate
        self.shrinkage *= rate
        if self.is_linear:
            self.leaf_const = self.leaf_const * rate
            self.leaf_coeff = [[c * rate for c in cs] for cs in self.leaf_coeff]

    def add_bias(self, val: float) -> None:
        """Reference ``Tree::AddBias`` (``tree.h:212``)."""
        self.leaf_value += val
        self.internal_value += val
        if self.is_linear:
            self.leaf_const = self.leaf_const + val
        self.shrinkage = 1.0

    # ------------------------------------------------------------------
    def to_text(self, tree_index: int) -> str:
        """Serialize in the reference model-file grammar
        (``Tree::ToString``, ``src/io/tree.cpp:333``)."""
        m = self.num_internal
        lines = [f"Tree={tree_index}",
                 f"num_leaves={self.num_leaves}",
                 f"num_cat={len(self.cat_boundaries) - 1}"]

        def arr(name, a, fmt="{}"):
            lines.append(f"{name}=" + " ".join(fmt.format(v) for v in a))
        if m > 0 and self.num_leaves > 1:
            arr("split_feature", self.split_feature)
            arr("split_gain", self.split_gain, "{:g}")
            arr("threshold", self.threshold, "{:.17g}")
            arr("decision_type", self.decision_type)
            arr("left_child", self.left_child)
            arr("right_child", self.right_child)
            arr("leaf_value", self.leaf_value, "{:.17g}")
            arr("leaf_weight", self.leaf_weight, "{:g}")
            arr("leaf_count", self.leaf_count)
            arr("internal_value", self.internal_value, "{:g}")
            arr("internal_weight", self.internal_weight, "{:g}")
            arr("internal_count", self.internal_count)
            if len(self.cat_boundaries) > 1:
                arr("cat_boundaries", self.cat_boundaries)
                arr("cat_threshold", self.cat_threshold)
        else:
            lines.append("leaf_value=" + "{:.17g}".format(
                self.leaf_value[0] if len(self.leaf_value) else 0.0))
        if not self.is_linear:
            # ALWAYS write is_linear: the reference's text parser
            # (tree.cpp:694) only assigns is_linear_ when the key is present
            # and otherwise leaves the member uninitialized, so a file
            # without it makes reference builds treat random trees as empty
            # linear models (predicting 0); the reference's own writer emits
            # it unconditionally (Tree::ToString, tree.cpp:375)
            lines.append("is_linear=0")
        else:
            # reference linear-tree grammar (Tree::ToString, tree.cpp:375-399)
            lines.append("is_linear=1")
            arr("leaf_const", self.leaf_const, "{:.17g}")
            arr("num_features", [len(f) for f in self.leaf_features])
            lines.append("leaf_features="
                         + " ".join(" ".join(str(f) for f in fs)
                                    for fs in self.leaf_features if fs))
            lines.append("leaf_coeff="
                         + " ".join(" ".join("{:.17g}".format(c) for c in cs)
                                    for cs in self.leaf_coeff if cs))
        lines.append(f"shrinkage={self.shrinkage:g}")
        lines.append("")
        return "\n".join(lines)

    @classmethod
    def from_text(cls, block: str) -> "Tree":
        """Parse one ``Tree=N`` block of a model file (``tree.cpp`` load ctor)."""
        kv = {}
        for line in block.strip().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
        nl = int(kv.get("num_leaves", 1))
        t = cls(nl)
        t.shrinkage = float(kv.get("shrinkage", 1.0))

        def parse_linear(t):
            if int(kv.get("is_linear", "0")) == 0:
                return
            t.is_linear = True
            n_leaves = max(1, t.num_leaves)
            t.leaf_const = (np.array([float(x) for x in kv["leaf_const"].split()])
                            if "leaf_const" in kv else np.zeros(n_leaves))
            counts = ([int(x) for x in kv["num_features"].split()]
                      if "num_features" in kv else [0] * n_leaves)
            feats_flat = ([int(x) for x in kv.get("leaf_features", "").split()])
            coefs_flat = ([float(x) for x in kv.get("leaf_coeff", "").split()])
            t.leaf_features, t.leaf_coeff, o = [], [], 0
            for c in counts:
                t.leaf_features.append(feats_flat[o:o + c])
                t.leaf_coeff.append(coefs_flat[o:o + c])
                o += c

        if nl <= 1:
            if "leaf_value" in kv:
                t.leaf_value = np.array([float(x) for x in kv["leaf_value"].split()], np.float64)
            parse_linear(t)
            return t

        def get(name, dtype, default=None):
            if name not in kv:
                return default
            return np.array([dtype(x) for x in kv[name].split()])
        t.split_feature = get("split_feature", int).astype(np.int32)
        t.split_feature_inner = t.split_feature.copy()
        # the grammar stores real-valued thresholds only; bin-space ones
        # are rebuilt on demand (bin_numeric_thresholds) against a Dataset
        t._has_bin_thresholds = False
        sg = get("split_gain", float)
        t.split_gain = sg.astype(np.float32) if sg is not None else np.zeros(nl - 1, np.float32)
        t.threshold = get("threshold", float).astype(np.float64)
        t.decision_type = get("decision_type", int, np.zeros(nl - 1)).astype(np.int8)
        t.left_child = get("left_child", int).astype(np.int32)
        t.right_child = get("right_child", int).astype(np.int32)
        t.leaf_value = get("leaf_value", float).astype(np.float64)
        lw = get("leaf_weight", float)
        t.leaf_weight = lw.astype(np.float64) if lw is not None else np.zeros(nl)
        lc = get("leaf_count", int)
        t.leaf_count = lc.astype(np.int64) if lc is not None else np.zeros(nl, np.int64)
        iv = get("internal_value", float)
        t.internal_value = iv.astype(np.float64) if iv is not None else np.zeros(nl - 1)
        ic = get("internal_count", int)
        t.internal_count = ic.astype(np.int64) if ic is not None else np.zeros(nl - 1, np.int64)
        if "cat_boundaries" in kv:
            t.cat_boundaries = [int(x) for x in kv["cat_boundaries"].split()]
            t.cat_threshold = [int(x) for x in kv["cat_threshold"].split()]
        parse_linear(t)
        return t

    def to_json(self) -> dict:
        """Structural dump (reference ``Tree::ToJSON``, ``tree.cpp:409``)."""
        def node_json(i):
            if i < 0:
                leaf = ~i
                d = {"leaf_index": int(leaf),
                     "leaf_value": float(self.leaf_value[leaf]),
                     "leaf_weight": float(self.leaf_weight[leaf]),
                     "leaf_count": int(self.leaf_count[leaf])}
                if self.is_linear:
                    d["leaf_const"] = (float(self.leaf_const[leaf])
                                       if len(self.leaf_const) > leaf else 0.0)
                    d["leaf_features"] = list(self.leaf_features[leaf]) \
                        if leaf < len(self.leaf_features) else []
                    d["leaf_coeff"] = list(self.leaf_coeff[leaf]) \
                        if leaf < len(self.leaf_coeff) else []
                return d
            return {
                "split_index": int(i),
                "split_feature": int(self.split_feature[i]),
                "split_gain": float(self.split_gain[i]),
                "threshold": float(self.threshold[i]),
                "decision_type": "==" if self.is_categorical_split(i) else "<=",
                "default_left": self.default_left(i),
                "missing_type": ["None", "Zero", "NaN"][min(2, self.missing_type(i))],
                "internal_value": float(self.internal_value[i]),
                "internal_count": int(self.internal_count[i]),
                "left_child": node_json(int(self.left_child[i])),
                "right_child": node_json(int(self.right_child[i])),
            }
        return {"num_leaves": int(self.num_leaves), "num_cat": len(self.cat_boundaries) - 1,
                "shrinkage": self.shrinkage, "is_linear": int(self.is_linear),
                "tree_structure": node_json(0) if self.num_leaves > 1 else
                {"leaf_value": float(self.leaf_value[0]) if len(self.leaf_value) else 0.0}}
