"""GOSS: Gradient-based One-Side Sampling (reference ``src/boosting/goss.hpp``).

Keeps the top ``top_rate`` fraction of rows by |g·h| and a random
``other_rate`` fraction of the rest, scaling the sampled rows' gradients and
hessians by ``(1-top_rate)/other_rate`` (``goss.hpp:103-152``) — expressed as
device-side ``top_k`` + masked scaling instead of a partial sort.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.random_gen import key_for_iteration
from .gbdt import GBDT


def goss_mask_from_importance(cfg, imp, u, k_top: int):
    """(mask, amplify) from per-row |g·h| importance and a per-row uniform
    draw: EXACTLY ``k_top`` top rows plus an ``other_rate`` random sample of
    the rest, sampled rows amplified by ``(1-top_rate)/other_rate``
    (goss.hpp:103-152).  The shared math of GOSS._bagging_weights and the
    distributed trainer — the two paths must stay byte-identical for
    multi-process parity.  An ``imp >= threshold`` mask would inflate
    unboundedly on ties (identical |g*h| is the norm in early iterations),
    which both deviates from the reference's partial sort and defeats the
    subset-capacity bound."""
    n = imp.shape[0]
    _, top_idx = jax.lax.top_k(imp, k_top)
    is_top = jnp.zeros(n, bool).at[top_idx].set(True)
    sampled = (u < cfg.other_rate) & ~is_top
    mask = (is_top | sampled).astype(jnp.float32)
    scale = (1.0 - cfg.top_rate) / max(cfg.other_rate, 1e-12)
    return mask, jnp.where(sampled, scale, 1.0)


class GOSS(GBDT):
    def _bagging_weights(self, iteration, grad, hess):
        cfg = self.config
        n = self.train_data.num_data
        if cfg.top_rate + cfg.other_rate >= 1.0:
            return None, grad, hess
        # importance = sum over classes of |g*h| (goss.hpp:115)
        imp = jnp.sum(jnp.abs(grad * hess), axis=0)
        key = key_for_iteration(cfg.bagging_seed, iteration)
        mask, amplify = goss_mask_from_importance(
            cfg, imp, jax.random.uniform(key, (n,)),
            max(1, int(cfg.top_rate * n)))
        amplify = amplify[None, :]
        return mask, grad * amplify, hess * amplify

    # -- bagging-subset compaction (models/gbdt.py): GOSS keeps
    # top_rate + ~other_rate of the rows and re-bags EVERY iteration, so the
    # compacted grower pass pays one re-gather per iteration but shrinks
    # every histogram/partition pass to O(kept rows)
    def _bag_subset_capacity(self):
        cfg = self.config
        if (cfg.top_rate + cfg.other_rate >= self._BAG_SUBSET_MAX_FRACTION
                or getattr(self, "_mesh", None) is not None):
            return None
        n = self.train_data.num_data
        k_top = max(1, int(cfg.top_rate * n))
        return self._capacity_with_margin(k_top + (n - k_top) * cfg.other_rate,
                                          n)

    def _bag_subset_refresh(self, iteration: int) -> bool:
        return True                 # gradient-based membership: every iter
