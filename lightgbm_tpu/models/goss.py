"""GOSS: Gradient-based One-Side Sampling (reference ``src/boosting/goss.hpp``).

Keeps the top ``top_rate`` fraction of rows by |g·h| and a random
``other_rate`` fraction of the rest, scaling the sampled rows' gradients and
hessians by ``(1-top_rate)/other_rate`` (``goss.hpp:103-152``) — expressed as
device-side ``top_k`` + masked scaling instead of a partial sort.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils.random_gen import key_for_iteration
from .gbdt import GBDT


class GOSS(GBDT):
    def _bagging_weights(self, iteration, grad, hess):
        cfg = self.config
        n = self.train_data.num_data
        top_rate, other_rate = cfg.top_rate, cfg.other_rate
        if top_rate + other_rate >= 1.0:
            return None, grad, hess
        # importance = sum over classes of |g*h| (goss.hpp:115)
        imp = jnp.sum(jnp.abs(grad * hess), axis=0)
        top_k = max(1, int(top_rate * n))
        thresh = jax.lax.top_k(imp, top_k)[0][-1]
        is_top = imp >= thresh
        key = key_for_iteration(cfg.bagging_seed, iteration)
        sampled = (jax.random.uniform(key, (n,)) < other_rate) & ~is_top
        mask = (is_top | sampled).astype(jnp.float32)
        scale = (1.0 - top_rate) / max(other_rate, 1e-12)
        amplify = jnp.where(sampled, scale, 1.0)[None, :]
        return mask, grad * amplify, hess * amplify
