"""Model text serialization — reference-compatible grammar.

Mirrors ``src/boosting/gbdt_model_text.cpp`` (save ``:311``, load ``:416``):
a header (version/num_class/objective/feature names/feature infos), ``Tree=N``
blocks, ``end of trees``, feature importances, and a parameters section, so
models round-trip with the reference's loader.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..config import Config
from ..io.bin import BinType
from ..utils.log import Log, check
from .tree import Tree

_VERSION = "v3"


def feature_infos_from_dataset(dataset) -> List[str]:
    """Per-feature ``[min:max]`` / categorical ``a:b:c`` infos
    (reference ``Dataset::DumpTextFile`` feature_infos)."""
    infos = []
    for f in range(dataset.num_total_features):
        m = dataset.bin_mappers[f]
        if m.is_trivial:
            infos.append("none")
        elif m.bin_type == BinType.CATEGORICAL:
            infos.append(":".join(str(c) for c in m.bin_2_categorical))
        else:
            infos.append(f"[{m.min_val:g}:{m.max_val:g}]")
    return infos


def save_model_to_string(gbdt, num_iteration: int = -1,
                         start_iteration: int = 0,
                         feature_importance_type: int = 0) -> str:
    cfg: Config = gbdt.config
    K = gbdt.num_tree_per_iteration
    models = gbdt.models
    n_total_iters = len(models) // max(1, K)
    if num_iteration is None or num_iteration <= 0:
        num_iteration = n_total_iters - start_iteration
    num_iteration = min(num_iteration, n_total_iters - start_iteration)
    used = models[start_iteration * K:(start_iteration + num_iteration) * K]

    lines = ["tree", f"version={_VERSION}", f"num_class={cfg.num_class}",
             f"num_tree_per_iteration={K}", "label_index=0",
             f"max_feature_idx={gbdt.max_feature_idx}",
             f"objective={_objective_string(cfg)}"]
    if getattr(gbdt, "average_output", False):
        lines.append("average_output")
    fnames = (gbdt.train_data.feature_names if gbdt.train_data is not None
              else [f"Column_{i}" for i in range(gbdt.max_feature_idx + 1)])
    lines.append("feature_names=" + " ".join(fnames))
    if gbdt.train_data is not None:
        lines.append("feature_infos=" + " ".join(feature_infos_from_dataset(gbdt.train_data)))
    else:
        lines.append("feature_infos=" + " ".join(
            ["none"] * (gbdt.max_feature_idx + 1)))
    tree_strs = [t.to_text(i) for i, t in enumerate(used)]
    lines.append("tree_sizes=" + " ".join(str(len(s) + 1) for s in tree_strs))
    lines.append("")
    body = "\n".join(lines) + "\n" + "\n".join(tree_strs) + "\n"
    body += "end of trees\n\n"

    imp = gbdt.feature_importance(
        "gain" if feature_importance_type == 1 else "split")
    order = np.argsort(-imp, kind="stable")
    body += "feature_importances:\n"
    for f in order:
        if imp[f] > 0:
            body += f"{fnames[f]}={int(imp[f]) if feature_importance_type == 0 else imp[f]}\n"
    body += "\nparameters:\n"
    for k, v in cfg.to_dict(only_non_default=True).items():
        if isinstance(v, list):
            v = ",".join(str(x) for x in v)
        body += f"[{k}: {v}]\n"
    body += "end of parameters\n"
    return body


def _objective_string(cfg: Config) -> str:
    s = cfg.objective
    if cfg.objective in ("multiclass", "multiclassova"):
        s += f" num_class:{cfg.num_class}"
    if cfg.objective == "binary":
        s += f" sigmoid:{cfg.sigmoid:g}"
    if cfg.objective in ("lambdarank", "rank_xendcg"):
        pass
    return s


def format_pandas_categorical(pandas_categorical) -> str:
    """Trailing ``pandas_categorical:<json>`` line, the same format the
    reference python package appends after the C++ model text
    (``basic.py _dump_pandas_categorical:445``); the reference's text
    parser ignores trailing content, so files stay interoperable."""
    import json

    def _default(o):
        if isinstance(o, np.generic):
            return o.item()
        raise TypeError(f"cannot serialize {type(o).__name__}")

    return ("\npandas_categorical:"
            + json.dumps(pandas_categorical, default=_default) + "\n")


def parse_pandas_categorical(text: str):
    """Recover the category lists from a saved model's trailing line
    (reference ``_load_pandas_categorical``, ``basic.py:455``)."""
    import json
    tag = "pandas_categorical:"
    pos = text.rfind("\n" + tag)
    if pos < 0:
        return None
    lines = text[pos + 1 + len(tag):].splitlines()
    if not lines:            # file truncated right after the tag
        return None
    try:
        return json.loads(lines[0])
    except json.JSONDecodeError:
        return None


def load_model_from_string(text: str, gbdt_cls, config: Optional[Config] = None):
    """Parse a model file (reference ``GBDT::LoadModelFromString``,
    ``gbdt_model_text.cpp:416``)."""
    check(text.lstrip().startswith("tree"), "unknown model format")
    header, _, rest = text.partition("\nTree=")
    kv = {}
    for line in header.splitlines():
        if "=" in line:
            k, v = line.split("=", 1)
            kv[k.strip()] = v.strip()

    params = {}
    if "parameters:" in text:
        psec = text.split("parameters:", 1)[1].split("end of parameters", 1)[0]
        for line in psec.splitlines():
            line = line.strip()
            if line.startswith("[") and ":" in line:
                k, v = line[1:-1].split(":", 1)
                params[k.strip()] = v.strip()
    obj_str = kv.get("objective", "regression").split()
    params.setdefault("objective", obj_str[0] if obj_str else "regression")
    for tok in obj_str[1:]:
        if ":" in tok:
            k, v = tok.split(":", 1)
            params.setdefault(k, v)
    cfg = config or Config.from_params(params)

    gbdt = gbdt_cls(cfg)
    gbdt.num_tree_per_iteration = int(kv.get("num_tree_per_iteration", 1))
    gbdt.num_class = int(kv.get("num_class", 1))
    gbdt.max_feature_idx = int(kv.get("max_feature_idx", 0))
    gbdt.feature_names_ = kv.get("feature_names", "").split()
    gbdt.average_output = "average_output" in header.split()

    from ..objective import create_objective
    gbdt.objective = create_objective(cfg)

    if rest:
        tree_blocks = ("Tree=" + rest).split("end of trees")[0]
        blocks = tree_blocks.split("\nTree=")
        for i, b in enumerate(blocks):
            if not b.strip():
                continue
            if not b.startswith("Tree="):
                b = "Tree=" + b
            gbdt.models.append(Tree.from_text(b))
    gbdt.iter_ = len(gbdt.models) // max(1, gbdt.num_tree_per_iteration)
    return gbdt
