"""DART: Dropouts meet Multiple Additive Regression Trees
(reference ``src/boosting/dart.hpp``).

Per iteration: a random subset of existing trees is "dropped" (score
contributions subtracted), the new tree is fit against the reduced scores, and
both the new tree and the dropped trees are re-weighted
(``DroppingTrees`` ``dart.hpp:97``, ``Normalize`` ``:158``).  Dropped-tree
score deltas are recomputed by device-side binned traversal (tree arrays are
tiny and kept on device) instead of cached per-tree prediction buffers.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from ..ops.predict import predict_leaf_binned
from ..utils.random_gen import Random
from .gbdt import GBDT


class DART(GBDT):
    def init_train(self, train_data):
        super().init_train(train_data)
        self._device_trees: List = []            # per-model TreeArrays
        self._tree_weights: List[float] = []     # current scale of each model
        self._rng = Random(self.config.drop_seed)
        self.shrinkage_rate = 1.0                # DART applies lr via normalization

    # -- helpers ------------------------------------------------------------
    def _tree_score_delta(self, model_idx: int, bins, scale: float):
        ta = self._device_trees[model_idx]
        leaf = predict_leaf_binned(ta, bins, self._dd.nan_bins, efb=self._dd.efb)
        vals = ta.leaf_value * scale
        return vals[leaf]

    def train_one_iter(self, grad=None, hess=None):
        cfg = self.config
        K = self.num_tree_per_iteration
        n_models = len(self.models)
        n_iters_done = n_models // max(1, K)

        # --- choose drop set (dart.hpp:97) ---
        drop_iters: List[int] = []
        if n_iters_done > 0 and self._rng.next_float() >= cfg.skip_drop:
            if cfg.uniform_drop:
                drop_prob = 1.0 / max(1, n_iters_done)
                for i in range(n_iters_done):
                    if self._rng.next_float() < max(drop_prob, cfg.drop_rate):
                        drop_iters.append(i)
            else:
                for i in range(n_iters_done):
                    if self._rng.next_float() < cfg.drop_rate:
                        drop_iters.append(i)
            if cfg.max_drop > 0 and len(drop_iters) > cfg.max_drop:
                sel = np.random.default_rng(self._rng.next_int(0, 1 << 30)).choice(
                    len(drop_iters), cfg.max_drop, replace=False)
                drop_iters = [drop_iters[i] for i in sorted(sel)]

        # --- subtract dropped trees from scores ---
        for it in drop_iters:
            for k in range(K):
                mi = it * K + k
                w = self._tree_weights[mi]
                self._train_score = self._train_score.at[k].add(
                    -self._tree_score_delta(mi, self._dd.bins, w))
                for vi, vset in enumerate(self.valid_sets):
                    ta = self._device_trees[mi]
                    leaf = predict_leaf_binned(ta, vset.device_data().bins,
                                               self._dd.nan_bins, efb=self._dd.efb)
                    self._valid_scores[vi] = self._valid_scores[vi].at[k].add(
                        -(ta.leaf_value * w)[leaf])

        n_before = len(self.models)
        stop = super().train_one_iter(grad, hess)

        # --- normalize (dart.hpp:158) ---
        k_drop = len(drop_iters)
        lr = self.config.learning_rate
        if self.config.xgboost_dart_mode:
            new_scale = lr / (1.0 + lr)                 # xgboost mode
            old_factor = 1.0 / (1.0 + lr)
        else:
            new_scale = lr / (k_drop + 1.0) if k_drop > 0 else lr
            old_factor = k_drop / (k_drop + 1.0) if k_drop > 0 else 1.0

        # scale the newly-added trees by new_scale (they were added with
        # weight 1.0 by the base class since shrinkage_rate == 1)
        for mi in range(n_before, len(self.models)):
            self.models[mi].shrink(new_scale)
            self._tree_weights[mi] = new_scale
            k = mi - n_before
            adj = new_scale - 1.0
            ta = self._device_trees[mi]
            self._train_score = self._train_score.at[k].add(
                self._tree_score_delta(mi, self._dd.bins, adj))
            for vi, vset in enumerate(self.valid_sets):
                leaf = predict_leaf_binned(ta, vset.device_data().bins,
                                               self._dd.nan_bins, efb=self._dd.efb)
                self._valid_scores[vi] = self._valid_scores[vi].at[k].add(
                    (ta.leaf_value * adj)[leaf])

        # re-add dropped trees with reduced weight
        for it in drop_iters:
            for k in range(K):
                mi = it * K + k
                old_w = self._tree_weights[mi]
                new_w = old_w * old_factor
                self.models[mi].shrink(old_factor)
                self._tree_weights[mi] = new_w
                self._train_score = self._train_score.at[k].add(
                    self._tree_score_delta(mi, self._dd.bins, new_w))
                for vi, vset in enumerate(self.valid_sets):
                    ta = self._device_trees[mi]
                    leaf = predict_leaf_binned(ta, vset.device_data().bins,
                                               self._dd.nan_bins, efb=self._dd.efb)
                    self._valid_scores[vi] = self._valid_scores[vi].at[k].add(
                        (ta.leaf_value * new_w)[leaf])
        return stop
