"""Ranking metrics: NDCG@k and MAP@k.

Analog of the reference ``NDCGMetric`` (``src/metric/rank_metric.hpp:19``) and
``MapMetric`` (``src/metric/map_metric.hpp:21``) with ``DCGCalculator``
(``src/metric/dcg_calculator.cpp``).  The reference loops queries under
OpenMP; here all queries are evaluated at once in a padded ``[Q, L]`` numpy
layout (sort once, mask padded slots).
"""
from __future__ import annotations

from typing import List

import numpy as np

from .base import Metric
from . import register_metric
from ..objective.rank import default_label_gain, check_rank_labels
from ..utils.log import LightGBMError


def _padded_layout(boundaries: np.ndarray):
    counts = np.diff(boundaries).astype(np.int64)
    Q, L = len(counts), int(max(1, counts.max()))
    idx = boundaries[:-1, None] + np.minimum(np.arange(L)[None, :],
                                             np.maximum(counts[:, None] - 1, 0))
    mask = np.arange(L)[None, :] < counts[:, None]
    return idx, mask, counts


def _sorted_by_score(score, label, idx, mask):
    """Labels per query re-ordered by descending score (stable)."""
    s = score[idx]
    s_masked = np.where(mask, s, -np.inf)
    order = np.argsort(-s_masked, axis=1, kind="stable")
    return np.take_along_axis(label[idx], order, axis=1)


class NDCGMetric(Metric):
    name = "ndcg"
    higher_better = True

    def __init__(self, config):
        super().__init__(config)
        self.eval_at = list(config.eval_at) or [1, 2, 3, 4, 5]
        self.label_gain = (np.asarray(config.label_gain, np.float64)
                           if config.label_gain else default_label_gain())

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.query_boundaries is None:
            raise LightGBMError("The NDCG metric requires query information")
        check_rank_labels(self.label, len(self.label_gain))
        b = np.asarray(self.query_boundaries, np.int64)
        self._idx, self._mask, self._counts = _padded_layout(b)
        Q, L = self._mask.shape
        self._disc = 1.0 / np.log2(2.0 + np.arange(L))
        # ideal (max) DCG per query per k: labels sorted descending
        lab = np.where(self._mask, self.label[self._idx], -1)
        ideal = -np.sort(-lab, axis=1)                 # descending
        gains_ideal = np.where(ideal >= 0, self.label_gain[np.maximum(ideal, 0)
                                                           .astype(np.int64)], 0.0)
        csum = np.cumsum(gains_ideal * self._disc[None, :], axis=1)
        self._inv_max = {}
        for k in self.eval_at:
            kk = np.minimum(k, self._counts) - 1
            mx = csum[np.arange(Q), np.maximum(kk, 0)]
            inv = np.where(mx > 0, 1.0 / np.maximum(mx, 1e-300), -1.0)
            self._inv_max[k] = inv
        # per-query weights: reference uses metadata query weights; we derive
        # them from row weights (constant within query) when present
        if self.weight is not None:
            self._qw = self.weight[b[:-1]].astype(np.float64)
        else:
            self._qw = np.ones(Q, np.float64)
        self._sum_qw = float(self._qw.sum())

    def eval(self, score, objective=None) -> List:
        score = np.asarray(score, np.float64).ravel()
        sl = _sorted_by_score(score, self.label, self._idx, self._mask)
        gains = np.where(self._mask,
                         self.label_gain[np.maximum(sl, 0).astype(np.int64)], 0.0)
        csum = np.cumsum(gains * self._disc[None, :], axis=1)
        Q = len(self._counts)
        out = []
        for k in self.eval_at:
            kk = np.minimum(k, self._counts) - 1
            dcg = csum[np.arange(Q), np.maximum(kk, 0)]
            inv = self._inv_max[k]
            ndcg = np.where(inv <= 0, 1.0, dcg * np.maximum(inv, 0.0))
            val = float(np.sum(ndcg * self._qw) / self._sum_qw)
            out.append((f"ndcg@{k}", val, True))
        return out


class MapMetric(Metric):
    name = "map"
    higher_better = True

    def __init__(self, config):
        super().__init__(config)
        self.eval_at = list(config.eval_at) or [1, 2, 3, 4, 5]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.query_boundaries is None:
            raise LightGBMError("For MAP metric, there should be query information")
        b = np.asarray(self.query_boundaries, np.int64)
        self._idx, self._mask, self._counts = _padded_layout(b)
        rel = (self.label[self._idx] > 0.5) & self._mask
        self._npos = rel.sum(axis=1)
        if self.weight is not None:
            self._qw = self.weight[b[:-1]].astype(np.float64)
        else:
            self._qw = np.ones(len(self._counts), np.float64)
        self._sum_qw = float(self._qw.sum())

    def eval(self, score, objective=None) -> List:
        score = np.asarray(score, np.float64).ravel()
        sl = _sorted_by_score(score, self.label, self._idx, self._mask)
        hit = (sl > 0.5) & self._mask                      # [Q, L]
        cum_hits = np.cumsum(hit, axis=1)
        ranks = np.arange(1, hit.shape[1] + 1)[None, :]
        prec_at_hit = np.where(hit, cum_hits / ranks, 0.0)
        csum_ap = np.cumsum(prec_at_hit, axis=1)
        Q = len(self._counts)
        out = []
        for k in self.eval_at:
            kk = np.minimum(k, self._counts)
            sum_ap = csum_ap[np.arange(Q), np.maximum(kk - 1, 0)]
            denom = np.minimum(self._npos, kk)
            ap = np.where(self._npos > 0,
                          sum_ap / np.maximum(denom, 1), 1.0)
            val = float(np.sum(ap * self._qw) / self._sum_qw)
            out.append((f"map@{k}", val, True))
        return out


register_metric("ndcg", NDCGMetric)
register_metric("map", MapMetric)

__all__ = ["NDCGMetric", "MapMetric"]
