"""Metric interface + regression/binary/multiclass metrics.

Analog of the reference ``Metric`` (``include/LightGBM/metric.h``;
implementations ``src/metric/{regression,binary,multiclass}_metric.hpp``).
``eval(score, objective)`` receives RAW scores and uses the objective's
output transform, exactly like the reference.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..config import Config


class Metric:
    name: str = "base"
    higher_better: bool = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = metadata.label
        self.weight = metadata.weight
        self.query_boundaries = metadata.query_boundaries
        self.sum_weights = (float(np.sum(self.weight))
                            if self.weight is not None else float(num_data))

    def eval(self, score: np.ndarray, objective=None) -> List[Tuple[str, float, bool]]:
        raise NotImplementedError

    # -- helpers --------------------------------------------------------
    def _transform(self, score: np.ndarray, objective) -> np.ndarray:
        if objective is not None:
            out = objective.convert_output(score)
            return np.asarray(out)
        return score

    def _avg(self, pointwise: np.ndarray) -> float:
        if self.weight is not None:
            return float(np.sum(pointwise * self.weight) / self.sum_weights)
        return float(np.mean(pointwise))


class _PointwiseRegressionMetric(Metric):
    def point_loss(self, y: np.ndarray, p: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def eval(self, score, objective=None):
        pred = self._transform(score, objective)
        return [(self.name, self._avg(self.point_loss(self.label, pred)), self.higher_better)]


class L2Metric(_PointwiseRegressionMetric):
    name = "l2"

    def point_loss(self, y, p):
        return (y - p) ** 2


class RMSEMetric(_PointwiseRegressionMetric):
    name = "rmse"

    def eval(self, score, objective=None):
        pred = self._transform(score, objective)
        return [(self.name, float(np.sqrt(self._avg((self.label - pred) ** 2))), False)]


class L1Metric(_PointwiseRegressionMetric):
    name = "l1"

    def point_loss(self, y, p):
        return np.abs(y - p)


class QuantileMetric(_PointwiseRegressionMetric):
    name = "quantile"

    def point_loss(self, y, p):
        a = self.config.alpha
        d = y - p
        return np.where(d >= 0, a * d, (a - 1) * d)


class HuberMetric(_PointwiseRegressionMetric):
    name = "huber"

    def point_loss(self, y, p):
        a = self.config.alpha
        d = np.abs(y - p)
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_PointwiseRegressionMetric):
    name = "fair"

    def point_loss(self, y, p):
        c = self.config.fair_c
        x = np.abs(y - p)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseRegressionMetric):
    name = "poisson"

    def point_loss(self, y, p):
        eps = 1e-10
        return p - y * np.log(np.maximum(p, eps))


class MAPEMetric(_PointwiseRegressionMetric):
    name = "mape"

    def point_loss(self, y, p):
        return np.abs((y - p) / np.maximum(1.0, np.abs(y)))


class GammaMetric(_PointwiseRegressionMetric):
    name = "gamma"

    def point_loss(self, y, p):
        psi = 1.0
        theta = -1.0 / np.maximum(p, 1e-10)
        a = psi
        b = -np.log(-theta)
        c = 1.0 / psi * np.log(y / psi) - np.log(y) - 0  # lgamma(1/psi) const dropped
        from scipy.special import gammaln  # scipy is available with sklearn
        c = 1.0 / psi * np.log(y / psi) - np.log(y) - gammaln(1.0 / psi)
        return -((y * theta + b) / a + c)


class GammaDevianceMetric(_PointwiseRegressionMetric):
    name = "gamma_deviance"

    def point_loss(self, y, p):
        eps = 1e-10
        frac = y / np.maximum(p, eps)
        return 2.0 * (frac - np.log(np.maximum(frac, eps)) - 1.0)


class TweedieMetric(_PointwiseRegressionMetric):
    name = "tweedie"

    def point_loss(self, y, p):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        p = np.maximum(p, eps)
        a = y * np.exp((1.0 - rho) * np.log(p)) / (1.0 - rho)
        b = np.exp((2.0 - rho) * np.log(p)) / (2.0 - rho)
        return -a + b


class BinaryLoglossMetric(Metric):
    name = "binary_logloss"

    def eval(self, score, objective=None):
        prob = np.clip(self._transform(score, objective), 1e-15, 1 - 1e-15)
        y = (self.label > 0).astype(np.float64)
        loss = -(y * np.log(prob) + (1 - y) * np.log(1 - prob))
        return [(self.name, self._avg(loss), False)]


class BinaryErrorMetric(Metric):
    name = "binary_error"

    def eval(self, score, objective=None):
        prob = self._transform(score, objective)
        y = (self.label > 0).astype(np.float64)
        err = ((prob > 0.5) != (y > 0)).astype(np.float64)
        return [(self.name, self._avg(err), False)]


class AUCMetric(Metric):
    name = "auc"
    higher_better = True

    def eval(self, score, objective=None):
        # weighted rank-sum AUC with tie handling (reference
        # binary_metric.hpp AUCMetric::Eval), vectorized over tie groups
        score = np.asarray(score, dtype=np.float64).ravel()
        y = (self.label > 0)
        w = (self.weight if self.weight is not None
             else np.ones(len(y))).astype(np.float64)
        order = np.argsort(score, kind="mergesort")
        s, ys, ws = score[order], y[order], w[order]
        pos_w = ws[ys].sum()
        neg_w = ws[~ys].sum()
        if pos_w <= 0 or neg_w <= 0:
            return [(self.name, 0.5, True)]
        # group boundaries of tied scores
        new_grp = np.empty(len(s), bool)
        new_grp[0] = True
        new_grp[1:] = s[1:] != s[:-1]
        gid = np.cumsum(new_grp) - 1
        n_grp = gid[-1] + 1
        wp = np.bincount(gid, weights=ws * ys, minlength=n_grp)       # pos mass/group
        wn = np.bincount(gid, weights=ws * ~ys, minlength=n_grp)      # neg mass/group
        neg_below = np.concatenate([[0.0], np.cumsum(wn)[:-1]])
        auc = np.sum(wp * (neg_below + wn / 2.0)) / (pos_w * neg_w)
        return [(self.name, float(auc), True)]


class AveragePrecisionMetric(Metric):
    name = "average_precision"
    higher_better = True

    def eval(self, score, objective=None):
        y = (self.label > 0).astype(np.float64)
        w = self.weight if self.weight is not None else np.ones(len(y))
        order = np.argsort(-np.asarray(score), kind="mergesort")
        ys, ws = y[order], w[order]
        tp = np.cumsum(ws * ys)
        fp = np.cumsum(ws * (1 - ys))
        precision = tp / np.maximum(tp + fp, 1e-20)
        total_pos = tp[-1]
        if total_pos <= 0:
            return [(self.name, 0.0, True)]
        ap = np.sum(precision * ws * ys) / total_pos
        return [(self.name, float(ap), True)]


class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, score, objective=None):
        # score: [K, N]
        prob = np.clip(self._transform(score, objective), 1e-15, 1.0)
        lbl = self.label.astype(np.int64)
        p_true = prob[lbl, np.arange(len(lbl))]
        return [(self.name, self._avg(-np.log(p_true)), False)]


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, score, objective=None):
        prob = self._transform(score, objective)     # [K, N]
        lbl = self.label.astype(np.int64)
        k = self.config.multi_error_top_k
        if k <= 1:
            err = (np.argmax(prob, axis=0) != lbl).astype(np.float64)
        else:
            topk = np.argsort(-prob, axis=0)[:k]
            err = (~(topk == lbl[None, :]).any(axis=0)).astype(np.float64)
        return [(self.name if k <= 1 else f"multi_error@{k}", self._avg(err), False)]
