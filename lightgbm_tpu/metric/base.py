"""Metric interface + regression/binary/multiclass metrics.

Analog of the reference ``Metric`` (``include/LightGBM/metric.h``;
implementations ``src/metric/{regression,binary,multiclass}_metric.hpp``).
``eval(score, objective)`` receives RAW scores and uses the objective's
output transform, exactly like the reference.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..config import Config


class Metric:
    name: str = "base"
    higher_better: bool = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = metadata.label
        self.weight = metadata.weight
        self.query_boundaries = metadata.query_boundaries
        self.sum_weights = (float(np.sum(self.weight))
                            if self.weight is not None else float(num_data))

    def eval(self, score: np.ndarray, objective=None) -> List[Tuple[str, float, bool]]:
        raise NotImplementedError

    # -- helpers --------------------------------------------------------
    def _transform(self, score: np.ndarray, objective) -> np.ndarray:
        if objective is not None:
            out = objective.convert_output(score)
            return np.asarray(out)
        return score

    def _avg(self, pointwise: np.ndarray) -> float:
        if self.weight is not None:
            return float(np.sum(pointwise * self.weight) / self.sum_weights)
        return float(np.mean(pointwise))


class _PointwiseRegressionMetric(Metric):
    def point_loss(self, y: np.ndarray, p: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def eval(self, score, objective=None):
        pred = self._transform(score, objective)
        return [(self.name, self._avg(self.point_loss(self.label, pred)), self.higher_better)]


class L2Metric(_PointwiseRegressionMetric):
    name = "l2"

    def point_loss(self, y, p):
        return (y - p) ** 2


class RMSEMetric(_PointwiseRegressionMetric):
    name = "rmse"

    def eval(self, score, objective=None):
        pred = self._transform(score, objective)
        return [(self.name, float(np.sqrt(self._avg((self.label - pred) ** 2))), False)]


class L1Metric(_PointwiseRegressionMetric):
    name = "l1"

    def point_loss(self, y, p):
        return np.abs(y - p)


class QuantileMetric(_PointwiseRegressionMetric):
    name = "quantile"

    def point_loss(self, y, p):
        a = self.config.alpha
        d = y - p
        return np.where(d >= 0, a * d, (a - 1) * d)


class HuberMetric(_PointwiseRegressionMetric):
    name = "huber"

    def point_loss(self, y, p):
        a = self.config.alpha
        d = np.abs(y - p)
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_PointwiseRegressionMetric):
    name = "fair"

    def point_loss(self, y, p):
        c = self.config.fair_c
        x = np.abs(y - p)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseRegressionMetric):
    name = "poisson"

    def point_loss(self, y, p):
        eps = 1e-10
        return p - y * np.log(np.maximum(p, eps))


class MAPEMetric(_PointwiseRegressionMetric):
    name = "mape"

    def point_loss(self, y, p):
        return np.abs((y - p) / np.maximum(1.0, np.abs(y)))


class GammaMetric(_PointwiseRegressionMetric):
    name = "gamma"

    def point_loss(self, y, p):
        psi = 1.0
        theta = -1.0 / np.maximum(p, 1e-10)
        a = psi
        b = -np.log(-theta)
        c = 1.0 / psi * np.log(y / psi) - np.log(y) - 0  # lgamma(1/psi) const dropped
        from scipy.special import gammaln  # scipy is available with sklearn
        c = 1.0 / psi * np.log(y / psi) - np.log(y) - gammaln(1.0 / psi)
        return -((y * theta + b) / a + c)


class GammaDevianceMetric(_PointwiseRegressionMetric):
    name = "gamma_deviance"

    def point_loss(self, y, p):
        eps = 1e-10
        frac = y / np.maximum(p, eps)
        return 2.0 * (frac - np.log(np.maximum(frac, eps)) - 1.0)


class TweedieMetric(_PointwiseRegressionMetric):
    name = "tweedie"

    def point_loss(self, y, p):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        p = np.maximum(p, eps)
        a = y * np.exp((1.0 - rho) * np.log(p)) / (1.0 - rho)
        b = np.exp((2.0 - rho) * np.log(p)) / (2.0 - rho)
        return -a + b


class BinaryLoglossMetric(Metric):
    name = "binary_logloss"

    def eval(self, score, objective=None):
        prob = np.clip(self._transform(score, objective), 1e-15, 1 - 1e-15)
        y = (self.label > 0).astype(np.float64)
        loss = -(y * np.log(prob) + (1 - y) * np.log(1 - prob))
        return [(self.name, self._avg(loss), False)]


class BinaryErrorMetric(Metric):
    name = "binary_error"

    def eval(self, score, objective=None):
        prob = self._transform(score, objective)
        y = (self.label > 0).astype(np.float64)
        err = ((prob > 0.5) != (y > 0)).astype(np.float64)
        return [(self.name, self._avg(err), False)]


class AUCMetric(Metric):
    name = "auc"
    higher_better = True

    def eval(self, score, objective=None):
        # weighted rank-sum AUC with tie handling (reference
        # binary_metric.hpp AUCMetric::Eval), vectorized over tie groups
        score = np.asarray(score, dtype=np.float64).ravel()
        y = (self.label > 0)
        w = (self.weight if self.weight is not None
             else np.ones(len(y))).astype(np.float64)
        order = np.argsort(score, kind="mergesort")
        s, ys, ws = score[order], y[order], w[order]
        pos_w = ws[ys].sum()
        neg_w = ws[~ys].sum()
        if pos_w <= 0 or neg_w <= 0:
            return [(self.name, 0.5, True)]
        # group boundaries of tied scores
        new_grp = np.empty(len(s), bool)
        new_grp[0] = True
        new_grp[1:] = s[1:] != s[:-1]
        gid = np.cumsum(new_grp) - 1
        n_grp = gid[-1] + 1
        wp = np.bincount(gid, weights=ws * ys, minlength=n_grp)       # pos mass/group
        wn = np.bincount(gid, weights=ws * ~ys, minlength=n_grp)      # neg mass/group
        neg_below = np.concatenate([[0.0], np.cumsum(wn)[:-1]])
        auc = np.sum(wp * (neg_below + wn / 2.0)) / (pos_w * neg_w)
        return [(self.name, float(auc), True)]


class AveragePrecisionMetric(Metric):
    name = "average_precision"
    higher_better = True

    def eval(self, score, objective=None):
        y = (self.label > 0).astype(np.float64)
        w = self.weight if self.weight is not None else np.ones(len(y))
        order = np.argsort(-np.asarray(score), kind="mergesort")
        ys, ws = y[order], w[order]
        tp = np.cumsum(ws * ys)
        fp = np.cumsum(ws * (1 - ys))
        precision = tp / np.maximum(tp + fp, 1e-20)
        total_pos = tp[-1]
        if total_pos <= 0:
            return [(self.name, 0.0, True)]
        ap = np.sum(precision * ws * ys) / total_pos
        return [(self.name, float(ap), True)]


class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, score, objective=None):
        # score: [K, N]
        prob = np.clip(self._transform(score, objective), 1e-15, 1.0)
        lbl = self.label.astype(np.int64)
        p_true = prob[lbl, np.arange(len(lbl))]
        return [(self.name, self._avg(-np.log(p_true)), False)]


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, score, objective=None):
        prob = self._transform(score, objective)     # [K, N]
        lbl = self.label.astype(np.int64)
        k = self.config.multi_error_top_k
        if k <= 1:
            err = (np.argmax(prob, axis=0) != lbl).astype(np.float64)
        else:
            topk = np.argsort(-prob, axis=0)[:k]
            err = (~(topk == lbl[None, :]).any(axis=0)).astype(np.float64)
        return [(self.name if k <= 1 else f"multi_error@{k}", self._avg(err), False)]


class AucMuMetric(Metric):
    """AUC-mu multiclass ranking metric (Kleiman & Page 2019), the analog of
    the reference ``AucMuMetric`` (``src/metric/multiclass_metric.hpp:183``).

    For every class pair (i, j), rows of the two classes are projected onto
    the separating direction ``t1 * (w_i - w_j) . score`` and a pairwise
    Mann-Whitney statistic is computed (ties credit 0.5, matching the
    reference's "j first then subtract half the tied j mass" accounting);
    the result averages over all C(K, 2) pairs.  Raw scores are used, as in
    the reference.  One deviation: ties are exact-equality groups rather
    than kEpsilon(=1e-15)-chained comparisons — indistinguishable except for
    adversarially spaced scores.
    """
    name = "auc_mu"
    higher_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        from ..utils.log import LightGBMError
        K = self.config.num_class
        if K < 2:
            raise LightGBMError("auc_mu requires num_class >= 2")
        self.num_class = K
        lbl = self.label.astype(np.int64)
        self._idx_by_class = [np.flatnonzero(lbl == c) for c in range(K)]
        if self.weight is not None:
            self._class_weight_sums = np.asarray(
                [float(self.weight[ix].sum()) for ix in self._idx_by_class])
        # class-weight matrix (reference config.cpp:157-180: default is
        # all-ones with zero diagonal; user matrix must be KxK, diagonal
        # forced to zero)
        W = self.config.auc_mu_weights
        if W:
            if len(W) != K * K:
                raise LightGBMError(
                    f"auc_mu_weights must have {K * K} elements, "
                    f"but found {len(W)}")
            mat = np.asarray(W, np.float64).reshape(K, K)
            np.fill_diagonal(mat, 0.0)
        else:
            mat = np.ones((K, K), np.float64)
            np.fill_diagonal(mat, 0.0)
        self._class_weights = mat

    def eval(self, score, objective=None):
        K = self.num_class
        lbl = self.label.astype(np.int64)
        ans = 0.0
        for i in range(K):
            ix_i = self._idx_by_class[i]
            for j in range(i + 1, K):
                ix_j = self._idx_by_class[j]
                if len(ix_i) == 0 or len(ix_j) == 0:
                    continue
                curr_v = self._class_weights[i] - self._class_weights[j]
                t1 = curr_v[i] - curr_v[j]
                idx = np.concatenate([ix_i, ix_j])
                d = t1 * (curr_v @ score[:, idx])             # [ni+nj]
                is_i = lbl[idx] == i
                w = (self.weight[idx] if self.weight is not None
                     else np.ones(len(idx)))
                order = np.argsort(d, kind="stable")
                d_s, is_i_s, w_s = d[order], is_i[order], w[order]
                jw = np.where(~is_i_s, w_s, 0.0)
                new_grp = np.concatenate([[True], np.diff(d_s) != 0.0])
                gid = np.cumsum(new_grp) - 1
                n_grp = int(gid[-1]) + 1
                jw_grp = np.bincount(gid, weights=jw, minlength=n_grp)
                j_below = np.concatenate([[0.0], np.cumsum(jw_grp)])[:-1]
                credit = j_below[gid] + 0.5 * jw_grp[gid]
                s_ij = float(np.sum(np.where(is_i_s, w_s * credit, 0.0)))
                if self.weight is None:
                    ans += s_ij / len(ix_i) / len(ix_j)
                else:
                    ans += (s_ij / self._class_weight_sums[i]
                            / self._class_weight_sums[j])
        ans = 2.0 * ans / K / (K - 1)
        return [(self.name, float(ans), True)]
