"""Metric factory (reference ``src/metric/metric.cpp:18-62``)."""
from __future__ import annotations

from typing import List

from ..config import Config
from ..utils.log import Log
from .base import (Metric, L1Metric, L2Metric, RMSEMetric, QuantileMetric,
                   HuberMetric, FairMetric, PoissonMetric, MAPEMetric,
                   GammaMetric, GammaDevianceMetric, TweedieMetric,
                   BinaryLoglossMetric, BinaryErrorMetric, AUCMetric,
                   AveragePrecisionMetric, MultiLoglossMetric, MultiErrorMetric,
                   AucMuMetric)

_ALIASES = {
    "mean_squared_error": "l2", "mse": "l2", "regression": "l2", "regression_l2": "l2",
    "l2_root": "rmse", "root_mean_squared_error": "rmse",
    "mean_absolute_error": "l1", "regression_l1": "l1", "mae": "l1",
    "mean_absolute_percentage_error": "mape",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss", "ova": "multi_logloss", "ovr": "multi_logloss",
    "xentropy": "cross_entropy", "xentlambda": "cross_entropy_lambda",
    "kldiv": "kullback_leibler",
    "mean_average_precision": "map",
}

_REGISTRY = {
    "l1": L1Metric, "l2": L2Metric, "rmse": RMSEMetric, "quantile": QuantileMetric,
    "huber": HuberMetric, "fair": FairMetric, "poisson": PoissonMetric,
    "mape": MAPEMetric, "gamma": GammaMetric, "gamma_deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric, "binary_logloss": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric, "auc": AUCMetric,
    "average_precision": AveragePrecisionMetric,
    "multi_logloss": MultiLoglossMetric, "multi_error": MultiErrorMetric,
    "auc_mu": AucMuMetric,
}


def create_metric(name: str, config: Config):
    name = _ALIASES.get(name, name)
    if name in ("ndcg", "map"):
        from . import rank  # registers itself
    if name in ("cross_entropy", "cross_entropy_lambda", "kullback_leibler"):
        from . import xentropy  # registers itself
    if name in ("custom", "none", "null", "na", ""):
        return None
    if name not in _REGISTRY:
        Log.warning("Unknown metric %s, ignored", name)
        return None
    return _REGISTRY[name](config)


def create_metrics(config: Config) -> List[Metric]:
    out = []
    seen = set()
    for name in config.metric:
        name = _ALIASES.get(name, name)
        if name in seen:
            continue
        seen.add(name)
        m = create_metric(name, config)
        if m is not None:
            out.append(m)
    return out


def register_metric(name: str, cls) -> None:
    _REGISTRY[name] = cls


__all__ = ["Metric", "create_metric", "create_metrics", "register_metric"]
