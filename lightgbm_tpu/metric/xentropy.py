"""Cross-entropy metrics (reference ``src/metric/xentropy_metric.hpp``):
``cross_entropy`` (:71), ``cross_entropy_lambda`` (:166) and
``kullback_leibler`` (:249)."""
from __future__ import annotations

import numpy as np

from .base import Metric
from . import register_metric


def _xent(y: np.ndarray, p: np.ndarray) -> np.ndarray:
    p = np.clip(p, 1e-15, 1.0 - 1e-15)
    return -(y * np.log(p) + (1.0 - y) * np.log(1.0 - p))


class CrossEntropyMetric(Metric):
    name = "cross_entropy"

    def eval(self, score, objective=None):
        p = np.asarray(self._transform(score, objective), np.float64).ravel()
        return [(self.name, self._avg(_xent(self.label, p)), False)]


class CrossEntropyLambdaMetric(Metric):
    name = "cross_entropy_lambda"

    def eval(self, score, objective=None):
        score = np.asarray(score, np.float64).ravel()
        if objective is not None:
            hhat = np.asarray(objective.convert_output(score))
        else:
            hhat = np.log1p(np.exp(score))
        w = self.weight if self.weight is not None else 1.0
        p = 1.0 - np.exp(-w * hhat)
        # reference averages by num_data, not sum of weights
        # (xentropy_metric.hpp:221)
        loss = float(np.mean(_xent(self.label, p)))
        return [(self.name, loss, False)]


class KullbackLeiblerDivergence(Metric):
    name = "kullback_leibler"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        y = np.clip(self.label.astype(np.float64), 1e-15, 1.0 - 1e-15)
        ent = y * np.log(y) + (1.0 - y) * np.log(1.0 - y)
        # degenerate labels 0/1 contribute zero entropy
        ent = np.where((self.label <= 0) | (self.label >= 1), 0.0, ent)
        self._offset = self._avg(ent)

    def eval(self, score, objective=None):
        p = np.asarray(self._transform(score, objective), np.float64).ravel()
        return [(self.name, self._offset + self._avg(_xent(self.label, p)), False)]


register_metric("cross_entropy", CrossEntropyMetric)
register_metric("cross_entropy_lambda", CrossEntropyLambdaMetric)
register_metric("kullback_leibler", KullbackLeiblerDivergence)

__all__ = ["CrossEntropyMetric", "CrossEntropyLambdaMetric",
           "KullbackLeiblerDivergence"]
