"""lightgbm_tpu: a TPU-native gradient-boosting (GBDT) framework.

A from-scratch re-design of LightGBM's capabilities for TPUs: JAX/XLA/Pallas
compute (one-hot MXU histograms, single-program leaf-wise tree growth,
``shard_map`` collectives for distributed training) behind the familiar
LightGBM Python API surface (``Dataset``/``Booster``/``train``/``cv``/sklearn
wrappers).
"""
from .basic import Booster, Dataset
from .callback import early_stopping, print_evaluation, log_evaluation, \
    record_evaluation, reset_parameter
from .config import Config
from .engine import CVBooster, cv, train
from .utils.log import LightGBMError, register_log_callback

__version__ = "0.1.0"

__all__ = ["Booster", "Dataset", "Config", "CVBooster", "cv", "train",
           "LightGBMError", "register_log_callback", "early_stopping",
           "print_evaluation", "log_evaluation", "record_evaluation",
           "reset_parameter", "__version__"]


def __getattr__(name):
    # lazy imports for optional API surfaces
    if name in ("LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"):
        from . import sklearn as _sk
        return getattr(_sk, name)
    if name in ("serve", "PredictorArtifact", "Predictor", "MicroBatcher",
                "QueueSaturatedError"):
        from . import serve as _serve
        return _serve if name == "serve" else getattr(_serve, name)
    if name in ("DistLGBMClassifier", "DistLGBMRegressor"):
        from .parallel import estimators as _est
        return getattr(_est, name)
    if name == "stream":
        from . import stream as _stream
        return _stream
    if name.startswith("plot_") or name in ("create_tree_digraph", "plotting"):
        import importlib
        _pl = importlib.import_module(".plotting", __name__)
        return _pl if name == "plotting" else getattr(_pl, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
