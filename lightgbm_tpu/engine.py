"""Training entry points: ``train()`` and ``cv()``
(reference ``python-package/lightgbm/engine.py:15,392``)."""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .config import Config
from .utils.log import Log, LightGBMError

__all__ = ["train", "cv", "CVBooster"]


def _apply_dataset_kwargs(train_set: Dataset, feature_name,
                          categorical_feature) -> None:
    """Shared by train()/cv(): the reference applies these kwargs to the
    training Dataset before construction (``engine.py:96-99``)."""
    if feature_name != "auto":
        train_set.set_feature_name(feature_name)
    if categorical_feature != "auto":
        train_set.set_categorical_feature(categorical_feature)


def train(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj: Optional[Callable] = None, feval: Optional[Callable] = None,
          init_model: Optional[str] = None,
          feature_name: Any = "auto", categorical_feature: Any = "auto",
          early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[Dict] = None,
          verbose_eval: Any = True, learning_rates: Any = None,
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None) -> Booster:
    """Train a booster (reference ``engine.py:15``; loop at ``:230-270``).

    The positional parameter order is the REFERENCE's exactly, so
    positionally-called reference code binds every argument the same way.
    """
    params = dict(params or {})
    _apply_dataset_kwargs(train_set, feature_name, categorical_feature)
    # resolve aliases that control the loop itself
    for alias in ("num_iterations", "num_iteration", "n_iter", "num_tree", "num_trees",
                  "num_round", "num_rounds", "num_boost_round", "n_estimators"):
        if alias in params:
            num_boost_round = int(params.pop(alias))
    for alias in ("early_stopping_round", "early_stopping_rounds", "early_stopping",
                  "n_iter_no_change"):
        if alias in params:
            early_stopping_rounds = int(params.pop(alias))
    if fobj is not None:
        params["objective"] = "none"

    booster = Booster(params=params, train_set=train_set)
    contains_train = False
    if valid_sets:
        user_named = valid_names is not None
        valid_names = valid_names or [f"valid_{i}" for i in range(len(valid_sets))]
        for vs, name in zip(valid_sets, valid_names):
            if vs is train_set:
                # reference engine.py: a user-supplied name for the train set
                # renames it everywhere (eval output AND the early-stopping
                # skip, which compares against _train_data_name); the train
                # set is NOT an eval_valid entry — name_valid_sets must stay
                # index-aligned with the gbdt's valid sets
                contains_train = True
                booster._gbdt.config.is_provide_training_metric = True
                if user_named:
                    booster.set_train_data_name(name)
                continue
            booster.add_valid(vs, name)

    if init_model is not None:
        prev = (Booster(model_file=init_model) if isinstance(init_model, str)
                else init_model)
        booster._gbdt.continue_from(prev._gbdt)

    cbs = list(callbacks or [])
    if learning_rates is not None:
        # reference engine.py: list or callable(iter) -> reset_parameter
        cbs.append(callback_mod.reset_parameter(learning_rate=learning_rates))
    if verbose_eval is True:
        cbs.append(callback_mod.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval > 0:
        cbs.append(callback_mod.print_evaluation(verbose_eval))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.append(callback_mod.early_stopping(early_stopping_rounds))
    if evals_result is not None:
        cbs.append(callback_mod.record_evaluation(evals_result))
    cbs_before = [cb for cb in cbs if getattr(cb, "before_iteration", False)]
    cbs_after = [cb for cb in cbs if not getattr(cb, "before_iteration", False)]
    cbs_before.sort(key=lambda cb: getattr(cb, "order", 0))
    cbs_after.sort(key=lambda cb: getattr(cb, "order", 0))

    snapshot_freq = booster._gbdt.config.snapshot_freq
    evaluation_result_list = []         # stays [] when num_boost_round == 0
    for i in range(num_boost_round):
        for cb in cbs_before:
            cb(callback_mod.CallbackEnv(booster, params, i, 0, num_boost_round, None))
        stopped = booster.update(fobj=fobj)
        if snapshot_freq > 0 and (i + 1) % snapshot_freq == 0:
            # periodic checkpoint (reference GBDT::Train, gbdt.cpp:277-281):
            # <output_model>.snapshot_iter_<N>
            booster.save_model(
                f"{booster._gbdt.config.output_model}.snapshot_iter_{i + 1}")

        evaluation_result_list = []
        if booster._gbdt.valid_sets or booster._gbdt.config.is_provide_training_metric:
            evaluation_result_list = booster._gbdt.eval_current()
        if feval is not None:
            # feval-only rows: builtins are already in the list via
            # eval_current, so re-running them per valid set (and once more
            # for a train set inside valid_sets) would emit duplicates
            if contains_train:
                evaluation_result_list.extend(booster._feval_results(
                    getattr(booster, "_train_data_name", "training"), -1,
                    feval))
            for vi, vname in enumerate(booster.name_valid_sets):
                evaluation_result_list.extend(
                    booster._feval_results(vname, vi, feval))
        try:
            for cb in cbs_after:
                cb(callback_mod.CallbackEnv(booster, params, i, 0, num_boost_round,
                                            evaluation_result_list))
        except callback_mod.EarlyStopException as e:
            booster.best_iteration = e.best_iteration + 1
            for name, metric, score, _ in (e.best_score or []):
                booster.best_score.setdefault(name, {})[metric] = score
            break
        if stopped:
            break
    if booster.best_iteration < 0 and evaluation_result_list:
        for name, metric, score, _ in evaluation_result_list:
            booster.best_score.setdefault(name, {})[metric] = score
    return booster


class CVBooster:
    """Ensemble of per-fold boosters (reference ``engine.py:278``)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, nfold: int, params, seed: int,
                  stratified: bool, shuffle: bool):
    full_data.construct()
    num_data = full_data.num_data()
    label = full_data.get_label()
    rng = np.random.default_rng(seed)
    if stratified and label is not None:
        order = np.argsort(label, kind="stable")
        if shuffle:
            # shuffle within label groups to keep stratification
            folds_assign = np.empty(num_data, np.int64)
            folds_assign[order] = np.arange(num_data) % nfold
            perm_map = rng.permutation(nfold)
            folds_assign = perm_map[folds_assign]
        else:
            folds_assign = np.empty(num_data, np.int64)
            folds_assign[order] = np.arange(num_data) % nfold
    else:
        idx = rng.permutation(num_data) if shuffle else np.arange(num_data)
        folds_assign = np.empty(num_data, np.int64)
        folds_assign[idx] = np.arange(num_data) % nfold
    for k in range(nfold):
        test_idx = np.where(folds_assign == k)[0]
        train_idx = np.where(folds_assign != k)[0]
        yield train_idx, test_idx


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, fobj=None, feval=None, init_model=None,
       feature_name="auto", categorical_feature="auto",
       early_stopping_rounds: Optional[int] = None, fpreproc=None,
       verbose_eval=None, show_stdv: bool = True, seed: int = 0,
       callbacks=None, eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, List[float]]:
    """Cross-validation (reference ``engine.py:392``): per-fold boosters,
    aggregated mean/stdv curves, optional ``fpreproc`` per-fold transform,
    callbacks over the aggregate (``cv_agg``) results."""
    params = dict(params or {})
    if metrics is not None:
        params["metric"] = metrics
    if params.get("objective") in ("multiclass", "multiclassova") and not stratified:
        pass
    if params.get("objective") in (None, "regression") and stratified:
        stratified = False

    _apply_dataset_kwargs(train_set, feature_name, categorical_feature)
    train_set.construct()
    results: Dict[str, List[float]] = {}
    cvbooster = CVBooster()

    if folds is None:
        folds = list(_make_n_folds(train_set, nfold, params, seed, stratified, shuffle))
    elif hasattr(folds, "split"):
        folds = list(folds.split(np.zeros(train_set.num_data()),
                                 train_set.get_label()))

    fold_boosters = []
    for train_idx, test_idx in folds:
        tr = train_set.subset(train_idx)
        te = train_set.subset(test_idx)
        fold_params = dict(params)
        if fpreproc is not None:
            # per-fold preprocessing hook (reference fpreproc contract:
            # (dtrain, dtest, params) -> same triple)
            tr, te, fold_params = fpreproc(tr, te, dict(params))
        bst = Booster(params=fold_params, train_set=tr)
        bst.add_valid(te, "valid")
        fold_boosters.append(bst)
        cvbooster.append(bst)

    from . import callback as callback_mod
    cbs = list(callbacks or [])
    cbs_before = [c for c in cbs if getattr(c, "before_iteration", False)]
    cbs_after = [c for c in cbs if not getattr(c, "before_iteration", False)]
    if verbose_eval:
        period = 1 if verbose_eval is True else int(verbose_eval)
        cbs_after.append(callback_mod.print_evaluation(period, show_stdv))
    for c in (cbs_before, cbs_after):
        c.sort(key=lambda cb: getattr(cb, "order", 0))

    best_iter = num_boost_round
    no_improve = 0
    best_mean: Dict[str, float] = {}
    for i in range(num_boost_round):
        for cb in cbs_before:
            cb(callback_mod.CallbackEnv(cvbooster, params, i, 0,
                                        num_boost_round, None))
        agg: Dict[str, List[float]] = {}
        hib_map: Dict[str, bool] = {}
        for bst in fold_boosters:
            bst.update(fobj=fobj)
            res = bst._gbdt.eval_current()
            for name, metric, val, hib in res:
                if name == "training" and not eval_train_metric:
                    continue
                key = f"{name} {metric}"
                agg.setdefault(key, []).append(val)
                hib_map[key] = hib
            if feval is not None:
                # the PYTHON-level Datasets (get_label/get_weight), not the
                # inner binned ones; feval runs on every eval set like the
                # reference (training included when eval_train_metric)
                evals = [("valid",
                          np.asarray(bst._gbdt._valid_scores[0], np.float64),
                          bst.valid_sets_py[0]
                          if getattr(bst, "valid_sets_py", None) else None)]
                if eval_train_metric:
                    evals.append(("training",
                                  np.asarray(bst._gbdt._train_score,
                                             np.float64),
                                  bst.train_set))
                for ename, score, dset in evals:
                    s = (score[0] if bst._gbdt.num_tree_per_iteration == 1
                         else score)
                    fres = feval(s, dset)
                    if isinstance(fres, tuple):
                        fres = [fres]
                    for mname, val, hib in fres:
                        key = f"{ename} {mname}"
                        agg.setdefault(key, []).append(val)
                        hib_map[key] = hib
        env_list = [("cv_agg", key, float(np.mean(vals)), hib_map[key],
                     float(np.std(vals))) for key, vals in agg.items()]
        for key, vals in agg.items():
            results.setdefault(f"{key}-mean", []).append(float(np.mean(vals)))
            results.setdefault(f"{key}-stdv", []).append(float(np.std(vals)))
        stop_now = False
        try:
            for cb in cbs_after:
                cb(callback_mod.CallbackEnv(cvbooster, params, i, 0,
                                            num_boost_round, env_list))
        except callback_mod.EarlyStopException as e:
            best_iter = e.best_iteration + 1
            stop_now = True
        if early_stopping_rounds and agg and not stop_now:
            key0 = next(iter(agg))
            mean0 = float(np.mean(agg[key0]))
            better = (mean0 > best_mean.get(key0, -np.inf)) if hib_map[key0] \
                else (mean0 < best_mean.get(key0, np.inf))
            if better:
                best_mean[key0] = mean0
                best_iter = i + 1
                no_improve = 0
            else:
                no_improve += 1
                if no_improve >= early_stopping_rounds:
                    stop_now = True
        if stop_now:
            for key in list(results):
                results[key] = results[key][:best_iter]
            break
    cvbooster.best_iteration = best_iter
    if return_cvbooster:
        results["cvbooster"] = cvbooster
    return results
