"""Config-file driven CLI application.

Analog of the reference CLI (``src/main.cpp``, ``src/application/
application.cpp``): ``python -m lightgbm_tpu config=train.conf [k=v ...]``
with tasks train / predict / convert_model / refit (``config.h:29``).
Accepts the reference's ``key = value`` config-file grammar (comments with
``#``), so the reference's ``examples/*/train.conf`` files run unchanged.
"""
from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .config import Config
from .engine import train as train_fn
from .utils.log import Log, LightGBMError


def parse_config_file(path: str) -> Dict[str, str]:
    """``key = value`` lines, ``#`` comments (reference ``Config::KV2Map`` /
    config-file loading, ``application.cpp:52-85``)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def parse_argv(argv: List[str]) -> Dict[str, str]:
    """CLI ``key=value`` arguments; ``config=<file>`` pulls in a config file
    with CLI taking precedence (reference ``Application::Application``)."""
    cli: Dict[str, str] = {}
    for arg in argv:
        if "=" not in arg:
            raise LightGBMError(f"unknown argument {arg!r}; expected key=value")
        k, v = arg.split("=", 1)
        cli[k.strip()] = v.strip()
    params: Dict[str, str] = {}
    if "config" in cli:
        params.update(parse_config_file(cli.pop("config")))
    params.update(cli)                       # CLI overrides the file
    return params


class Application:
    """Task dispatcher (reference ``Application::Run``)."""

    def __init__(self, params: Dict[str, str]):
        self.raw_params = dict(params)
        self.config = Config.from_params(params)

    def run(self) -> None:
        task = self.config.task
        if task == "train":
            self.train()
        elif task == "predict":
            self.predict()
        elif task == "convert_model":
            self.convert_model()
        elif task == "refit":
            self.refit()
        else:
            raise LightGBMError(f"unknown task {task!r}")

    # ------------------------------------------------------------------
    def _resolve(self, path: str) -> str:
        """Paths in a config file are relative to the CWD, like the
        reference CLI."""
        return path

    def train(self) -> None:
        cfg = self.config
        if not cfg.data:
            raise LightGBMError("no training data: set data=<file>")
        params = dict(self.raw_params)
        params.pop("task", None)
        params.pop("data", None)
        params.pop("valid", None)
        for alias in ("valid_data", "valid_data_file", "test", "test_data",
                      "output_model", "input_model", "output_result"):
            params.pop(alias, None)
        train_set = Dataset(self._resolve(cfg.data), params=params)
        valid_sets, valid_names = [], []
        for i, v in enumerate(cfg.valid):
            valid_sets.append(Dataset(self._resolve(v), params=params,
                                      reference=train_set))
            valid_names.append(os.path.basename(v))
        init_model = cfg.input_model if cfg.input_model else None
        booster = train_fn(params, train_set,
                           num_boost_round=cfg.num_iterations,
                           valid_sets=valid_sets or None,
                           valid_names=valid_names or None,
                           init_model=init_model,
                           verbose_eval=cfg.metric_freq if cfg.verbosity >= 0 else False)
        booster.save_model(cfg.output_model)
        Log.info("Finished training; model saved to %s", cfg.output_model)

    def predict(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            raise LightGBMError("no model: set input_model=<file>")
        if not cfg.data:
            raise LightGBMError("no data to predict: set data=<file>")
        booster = Booster(model_file=self._resolve(cfg.input_model))
        from .io.loader import load_file
        X = load_file(self._resolve(cfg.data), cfg)[0]
        pred = booster.predict(
            X, raw_score=cfg.predict_raw_score,
            pred_leaf=cfg.predict_leaf_index,
            pred_contrib=cfg.predict_contrib,
            num_iteration=cfg.num_iteration_predict,
            start_iteration=cfg.start_iteration_predict,
            predict_disable_shape_check=cfg.predict_disable_shape_check)
        pred = np.atleast_1d(pred)
        with open(cfg.output_result, "w") as f:
            if pred.ndim == 1:
                f.write("\n".join(repr(float(v)) for v in pred) + "\n")
            else:
                for row in pred:
                    f.write("\t".join(repr(float(v)) for v in row) + "\n")
        Log.info("Finished prediction; results saved to %s", cfg.output_result)

    def convert_model(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            raise LightGBMError("no model: set input_model=<file>")
        booster = Booster(model_file=self._resolve(cfg.input_model))
        from .models.convert import model_to_cpp
        code = model_to_cpp(booster._gbdt)
        with open(cfg.convert_model, "w") as f:
            f.write(code)
        Log.info("Finished converting model; code saved to %s", cfg.convert_model)

    def refit(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            raise LightGBMError("no model: set input_model=<file>")
        if not cfg.data:
            raise LightGBMError("no data: set data=<file>")
        booster = Booster(model_file=self._resolve(cfg.input_model))
        from .io.loader import load_file
        X, y = load_file(self._resolve(cfg.data), cfg)[:2]
        booster.refit(X, y, decay_rate=cfg.refit_decay_rate)
        booster.save_model(cfg.output_model)
        Log.info("Finished refit; model saved to %s", cfg.output_model)


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "obs-report":
        # observability subcommand: render the perf journal + telemetry
        # snapshot (docs/OBSERVABILITY.md) — not a key=value task
        from .obs.report import main as obs_report_main
        return obs_report_main(argv[1:])
    if not argv:
        print("usage: python -m lightgbm_tpu config=<file> [key=value ...]\n"
              "       python -m lightgbm_tpu obs-report [--format md|json] "
              "[--roofline] [--regressions [--gate]] "
              "[--health [--health-url HOST:PORT]]")
        return 1
    try:
        Application(parse_argv(argv)).run()
    except LightGBMError as e:
        Log.warning("error: %s", e)
        return 2
    return 0
