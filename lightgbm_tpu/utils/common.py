"""Small shared helpers (the analog of ``utils/common.h`` — only the pieces
that survive the move to JAX/numpy; string parsing lives in ``io.loader``)."""
from __future__ import annotations

import numpy as np

# Machine epsilon / sentinel values mirroring the reference's meta.h constants.
K_EPSILON = 1e-15
K_ZERO_THRESHOLD = 1e-35
K_MIN_SCORE = -np.inf
K_MAX_SCORE = np.inf


def round_int(x: float) -> int:
    """Round-half-away-from-zero used by min_data_in_leaf count estimation
    (reference ``Common::RoundInt``, used at ``feature_histogram.hpp:869``)."""
    return int(x + 0.5) if x >= 0 else -int(-x + 0.5)


def softmax(x: np.ndarray, axis: int = -1) -> np.ndarray:
    m = np.max(x, axis=axis, keepdims=True)
    e = np.exp(x - m)
    return e / np.sum(e, axis=axis, keepdims=True)


def arg_max_at_k(values: np.ndarray, k: int) -> np.ndarray:
    """Indices of the top-k values (reference ``ArrayArgs::ArgMaxAtK``)."""
    if k >= len(values):
        return np.argsort(-values, kind="stable")
    part = np.argpartition(-values, k)[:k]
    return part[np.argsort(-values[part], kind="stable")]


def construct_bitset(vals, n_bits: int | None = None) -> np.ndarray:
    """Pack a list of non-negative ints into a uint32 bitset (reference
    ``Common::ConstructBitset`` — used for categorical split thresholds)."""
    vals = np.asarray(vals, dtype=np.int64)
    size = int(vals.max()) // 32 + 1 if len(vals) else 1
    if n_bits is not None:
        size = max(size, (n_bits + 31) // 32)
    out = np.zeros(size, dtype=np.uint32)
    for v in vals:
        out[v // 32] |= np.uint32(1) << np.uint32(v % 32)
    return out


def find_in_bitset(bitset: np.ndarray, val: int) -> bool:
    """Reference ``Common::FindInBitset``."""
    i = val // 32
    if val < 0 or i >= len(bitset):
        return False
    return bool((int(bitset[i]) >> (val % 32)) & 1)
