"""Leveled logger with pluggable callback.

TPU-native analog of the reference logger (``include/LightGBM/utils/log.h:26``):
four levels (Fatal < Warning < Info < Debug), printf-style messages, and a
redirectable sink so host frameworks (tests, notebooks) can capture output the
way the reference's R/Python bindings do via ``LGBM_RegisterLogCallback``.
"""
from __future__ import annotations

import enum
import sys
from typing import Callable, Optional


class LogLevel(enum.IntEnum):
    FATAL = -1
    WARNING = 0
    INFO = 1
    DEBUG = 2


class LightGBMError(Exception):
    """Raised on fatal errors (the analog of ``Log::Fatal`` + C-API error)."""


_callback: Optional[Callable[[str], None]] = None
_level: LogLevel = LogLevel.INFO


def register_log_callback(cb: Optional[Callable[[str], None]]) -> None:
    global _callback
    _callback = cb


def reset_log_level(level: LogLevel | int) -> None:
    global _level
    _level = LogLevel(level)


def get_log_level() -> LogLevel:
    return _level


def _write(msg: str) -> None:
    if _callback is not None:
        _callback(msg + "\n")
    else:
        sys.stdout.write(msg + "\n")
        sys.stdout.flush()


class Log:
    @staticmethod
    def debug(fmt: str, *args) -> None:
        if _level >= LogLevel.DEBUG:
            _write("[LightGBM-TPU] [Debug] " + (fmt % args if args else fmt))

    @staticmethod
    def info(fmt: str, *args) -> None:
        if _level >= LogLevel.INFO:
            _write("[LightGBM-TPU] [Info] " + (fmt % args if args else fmt))

    @staticmethod
    def warning(fmt: str, *args) -> None:
        if _level >= LogLevel.WARNING:
            _write("[LightGBM-TPU] [Warning] " + (fmt % args if args else fmt))

    @staticmethod
    def fatal(fmt: str, *args) -> None:
        msg = fmt % args if args else fmt
        _write("[LightGBM-TPU] [Fatal] " + msg)
        raise LightGBMError(msg)


def check(cond: bool, msg: str = "check failed") -> None:
    """Analog of the reference's ``CHECK_*`` macros (``utils/log.h``)."""
    if not cond:
        Log.fatal(msg)
