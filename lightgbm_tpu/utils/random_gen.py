"""Deterministic RNG utilities.

The reference uses a tiny per-block linear-congruential RNG (``utils/random.h``)
so bagging/sampling is reproducible regardless of thread count
(``src/boosting/gbdt.cpp:190``).  The TPU-native equivalent is simpler and
stronger: ``jax.random`` keys are already counter-based and order-independent,
so per-block determinism falls out of key folding.  We keep a small host-side
LCG with the same contract for host code paths (bin sampling, cv folds).
"""
from __future__ import annotations

import numpy as np
import jax


class Random:
    """Host-side deterministic RNG (next_short/next_int/sample contract of
    the reference's ``Random`` class, ``utils/random.h``)."""

    def __init__(self, seed: int = 0) -> None:
        self._state = np.uint32(seed if seed >= 0 else 0)

    def next_short(self, lo: int, hi: int) -> int:
        return lo + self._rand16() % (hi - lo)

    def next_int(self, lo: int, hi: int) -> int:
        r = (np.uint32(self._rand16()) << np.uint32(16)) | np.uint32(self._rand16())
        return int(lo + r % np.uint32(hi - lo))

    def next_float(self) -> float:
        return self._rand16() / 65536.0

    def _rand16(self) -> int:
        # LCG constants as in C++ minstd-style generators; value truncated to 16 bits.
        self._state = np.uint32((int(self._state) * 214013 + 2531011) & 0xFFFFFFFF)
        return int((int(self._state) >> 16) & 0x7FFF)

    def sample(self, total: int, k: int) -> np.ndarray:
        """Reservoir-free sorted sampling of k indices out of total (matches the
        reference contract of Random::Sample: sorted unique indices)."""
        if k >= total:
            return np.arange(total, dtype=np.int64)
        rng = np.random.default_rng(int(self._state))
        idx = rng.choice(total, size=k, replace=False)
        idx.sort()
        return idx.astype(np.int64)


def key_for_iteration(seed: int, iteration: int, salt: int = 0) -> jax.Array:
    """Per-iteration PRNG key: deterministic in (seed, iteration) and
    independent of device count — the TPU analog of per-block RNG streams."""
    key = jax.random.key(np.uint32(seed))
    key = jax.random.fold_in(key, np.uint32(iteration))
    if salt:
        key = jax.random.fold_in(key, np.uint32(salt))
    return key
