"""String-keyed hierarchical wall-clock timer.

Parity with the reference's ``Common::Timer`` / ``FunctionTimer``
(``include/LightGBM/utils/common.h:931,995``): named accumulating scopes and an
aggregate printout.  On TPU the heavyweight profiling story is
``jax.profiler``; this host timer exists for quick parity-style breakdowns of
the boosting loop.

Thread-safety and nesting: ``global_timer`` is shared by the boosting loop
AND the serve worker threads, so the accumulators sit behind a lock and the
in-flight starts live in per-thread stacks — the same scope name may nest
(recursive helpers) and run concurrently on many threads without corrupting
each other's start times.  When a tracer is attached
(``attach_tracer``, see ``obs/tracer.py``), every scope additionally records
a span, turning the aggregate timer into a timeline with zero call-site
changes.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict

from .log import Log


class Timer:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._acc: dict[str, float] = defaultdict(float)
        self._count: dict[str, int] = defaultdict(int)
        self._local = threading.local()
        self._tracer = None

    # ------------------------------------------------------------------
    def _starts(self) -> "dict[str, list[float]]":
        st = getattr(self._local, "starts", None)
        if st is None:
            st = self._local.starts = defaultdict(list)
        return st

    def attach_tracer(self, tracer) -> None:
        """Mirror every scope into ``tracer`` as a span (obs.tracer API:
        ``begin(name)`` / ``end(name)``)."""
        self._tracer = tracer

    def detach_tracer(self) -> None:
        self._tracer = None

    # ------------------------------------------------------------------
    def start(self, name: str) -> None:
        self._starts()[name].append(time.perf_counter())
        t = self._tracer
        if t is not None:
            t.begin(name)

    def stop(self, name: str) -> None:
        stack = self._starts().get(name)
        if not stack:
            return
        t0 = stack.pop()
        dt = time.perf_counter() - t0
        with self._lock:
            self._acc[name] += dt
            self._count[name] += 1
        t = self._tracer
        if t is not None:
            t.end(name)

    @contextlib.contextmanager
    def scope(self, name: str):
        self.start(name)
        try:
            yield
        finally:
            self.stop(name)

    # ------------------------------------------------------------------
    def seconds(self, name: str) -> float:
        """Accumulated seconds for one scope (0.0 when never stopped)."""
        with self._lock:
            return self._acc.get(name, 0.0)

    def calls(self, name: str) -> int:
        with self._lock:
            return self._count.get(name, 0)

    def items(self):
        with self._lock:
            return sorted(self._acc.items(), key=lambda kv: -kv[1])

    def reset(self) -> None:
        with self._lock:
            self._acc.clear()
            self._count.clear()
        # only the calling thread's in-flight starts can be dropped here;
        # other threads' stacks are theirs to unwind
        starts = getattr(self._local, "starts", None)
        if starts is not None:
            starts.clear()

    def print(self) -> None:
        for name, secs in self.items():
            Log.debug("%s: %.3fs (%d calls)", name, secs, self.calls(name))


#: process-global timer, mirroring the reference's ``global_timer``
global_timer = Timer()
