"""String-keyed hierarchical wall-clock timer.

Parity with the reference's ``Common::Timer`` / ``FunctionTimer``
(``include/LightGBM/utils/common.h:931,995``): named accumulating scopes and an
aggregate printout.  On TPU the heavyweight profiling story is
``jax.profiler``; this host timer exists for quick parity-style breakdowns of
the boosting loop.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict

from .log import Log


class Timer:
    def __init__(self) -> None:
        self._acc: dict[str, float] = defaultdict(float)
        self._count: dict[str, int] = defaultdict(int)
        self._start: dict[str, float] = {}

    def start(self, name: str) -> None:
        self._start[name] = time.perf_counter()

    def stop(self, name: str) -> None:
        t0 = self._start.pop(name, None)
        if t0 is not None:
            self._acc[name] += time.perf_counter() - t0
            self._count[name] += 1

    @contextlib.contextmanager
    def scope(self, name: str):
        self.start(name)
        try:
            yield
        finally:
            self.stop(name)

    def items(self):
        return sorted(self._acc.items(), key=lambda kv: -kv[1])

    def reset(self) -> None:
        self._acc.clear()
        self._count.clear()
        self._start.clear()

    def print(self) -> None:
        for name, secs in self.items():
            Log.debug("%s: %.3fs (%d calls)", name, secs, self._count[name])


#: process-global timer, mirroring the reference's ``global_timer``
global_timer = Timer()
