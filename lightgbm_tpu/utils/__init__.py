from .log import Log, LogLevel, LightGBMError, register_log_callback, reset_log_level, check
from .timer import Timer, global_timer
from .random_gen import Random, key_for_iteration
from . import common

__all__ = [
    "Log", "LogLevel", "LightGBMError", "register_log_callback",
    "reset_log_level", "check", "Timer", "global_timer", "Random",
    "key_for_iteration", "common",
]
