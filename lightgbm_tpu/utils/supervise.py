"""Hardened subprocess supervision: the ``bench.probe_backend`` pattern
(own process group + ``killpg`` on timeout + temp-file output so a surviving
grandchild can't block the parent through an inherited pipe) generalized
into reusable primitives for unattended perf capture:

- :func:`run_stage` — run one command under a wall-clock budget with
  retries and jittered exponential backoff; every attempt is crash- and
  hang-isolated from the caller.
- :class:`Heartbeat` — structured append-only jsonl progress records, so
  an unattended run leaves a legible trail even when it dies mid-stage.
- :class:`SingleOwnerLock` — pid-checked lock file guaranteeing only one
  process ever touches the TPU; stale locks (dead owner) are reclaimed.

STDLIB-ONLY by design: the watcher and bench front-ends must be able to
load this module without importing the ``lightgbm_tpu`` package (whose
``__init__`` pulls in jax — exactly the import a wedged axon tunnel can
punish).  Load it package-free via ``bench._load_supervise()`` or::

    spec = importlib.util.spec_from_file_location("supervise", path)

The module itself must therefore never import jax, numpy, or anything
from ``lightgbm_tpu``.
"""
from __future__ import annotations

import json
import os
import random
import re
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field


# --------------------------------------------------------------------------
# process-group reaping
# --------------------------------------------------------------------------

def _descendants(root: int) -> list:
    """Pids of every live descendant of ``root`` via a /proc ppid scan.
    Needed because killpg alone misses grandchildren that called setsid
    themselves (e.g. a supervised stage that itself uses run_stage): a new
    session is a new process group, outside the root's.  Collected BEFORE
    the kill — afterwards orphans reparent to init and the chain is
    lost."""
    children: dict = {}
    try:
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            try:
                with open(f"/proc/{entry}/stat") as f:
                    stat = f.read()
                # field 4 (after the parenthesised comm, which may contain
                # spaces): ppid
                ppid = int(stat.rsplit(")", 1)[1].split()[1])
            except (OSError, ValueError, IndexError):
                continue
            children.setdefault(ppid, []).append(int(entry))
    except OSError:
        return []
    out, frontier = [], [root]
    while frontier:
        p = frontier.pop()
        for c in children.get(p, ()):
            out.append(c)
            frontier.append(c)
    return out


def kill_process_group(pid: int, reap_timeout: float = 5.0,
                       proc: "subprocess.Popen | None" = None) -> bool:
    """SIGKILL the whole process TREE rooted at ``pid``: its process
    group, plus every /proc-walked descendant's group (a descendant that
    called setsid — a nested run_stage stage — left the root's group and
    would otherwise survive as an orphan holding the TPU).  Reaps the
    direct child; returns True when reaped (False = D-state unreapable
    child: give up and move on — never block the supervisor on it)."""
    strays = _descendants(pid)
    try:
        mypg = os.getpgid(0)
    except OSError:
        mypg = -1
    try:
        os.killpg(pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    for s in strays:
        try:
            pg = os.getpgid(s)
        except (ProcessLookupError, OSError):
            pg = -1
        try:
            if pg > 0 and pg != mypg:
                os.killpg(pg, signal.SIGKILL)
            else:
                os.kill(s, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            pass
    if proc is None:
        return True
    try:
        proc.wait(reap_timeout)
        return True
    except subprocess.TimeoutExpired:
        return False


def backoff_schedule(retries: int, base: float, factor: float = 2.0,
                     cap: float = 600.0, jitter: float = 0.25,
                     rng: "random.Random | None" = None) -> list:
    """Jittered exponential backoff delays for ``retries`` re-attempts:
    ``min(cap, base * factor**i)`` each scaled by ``1 ± jitter`` (full
    jitter would let delays collapse to ~0; a bounded band keeps the
    schedule monotone-ish while decorrelating concurrent pollers)."""
    rng = rng or random.Random()
    out = []
    for i in range(retries):
        d = min(cap, base * (factor ** i))
        out.append(d * (1.0 + jitter * (2.0 * rng.random() - 1.0)))
    return out


# --------------------------------------------------------------------------
# stage runner
# --------------------------------------------------------------------------

@dataclass
class StageResult:
    """Outcome of one :func:`run_stage` call (the LAST attempt)."""
    name: str
    status: str                 # "ok" | "crash" | "timeout" | "unreaped"
    returncode: "int | None"
    attempts: int
    elapsed: float              # wall-clock across all attempts, incl. backoff
    output_tail: str = ""       # merged stdout+stderr tail of the last attempt
    # flight-recorder dumps collected from a failed child (run_stage's
    # flight_dir): forensic jsonl files moved beside the caller's journal
    flight_dumps: "list" = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.status == "ok"

    def to_record(self) -> dict:
        rec = {"stage": self.name, "status": self.status,
               "returncode": self.returncode, "attempts": self.attempts,
               "elapsed_sec": round(self.elapsed, 3)}
        if self.flight_dumps:
            rec["flight_dumps"] = list(self.flight_dumps)
        return rec


def run_stage(name: str, argv: list, timeout: float, retries: int = 0,
              backoff: float = 5.0, backoff_factor: float = 2.0,
              backoff_cap: float = 600.0, jitter: float = 0.25,
              env: "dict | None" = None, cwd: "str | None" = None,
              heartbeat=None, tail_bytes: int = 8192,
              sleep=time.sleep, rng: "random.Random | None" = None,
              flight_dir: "str | None" = None,
              ) -> StageResult:
    """Run ``argv`` as a timeout-guarded, crash-isolated stage.

    Each attempt runs in its own session/process group; on timeout the
    WHOLE group is SIGKILLed (a hung jax init routinely leaves tunnel
    helper grandchildren — ``kill(p.pid)`` alone orphans them holding the
    TPU).  Output goes to a temp file, never a pipe, so a grandchild that
    survives an incomplete kill cannot block us on read.  A nonzero exit
    or timeout is retried up to ``retries`` times with jittered
    exponential backoff; ``sleep``/``rng`` are injectable so tests can
    verify the schedule without wall-clock cost.

    ``heartbeat`` is any callable accepting ``(event, **fields)`` — see
    :class:`Heartbeat`.  Never raises for child failures; the caller
    branches on ``StageResult.status``.

    ``flight_dir``: arm the child's flight recorder.  Each attempt gets a
    private scratch dir exported as ``LGBM_FLIGHT_DIR``; when the attempt
    fails (crash/timeout/unreaped) any ``flight_*.jsonl`` the child's
    recorder flushed — including the last periodic flush of a SIGKILLed
    child — is moved into ``flight_dir`` (collision-safe names recorded
    in ``StageResult.flight_dumps``); an ok attempt's scratch is dropped.
    """
    hb = heartbeat or (lambda event, **kv: None)
    delays = backoff_schedule(retries, backoff, backoff_factor,
                              backoff_cap, jitter, rng)
    t_start = time.monotonic()
    status, rc, tail = "crash", None, ""
    flight_dumps: list = []
    for attempt in range(retries + 1):
        hb("stage_attempt", stage=name, attempt=attempt,
           argv=list(map(str, argv)), timeout=timeout)
        t_a = time.monotonic()
        child_env, flight_tmp = env, None
        if flight_dir is not None:
            os.makedirs(flight_dir, exist_ok=True)
            # scratch INSIDE flight_dir: collection is a same-filesystem
            # rename, atomic even against a half-written later dump
            flight_tmp = tempfile.mkdtemp(
                dir=flight_dir, prefix=f".flight_{_safe_name(name)}_")
            child_env = dict(os.environ if env is None else env)
            child_env["LGBM_FLIGHT_DIR"] = flight_tmp
        try:
            with tempfile.TemporaryFile(mode="w+", errors="replace") as out:
                try:
                    p = subprocess.Popen(argv, stdout=out,
                                         stderr=subprocess.STDOUT,
                                         stdin=subprocess.DEVNULL,
                                         env=child_env, cwd=cwd,
                                         start_new_session=True)
                except OSError as e:
                    status, rc, tail = "crash", -1, f"spawn failed: {e}"
                    hb("stage_spawn_error", stage=name, attempt=attempt,
                       error=str(e))
                    break           # argv itself is broken: retrying is noise
                try:
                    rc = p.wait(timeout)
                    status = "ok" if rc == 0 else "crash"
                except subprocess.TimeoutExpired:
                    reaped = kill_process_group(p.pid, proc=p)
                    status = "timeout" if reaped else "unreaped"
                    rc = None
                try:
                    out.seek(0, os.SEEK_END)
                    out.seek(max(0, out.tell() - tail_bytes))
                    tail = out.read()
                except (OSError, ValueError):
                    tail = ""
        finally:
            if flight_tmp is not None:
                collected = _collect_flight_dumps(
                    flight_tmp, flight_dir, name, attempt,
                    keep=status != "ok")
                flight_dumps.extend(collected)
                if collected:
                    hb("stage_flight_dump", stage=name, attempt=attempt,
                       dumps=collected)
        hb("stage_result", stage=name, attempt=attempt, status=status,
           returncode=rc, secs=round(time.monotonic() - t_a, 3))
        if status == "ok":
            break
        if attempt < retries:
            hb("stage_backoff", stage=name, attempt=attempt,
               delay_sec=round(delays[attempt], 3))
            sleep(delays[attempt])
    return StageResult(name=name, status=status, returncode=rc,
                       attempts=attempt + 1,
                       elapsed=time.monotonic() - t_start,
                       output_tail=tail, flight_dumps=flight_dumps)


def _safe_name(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", str(name))


def _collect_flight_dumps(tmp: str, dest: str, name: str, attempt: int,
                          keep: bool) -> list:
    """Move a failed attempt's ``flight_*.jsonl`` from its scratch dir into
    ``dest`` under collision-safe names; drop the scratch dir either way."""
    out: list = []
    try:
        files = sorted(f for f in os.listdir(tmp)
                       if f.startswith("flight_") and f.endswith(".jsonl"))
    except OSError:
        files = []
    if keep:
        for f in files:
            target = os.path.join(
                dest, f"flight_{_safe_name(name)}_a{attempt}_"
                      f"{f[len('flight_'):]}")
            try:
                os.replace(os.path.join(tmp, f), target)
                out.append(target)
            except OSError:
                pass
    shutil.rmtree(tmp, ignore_errors=True)
    return out


def extract_json_line(text: str):
    """Last parseable ``{...}`` line of a stage's output, or None — the
    bench scripts' one-JSON-line contract, parsed in exactly one place
    (the watcher's headline extraction and the suite's subprocess
    big-headline share it)."""
    for line in reversed(text.splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                pass
    return None


# --------------------------------------------------------------------------
# heartbeat
# --------------------------------------------------------------------------

class Heartbeat:
    """Append-only jsonl heartbeat: one self-describing record per event,
    flushed per write (the reader is usually a human tailing the file after
    the unattended run died).  Instances are callable with the
    ``(event, **fields)`` shape :func:`run_stage` expects."""

    def __init__(self, path: str, extra: "dict | None" = None):
        self.path = path
        self._extra = dict(extra or {})
        self._seq = 0

    def __call__(self, event: str, **fields) -> None:
        self.beat(event, **fields)

    def beat(self, event: str, **fields) -> None:
        rec = {"ts": round(time.time(), 3), "seq": self._seq,
               "pid": os.getpid(), "event": event,
               **self._extra, **fields}
        self._seq += 1
        try:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            pass                   # heartbeat must never kill the watcher


# --------------------------------------------------------------------------
# single-owner lock
# --------------------------------------------------------------------------

class LockHeldError(RuntimeError):
    """Another live process owns the lock; the message names it."""


class SingleOwnerLock:
    """Pid-checked lock file: exactly one process may own the TPU window.

    Acquisition publishes the lock by HARD-LINKING a fully written temp
    file into place — atomic on every POSIX fs, and the body (owner
    pid/host/argv, so a refusal can say WHO holds it) is complete the
    instant the lock exists: there is no empty-file window for a racing
    acquirer to misread as corrupt/stale.  A lock whose owner pid is dead
    is stale (the watcher crashed without cleanup) and is reclaimed under
    an flock-serialized critical section.  Pid liveness is only
    meaningful on the same host — a lock from another host, or one with
    an unreadable body, is honored as live (fail safe; remove by hand)."""

    def __init__(self, path: str):
        self.path = path
        self._owned = False

    def acquire(self) -> "SingleOwnerLock":
        payload = json.dumps({"pid": os.getpid(),
                              "host": socket.gethostname(),
                              "since": round(time.time(), 3),
                              "argv": sys.argv})
        tmp = f"{self.path}.{os.getpid()}.tmp"
        for _ in range(3):          # extra passes after reclaim/vanish races
            with open(tmp, "w") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            try:
                os.link(tmp, self.path)     # atomic create WITH content
                self._owned = True
                return self
            except FileExistsError:
                owner = self._read_owner()
                if owner is None:
                    continue                # vanished under us: just retry
                if self._owner_alive(owner):
                    raise LockHeldError(
                        f"lock {self.path} held by pid {owner.get('pid')} "
                        f"on {owner.get('host')} since {owner.get('since')} "
                        f"({owner.get('argv')}) — refusing to start; remove "
                        "the file only if that process is truly gone")
                self._reclaim_stale()
            finally:
                try:
                    os.unlink(tmp)
                except FileNotFoundError:
                    pass
        raise LockHeldError(f"lock {self.path} could not be acquired "
                            "(lost the reclaim race repeatedly)")

    def _reclaim_stale(self) -> None:
        """Unlink a stale lock under an flock-serialized critical section.
        A blind unlink races two concurrent reclaimers: the loser could
        delete the winner's FRESH lock and both would own the TPU.  The
        guard file serializes check-then-unlink; the re-read inside the
        section ensures we only ever delete a lock whose owner is dead."""
        import fcntl
        with open(self.path + ".guard", "w") as g:
            fcntl.flock(g, fcntl.LOCK_EX)
            owner = self._read_owner()
            if owner is None or self._owner_alive(owner):
                return              # vanished, or reclaimed-and-reacquired
            try:
                os.unlink(self.path)
            except FileNotFoundError:
                pass

    def release(self) -> None:
        if self._owned:
            self._owned = False
            try:
                os.unlink(self.path)
            except FileNotFoundError:
                pass

    def _read_owner(self):
        """Owner dict; {} for an unreadable/corrupt body; None when the
        file vanished (another process released or reclaimed it)."""
        try:
            with open(self.path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            return {}

    def _owner_alive(self, owner: dict) -> bool:
        pid = owner.get("pid")
        if not isinstance(pid, int):
            # our own locks are link-published with a complete body, so a
            # corrupt one is foreign/hand-made: fail safe, honor as live
            return True
        if owner.get("host") not in (None, socket.gethostname()):
            return True             # foreign host: cannot check, fail safe
        try:
            os.kill(pid, 0)
            return True
        except ProcessLookupError:
            return False
        except PermissionError:
            return True             # exists, owned by someone else

    def __enter__(self) -> "SingleOwnerLock":
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


# --------------------------------------------------------------------------
# atomic journal io (shared by the watcher's state file)
# --------------------------------------------------------------------------

def write_json_atomic(path: str, obj) -> None:
    """Write-then-rename so a crash mid-write can never leave a torn
    journal (the resume path reads this file first thing)."""
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_", suffix=".json")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except FileNotFoundError:
            pass
        raise


def read_json(path: str, default=None):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return default
