"""Structured run events: one schema, one writer, one results file.

Every measurement in the repo lands in ``perf_results.jsonl`` (or the
file ``WATCHER_PERF_LOG`` points at).  Historically each bench script
carried its own copy of the path resolution and a bare ``json.dumps``
append; this module is the single replacement:

- :func:`perf_log_path` — the one copy of the ``WATCHER_PERF_LOG``-or-
  repo-root resolution previously duplicated across six scripts;
- :class:`EventLog` — a thread-safe, atomic-append jsonl sink stamping
  every record with the versioned envelope (``schema_version``,
  ``run_id``, wall clock ``ts``, monotonic clock ``mono``, ``event``);
- :func:`validate_event` / :func:`classify_record` — the schema
  validator the report layer uses to tolerate legacy (pre-schema) lines.

Compatibility: the envelope keeps a ``stage`` field mirroring ``event``
(unless the caller sets its own) because the perf-suite resume markers
and the watcher journal key on ``stage`` — old readers keep working on
new lines, and the report reader accepts old lines.

This module is deliberately stdlib-only: the watcher/suite supervisors
must be able to load it WITHOUT importing the ``lightgbm_tpu`` package
(whose ``__init__`` pulls in jax — see ``bench.load_obs``).
"""
from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

#: bump when the envelope changes shape; readers tolerate every version
#: they know plus pre-schema ("legacy") lines
SCHEMA_VERSION = 1

#: envelope fields every schema event carries
REQUIRED_FIELDS = ("schema_version", "run_id", "event", "ts", "mono")

#: the event kind marking a bench script's final one-JSON-line summary
SUMMARY_EVENT = "bench_summary"

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def perf_log_path(env: Optional[Dict[str, str]] = None) -> str:
    """The results file: ``WATCHER_PERF_LOG`` when the watcher points every
    stage at one journal, else the repo-root ``perf_results.jsonl``."""
    env = os.environ if env is None else env
    return env.get("WATCHER_PERF_LOG") or os.path.join(
        _REPO_ROOT, "perf_results.jsonl")


def new_run_id() -> str:
    """Short unique id correlating every event of one process/run."""
    return uuid.uuid4().hex[:12]


def make_event(event: str, run_id: str, **fields: Any) -> Dict[str, Any]:
    """Build a schema-stamped record (no I/O).  Caller fields win over
    nothing — envelope keys are reserved and always overwritten."""
    rec = dict(fields)
    rec["schema_version"] = SCHEMA_VERSION
    rec["run_id"] = run_id
    rec["event"] = str(event)
    rec["ts"] = time.time()
    rec["mono"] = time.monotonic()
    # legacy-reader compat: suite resume markers / watcher records key on
    # "stage"; mirror the kind unless the caller carries its own stage
    rec.setdefault("stage", rec["event"])
    return rec


def validate_event(rec: Any) -> List[str]:
    """Schema check; returns a list of problems (empty = valid)."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    for k in REQUIRED_FIELDS:
        if k not in rec:
            errs.append(f"missing field {k!r}")
    if errs:
        return errs
    if not isinstance(rec["schema_version"], int) or rec["schema_version"] < 1:
        errs.append("schema_version must be an int >= 1")
    if not isinstance(rec["run_id"], str) or not rec["run_id"]:
        errs.append("run_id must be a non-empty string")
    if not isinstance(rec["event"], str) or not rec["event"]:
        errs.append("event must be a non-empty string")
    for k in ("ts", "mono"):
        if not isinstance(rec[k], (int, float)) or isinstance(rec[k], bool):
            errs.append(f"{k} must be a number")
    return errs


def classify_record(line: str) -> Tuple[str, Optional[Dict[str, Any]]]:
    """Classify one jsonl line: ``("event", rec)`` for schema-valid records,
    ``("legacy", rec)`` for pre-schema JSON objects (the six old writers'
    shapes), ``("bad", None)`` for anything unparseable/invalid."""
    line = line.strip()
    if not line:
        return "bad", None
    try:
        rec = json.loads(line)
    except (ValueError, TypeError):
        return "bad", None
    if not isinstance(rec, dict):
        return "bad", None
    if "schema_version" not in rec:
        return "legacy", rec
    return ("event", rec) if not validate_event(rec) else ("bad", rec)


class EventLog:
    """Thread-safe atomic-append jsonl sink with the schema envelope.

    Each record is serialized to one line and written with a single
    ``write`` call on a file opened in append mode, so concurrent writers
    (serve worker threads, the watcher's stage subprocesses sharing
    ``WATCHER_PERF_LOG``) interleave whole lines, never fragments.

    ``echo=True`` also prints each line to stdout (the bench scripts'
    historical behavior — the suite/watcher scrape stdout for progress).
    """

    _defaults: Dict[str, "EventLog"] = {}
    _defaults_lock = threading.Lock()

    #: process-wide record taps (the flight recorder's ring).  Class level
    #: on purpose: a dump must see events from EVERY log in the process
    #: (telemetry journal + serve log + ad-hoc EventLogs), and observers
    #: outlive any single log instance.
    _observers: List[Any] = []

    @classmethod
    def add_observer(cls, fn: Any) -> None:
        """Register ``fn(rec)`` to be called (outside the write lock) with
        every record any :class:`EventLog` in the process appends.
        Observer exceptions are swallowed — a broken tap must never break
        the journal."""
        with cls._defaults_lock:
            if fn not in cls._observers:
                cls._observers.append(fn)

    @classmethod
    def remove_observer(cls, fn: Any) -> None:
        with cls._defaults_lock:
            if fn in cls._observers:
                cls._observers.remove(fn)

    @classmethod
    def _notify(cls, rec: Dict[str, Any]) -> None:
        for fn in list(cls._observers):
            try:
                fn(rec)
            except Exception:
                pass

    def __init__(self, path: Optional[str] = None, *,
                 run_id: Optional[str] = None, echo: bool = False):
        self.path = path or perf_log_path()
        self.run_id = run_id or new_run_id()
        self.echo = bool(echo)
        self._lock = threading.Lock()

    @classmethod
    def default(cls, *, echo: bool = False) -> "EventLog":
        """Process-wide log for the resolved :func:`perf_log_path` (one
        ``run_id`` per process per path).  ``echo=True`` upgrades an
        existing silent default — bench mains want echo, library callers
        don't care."""
        path = perf_log_path()
        with cls._defaults_lock:
            log = cls._defaults.get(path)
            if log is None:
                log = cls(path, echo=echo)
                cls._defaults[path] = log
            elif echo:
                log.echo = True
            return log

    # ------------------------------------------------------------------
    def emit(self, event: str, **fields: Any) -> Dict[str, Any]:
        """Append one schema-stamped record; returns it."""
        rec = make_event(event, self.run_id, **fields)
        self._write(rec)
        return rec

    def emit_raw(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        """Append a caller-built record verbatim (no envelope) — for
        relaying already-stamped records (e.g. the watcher forwarding a
        stage's summary)."""
        self._write(rec)
        return rec

    def summary(self, **fields: Any) -> Dict[str, Any]:
        """Emit a bench script's final summary: appended to the log AND
        printed as the last stdout line (the one-JSON-line contract,
        ``supervise.extract_json_line``).  Validates before writing so a
        malformed summary fails the bench loudly, not the reader later.

        Surfaces the tracer's silent data loss: when the process tracer has
        dropped spans (ring overflow) the summary carries a
        ``tracer_dropped`` count so no bench can claim complete span
        coverage it doesn't have."""
        if "tracer_dropped" not in fields:
            try:  # lazy: keep module import order free of cycles
                from .tracer import get_tracer
                dropped = get_tracer().dropped
            except Exception:
                dropped = 0
            if dropped:
                fields["tracer_dropped"] = dropped
        rec = make_event(SUMMARY_EVENT, self.run_id, **fields)
        errs = validate_event(rec)
        if errs:
            raise ValueError(f"invalid bench summary: {'; '.join(errs)}")
        line = json.dumps(rec)
        with self._lock:
            self._append_line(line)
        print(line, flush=True)
        self._notify(rec)
        return rec

    # ------------------------------------------------------------------
    def _write(self, rec: Dict[str, Any]) -> None:
        line = json.dumps(rec)
        with self._lock:
            self._append_line(line)
        if self.echo:
            print(line, flush=True)
        self._notify(rec)

    def _append_line(self, line: str) -> None:
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(line + "\n")
