"""Flight recorder: crash-proof forensics for live runs.

Post-hoc telemetry (``events.py`` journals) answers "what happened" only
when the process got to write it.  A stage that is SIGKILLed by the
watcher's hang reaper, segfaults inside jaxlib, or dies to an unhandled
exception leaves an exit code and a truncated journal.  This module
keeps a bounded in-memory ring of the last N schema events (tapped off
:class:`~lightgbm_tpu.obs.events.EventLog` via its observer hook) plus
the open-span tails of every thread, and flushes them atomically to
``flight_<run_id>.jsonl``:

- eagerly every ``flush_every`` records (SIGKILL cannot be caught — the
  last periodic flush IS the forensic record for a hard kill);
- on ``atexit``, on an unhandled exception (chained ``sys.excepthook``),
  and on the ``faulthandler``-style fatal/termination signals (handler
  dumps, restores the previous disposition, and re-raises so exit
  status is preserved).

Dump layout (one JSON object per line, all schema-stamped):
``flight_dump`` header (reason, pid, counts, tracer ``dropped``), then
the ring's events oldest-first, then ``flight_span`` records — the
completed-span tail and every thread's still-open spans (``open: true``
with the span's age).

Destination precedence: the ``LGBM_FLIGHT_DIR`` environment variable
(how ``supervise.run_stage`` redirects a child's dump into a collectible
location) beats the ``dir`` argument beats the directory of
:func:`~lightgbm_tpu.obs.events.perf_log_path`.

Deliberately stdlib-only and importable via the jax-free
``bench.load_obs()`` path — the watcher's fake stages exercise it
without numpy in the interpreter.
"""
from __future__ import annotations

import atexit
import collections
import json
import os
import signal
import sys
import threading
import traceback
from typing import Any, Dict, List, Optional

from .events import EventLog, make_event, new_run_id, perf_log_path

__all__ = ["FlightRecorder", "install", "get_recorder", "uninstall",
           "dump", "FATAL_SIGNALS"]

#: prefix of every dump file (``supervise.run_stage`` globs on it)
FLIGHT_PREFIX = "flight_"

#: termination/fatal signals the recorder dumps on.  SIGINT is left
#: alone (KeyboardInterrupt reaches the excepthook path); SIGKILL is
#: uncatchable by design — covered by the eager periodic flush.
FATAL_SIGNALS = ("SIGTERM", "SIGQUIT", "SIGABRT",
                 "SIGSEGV", "SIGBUS", "SIGFPE", "SIGILL")


class FlightRecorder:
    """Bounded event ring + span tails with atomic crash dumps."""

    def __init__(self, dir: Optional[str] = None,
                 run_id: Optional[str] = None, *,
                 capacity: int = 256, flush_every: int = 32,
                 span_tail: int = 64):
        env_dir = os.environ.get("LGBM_FLIGHT_DIR")
        self.dir = env_dir or dir or os.path.dirname(
            os.path.abspath(perf_log_path()))
        self.run_id = run_id or new_run_id()
        self.capacity = int(capacity)
        self.flush_every = max(1, int(flush_every))
        self.span_tail = int(span_tail)
        self.path = os.path.join(
            self.dir, f"{FLIGHT_PREFIX}{self.run_id}.jsonl")
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._lock = threading.Lock()
        self._since_flush = 0
        self.dump_count = 0
        self._installed = False
        self._prev_excepthook: Any = None
        self._prev_handlers: Dict[int, Any] = {}
        self._in_dump = False

    # ------------------------------------------------------------------
    def record(self, rec: Dict[str, Any]) -> None:
        """Ring in one already-stamped record (the EventLog observer)."""
        flush = False
        with self._lock:
            self._ring.append(rec)
            self._since_flush += 1
            if self._since_flush >= self.flush_every:
                self._since_flush = 0
                flush = True
        if flush:
            self.dump("periodic")

    def note(self, event: str, **fields: Any) -> Dict[str, Any]:
        """Stamp + ring a record directly (no journal write): for facts
        that only matter if the process dies."""
        rec = make_event(event, self.run_id, **fields)
        self.record(rec)
        return rec

    def last_event(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._ring[-1]) if self._ring else None

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._ring]

    # ------------------------------------------------------------------
    def _span_records(self) -> List[Dict[str, Any]]:
        try:
            from .tracer import get_tracer
            t = get_tracer()
        except Exception:
            return []
        recs: List[Dict[str, Any]] = []
        try:
            for s in t.spans()[-self.span_tail:]:
                recs.append(make_event(
                    "flight_span", self.run_id, name=s.name, tid=s.tid,
                    depth=s.depth, duration_s=round(s.duration, 6),
                    open=False))
            for o in t.open_spans():
                recs.append(make_event(
                    "flight_span", self.run_id, name=o["name"],
                    tid=o["tid"], depth=o["depth"], age_s=o["age_s"],
                    open=True))
        except Exception:
            pass
        return recs

    def dump(self, reason: str = "manual") -> Optional[str]:
        """Atomically (tmp + ``os.replace``) write the dump file; returns
        its path, or None if a concurrent dump is already writing."""
        with self._lock:
            if self._in_dump:       # re-entrant signal during a dump
                return None
            self._in_dump = True
            events = [dict(r) for r in self._ring]
            self._since_flush = 0
        try:
            spans = self._span_records()
            try:
                from .tracer import get_tracer
                dropped = get_tracer().dropped
            except Exception:
                dropped = 0
            header = make_event(
                "flight_dump", self.run_id, reason=str(reason),
                pid=os.getpid(), events=len(events), spans=len(spans),
                tracer_dropped=dropped)
            tmp = f"{self.path}.tmp.{os.getpid()}"
            os.makedirs(self.dir, exist_ok=True)
            with open(tmp, "w") as f:
                for rec in [header] + events + spans:
                    f.write(json.dumps(rec) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self.dump_count += 1
            return self.path
        except Exception:
            return None         # a recorder must never crash its host
        finally:
            with self._lock:
                self._in_dump = False

    # ------------------------------------------------------------------
    def install(self) -> "FlightRecorder":
        """Tap the EventLog stream and arm atexit/excepthook/signal
        dumps.  Idempotent."""
        if self._installed:
            return self
        self._installed = True
        EventLog.add_observer(self.record)
        atexit.register(self._atexit)
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._excepthook
        for name in FATAL_SIGNALS:
            sig = getattr(signal, name, None)
            if sig is None:
                continue
            try:
                self._prev_handlers[sig] = signal.signal(
                    sig, self._on_signal)
            except (ValueError, OSError, RuntimeError):
                pass    # non-main thread or unsupported signal
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        EventLog.remove_observer(self.record)
        try:
            atexit.unregister(self._atexit)
        except Exception:
            pass
        if sys.excepthook is self._excepthook:
            sys.excepthook = self._prev_excepthook or sys.__excepthook__
        for sig, prev in self._prev_handlers.items():
            try:
                if signal.getsignal(sig) is self._on_signal:
                    signal.signal(sig, prev)
            except (ValueError, OSError, RuntimeError):
                pass
        self._prev_handlers.clear()

    # ------------------------------------------------------------------
    def _atexit(self) -> None:
        if self._ring or self.dump_count:
            self.dump("atexit")

    def _excepthook(self, etype, value, tb) -> None:
        try:
            tail = traceback.format_exception(etype, value, tb)[-8:]
            self.note("unhandled_exception", type=etype.__name__,
                      message=str(value)[:500],
                      traceback_tail="".join(tail)[-2000:])
            self.dump("exception")
        except Exception:
            pass
        prev = self._prev_excepthook or sys.__excepthook__
        prev(etype, value, tb)

    def _on_signal(self, signum, frame) -> None:
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        try:
            self.note("fatal_signal", signal=name, signum=int(signum))
            self.dump(f"signal_{name}")
        except Exception:
            pass
        # restore the previous disposition and re-raise: the process dies
        # with the status the signal implies (watcher reaping semantics,
        # shell wait status) instead of a handler swallowing it
        prev = self._prev_handlers.get(signum)
        try:
            signal.signal(signum, prev if prev is not None
                          else signal.SIG_DFL)
        except (ValueError, OSError, RuntimeError):
            pass
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)
        else:
            os.kill(os.getpid(), signum)


# ----------------------------------------------------------------------
_RECORDER: Optional[FlightRecorder] = None
_LOCK = threading.Lock()


def install(dir: Optional[str] = None, run_id: Optional[str] = None,
            **kwargs: Any) -> FlightRecorder:
    """Install the process-wide recorder (idempotent: the first install
    wins — one flight file per process)."""
    global _RECORDER
    with _LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder(dir, run_id, **kwargs).install()
        return _RECORDER


def get_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def uninstall() -> None:
    """Tear down the process recorder (tests)."""
    global _RECORDER
    with _LOCK:
        if _RECORDER is not None:
            _RECORDER.uninstall()
            _RECORDER = None


def dump(reason: str = "manual") -> Optional[str]:
    """Dump now if a recorder is installed; returns the dump path."""
    rec = _RECORDER
    return rec.dump(reason) if rec is not None else None
