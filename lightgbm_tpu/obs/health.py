"""Live health plane: metrics exposition, health status, SLO burn rate.

Everything in ``obs/`` so far is post-hoc — journals and reports read
after the run.  This module makes a live process observable while it is
running:

- :func:`start_health_server` — a daemon-thread ``http.server`` bound to
  127.0.0.1 answering ``GET /metrics`` (Prometheus text exposition
  rendered from the process :class:`~lightgbm_tpu.obs.metrics
  .MetricsRegistry`) and ``GET /healthz`` (the JSON of
  :func:`health_snapshot`).  Enabled by the ``obs_health_port`` config
  knob (or the ``LGBM_OBS_HEALTH_PORT`` env var the watcher exports to
  its stages); auto-started by the boosting loops and
  ``serve.Predictor``.  ``port=0`` binds an ephemeral port (tests).
- :func:`set_status` — a tiny process-wide status board (run_id, stage,
  iteration, last numeric check …) the training loops update per
  iteration; ``/healthz`` reads it.
- :class:`SLOMonitor` — per-model multi-window (default 5 min / 1 h)
  burn rates for p99 latency and error-rate objectives
  (``serve_slo_p99_ms`` / ``serve_slo_error_rate``), fed from the serve
  batcher's request stream.  Burn rate = observed bad fraction divided
  by the objective's error budget (the SRE convention: 1.0 = exactly
  consuming budget, >1 = burning it).
- :class:`DivergenceError` + :func:`numeric_verdict` — the structured
  failure the numeric-health sentinels in ``GBDT``/``StreamGBDT`` raise
  when gradients/hessians/leaf values go NaN/Inf, carrying the stats and
  the flight-dump path.

Deliberately stdlib-only (loadable via the jax-free ``bench.load_obs()``
path) — the device-side reductions live in the model layer; this module
only judges their host-side scalars.
"""
from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import metrics as _metrics

__all__ = [
    "DivergenceError", "SLOMonitor", "HealthServer", "numeric_verdict",
    "check_numeric",
    "render_prometheus", "health_snapshot", "set_status", "get_status",
    "start_health_server", "maybe_start", "get_server", "stop_health_server",
    "register_slo", "unregister_slo", "slo_reports",
]

_START_TIME = time.time()


# ----------------------------------------------------------------------
# numeric divergence
# ----------------------------------------------------------------------
class DivergenceError(RuntimeError):
    """Numeric health sentinel tripped: NaN/Inf in gradients, hessians or
    leaf values.  ``detail`` holds the per-array stats
    (``finite_frac`` / ``max_abs``), ``flight_path`` the forensic dump
    written before raising.

    Derives ``RuntimeError`` (not ``LightGBMError``) so the stdlib-only
    obs package stays importable without the main package.
    """

    def __init__(self, message: str, *, iteration: Optional[int] = None,
                 detail: Optional[Dict[str, Any]] = None,
                 flight_path: Optional[str] = None):
        super().__init__(message)
        self.iteration = iteration
        self.detail = detail or {}
        self.flight_path = flight_path


def check_numeric(stats: Dict[str, Dict[str, float]], *,
                  iteration: int, kind: str = "train",
                  log: Any = None) -> bool:
    """Judge sentinel stats, record the verdict, raise on divergence.

    Updates the status board, emits a ``numeric_health`` event (to the
    telemetry ``log`` when given, else into the flight ring so a later
    dump carries it), and on NaN/Inf writes a flight dump and raises
    :class:`DivergenceError` carrying its path.  The caller supplies the
    host-side scalars — this module never touches device arrays."""
    ok, bad = numeric_verdict(stats)
    flat = {f"{name}_{key}": val for name, s in stats.items()
            for key, val in s.items()}
    set_status(last_numeric_check=iteration, numeric_ok=ok)
    from . import flight as _flight
    if log is not None:
        log.emit("numeric_health", iteration=iteration, kind=kind,
                 ok=ok, **flat)
    else:
        rec = _flight.get_recorder()
        if rec is not None:
            rec.note("numeric_health", iteration=iteration, kind=kind,
                     ok=ok, **flat)
    if ok:
        return True
    path = _flight.dump(f"divergence_iter{iteration}")
    raise DivergenceError(
        f"numeric divergence at iteration {iteration}: non-finite values "
        f"in {', '.join(bad)} (see numeric_health event"
        + (f"; flight dump {path}" if path else "") + ")",
        iteration=iteration, detail=stats, flight_path=path)


def numeric_verdict(stats: Dict[str, Dict[str, float]]
                    ) -> Tuple[bool, List[str]]:
    """Judge per-array sentinel stats.  ``stats`` maps an array name
    (``grad``/``hess``/``leaf_value``) to ``{"finite_frac": f,
    "max_abs": m}``.  Returns ``(ok, bad_names)`` — an array is bad when
    any sampled element is non-finite."""
    bad: List[str] = []
    for name, s in stats.items():
        frac = s.get("finite_frac")
        mx = s.get("max_abs")
        if frac is not None and (not math.isfinite(frac) or frac < 1.0):
            bad.append(name)
        elif mx is not None and not math.isfinite(mx):
            bad.append(name)
    return (not bad, bad)


# ----------------------------------------------------------------------
# process status board
# ----------------------------------------------------------------------
_STATUS: Dict[str, Any] = {}
_STATUS_LOCK = threading.Lock()


def set_status(**fields: Any) -> None:
    """Merge fields into the process status board (``/healthz``)."""
    with _STATUS_LOCK:
        _STATUS.update(fields)
        _STATUS["status_ts"] = time.time()


def get_status() -> Dict[str, Any]:
    with _STATUS_LOCK:
        return dict(_STATUS)


def _reset_status() -> None:
    """Test seam."""
    with _STATUS_LOCK:
        _STATUS.clear()


# ----------------------------------------------------------------------
# SLO burn rate
# ----------------------------------------------------------------------
class SLOMonitor:
    """Multi-window burn-rate tracker for one served model.

    Objectives: ``p99_ms`` (latency) and ``error_rate`` (bad-request
    fraction: exceptions + sheds).  For each window the monitor reports
    the observed error rate and p99 over that window plus burn rates:

    - ``error_burn`` = observed bad fraction / ``error_rate`` objective;
    - ``latency_burn`` = observed p99 / ``p99_ms`` objective.

    A window is ``breached`` when either burn is >= 1.  Requests are
    bucketed per ~window/60 for the counting stats; latencies keep a
    bounded per-window deque (p99 over the last <= 4096 samples).
    ``clock`` is injectable for tests.
    """

    MAX_LATENCIES = 4096

    def __init__(self, name: str, *, p99_ms: Optional[float] = None,
                 error_rate: Optional[float] = None,
                 windows: Tuple[float, ...] = (300.0, 3600.0),
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.p99_ms = float(p99_ms) if p99_ms else None
        self.error_rate = float(error_rate) if error_rate else None
        self.windows = tuple(float(w) for w in windows)
        self._clock = clock
        self._lock = threading.Lock()
        # (bucket_start, requests, bad) buckets, finest granularity
        self._bucket_s = max(1.0, min(self.windows) / 60.0)
        horizon = max(self.windows)
        self._buckets: deque = deque(
            maxlen=int(horizon / self._bucket_s) + 2)
        # (t, latency_ms) samples, bounded
        self._latencies: deque = deque(maxlen=self.MAX_LATENCIES)

    @property
    def enabled(self) -> bool:
        return self.p99_ms is not None or self.error_rate is not None

    # ------------------------------------------------------------------
    def observe(self, latency_ms: Optional[float] = None,
                bad: bool = False) -> None:
        """Record one request outcome (a shed or an exception is
        ``bad=True`` with no latency)."""
        now = self._clock()
        with self._lock:
            start = math.floor(now / self._bucket_s) * self._bucket_s
            if self._buckets and self._buckets[-1][0] == start:
                b = self._buckets[-1]
                self._buckets[-1] = (b[0], b[1] + 1, b[2] + (1 if bad else 0))
            else:
                self._buckets.append((start, 1, 1 if bad else 0))
            if latency_ms is not None:
                self._latencies.append((now, float(latency_ms)))

    # ------------------------------------------------------------------
    def report(self) -> Dict[str, Any]:
        now = self._clock()
        with self._lock:
            buckets = list(self._buckets)
            lats = list(self._latencies)
        out: Dict[str, Any] = {
            "model": self.name,
            "objectives": {"p99_ms": self.p99_ms,
                           "error_rate": self.error_rate},
            "windows": {},
        }
        breached = False
        for w in self.windows:
            cutoff = now - w
            req = sum(b[1] for b in buckets if b[0] + self._bucket_s > cutoff)
            bad = sum(b[2] for b in buckets if b[0] + self._bucket_s > cutoff)
            wl = sorted(l for t, l in lats if t > cutoff)
            p99 = wl[max(0, math.ceil(0.99 * len(wl)) - 1)] if wl else None
            err = (bad / req) if req else 0.0
            win: Dict[str, Any] = {
                "requests": req, "bad": bad,
                "error_rate": round(err, 6),
                "p99_ms": round(p99, 3) if p99 is not None else None,
            }
            wb = False
            if self.error_rate:
                win["error_burn"] = round(err / self.error_rate, 3)
                wb = wb or win["error_burn"] >= 1.0 and bad > 0
            if self.p99_ms and p99 is not None:
                win["latency_burn"] = round(p99 / self.p99_ms, 3)
                wb = wb or win["latency_burn"] >= 1.0
            win["breached"] = wb
            breached = breached or wb
            out["windows"][f"{int(w)}s"] = win
        out["breached"] = breached
        return out


_SLOS: Dict[str, SLOMonitor] = {}
_SLOS_LOCK = threading.Lock()


def register_slo(monitor: SLOMonitor) -> SLOMonitor:
    """Expose a monitor in ``/healthz``/``/metrics`` (keyed by model)."""
    with _SLOS_LOCK:
        _SLOS[monitor.name] = monitor
    return monitor


def unregister_slo(name: str) -> None:
    with _SLOS_LOCK:
        _SLOS.pop(name, None)


def slo_reports() -> List[Dict[str, Any]]:
    with _SLOS_LOCK:
        monitors = list(_SLOS.values())
    return [m.report() for m in monitors]


# ----------------------------------------------------------------------
# prometheus text exposition
# ----------------------------------------------------------------------
_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str, prefix: str) -> str:
    n = prefix + _NAME_RE.sub("_", name)
    return n if not n[:1].isdigit() else "_" + n


def render_prometheus(snapshot: Optional[Dict[str, Dict[str, Any]]] = None,
                      *, prefix: str = "lgbtpu_") -> str:
    """Prometheus text exposition (0.0.4) of a registry snapshot:
    counters and gauges natively, histograms as summaries with
    ``quantile`` labels from the reservoir percentiles."""
    if snapshot is None:
        snapshot = _metrics.snapshot()
    lines: List[str] = []
    for name in sorted(snapshot):
        m = snapshot[name]
        pn = _prom_name(name, prefix)
        kind = m.get("type")
        if kind in ("counter", "gauge"):
            lines.append(f"# TYPE {pn} {kind}")
            lines.append(f"{pn} {m.get('value', 0)}")
        elif kind == "histogram":
            lines.append(f"# TYPE {pn} summary")
            for q, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
                v = m.get(key)
                if v is not None:
                    lines.append(f'{pn}{{quantile="{q}"}} {v}')
            lines.append(f"{pn}_sum {m.get('sum', 0)}")
            lines.append(f"{pn}_count {m.get('count', 0)}")
    # process-level series the scrape always gets
    up = prefix + "health_uptime_seconds"
    lines.append(f"# TYPE {up} gauge")
    lines.append(f"{up} {round(time.time() - _START_TIME, 3)}")
    try:
        from .tracer import get_tracer
        t = get_tracer()
        td = prefix + "tracer_dropped_total"
        lines.append(f"# TYPE {td} counter")
        lines.append(f"{td} {t.dropped}")
    except Exception:
        pass
    for rep in slo_reports():
        model = rep["model"].replace('"', "'")
        for wname, win in rep["windows"].items():
            for key in ("error_burn", "latency_burn"):
                if key in win:
                    mn = prefix + f"slo_{key}"
                    lines.append(
                        f'{mn}{{model="{model}",window="{wname}"}} '
                        f'{win[key]}')
    return "\n".join(lines) + "\n"


# ----------------------------------------------------------------------
# /healthz snapshot
# ----------------------------------------------------------------------
def health_snapshot() -> Dict[str, Any]:
    """The ``/healthz`` JSON — also usable offline (``obs-report
    --health``): status board, tracer drop count, device-memory
    watermark gauges, SLO reports, flight-recorder state."""
    status = get_status()
    snap = _metrics.snapshot()
    device_memory = {
        name: m.get("value") for name, m in sorted(snap.items())
        if m.get("type") == "gauge" and "device" in name and "bytes" in name
    }
    tracer_info: Dict[str, Any] = {}
    try:
        from .tracer import get_tracer
        t = get_tracer()
        tracer_info = {"spans": len(t.spans()), "dropped": t.dropped,
                       "capacity": t.capacity,
                       "open_spans": len(t.open_spans())}
    except Exception:
        pass
    flight_info: Dict[str, Any] = {}
    last_event_ts: Optional[float] = None
    try:
        from . import flight as _flight
        rec = _flight.get_recorder()
        if rec is not None:
            last = rec.last_event()
            last_event_ts = last.get("ts") if last else None
            flight_info = {"path": rec.path, "events": len(rec.snapshot()),
                           "dumps": rec.dump_count}
    except Exception:
        pass
    slos = slo_reports()
    return {
        "ok": bool(status.get("numeric_ok", True))
        and not any(r.get("breached") for r in slos),
        "pid": os.getpid(),
        "uptime_s": round(time.time() - _START_TIME, 3),
        "run_id": status.get("run_id"),
        "stage": status.get("stage"),
        "iteration": status.get("iteration"),
        "status": status,
        "last_event_ts": last_event_ts,
        "tracer": tracer_info,
        "device_memory": device_memory,
        "slo": slos,
        "flight": flight_info,
    }


# ----------------------------------------------------------------------
# exposition server
# ----------------------------------------------------------------------
class _Handler(BaseHTTPRequestHandler):
    server_version = "lgbtpu-health/1"

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = render_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path in ("/healthz", "/health", "/"):
                body = (json.dumps(health_snapshot(), default=str)
                        + "\n").encode()
                ctype = "application/json"
            else:
                self.send_error(404)
                return
        except Exception as exc:   # a scrape must never kill the server
            body = json.dumps({"error": str(exc)}).encode()
            self.send_response(500)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


class HealthServer:
    """Background-thread HTTP exposition bound to 127.0.0.1."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="lgbtpu-health",
            kwargs={"poll_interval": 0.25}, daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        self._thread.join(timeout=2.0)


_SERVER: Optional[HealthServer] = None
_SERVER_LOCK = threading.Lock()


def start_health_server(port: int) -> Optional[HealthServer]:
    """Start (or return) the process health server.  Idempotent — the
    first successful bind wins; a bind failure warns and returns None
    (a busy port must not kill training)."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None:
            return _SERVER
        try:
            _SERVER = HealthServer(int(port))
        except OSError as exc:
            import warnings
            warnings.warn(f"obs health server failed to bind port "
                          f"{port}: {exc}", RuntimeWarning, stacklevel=2)
            return None
        set_status(health_port=_SERVER.port)
        return _SERVER


def maybe_start(port: Optional[int] = None) -> Optional[HealthServer]:
    """Start the server when enabled: explicit ``port`` (config knob)
    wins, else the ``LGBM_OBS_HEALTH_PORT`` env var (how the watcher
    arms its stage subprocesses).  ``None``/unset → no server."""
    if port is None or int(port) <= 0:
        env = os.environ.get("LGBM_OBS_HEALTH_PORT", "")
        try:
            port = int(env) if env else None
        except ValueError:
            port = None
        if port is None:
            return _SERVER
    return start_health_server(int(port))


def get_server() -> Optional[HealthServer]:
    return _SERVER


def stop_health_server() -> None:
    """Test seam."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None:
            _SERVER.close()
            _SERVER = None

