"""Process-wide metrics: counters, gauges, rolling-percentile histograms.

The registry is the in-memory side of the telemetry layer: instrumentation
points (boosting loop, stream pipeline, distributed reductions, serve
batcher) update named metrics cheaply and thread-safely; anyone —
``obs.report``, the serve heartbeat, a test — takes a :func:`snapshot` on
demand.  Nothing here touches jax or does I/O.

Histograms use reservoir sampling (Vitter's algorithm R, fixed-size
uniform sample) so online p50/p99 over an unbounded observation stream
costs O(reservoir) memory and O(1) amortized per observation — the
serve-path latency reporting shape (p50/p99 under load) without keeping
every request's latency.
"""
from __future__ import annotations

import random
import threading
import zlib
from typing import Any, Dict, List, Optional

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "counter", "gauge", "histogram",
           "snapshot", "reset"]


class Counter:
    """Monotonically increasing integer."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def snapshot(self) -> Dict[str, Any]:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-write-wins scalar; ``set_max`` keeps the running maximum."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value: float = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def set_max(self, v: float) -> None:
        with self._lock:
            if v > self._value:
                self._value = v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> Dict[str, Any]:
        return {"type": "gauge", "value": self.value}


class Histogram:
    """Rolling-percentile histogram over a fixed-size uniform reservoir.

    Tracks exact count/sum/min/max; percentiles come from the reservoir
    (exact until ``reservoir_size`` observations, uniformly sampled
    after).  The sampler is seeded from the metric name so snapshots are
    reproducible run to run for a fixed observation stream.
    """

    def __init__(self, name: str, reservoir_size: int = 512):
        if reservoir_size < 1:
            raise ValueError("reservoir_size must be >= 1")
        self.name = name
        self.reservoir_size = int(reservoir_size)
        self._lock = threading.Lock()
        self._rng = random.Random(zlib.crc32(name.encode()))
        self._sample: List[float] = []
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            if len(self._sample) < self.reservoir_size:
                self._sample.append(v)
            else:
                # algorithm R: keep each of the n seen values with p = k/n
                j = self._rng.randrange(self._count)
                if j < self.reservoir_size:
                    self._sample[j] = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def percentile(self, q: float) -> Optional[float]:
        """Nearest-rank percentile from the reservoir; None when empty."""
        with self._lock:
            if not self._sample:
                return None
            xs = sorted(self._sample)
        i = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
        return xs[i]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            if not self._count:
                return {"type": "histogram", "count": 0}
            out = {"type": "histogram", "count": self._count,
                   "sum": self._sum, "min": self._min, "max": self._max,
                   "mean": self._sum / self._count}
            xs = sorted(self._sample)
        for label, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99)):
            i = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
            out[label] = xs[i]
        return out


class MetricsRegistry:
    """Name -> metric map with get-or-create accessors.

    Accessors are type-checked: asking for ``counter("x")`` after someone
    registered ``x`` as a gauge is a programming error worth failing on.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}

    def _get(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} is a {type(m).__name__}, "
                    f"not a {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, reservoir_size: int = 512) -> Histogram:
        return self._get(name, Histogram, reservoir_size)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Point-in-time dump of every metric, name-sorted (JSON-ready)."""
        with self._lock:
            items = sorted(self._metrics.items())
        return {name: m.snapshot() for name, m in items}

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str, reservoir_size: int = 512) -> Histogram:
    return _REGISTRY.histogram(name, reservoir_size)


def snapshot() -> Dict[str, Dict[str, Any]]:
    return _REGISTRY.snapshot()


def reset() -> None:
    _REGISTRY.reset()
