"""XLA cost ledger + roofline/MFU accounting (device-truth attribution).

One audited peak table and one cost model for the whole repo: every MFU
or peak-rate figure printed anywhere (bench.py, scripts/tpu_perf_suite.py,
scripts/bench_onehot_variants.py, obs-report) must come through here —
``tests/test_obs.py`` greps the tree to enforce it.  Before this module
three hand-rolled formulas with three local peak tables disagreed about
what "12% MFU" meant; now XLA's own compiled-program cost model is the
source of truth and the analytic work models are labelled predictions.

Stdlib-only at import (the watcher/suite load ``obs`` jax-free via
``bench.load_obs()``): jax is imported lazily inside the few functions
that touch a device, and the :class:`CostLedger` duck-types the
``Compiled`` objects callers hand it.

Two layers:

- **peaks + math** — :data:`PEAK_RATES` (bf16 FLOP/s + HBM B/s per chip
  kind), :func:`peak_flops`, :func:`peak_bandwidth`, :func:`mfu`,
  :func:`arithmetic_intensity`, :func:`ridge_intensity`,
  :func:`roofline` (the full achieved-vs-peak record with the
  compute-vs-bandwidth-bound classification);
- **ledger** — :class:`CostLedger` wraps named jit/lowered programs,
  records ``Compiled.cost_analysis()`` (flops, bytes accessed,
  transcendentals) and ``memory_analysis()`` (argument/output/temp
  bytes; peak is derived — jax 0.4 exposes no peak field), joins them
  with measured wall times, and emits one ``program_cost`` schema event
  per program through the existing :class:`~.events.EventLog` for
  ``obs-report --roofline`` to render.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

__all__ = ["PEAK_RATES", "DEFAULT_CHIP", "normalize_chip", "peak_flops",
           "peak_bandwidth", "mfu", "arithmetic_intensity",
           "ridge_intensity", "classify_bound", "roofline", "CostLedger",
           "get_ledger", "reset_ledger", "current_chip", "analyze_jitted",
           "record_watermarks", "set_stats_provider", "COST_EVENT"]

#: event name the ledger emits per program (rendered by --roofline)
COST_EVENT = "program_cost"

# --------------------------------------------------------------------------
# THE peak table.  Published per-chip dense-bf16 matmul peak and HBM
# bandwidth; keys are lowercased ``device.device_kind`` values with the
# platform name as fallback.  The CPU row is a deliberately round
# container-class estimate (AVX-512 Xeon-ish) so CPU-fallback runs still
# produce a finite, labelled MFU instead of a lie or a crash.
# --------------------------------------------------------------------------
PEAK_RATES: Dict[str, Dict[str, float]] = {
    "tpu v4":      {"flops": 275e12, "bytes_per_sec": 1228e9},
    "tpu v5e":     {"flops": 197e12, "bytes_per_sec": 819e9},
    "tpu v5 lite": {"flops": 197e12, "bytes_per_sec": 819e9},
    "tpu v5p":     {"flops": 459e12, "bytes_per_sec": 2765e9},
    "tpu v6e":     {"flops": 918e12, "bytes_per_sec": 1640e9},
    "tpu v6 lite": {"flops": 918e12, "bytes_per_sec": 1640e9},
    "cpu":         {"flops": 3.3e12,  "bytes_per_sec": 150e9},
}

#: unrecognized TPU kinds price against v5e (the fleet's common chip)
DEFAULT_CHIP = "tpu v5e"


def normalize_chip(kind: Optional[str]) -> str:
    """Map a ``device_kind``/platform string onto a peak-table key."""
    k = (kind or "").strip().lower()
    if k in PEAK_RATES:
        return k
    if "cpu" in k or k in ("", "interpreter"):
        return "cpu"
    return DEFAULT_CHIP


def peak_flops(kind: Optional[str]) -> float:
    return PEAK_RATES[normalize_chip(kind)]["flops"]


def peak_bandwidth(kind: Optional[str]) -> float:
    return PEAK_RATES[normalize_chip(kind)]["bytes_per_sec"]


def mfu(flops: float, seconds: float, kind: Optional[str]) -> float:
    """Model FLOPs Utilization: achieved FLOP/s over the chip's peak."""
    if seconds <= 0.0:
        return 0.0
    return flops / seconds / peak_flops(kind)


def arithmetic_intensity(flops: float, bytes_accessed: float) -> float:
    """FLOPs per byte moved (the roofline x-axis)."""
    return flops / bytes_accessed if bytes_accessed > 0 else float("inf")


def ridge_intensity(kind: Optional[str]) -> float:
    """The roofline ridge point: intensities above it are compute-bound."""
    return peak_flops(kind) / peak_bandwidth(kind)


def classify_bound(intensity: float, kind: Optional[str]) -> str:
    return ("compute" if intensity >= ridge_intensity(kind)
            else "bandwidth")


def roofline(flops: float, bytes_accessed: float, seconds: float,
             kind: Optional[str]) -> Dict[str, Any]:
    """Full achieved-vs-peak record for one timed program execution."""
    chip = normalize_chip(kind)
    ach_f = flops / seconds if seconds > 0 else 0.0
    ach_b = bytes_accessed / seconds if seconds > 0 else 0.0
    ai = arithmetic_intensity(flops, bytes_accessed)
    return {
        "chip": chip,
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "seconds": seconds,
        "achieved_flops_per_sec": ach_f,
        "achieved_bytes_per_sec": ach_b,
        "mfu": ach_f / peak_flops(chip),
        "hbm_util": ach_b / peak_bandwidth(chip),
        "intensity": ai,
        "ridge_intensity": ridge_intensity(chip),
        "bound": classify_bound(ai, chip),
    }


# --------------------------------------------------------------------------
# device access (lazy jax; every entry point tolerates a jax-free process)
# --------------------------------------------------------------------------

def current_chip() -> str:
    """Peak-table key for the ambient default device ('cpu' when jax is
    absent or the backend is unreachable)."""
    try:
        import jax
        d = jax.devices()[0]
        return normalize_chip(getattr(d, "device_kind", "") or d.platform)
    except Exception:
        return "cpu"


#: test seam for :func:`record_watermarks` — ``device.memory_stats()`` is
#: None on CPU, so CPU-only tests inject a fake provider here
_STATS_PROVIDER: Optional[Callable[[], Optional[Dict[str, Any]]]] = None


def set_stats_provider(
        fn: Optional[Callable[[], Optional[Dict[str, Any]]]]) -> None:
    global _STATS_PROVIDER
    _STATS_PROVIDER = fn


def _device_memory_stats() -> Optional[Dict[str, Any]]:
    if _STATS_PROVIDER is not None:
        return _STATS_PROVIDER()
    try:
        import jax
        return jax.devices()[0].memory_stats()
    except Exception:
        return None


def record_watermarks(prefix: str, registry: Any = None) -> Dict[str, int]:
    """Mirror ``device.memory_stats()`` watermarks into the metrics
    registry as ``<prefix>.device_bytes_in_use`` (last value) and
    ``<prefix>.device_peak_bytes_in_use`` (monotone max).  A local C++
    call, no device sync; returns ``{}`` where the backend publishes no
    stats (CPU) so call sites never need to branch."""
    stats = _device_memory_stats()
    if not stats:
        return {}
    if registry is None:
        from .metrics import get_registry
        registry = get_registry()
    out: Dict[str, int] = {}
    if "bytes_in_use" in stats:
        v = int(stats["bytes_in_use"])
        registry.gauge(f"{prefix}.device_bytes_in_use").set(v)
        out["bytes_in_use"] = v
    if "peak_bytes_in_use" in stats:
        v = int(stats["peak_bytes_in_use"])
        registry.gauge(f"{prefix}.device_peak_bytes_in_use").set_max(v)
        out["peak_bytes_in_use"] = v
    return out


# --------------------------------------------------------------------------
# the ledger
# --------------------------------------------------------------------------

def _cost_dict(compiled: Any) -> Dict[str, float]:
    """``Compiled.cost_analysis()`` normalized: jax 0.4 returns a LIST of
    per-executable dicts (element 0 on single-program jits), newer jax a
    plain dict; some backends return None.  Keys of interest: ``flops``,
    ``bytes accessed``, ``transcendentals``."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out = {}
    for key, name in (("flops", "flops"),
                      ("bytes accessed", "bytes_accessed"),
                      ("transcendentals", "transcendentals")):
        v = ca.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v >= 0:
            out[name] = float(v)
    return out


def _memory_dict(compiled: Any) -> Dict[str, int]:
    """``Compiled.memory_analysis()`` normalized.  jax 0.4's
    ``CompiledMemoryStats`` has argument/output/temp/alias sizes but NO
    peak field — ``peak_bytes`` is derived as arg+out+temp-alias (what
    the executable pins at once, the planning number OOM math needs)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    out = {}
    for attr, name in (("argument_size_in_bytes", "argument_bytes"),
                       ("output_size_in_bytes", "output_bytes"),
                       ("temp_size_in_bytes", "temp_bytes"),
                       ("alias_size_in_bytes", "alias_bytes"),
                       ("generated_code_size_in_bytes", "code_bytes")):
        v = getattr(ma, attr, None)
        if isinstance(v, int) and v >= 0:
            out[name] = v
    if {"argument_bytes", "output_bytes", "temp_bytes"} <= out.keys():
        out["peak_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                             + out["temp_bytes"] - out.get("alias_bytes", 0))
    return out


class CostLedger:
    """Named-program registry of XLA cost/memory analysis joined with
    measured wall time.

    ``record(name, compiled, **meta)`` captures the compiler's view once
    (at compile time — free); ``observe(name, seconds)`` accumulates
    measured executions; ``rooflines()`` joins the two against the peak
    table; ``emit(log)`` appends one ``program_cost`` schema event per
    program for ``obs-report --roofline``.
    """

    def __init__(self, chip: Optional[str] = None):
        self._chip = chip
        self._lock = threading.Lock()
        self._programs: Dict[str, Dict[str, Any]] = {}

    def __contains__(self, name: str) -> bool:
        return name in self._programs

    def names(self) -> List[str]:
        return list(self._programs)

    def entry(self, name: str) -> Dict[str, Any]:
        return dict(self._programs[name])

    # ------------------------------------------------------------------
    def record(self, name: str, compiled: Any = None, *,
               chip: Optional[str] = None, model_flops: Optional[float] = None,
               predicted_mfu: Optional[float] = None, **meta: Any) -> Dict:
        """Register/refresh a program.  ``compiled`` is any object with
        ``cost_analysis``/``memory_analysis`` (jax ``Compiled``); pass
        ``model_flops`` for an analytic work model to report alongside
        XLA's count, ``predicted_mfu`` for a work-model MFU bound."""
        ent: Dict[str, Any] = {"program": name,
                               "chip": chip or self._chip or current_chip()}
        if compiled is not None:
            ent["cost"] = _cost_dict(compiled)
            ent["memory"] = _memory_dict(compiled)
        if model_flops is not None:
            ent["model_flops"] = float(model_flops)
        if predicted_mfu is not None:
            ent["predicted_mfu"] = float(predicted_mfu)
        if meta:
            ent["meta"] = {k: v for k, v in meta.items()}
        with self._lock:
            prev = self._programs.get(name, {})
            ent.setdefault("calls", prev.get("calls", 0))
            ent.setdefault("total_seconds", prev.get("total_seconds", 0.0))
            self._programs[name] = ent
        return ent

    def observe(self, name: str, seconds: float, calls: int = 1) -> None:
        """Join ``calls`` measured executions totalling ``seconds`` with
        the program's recorded analysis (no-op for unknown names so call
        sites need no existence branch)."""
        if seconds is None or seconds < 0:
            return
        with self._lock:
            ent = self._programs.get(name)
            if ent is None:
                return
            ent["calls"] = ent.get("calls", 0) + int(calls)
            ent["total_seconds"] = ent.get("total_seconds", 0.0) + float(seconds)

    # ------------------------------------------------------------------
    def rooflines(self) -> List[Dict[str, Any]]:
        """One achieved-vs-peak record per OBSERVED program (programs with
        analysis but no timings are skipped: no wall time, no rate)."""
        out = []
        with self._lock:
            entries = [dict(e) for e in self._programs.values()]
        for ent in entries:
            calls = ent.get("calls", 0)
            secs = ent.get("total_seconds", 0.0)
            if not calls or secs <= 0:
                continue
            cost = ent.get("cost", {})
            flops = cost.get("flops", ent.get("model_flops", 0.0)) * calls
            byts = cost.get("bytes_accessed", 0.0) * calls
            rec = roofline(flops, byts, secs, ent["chip"])
            rec.update(program=ent["program"], calls=calls,
                       seconds_per_call=secs / calls,
                       flops_source=("xla" if "flops" in cost else "model"))
            for k in ("model_flops", "predicted_mfu", "memory", "meta"):
                if k in ent:
                    rec[k] = ent[k]
            if "model_flops" in ent:
                rec["model_mfu"] = mfu(ent["model_flops"] * calls, secs,
                                       ent["chip"])
            out.append(rec)
        return out

    def emit(self, log: Any = None, event: str = COST_EVENT) -> int:
        """Append one schema event per observed program; returns the
        count.  ``log`` defaults to the shared journal writer."""
        if log is None:
            from .events import EventLog
            log = EventLog.default()
        rows = self.rooflines()
        for rec in rows:
            log.emit(event, **_round_floats(rec))
        return len(rows)


def _round_floats(obj: Any, nd: int = 6) -> Any:
    if isinstance(obj, float):
        return round(obj, nd) if obj == obj and abs(obj) != float("inf") \
            else str(obj)
    if isinstance(obj, dict):
        return {k: _round_floats(v, nd) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_round_floats(v, nd) for v in obj]
    return obj


_LEDGER = CostLedger()


def get_ledger() -> CostLedger:
    """The process-wide ledger (mirrors the metrics-registry pattern)."""
    return _LEDGER


def reset_ledger() -> CostLedger:
    global _LEDGER
    _LEDGER = CostLedger()
    return _LEDGER


def analyze_jitted(name: str, fn: Callable, *args: Any,
                   ledger: Optional[CostLedger] = None,
                   **record_kw: Any) -> Dict[str, Any]:
    """Lower+compile ``fn`` AOT on ``args`` and record its analysis under
    ``name``.  For an already-jitted ``fn`` the compile is an executable
    cache hit, so the cost is one retrace.  Returns the ledger entry."""
    import jax
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args).compile()
    return (ledger or get_ledger()).record(name, compiled, **record_kw)
