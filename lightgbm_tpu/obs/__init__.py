"""Unified telemetry: structured events, metrics, tracing, reporting.

The observability subsystem (docs/OBSERVABILITY.md).  Four layers, all
stdlib-only so the supervising processes (watcher, perf suite) can load
them without importing jax:

- :mod:`.events` — versioned structured-event schema + the thread-safe
  jsonl :class:`~.events.EventLog` behind ``perf_results.jsonl``;
- :mod:`.metrics` — process-wide counters/gauges/reservoir-percentile
  histograms, snapshottable on demand;
- :mod:`.tracer` — nested, thread-safe spans exporting Chrome trace JSON
  and (optionally) riding ``jax.profiler`` annotations;
- :mod:`.report` — the ``python -m lightgbm_tpu obs-report`` renderer.

:class:`TrainTelemetry` is the glue the boosting loops hold: one object
wiring config knobs (``obs_telemetry``, ``obs_events_path``,
``obs_trace_device``) to an event log, the metrics registry, the global
tracer, and the ``global_timer`` -> tracer span bridge.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from . import costs, flight, health, regress
from .costs import CostLedger, get_ledger
from .events import (EventLog, SCHEMA_VERSION, classify_record, make_event,
                     new_run_id, perf_log_path, validate_event)
from .flight import FlightRecorder
from .health import DivergenceError, SLOMonitor
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      get_registry)
from .tracer import Span, Tracer, get_tracer

__all__ = ["EventLog", "SCHEMA_VERSION", "classify_record", "make_event",
           "new_run_id", "perf_log_path", "validate_event",
           "Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "Span", "Tracer", "get_tracer",
           "costs", "regress", "CostLedger", "get_ledger",
           "flight", "health", "FlightRecorder", "DivergenceError",
           "SLOMonitor", "TrainTelemetry"]


class TrainTelemetry:
    """Per-booster telemetry hook (constructed when ``obs_telemetry`` is
    on; the boosting loop holds ``None`` otherwise, so the off path costs
    one attribute check per iteration).

    Wires the config to the subsystem: events go to ``obs_events_path``
    (default: the shared perf journal), per-iteration seconds feed named
    histograms in the process registry, and ``global_timer`` scopes are
    bridged into the global tracer so the existing ``GBDT::*`` /
    ``StreamGBDT::*`` scopes become nested spans under each iteration's
    ``train/iteration`` span (with ``jax.profiler`` step annotation when
    ``obs_trace_device`` is set and a capture is active).
    """

    #: the global_timer scope names whose per-iteration deltas are
    #: reported as phase seconds (in-HBM and streaming loops)
    PHASE_SCOPES = ("GBDT::gradients", "GBDT::grow_tree",
                    "GBDT::update_score", "StreamGBDT::gradients",
                    "StreamGBDT::grow_tree", "StreamGBDT::update_score")

    def __init__(self, config: Any, kind: str = "train"):
        self.kind = kind
        path = getattr(config, "obs_events_path", "") or None
        self.log = EventLog(path) if path else EventLog.default()
        self.run_id = self.log.run_id
        self.metrics = get_registry()
        self.reservoir = int(getattr(config, "obs_reservoir_size", 512))
        self.tracer = get_tracer()
        self.tracer.annotate_device = bool(
            getattr(config, "obs_trace_device", False))
        from ..utils.timer import global_timer
        self._timer = global_timer
        global_timer.attach_tracer(self.tracer)
        self._phase_base: Dict[str, float] = {}
        # health plane: arm the flight recorder (dump lands beside the
        # journal unless LGBM_FLIGHT_DIR redirects it), publish the run
        # on the status board, start the exposition server when enabled
        flight.install(dir=os.path.dirname(os.path.abspath(self.log.path)),
                       run_id=self.run_id)
        health.set_status(run_id=self.run_id, stage=self.kind)
        health.maybe_start(getattr(config, "obs_health_port", 0))

    # ------------------------------------------------------------------
    def step(self, it: int):
        """Context for one boosting iteration: a ``train/iteration`` span
        (StepTraceAnnotation-backed when device tracing is on)."""
        return self.tracer.step("train/iteration", step=it)

    def phase_mark(self) -> None:
        """Remember the timer's accumulators at iteration start; the
        iteration event reports the deltas (the jitted growers are one
        compiled program, so phase seconds come from the host scopes)."""
        self._phase_base = {n: self._timer.seconds(n)
                            for n in self.PHASE_SCOPES}

    def phase_seconds(self) -> Dict[str, float]:
        out = {}
        for n in self.PHASE_SCOPES:
            dt = self._timer.seconds(n) - self._phase_base.get(n, 0.0)
            if dt > 0.0:
                short = n.split("::", 1)[-1]
                out[short] = round(dt, 6)
        return out

    # ------------------------------------------------------------------
    def iteration_event(self, it: int, *, trees: int,
                        extra: Optional[Dict[str, Any]] = None) -> None:
        """Emit the per-iteration training event + update metrics."""
        phases = self.phase_seconds()
        self.metrics.counter(f"{self.kind}.iterations").inc()
        for name, secs in phases.items():
            self.metrics.histogram(f"{self.kind}.{name}_seconds",
                                   self.reservoir).observe(secs)
        rec: Dict[str, Any] = {"iteration": it, "trees": trees,
                               "phase_seconds": phases}
        # device-memory watermarks (local stats read, no device sync; CPU
        # publishes none and the helper degrades to {}) + the cost-ledger
        # wall-time join for the recorded grow program
        wm = costs.record_watermarks(self.kind, self.metrics)
        if wm:
            rec["device_memory"] = wm
        if "grow_tree" in phases:
            get_ledger().observe(f"{self.kind}.grow_tree",
                                 phases["grow_tree"])
        if extra:
            rec.update(extra)
        self.log.emit(f"{self.kind}_iter", **rec)
        health.set_status(stage=self.kind, iteration=it)
        # surface the tracer's silent data loss once per overflow episode
        if self.tracer.dropped and not self.tracer.overflow_reported:
            self.tracer.overflow_reported = True
            self.log.emit("tracer_overflow", level="warning",
                          dropped=self.tracer.dropped,
                          capacity=self.tracer.capacity)

    def tree_event(self, it: int, *, num_leaves: int,
                   split_gains: Optional[List[float]] = None) -> None:
        """Per-materialized-tree stats: leaves + split-gain summary.  On
        the fast path this fires from ``_drain_pending`` (the existing
        host materialization point) so telemetry never forces an extra
        device sync."""
        self.metrics.histogram(f"{self.kind}.num_leaves",
                               self.reservoir).observe(num_leaves)
        rec: Dict[str, Any] = {"iteration": it, "num_leaves": num_leaves}
        if split_gains:
            gains = [float(g) for g in split_gains]
            rec["split_gain"] = {
                "splits": len(gains),
                "max": round(max(gains), 6),
                "mean": round(sum(gains) / len(gains), 6),
                "total": round(sum(gains), 6)}
            self.metrics.histogram(f"{self.kind}.split_gain",
                                   self.reservoir).observe(max(gains))
        self.log.emit(f"{self.kind}_tree", **rec)

    def close(self) -> None:
        self._timer.detach_tracer()
