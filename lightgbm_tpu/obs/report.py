"""Perf-trajectory report: render the results journal + a metrics snapshot.

``python -m lightgbm_tpu obs-report`` (and the watcher, after each TPU
window) reads ``perf_results.jsonl`` — schema events and legacy
pre-schema lines alike — and renders a markdown or JSON report: record
counts by kind, the headline bench summaries over time, watcher windows,
and the process's live metrics snapshot when one exists.

Legacy tolerance is the point: the journal predates the schema by many
sessions, so the loader classifies every line via ``events.classify_record``
instead of assuming the envelope, and nothing here throws on old shapes.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from .events import classify_record, perf_log_path

__all__ = ["load_perf_log", "summarize", "render_markdown", "render_json",
           "main"]


def load_perf_log(path: Optional[str] = None) -> Dict[str, Any]:
    """Read + classify every line; missing file -> empty load (a fresh
    checkout has no journal yet and the report must still render)."""
    path = path or perf_log_path()
    events: List[Dict[str, Any]] = []
    legacy: List[Dict[str, Any]] = []
    bad = 0
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        lines = []
    for line in lines:
        if not line.strip():
            continue
        kind, rec = classify_record(line)
        if kind == "event":
            events.append(rec)
        elif kind == "legacy":
            legacy.append(rec)
        else:
            bad += 1
    return {"path": path, "events": events, "legacy": legacy, "bad": bad,
            "total": len(events) + len(legacy) + bad}


def _stage_of(rec: Dict[str, Any]) -> str:
    return str(rec.get("event") or rec.get("stage") or rec.get("bench")
               or rec.get("metric") or "<unkeyed>")


def _is_summary(rec: Dict[str, Any]) -> bool:
    return (rec.get("event") == "bench_summary"
            or ("metric" in rec and "value" in rec)
            or "bench" in rec)


def summarize(loaded: Dict[str, Any],
              metrics_snapshot: Optional[Dict[str, Any]] = None,
              last_n: int = 12) -> Dict[str, Any]:
    """Aggregate the classified journal into the report's data model."""
    records = loaded["legacy"] + loaded["events"]
    by_stage: Dict[str, int] = {}
    ts_min = ts_max = None
    for rec in records:
        by_stage[_stage_of(rec)] = by_stage.get(_stage_of(rec), 0) + 1
        ts = rec.get("ts")
        if isinstance(ts, (int, float)) and not isinstance(ts, bool):
            ts_min = ts if ts_min is None else min(ts_min, ts)
            ts_max = ts if ts_max is None else max(ts_max, ts)
    summaries = [r for r in records if _is_summary(r)]
    windows = [r for r in records
               if _stage_of(r).startswith("watcher_window")]
    run_ids = sorted({r["run_id"] for r in loaded["events"]})
    return {
        "path": loaded["path"],
        "counts": {"total": loaded["total"],
                   "schema_events": len(loaded["events"]),
                   "legacy": len(loaded["legacy"]),
                   "bad": loaded["bad"]},
        "runs": len(run_ids),
        "ts_range": [ts_min, ts_max],
        "by_stage": dict(sorted(by_stage.items(),
                                key=lambda kv: (-kv[1], kv[0]))),
        "recent_summaries": summaries[-last_n:],
        "windows": windows[-last_n:],
        "metrics": metrics_snapshot or {},
    }


def _fmt_summary_row(rec: Dict[str, Any]) -> str:
    metric = rec.get("metric") or rec.get("bench") or rec.get("event")
    value = rec.get("value")
    unit = rec.get("unit", "")
    backend = rec.get("backend", "")
    val = "" if value is None else (f"{value:g}" if isinstance(
        value, (int, float)) and not isinstance(value, bool) else str(value))
    return f"| {metric} | {val} | {unit} | {backend} |"


def render_markdown(summary: Dict[str, Any]) -> str:
    c = summary["counts"]
    lines = ["# Perf trajectory report", "",
             f"Journal: `{summary['path']}`", "",
             f"- records: **{c['total']}** "
             f"({c['schema_events']} schema event(s), "
             f"{c['legacy']} legacy line(s), {c['bad']} unparseable)",
             f"- distinct runs (schema): {summary['runs']}"]
    ts = summary["ts_range"]
    if ts[0] is not None:
        lines.append(f"- wall-clock span: {ts[1] - ts[0]:.0f} s")
    lines += ["", "## Records by kind", "",
              "| kind | count |", "|---|---|"]
    for stage, n in summary["by_stage"].items():
        lines.append(f"| {stage} | {n} |")
    if summary["recent_summaries"]:
        lines += ["", "## Recent bench summaries", "",
                  "| metric | value | unit | backend |", "|---|---|---|---|"]
        for rec in summary["recent_summaries"]:
            lines.append(_fmt_summary_row(rec))
    if summary["windows"]:
        lines += ["", "## Watcher windows", ""]
        for rec in summary["windows"]:
            wid = rec.get("window_id", "?")
            lines.append(f"- window `{wid}`: "
                         + ", ".join(f"{k}={v}" for k, v in rec.items()
                                     if k not in ("stage", "event", "ts",
                                                  "mono", "run_id",
                                                  "schema_version",
                                                  "window_id")))
    if summary["metrics"]:
        lines += ["", "## Telemetry snapshot", "",
                  "| metric | value |", "|---|---|"]
        for name, snap in summary["metrics"].items():
            if snap.get("type") == "histogram" and snap.get("count"):
                val = (f"n={snap['count']} mean={snap['mean']:.4g} "
                       f"p50={snap['p50']:.4g} p99={snap['p99']:.4g}")
            else:
                val = f"{snap.get('value', 0):g}"
            lines.append(f"| {name} | {val} |")
    lines.append("")
    return "\n".join(lines)


def render_json(summary: Dict[str, Any]) -> str:
    return json.dumps(summary, indent=2, default=str)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu obs-report",
        description="render the perf journal + telemetry snapshot")
    ap.add_argument("--path", default=None,
                    help="journal to read (default: WATCHER_PERF_LOG or "
                         "repo perf_results.jsonl)")
    ap.add_argument("--format", choices=("md", "json"), default="md")
    ap.add_argument("--out", default=None,
                    help="write here instead of stdout")
    ap.add_argument("--no-metrics", action="store_true",
                    help="omit the in-process metrics snapshot")
    args = ap.parse_args(argv)

    snap = None
    if not args.no_metrics:
        from .metrics import snapshot as _snapshot
        snap = _snapshot()
    data = summarize(load_perf_log(args.path), metrics_snapshot=snap)
    text = render_markdown(data) if args.format == "md" else render_json(data)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
