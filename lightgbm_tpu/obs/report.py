"""Perf-trajectory report: render the results journal + a metrics snapshot.

``python -m lightgbm_tpu obs-report`` (and the watcher, after each TPU
window) reads ``perf_results.jsonl`` — schema events and legacy
pre-schema lines alike — and renders a markdown or JSON report: record
counts by kind, the headline bench summaries over time, watcher windows,
and the process's live metrics snapshot when one exists.

Legacy tolerance is the point: the journal predates the schema by many
sessions, so the loader classifies every line via ``events.classify_record``
instead of assuming the envelope, and nothing here throws on old shapes.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from . import costs as _costs
from . import regress as _regress
from .events import classify_record, perf_log_path

__all__ = ["load_perf_log", "summarize", "render_markdown", "render_json",
           "roofline_rows", "render_roofline", "render_regressions",
           "render_health", "main"]


def load_perf_log(path: Optional[str] = None) -> Dict[str, Any]:
    """Read + classify every line; missing file -> empty load (a fresh
    checkout has no journal yet and the report must still render)."""
    path = path or perf_log_path()
    events: List[Dict[str, Any]] = []
    legacy: List[Dict[str, Any]] = []
    bad = 0
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        lines = []
    for line in lines:
        if not line.strip():
            continue
        kind, rec = classify_record(line)
        if kind == "event":
            events.append(rec)
        elif kind == "legacy":
            legacy.append(rec)
        else:
            bad += 1
    return {"path": path, "events": events, "legacy": legacy, "bad": bad,
            "total": len(events) + len(legacy) + bad}


def _stage_of(rec: Dict[str, Any]) -> str:
    return str(rec.get("event") or rec.get("stage") or rec.get("bench")
               or rec.get("metric") or "<unkeyed>")


def _is_summary(rec: Dict[str, Any]) -> bool:
    return (rec.get("event") == "bench_summary"
            or ("metric" in rec and "value" in rec)
            or "bench" in rec)


def summarize(loaded: Dict[str, Any],
              metrics_snapshot: Optional[Dict[str, Any]] = None,
              last_n: int = 12,
              tracer_info: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Aggregate the classified journal into the report's data model."""
    records = loaded["legacy"] + loaded["events"]
    by_stage: Dict[str, int] = {}
    ts_min = ts_max = None
    for rec in records:
        by_stage[_stage_of(rec)] = by_stage.get(_stage_of(rec), 0) + 1
        ts = rec.get("ts")
        if isinstance(ts, (int, float)) and not isinstance(ts, bool):
            ts_min = ts if ts_min is None else min(ts_min, ts)
            ts_max = ts if ts_max is None else max(ts_max, ts)
    summaries = [r for r in records if _is_summary(r)]
    windows = [r for r in records
               if _stage_of(r).startswith("watcher_window")]
    run_ids = sorted({r["run_id"] for r in loaded["events"]})
    return {
        "path": loaded["path"],
        "counts": {"total": loaded["total"],
                   "schema_events": len(loaded["events"]),
                   "legacy": len(loaded["legacy"]),
                   "bad": loaded["bad"]},
        "runs": len(run_ids),
        "ts_range": [ts_min, ts_max],
        "by_stage": dict(sorted(by_stage.items(),
                                key=lambda kv: (-kv[1], kv[0]))),
        "recent_summaries": summaries[-last_n:],
        "windows": windows[-last_n:],
        "metrics": metrics_snapshot or {},
        "tracer": tracer_info or {},
    }


def _fmt_summary_row(rec: Dict[str, Any]) -> str:
    metric = rec.get("metric") or rec.get("bench") or rec.get("event")
    value = rec.get("value")
    unit = rec.get("unit", "")
    backend = rec.get("backend", "")
    val = "" if value is None else (f"{value:g}" if isinstance(
        value, (int, float)) and not isinstance(value, bool) else str(value))
    return f"| {metric} | {val} | {unit} | {backend} |"


def render_markdown(summary: Dict[str, Any]) -> str:
    c = summary["counts"]
    lines = ["# Perf trajectory report", "",
             f"Journal: `{summary['path']}`", "",
             f"- records: **{c['total']}** "
             f"({c['schema_events']} schema event(s), "
             f"{c['legacy']} legacy line(s), {c['bad']} unparseable)",
             f"- distinct runs (schema): {summary['runs']}"]
    ts = summary["ts_range"]
    if ts[0] is not None:
        lines.append(f"- wall-clock span: {ts[1] - ts[0]:.0f} s")
    tr = summary.get("tracer") or {}
    if tr:
        # the ring drops silently when full — the report is where that
        # data loss must become visible
        line = (f"- tracer: {tr.get('spans', 0)} span(s) recorded, "
                f"{tr.get('open_spans', 0)} open")
        if tr.get("dropped"):
            line += (f", **{tr['dropped']} dropped** "
                     f"(ring capacity {tr.get('capacity', '?')})")
        lines.append(line)
    lines += ["", "## Records by kind", "",
              "| kind | count |", "|---|---|"]
    for stage, n in summary["by_stage"].items():
        lines.append(f"| {stage} | {n} |")
    if summary["recent_summaries"]:
        lines += ["", "## Recent bench summaries", "",
                  "| metric | value | unit | backend |", "|---|---|---|---|"]
        for rec in summary["recent_summaries"]:
            lines.append(_fmt_summary_row(rec))
    if summary["windows"]:
        lines += ["", "## Watcher windows", ""]
        for rec in summary["windows"]:
            wid = rec.get("window_id", "?")
            lines.append(f"- window `{wid}`: "
                         + ", ".join(f"{k}={v}" for k, v in rec.items()
                                     if k not in ("stage", "event", "ts",
                                                  "mono", "run_id",
                                                  "schema_version",
                                                  "window_id")))
    if summary["metrics"]:
        lines += ["", "## Telemetry snapshot", "",
                  "| metric | value |", "|---|---|"]
        for name, snap in summary["metrics"].items():
            if snap.get("type") == "histogram" and snap.get("count"):
                val = (f"n={snap['count']} mean={snap['mean']:.4g} "
                       f"p50={snap['p50']:.4g} p99={snap['p99']:.4g}")
            else:
                val = f"{snap.get('value', 0):g}"
            lines.append(f"| {name} | {val} |")
    lines.append("")
    return "\n".join(lines)


def render_json(summary: Dict[str, Any]) -> str:
    return json.dumps(summary, indent=2, default=str)


# --------------------------------------------------------------------------
# --roofline: device-truth cost/MFU rows (obs.costs program_cost events)
# --------------------------------------------------------------------------

def roofline_rows(loaded: Dict[str, Any],
                  ledger: Optional[Any] = None) -> List[Dict[str, Any]]:
    """``program_cost`` records from the journal, plus the live in-process
    ledger's rooflines when one is passed (dedup: live rows win on name)."""
    rows = [r for r in loaded["events"] + loaded["legacy"]
            if r.get("event") == _costs.COST_EVENT
            or r.get("stage") == _costs.COST_EVENT]
    if ledger is not None:
        live = {r["program"]: r for r in ledger.rooflines()}
        rows = [r for r in rows if r.get("program") not in live]
        rows += list(live.values())
    return rows


def _num(v: Any, scale: float = 1.0, fmt: str = "{:.3g}") -> str:
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return fmt.format(v * scale)
    return "" if v is None else str(v)


def render_roofline(rows: List[Dict[str, Any]]) -> str:
    lines = ["## Roofline / MFU (XLA cost ledger)", ""]
    if not rows:
        lines += ["_no program_cost records (run a bench with the cost "
                  "ledger enabled, or emit a CostLedger)._", ""]
        return "\n".join(lines)
    lines += ["| program | chip | calls | ms/call | GFLOP/s | MFU | "
              "model MFU | GB/s | AI (F/B) | bound |",
              "|---|---|---|---|---|---|---|---|---|---|"]
    for r in rows:
        lines.append("| {} | {} | {} | {} | {} | {} | {} | {} | {} | {} |"
                     .format(r.get("program", "?"), r.get("chip", "?"),
                             r.get("calls", ""),
                             _num(r.get("seconds_per_call"), 1e3),
                             _num(r.get("achieved_flops_per_sec"), 1e-9),
                             _num(r.get("mfu"), fmt="{:.4f}"),
                             _num(r.get("model_mfu"),
                                  fmt="{:.4f}") or
                             _num(r.get("predicted_mfu"), fmt="{:.4f}"),
                             _num(r.get("achieved_bytes_per_sec"), 1e-9),
                             _num(r.get("intensity")),
                             r.get("bound", "")))
    lines.append("")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# --regressions: sentinel verdicts over journal + BENCH_r* history
# --------------------------------------------------------------------------

def render_regressions(result: Dict[str, Any], gate: bool = False) -> str:
    counts = result["counts"]
    lines = ["## Perf-regression sentinel", "",
             "- verdicts: " + (", ".join(f"{k}: **{v}**" for k, v in
                                         sorted(counts.items())) or "none"),
             f"- gate: {'**REGRESSED**' if result['regressed'] else 'clean'}"
             + (" (exit nonzero)" if gate and result["regressed"] else ""),
             ""]
    shown = [v for v in result["verdicts"] if v["verdict"] != "no-baseline"]
    hidden = len(result["verdicts"]) - len(shown)
    if shown:
        lines += ["| metric | field | backend | shape | verdict | "
                  "baseline | latest | Δ% | n |",
                  "|---|---|---|---|---|---|---|---|---|"]
        for v in shown:
            verdict = v["verdict"] + (f" ({v['severity']})"
                                      if v.get("severity") else "")
            lines.append("| {} | {} | {} | {} | {} | {} | {} | {} | {} |"
                         .format(v["metric"], v["field"], v["backend"],
                                 v["shape"] or "-", verdict,
                                 _num(v.get("baseline_median")),
                                 _num(v.get("latest")),
                                 _num(v.get("rel_change"), 100.0,
                                      "{:+.1f}"),
                                 v["n_baseline"]))
    if hidden:
        lines.append(f"\n_{hidden} series below the "
                     f"{_regress.MIN_BASELINE}-sample baseline floor "
                     "(no-baseline)._")
    lines.append("")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# --health: runtime health plane (live /healthz or in-process snapshot)
# --------------------------------------------------------------------------

def _health_data(url: Optional[str] = None) -> Dict[str, Any]:
    """The health payload: fetched from a live process's ``/healthz`` when
    ``--health-url`` is given, else this process's own snapshot (useful
    right after an in-process run, or for the flight/tracer state)."""
    if url:
        import urllib.request
        if "://" not in url:
            url = "http://" + url
        if not url.rstrip("/").endswith("/healthz"):
            url = url.rstrip("/") + "/healthz"
        with urllib.request.urlopen(url, timeout=5) as resp:
            return json.loads(resp.read().decode())
    from . import health as _health
    return _health.health_snapshot()


def render_health(data: Dict[str, Any]) -> str:
    lines = ["## Runtime health", "",
             f"- ok: {'**yes**' if data.get('ok') else '**NO**'}"
             f" (pid {data.get('pid', '?')}, "
             f"uptime {_num(data.get('uptime_s'))} s)"]
    if data.get("error"):
        lines.append(f"- fetch error: {data['error']} "
                     f"(url: {data.get('url')})")
        lines.append("")
        return "\n".join(lines)
    for key in ("run_id", "stage", "iteration"):
        if data.get(key) is not None:
            lines.append(f"- {key}: `{data[key]}`")
    if data.get("last_event_ts") is not None:
        lines.append(f"- last event ts: {_num(data['last_event_ts'])}")
    tr = data.get("tracer") or {}
    if tr:
        lines.append(f"- tracer: {tr.get('spans', 0)} span(s), "
                     f"{tr.get('open_spans', 0)} open, "
                     f"{tr.get('dropped', 0)} dropped")
    fl = data.get("flight") or {}
    if fl:
        lines.append(f"- flight recorder: {fl.get('events', 0)} event(s) "
                     f"in ring, {fl.get('dumps', 0)} dump(s) -> "
                     f"`{fl.get('path', '?')}`")
    status = data.get("status") or {}
    numeric = {k: v for k, v in status.items()
               if k.startswith(("numeric", "last_numeric"))}
    if numeric:
        lines.append("- numeric sentinels: "
                     + ", ".join(f"{k}={v}"
                                 for k, v in sorted(numeric.items())))
    dm = data.get("device_memory") or {}
    if dm:
        lines += ["", "### Device memory watermarks", "",
                  "| gauge | bytes |", "|---|---|"]
        for name, v in dm.items():
            lines.append(f"| {name} | {_num(v)} |")
    slos = data.get("slo") or []
    if slos:
        lines += ["", "### Serve SLO burn rates", "",
                  "| model | window | requests | error_rate | p99_ms | "
                  "error burn | latency burn | breached |",
                  "|---|---|---|---|---|---|---|---|"]
        for rep in slos:
            for wname, w in (rep.get("windows") or {}).items():
                lines.append(
                    "| {} | {} | {} | {} | {} | {} | {} | {} |".format(
                        rep.get("model", "?"), wname,
                        w.get("requests", 0), _num(w.get("error_rate")),
                        _num(w.get("p99_ms")), _num(w.get("error_burn")),
                        _num(w.get("latency_burn")),
                        "**yes**" if w.get("breached") else "no"))
    else:
        lines.append("- serve SLO: no objectives registered "
                     "(`serve_slo_p99_ms` / `serve_slo_error_rate`)")
    lines.append("")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu obs-report",
        description="render the perf journal + telemetry snapshot")
    ap.add_argument("--path", default=None,
                    help="journal to read (default: WATCHER_PERF_LOG or "
                         "repo perf_results.jsonl)")
    ap.add_argument("--format", choices=("md", "json"), default="md")
    ap.add_argument("--out", default=None,
                    help="write here instead of stdout")
    ap.add_argument("--no-metrics", action="store_true",
                    help="omit the in-process metrics snapshot")
    ap.add_argument("--roofline", action="store_true",
                    help="render only the cost-ledger roofline/MFU rows")
    ap.add_argument("--regressions", action="store_true",
                    help="render only the perf-regression sentinel verdicts")
    ap.add_argument("--health", action="store_true",
                    help="render only the runtime-health section (status "
                         "board, sentinels, SLO burn rates, flight state)")
    ap.add_argument("--health-url", default=None, metavar="HOST:PORT",
                    help="with --health: fetch /healthz from a live "
                         "process instead of this process's snapshot")
    ap.add_argument("--gate", action="store_true",
                    help="with --regressions: exit nonzero on any "
                         "regressed verdict")
    ap.add_argument("--bench-glob", default=None,
                    help="history round files for the sentinel "
                         "(default: BENCH_r*.json beside the journal)")
    args = ap.parse_args(argv)

    rc = 0
    loaded = load_perf_log(args.path)
    if args.roofline or args.regressions or args.health:
        # focused sections (CLI/gate mode): no base report around them
        parts = []
        payload: Dict[str, Any] = {}
        if args.roofline:
            rows = roofline_rows(loaded, ledger=_costs.get_ledger())
            parts.append(render_roofline(rows))
            payload["roofline"] = rows
        if args.regressions:
            res = _regress.scan(journal_path=loaded["path"],
                                bench_glob=args.bench_glob)
            parts.append(render_regressions(res, gate=args.gate))
            payload["regressions"] = res
            if args.gate and res["regressed"]:
                rc = 1
        if args.health:
            try:
                hdata = _health_data(args.health_url)
            except OSError as e:
                hdata = {"ok": False, "error": str(e),
                         "url": args.health_url}
            parts.append(render_health(hdata))
            payload["health"] = hdata
        text = ("\n".join(parts) if args.format == "md"
                else json.dumps(payload, indent=2, default=str))
    else:
        snap = None
        if not args.no_metrics:
            from .metrics import snapshot as _snapshot
            snap = _snapshot()
        tracer_info = None
        try:
            from .tracer import get_tracer
            t = get_tracer()
            if t.spans() or t.dropped or t.open_spans():
                tracer_info = {"spans": len(t.spans()),
                               "open_spans": len(t.open_spans()),
                               "dropped": t.dropped,
                               "capacity": t.capacity}
        except Exception:
            pass
        data = summarize(loaded, metrics_snapshot=snap,
                         tracer_info=tracer_info)
        text = (render_markdown(data) if args.format == "md"
                else render_json(data))
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return rc


if __name__ == "__main__":
    sys.exit(main())
