"""Perf-regression sentinel: self-judge every new number against history.

VERDICT weak #2: "hardware regression risk is unbounded" — a TPU window
landing slower than round 1 would burn silently.  This module closes
that: it builds robust per-(metric, backend, shape) baselines from the
accumulated history (``perf_results.jsonl`` schema + legacy lines, plus
the committed ``BENCH_r*.json`` round files) and classifies the latest
sample of every series as improved / ok / regressed / no-baseline with a
severity, so ``python -m lightgbm_tpu obs-report --regressions [--gate]``
(and the watcher's post-stage verdict records, and the perf suite's
closing ``regress`` phase) flag a slowdown loudly while the window is
still open.

Robustness choices:

- baseline = median, spread = MAD (scaled by 1.4826 to a normal-sigma
  equivalent) with a relative floor — one wedged outlier round (e.g.
  BENCH_r03's 2.0 s/tree next to 0.81/0.82) must not poison the center
  OR make the band so wide everything passes;
- min-sample floor: fewer than :data:`MIN_BASELINE` prior samples in a
  series -> ``no-baseline`` (never ``regressed``), so fresh metrics and
  renamed series (the honest-labeling fix) cannot false-positive;
- a verdict needs BOTH a robust-z excursion and a relative change above
  :data:`REL_THRESHOLD` — MAD can be ~0 on repeated identical values and
  a pure z-test would then flag noise.

Series keys: ``(metric, backend, shape)`` where shape collects the
fields that change the workload (rows, max_bin, variant, br,
num_leaves).  Metric names are canonicalized — size/backend-suffix
tokens (``_1m``, ``_200k``, ``_cpu_fallback``) are stripped because the
backend and rows already live in the key — so the corrected
``higgs_200k_cpu_fallback_train_throughput`` label continues the series
the mislabeled ``higgs_1m_train_throughput`` cpu/200k lines started.

Deliberately stdlib-only: the watcher/suite load this jax-free via
``bench.load_obs()`` and judge a possibly-wedged window from outside.
"""
from __future__ import annotations

import glob as _glob
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = ["MIN_BASELINE", "REL_THRESHOLD", "FIELD_DIRECTION",
           "canonical_metric", "extract_samples", "load_history",
           "classify", "scan", "VERDICT_EVENT"]

#: event name for emitted verdict records
VERDICT_EVENT = "regression_verdict"

#: a series needs this many PRIOR samples before its latest is judged
MIN_BASELINE = 3

#: relative change below this is never a verdict (noise floor)
REL_THRESHOLD = 0.15

#: robust-z (MAD-sigma) excursion required alongside the relative change
Z_THRESHOLD = 3.0

#: numeric fields worth judging, and which direction is better.  Only
#: fields listed here become series — free-form stage records carry too
#: much incidental timing (compile secs, probe secs) to judge raw.
FIELD_DIRECTION: Dict[str, str] = {
    "sec_per_tree": "lower", "ms": "lower", "ms_per_tree": "lower",
    "hist_kernel_ms": "lower", "p50_ms": "lower", "p99_ms": "lower",
    "predict_ms": "lower",
    "value": "higher",          # flipped to lower for ms/sec-unit summaries
    "vs_baseline": "higher", "mfu": "higher", "grows_per_sec": "higher",
    "rows_per_sec": "higher", "auc": "higher",
}

#: fields that define the workload shape (part of the series key)
SHAPE_FIELDS = ("rows", "max_bin", "variant", "br", "num_leaves", "name")

#: stage/event kinds whose records are judged even without the summary
#: shape (known perf-bearing micro-bench records)
STAGE_PREFIXES = ("hist_pallas", "hist_onehot", "hist_leaves",
                  "onehot_variant", "grow_", "headline_bench",
                  "bench_serve", "bench_stream")

_SIZE_TOKEN = re.compile(r"_(\d+(?:p\d+)?[km]?)(?=_|$)", re.IGNORECASE)


def canonical_metric(name: str) -> str:
    """Strip size / fallback tokens so renamed series keep their history
    (backend + rows live in the key, not the name)."""
    out = _SIZE_TOKEN.sub("", str(name))
    out = out.replace("_cpu_fallback", "").replace("_fallback", "")
    return out.strip("_") or str(name)


# --------------------------------------------------------------------------
# sample extraction
# --------------------------------------------------------------------------

def _flatten(rec: Dict[str, Any]) -> Dict[str, Any]:
    """Merge one level of the known nesting envelopes (``detail`` on bench
    summaries, ``result`` on watcher/suite stage records, ``parsed`` on
    BENCH round files) over the top-level fields."""
    out = dict(rec)
    for key in ("parsed", "result", "detail"):
        inner = out.pop(key, None)
        if isinstance(inner, dict):
            nested = inner.pop("detail", None)
            out.update(inner)
            if isinstance(nested, dict):
                out.update(nested)
    return out


def _base_name(rec: Dict[str, Any], flat: Dict[str, Any]) -> Optional[str]:
    if isinstance(flat.get("metric"), str):
        return canonical_metric(flat["metric"])
    for k in ("bench", "event", "stage"):
        v = rec.get(k) or flat.get(k)
        if isinstance(v, str) and v:
            if k in ("event", "stage") and not v.startswith(STAGE_PREFIXES):
                return None
            return v
    return None


def _direction(field: str, flat: Dict[str, Any]) -> str:
    d = FIELD_DIRECTION[field]
    if field == "value":
        unit = str(flat.get("unit", "")).lower()
        if "ms" in unit or unit in ("s", "sec", "secs", "seconds"):
            return "lower"
    return d


def extract_samples(rec: Dict[str, Any], seq: int = 0) -> List[Dict[str, Any]]:
    """Judgeable samples in one journal/bench record.  Each sample:
    ``{key, metric, backend, shape, field, value, direction, seq}`` where
    ``key`` is the hashable series identity."""
    if not isinstance(rec, dict):
        return []
    flat = _flatten(rec)
    # failed/aborted records carry no trustworthy numbers
    if flat.get("error") or flat.get("ok") is False or flat.get("skipped"):
        return []
    base = _base_name(rec, flat)
    if not base:
        return []
    backend = str(flat.get("backend", "") or "unknown").lower()
    shape = ",".join(f"{k}={flat[k]}" for k in SHAPE_FIELDS
                     if flat.get(k) is not None)
    out = []
    for field in FIELD_DIRECTION:
        v = flat.get(field)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        out.append({"key": (base, backend, shape, field), "metric": base,
                    "backend": backend, "shape": shape, "field": field,
                    "value": float(v),
                    "direction": _direction(field, flat), "seq": seq})
    return out


def load_history(journal_path: Optional[str] = None,
                 bench_glob: Optional[str] = None) -> List[Dict[str, Any]]:
    """All samples from the round files + journal, in chronological order
    (BENCH_r* sorted by name first — they predate the journal's schema
    era — then journal lines in file order)."""
    from .events import perf_log_path
    journal_path = journal_path or perf_log_path()
    if bench_glob is None:
        bench_glob = os.path.join(
            os.path.dirname(os.path.abspath(journal_path)), "BENCH_r*.json")
    samples: List[Dict[str, Any]] = []
    seq = 0
    for path in sorted(_glob.glob(bench_glob)):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(rec, dict) or rec.get("rc") not in (0, None):
            continue
        if isinstance(rec.get("parsed"), dict):
            samples.extend(extract_samples(rec, seq))
            seq += 1
    try:
        with open(journal_path) as f:
            lines = f.readlines()
    except OSError:
        lines = []
    for line in lines:
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            samples.extend(extract_samples(rec, seq))
            seq += 1
    return samples


# --------------------------------------------------------------------------
# classification
# --------------------------------------------------------------------------

def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def classify(baseline: List[float], latest: float, direction: str,
             min_baseline: int = MIN_BASELINE,
             rel_threshold: float = REL_THRESHOLD) -> Dict[str, Any]:
    """Verdict for ``latest`` against the prior samples of its series."""
    n = len(baseline)
    if n < min_baseline:
        return {"verdict": "no-baseline", "n_baseline": n}
    med = _median(baseline)
    mad = _median([abs(v - med) for v in baseline])
    scale = max(1.4826 * mad, 0.05 * abs(med), 1e-12)
    z = (latest - med) / scale
    rel = (latest - med) / abs(med) if med else 0.0
    # positive worse_* = the metric moved the WRONG way
    sign = 1.0 if direction == "lower" else -1.0
    worse_z, worse_rel = sign * z, sign * rel
    out = {"verdict": "ok", "n_baseline": n, "baseline_median": med,
           "baseline_mad": mad, "latest": latest, "z": round(z, 3),
           "rel_change": round(rel, 4), "direction": direction}
    if worse_z > Z_THRESHOLD and worse_rel > rel_threshold:
        out["verdict"] = "regressed"
        out["severity"] = ("critical" if worse_rel > 1.0 else
                           "major" if worse_rel > 0.5 else "minor")
    elif worse_z < -Z_THRESHOLD and worse_rel < -rel_threshold:
        out["verdict"] = "improved"
    return out


def scan(journal_path: Optional[str] = None,
         bench_glob: Optional[str] = None,
         samples: Optional[Iterable[Dict[str, Any]]] = None,
         min_baseline: int = MIN_BASELINE) -> Dict[str, Any]:
    """Judge the LATEST sample of every series against the rest.

    Returns ``{"verdicts": [...], "counts": {...}, "regressed": bool}``;
    verdicts are sorted worst-first (regressed > no-baseline > ok >
    improved, then by |rel_change|)."""
    if samples is None:
        samples = load_history(journal_path, bench_glob)
    series: Dict[Tuple, List[Dict[str, Any]]] = {}
    for s in samples:
        series.setdefault(s["key"], []).append(s)
    verdicts = []
    for key, ss in series.items():
        ss.sort(key=lambda s: s["seq"])
        latest = ss[-1]
        v = classify([s["value"] for s in ss[:-1]], latest["value"],
                     latest["direction"], min_baseline=min_baseline)
        v.update(metric=latest["metric"], backend=latest["backend"],
                 shape=latest["shape"], field=latest["field"])
        verdicts.append(v)
    rank = {"regressed": 0, "no-baseline": 1, "ok": 2, "improved": 3}
    verdicts.sort(key=lambda v: (rank[v["verdict"]],
                                 -abs(v.get("rel_change", 0.0)),
                                 v["metric"], v["field"]))
    counts: Dict[str, int] = {}
    for v in verdicts:
        counts[v["verdict"]] = counts.get(v["verdict"], 0) + 1
    return {"verdicts": verdicts, "counts": counts,
            "regressed": counts.get("regressed", 0) > 0}
