"""Span-based tracing: nested, thread-safe, Chrome-trace exportable.

The upgrade path for ``utils/timer.py``: ``Timer`` keeps its aggregate
role (name -> total seconds), while an attached :class:`Tracer` records
every scope as a *span* — begin/end timestamps, thread id, nesting depth
— so one training run exports a timeline instead of only totals.

- Spans nest per thread (a thread-local open-span stack), so
  ``train/iteration > GBDT::grow_tree`` renders as nested bars;
- :meth:`Tracer.export_chrome_trace` writes Chrome trace-event JSON
  (``ph: "X"`` complete events, microsecond clocks) loadable in Perfetto
  / ``chrome://tracing``;
- with ``annotate_device=True`` each span also enters a
  ``jax.profiler.TraceAnnotation`` (and :meth:`step` a
  ``StepTraceAnnotation``), so when a ``jax.profiler`` device capture is
  active the host spans line up with the XLA ops they dispatched — the
  host/device correlation story for TPU windows.

jax is imported lazily and only when device annotation is requested;
the module itself is stdlib-only.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["Span", "Tracer", "get_tracer"]


class Span:
    """One completed scope."""

    __slots__ = ("name", "start", "duration", "tid", "depth", "args")

    def __init__(self, name: str, start: float, duration: float,
                 tid: int, depth: int, args: Optional[Dict[str, Any]]):
        self.name = name
        self.start = start          # perf_counter seconds
        self.duration = duration    # seconds
        self.tid = tid
        self.depth = depth
        self.args = args


class _OpenSpan:
    __slots__ = ("name", "start", "args", "annotation")

    def __init__(self, name, start, args, annotation):
        self.name = name
        self.start = start
        self.args = args
        self.annotation = annotation    # entered jax TraceAnnotation or None


class Tracer:
    """Thread-safe span recorder with bounded memory.

    ``capacity`` bounds retained spans; beyond it new spans are counted in
    ``dropped`` instead of stored (a tracer must never become the leak it
    is measuring).
    """

    def __init__(self, capacity: int = 100_000,
                 annotate_device: bool = False):
        self.capacity = int(capacity)
        self.annotate_device = bool(annotate_device)
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self.dropped = 0
        #: set by the first ``tracer_overflow`` warning event so the
        #: warning fires once per overflow episode, not per iteration
        self.overflow_reported = False
        self._local = threading.local()
        # tid -> that thread's open-span stack; thread-locals are not
        # enumerable from another thread, and the flight recorder needs
        # the open spans of EVERY thread at crash time
        self._stacks: Dict[int, List[_OpenSpan]] = {}

    # ------------------------------------------------------------------
    def _stack(self) -> List[_OpenSpan]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
            with self._lock:
                self._stacks[threading.get_ident()] = st
        return st

    def open_spans(self) -> List[Dict[str, Any]]:
        """Snapshot of every thread's currently-open spans (crash
        forensics: what was in flight when the process died)."""
        now = time.perf_counter()
        out: List[Dict[str, Any]] = []
        with self._lock:
            stacks = {tid: list(st) for tid, st in self._stacks.items()}
        for tid, stack in sorted(stacks.items()):
            for depth, o in enumerate(stack):
                out.append({"name": o.name, "tid": tid, "depth": depth,
                            "age_s": round(now - o.start, 6),
                            "args": o.args})
        return out

    def _device_annotation(self, name: str, step: Optional[int] = None):
        """Enter a jax profiler annotation when asked and available."""
        if not self.annotate_device:
            return None
        try:
            from jax import profiler as _prof
            ann = (_prof.StepTraceAnnotation(name, step_num=step)
                   if step is not None else _prof.TraceAnnotation(name))
            ann.__enter__()
            return ann
        except Exception:
            return None     # no jax / no profiler: tracing degrades to host

    def begin(self, name: str, step: Optional[int] = None,
              **args: Any) -> None:
        """Open a span on the calling thread (pairs with :meth:`end`)."""
        ann = self._device_annotation(name, step)
        if step is not None:
            args = dict(args, step=step)
        self._stack().append(
            _OpenSpan(name, time.perf_counter(), args or None, ann))

    def end(self, name: str) -> None:
        """Close the innermost open span named ``name`` on this thread.
        Unbalanced ends are ignored (a tracer must not crash its host)."""
        now = time.perf_counter()
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].name == name:
                open_ = stack.pop(i)
                depth = i
                break
        else:
            return
        if open_.annotation is not None:
            try:
                open_.annotation.__exit__(None, None, None)
            except Exception:
                pass
        span = Span(name, open_.start, now - open_.start,
                    threading.get_ident(), depth, open_.args)
        with self._lock:
            if len(self._spans) < self.capacity:
                self._spans.append(span)
            else:
                self.dropped += 1

    @contextlib.contextmanager
    def span(self, name: str, **args: Any):
        self.begin(name, **args)
        try:
            yield
        finally:
            self.end(name)

    @contextlib.contextmanager
    def step(self, name: str, step: int):
        """A top-level per-iteration span; with device annotation on it
        rides ``jax.profiler.StepTraceAnnotation`` so the profiler groups
        the iteration's XLA ops under one step."""
        self.begin(name, step=step)
        try:
            yield
        finally:
            self.end(name)

    # ------------------------------------------------------------------
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def aggregate(self) -> Dict[str, Dict[str, Any]]:
        """Per-name totals (the ``Timer.items`` shape, from spans)."""
        out: Dict[str, Dict[str, Any]] = {}
        for s in self.spans():
            agg = out.setdefault(s.name, {"count": 0, "total_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += s.duration
        return out

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0
            self.overflow_reported = False

    # ------------------------------------------------------------------
    def export_chrome_trace(self, path: str) -> int:
        """Write Chrome trace-event JSON (Perfetto-loadable); returns the
        number of spans exported."""
        spans = self.spans()
        pid = os.getpid()
        events = []
        for s in spans:
            ev: Dict[str, Any] = {
                "name": s.name, "ph": "X", "pid": pid, "tid": s.tid,
                "ts": round(s.start * 1e6, 3),
                "dur": round(s.duration * 1e6, 3),
            }
            if s.args:
                ev["args"] = s.args
            events.append(ev)
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(events)


_TRACER = Tracer()


def get_tracer() -> Tracer:
    """The process-global tracer (what ``global_timer`` feeds when
    telemetry is on)."""
    return _TRACER
