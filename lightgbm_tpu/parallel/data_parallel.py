"""Data-parallel GBDT training step: rows sharded over a mesh axis.

TPU-native re-design of ``DataParallelTreeLearner``
(``src/treelearner/data_parallel_tree_learner.cpp``): the reference shards
rows across machines, builds local histograms, ReduceScatters the packed
histogram buffer so each rank owns full histograms for a feature block
(``:155-173``), searches splits on its block, then Allreduce-maxes the
serialized ``SplitInfo`` (``parallel_tree_learner.h:191-214``).

Here the same dataflow is one `shard_map` program: the grower runs on each
shard with ``GrowerConfig.axis_name`` set, and the reference's dataflow maps
onto collectives exactly (ops/grower.py ``reduce_hist`` /
``_reduce_split_global``):

- per split, local histograms join via ``lax.psum_scatter`` over the feature
  axis, so each shard RECEIVES, STORES and SEARCHES only its owned feature
  block — comm volume F*B/ndev per device per split (a full ``psum`` moves
  F*B and was the round-2 shape), and the histogram-subtraction store
  shrinks by 1/ndev too;
- each shard's local best split then rides a tiny ``pmax``-based SplitInfo
  allreduce (``_reduce_split_global`` = SyncUpGlobalBestSplit), after which
  every shard applies the identical split to its local rows — the
  reference's local ``DataPartition::Split``.

Paths that need a full-width histogram on every shard (EFB bundle
expansion, forced splits, CEGB-lazy) fall back to the full ``psum``.
``scripts/bench_dp_scaling.py`` measures the 1..8-shard curve on the
virtual CPU mesh.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.grower import GrowerConfig, grow_tree
from .mesh import DATA_AXIS, shard_map


def make_dp_train_step(grower_cfg: GrowerConfig,
                       feature_meta: dict,
                       grad_fn: Optional[Callable],
                       learning_rate: float,
                       mesh: jax.sharding.Mesh,
                       axis_name: str = DATA_AXIS,
                       num_class: int = 1,
                       external_grads: bool = False,
                       efb=None):
    """Build a jitted data-parallel one-iteration training step.

    Args:
      grower_cfg: static grower config; its ``axis_name`` is overridden.
      feature_meta: dict with replicated per-feature arrays
        (num_bins, default_bins, nan_bins, is_categorical, monotone).
      grad_fn: elementwise shard-local objective gradient —
        ``(score[n], label[n], weight[n]|None) -> (grad[n], hess[n])`` for
        one class, or ``(score[K,n], label, weight) -> ([K,n], [K,n])``
        when ``num_class > 1`` (softmax couples the classes, so gradients
        come from the full score matrix).
      learning_rate: shrinkage applied to leaf values in the score update.
      num_class: trees grown per iteration (one per class, in one
        ``lax.scan`` so the program compiles once).

    Returns a function
      ``(bins[N,F], label[N], score[N] or [K,N], row_weight[N], fmask[F],
         key, weight=None) -> (new_score, TreeArrays)``
    with rows sharded over ``axis_name`` and the tree(s) replicated
    (leaf arrays gain a leading class axis when ``num_class > 1``).
    ``row_weight`` carries the pad/bag mask; ``weight`` (or None) the user
    sample weights, applied inside the objective like the single-process
    engine (counts stay mask-based).
    """
    cfg = grower_cfg._replace(axis_name=axis_name)
    fm = feature_meta
    K = num_class

    def one_tree(grad, hess, bins, row_weight, fmask, key):
        tree, node_assign = grow_tree(
            bins, grad, hess, row_weight, fmask,
            fm["num_bins"], fm["default_bins"], fm["nan_bins"],
            fm["is_categorical"], fm["monotone"], key, cfg, efb=efb)
        delta = tree.leaf_value * learning_rate
        has_split = tree.num_leaves > 1
        return jnp.where(has_split, delta[node_assign], 0.0), tree

    def grow_all(grads, hesses, bins, score, row_weight, fmask, key):
        if K == 1:
            d, tree = one_tree(grads, hesses, bins, row_weight, fmask, key)
            return score + d, tree

        def body(carry, xs):
            g, h, k = xs
            d, tree = one_tree(g, h, bins, row_weight, fmask, k)
            return carry, (d, tree)

        keys = jax.random.split(key, K)
        _, (deltas, trees) = jax.lax.scan(
            body, 0, (grads, hesses, keys))
        return score + deltas, trees

    score_spec = P(axis_name) if K == 1 else P(None, axis_name)
    n_shards = mesh.shape[axis_name]

    def check_rows(n):
        if n % n_shards:
            raise ValueError(
                f"row count {n} is not divisible by the "
                f"{n_shards}-way '{axis_name}' mesh axis; pad rows with "
                f"pad_rows_to_multiple() and zero row_weight for pad rows")

    if external_grads:
        # gradients arrive precomputed (host-side rank objectives, GOSS /
        # bagging amplification applied by the caller)
        def step_ex(bins, grads, hesses, score, row_weight, fmask, key):
            return grow_all(grads, hesses, bins, score, row_weight, fmask,
                            key)

        sharded = shard_map(
            step_ex, mesh=mesh,
            in_specs=(P(axis_name), score_spec, score_spec, score_spec,
                      P(axis_name), P(), P()),
            out_specs=(score_spec, P()),
            check_vma=False)
        jitted = jax.jit(sharded)

        def checked_ex(bins, grads, hesses, score, row_weight, fmask, key):
            check_rows(bins.shape[0])
            return jitted(bins, grads, hesses, score, row_weight, fmask, key)
        return checked_ex

    def step(bins, label, score, row_weight, weight, fmask, key):
        grads, hesses = grad_fn(score, label, weight)
        return grow_all(grads, hesses, bins, score, row_weight, fmask, key)

    sharded = shard_map(
        step, mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), score_spec, P(axis_name),
                  P(axis_name), P(), P()),
        out_specs=(score_spec, P()),
        check_vma=False)  # tree outputs are replicated by construction (psum)
    jitted = jax.jit(sharded)

    def checked(bins, label, score, row_weight, fmask, key, weight=None):
        check_rows(bins.shape[0])
        if weight is None:
            weight = jnp.ones_like(label)
        return jitted(bins, label, score, row_weight, weight, fmask, key)
    return checked


def shard_rows(mesh: jax.sharding.Mesh, axis_name: str = DATA_AXIS):
    """NamedSharding placing the leading (row) axis on the mesh."""
    return jax.sharding.NamedSharding(mesh, P(axis_name))


def pad_rows_to_multiple(n: int, k: int) -> int:
    """Rows must divide the mesh axis; pad count (weights 0 for pad rows)."""
    return (-n) % k
