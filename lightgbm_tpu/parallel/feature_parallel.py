"""Feature-parallel GBDT training step: features sharded over a mesh axis.

TPU-native re-design of ``FeatureParallelTreeLearner``
(``src/treelearner/feature_parallel_tree_learner.cpp``): the reference keeps
ALL rows on every rank and shards only the split *search* by feature
(bin-count-balanced assignment, ``:38-57``), then allreduce-maxes the
serialized ``SplitInfo`` (``parallel_tree_learner.h:191-214``) so every rank
applies the identical split locally.

Here the binned matrix itself is sharded ``[N, F/nf]`` (saving HBM as well
as work), per-shard bests are combined with a ``pmax`` + masked-``psum``
broadcast (see ``ops.grower._reduce_split_global``), and — because columns
are sharded, unlike the reference — the winning shard broadcasts its
partition decision with one ``[N]`` psum per split.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.grower import GrowerConfig, grow_tree
from .mesh import FEATURE_AXIS, shard_map


def make_fp_train_step(grower_cfg: GrowerConfig,
                       feature_meta: dict,
                       grad_fn: Callable,
                       learning_rate: float,
                       mesh: jax.sharding.Mesh,
                       axis_name: str = FEATURE_AXIS):
    """Build a jitted feature-parallel one-iteration training step.

    Inputs at call time:
      bins ``[N, F]`` (sharded over features), label/score/row_weight ``[N]``
      (replicated), fmask ``[F]`` full-width (replicated), key.
    feature_meta arrays stay FULL-width and replicated.
    Returns ``(new_score[N], TreeArrays)`` — both replicated.
    """
    n_shards = mesh.shape[axis_name]
    cfg = grower_cfg._replace(axis_name=axis_name, parallel_mode="feature",
                              num_shards=n_shards)
    fm = feature_meta

    def step(bins, label, score, row_weight, fmask, key):
        # shared grad_fn convention with make_dp_train_step:
        # (score, label, weight); sample weights are not
        # wired through this learner's step
        grad, hess = grad_fn(score, label, None)
        tree, node_assign = grow_tree(
            bins, grad, hess, row_weight, fmask,
            fm["num_bins"], fm["default_bins"], fm["nan_bins"],
            fm["is_categorical"], fm["monotone"], key, cfg)
        delta = tree.leaf_value * learning_rate
        has_split = tree.num_leaves > 1
        new_score = score + jnp.where(has_split, delta[node_assign], 0.0)
        return new_score, tree

    sharded = shard_map(
        step, mesh=mesh,
        in_specs=(P(None, axis_name), P(), P(), P(), P(), P()),
        out_specs=(P(), P()),
        check_vma=False)  # outputs replicated by construction (psum-reduced)
    jitted = jax.jit(sharded)

    @functools.wraps(jitted)
    def checked(bins, label, score, row_weight, fmask, key):
        if bins.shape[1] % n_shards:
            raise ValueError(
                f"feature count {bins.shape[1]} is not divisible by the "
                f"{n_shards}-way '{axis_name}' mesh axis; pad features (all-"
                f"constant columns bin to a single bin and are never chosen)")
        return jitted(bins, label, score, row_weight, fmask, key)
    return checked


def pad_features_to_multiple(f: int, k: int) -> int:
    """Features must divide the mesh axis; number of pad columns needed."""
    return (-f) % k
