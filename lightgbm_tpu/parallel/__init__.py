"""Distributed training over JAX device meshes.

This package replaces the reference's entire ``src/network/`` layer
(hand-written Bruck allgather / recursive-halving reduce-scatter over TCP
sockets or MPI, ``network.cpp``, ``linkers_socket.cpp``, ``linkers_mpi.cpp``)
and its three parallel tree learners (``src/treelearner/
{data,feature,voting}_parallel_tree_learner.cpp``) with `shard_map` programs
over a `jax.sharding.Mesh`, where the communication patterns are single XLA
collectives riding ICI/DCN:

- histogram ReduceScatter        -> ``lax.psum`` / ``lax.psum_scatter``
- best-split Allreduce (max)     -> ``lax.pmax`` over a packed (gain, key)
- scalar GlobalSum / SyncUpBy*   -> ``lax.psum`` / ``lax.pmin`` / ``lax.pmax``

Multi-host bring-up (the reference's machine-list file + port handshake,
``linkers_socket.cpp``; Dask's cluster setup, ``python-package/lightgbm/
dask.py``) is ``jax.distributed.initialize`` + the standard TPU pod runtime.
"""
from .mesh import default_mesh, free_network, init_distributed, set_network
from ..io.distributed import distributed_dataset
from .trainer import train_distributed
from .data_parallel import make_dp_train_step, pad_rows_to_multiple, shard_rows
from .feature_parallel import make_fp_train_step, pad_features_to_multiple
from .voting_parallel import make_voting_train_step
from .estimators import DistLGBMClassifier, DistLGBMRegressor

__all__ = ["default_mesh", "init_distributed", "set_network",
           "free_network", "distributed_dataset", "train_distributed",
           "make_dp_train_step",
           "make_fp_train_step", "make_voting_train_step",
           "pad_rows_to_multiple", "pad_features_to_multiple", "shard_rows",
           "DistLGBMClassifier", "DistLGBMRegressor"]
