"""Device-mesh construction and multi-host initialization.

The reference builds its process mesh by parsing a machine-list file and
pairwise-connecting TCP sockets (``Linkers::Construct``,
``src/network/linkers_socket.cpp``) or from ``MPI_COMM_WORLD``
(``linkers_mpi.cpp``).  Here the runtime owns topology: we only name axes on
`jax.sharding.Mesh` and let XLA route collectives over ICI/DCN.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def default_mesh(num_devices: Optional[int] = None,
                 axis_name: str = DATA_AXIS,
                 devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    """1-D mesh over (a prefix of) the available devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} available")
        devices = devices[:num_devices]
    return jax.sharding.Mesh(np.asarray(devices), (axis_name,))


def mesh_2d(num_data: int, num_feature: int,
            devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    """(data, feature) mesh for combined row+feature sharding."""
    if devices is None:
        devices = jax.devices()
    n = num_data * num_feature
    if n > len(devices):
        raise ValueError(f"mesh {num_data}x{num_feature} needs {n} devices, "
                         f"only {len(devices)} available")
    arr = np.asarray(devices[:n]).reshape(num_data, num_feature)
    return jax.sharding.Mesh(arr, (DATA_AXIS, FEATURE_AXIS))


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (replaces ``LGBM_NetworkInit`` + machine lists,
    ``c_api.cpp`` / ``application.cpp:167-202``).  On TPU pods all arguments
    are discovered from the environment."""
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
