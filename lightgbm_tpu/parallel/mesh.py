"""Device-mesh construction and multi-host initialization.

The reference builds its process mesh by parsing a machine-list file and
pairwise-connecting TCP sockets (``Linkers::Construct``,
``src/network/linkers_socket.cpp``) or from ``MPI_COMM_WORLD``
(``linkers_mpi.cpp``).  Here the runtime owns topology: we only name axes on
`jax.sharding.Mesh` and let XLA route collectives over ICI/DCN.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``.

    Newer jax exports ``jax.shard_map`` with a ``check_vma`` flag; older
    releases (<= 0.4.x) only have ``jax.experimental.shard_map.shard_map``
    whose equivalent flag is ``check_rep``.  Every sharded program in this
    package goes through this ONE resolver so a jax upgrade/downgrade is a
    single-site change instead of a per-call-site hunt."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        try:
            return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)
        except TypeError:       # transitional releases: jax.shard_map + check_rep
            return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def default_mesh(num_devices: Optional[int] = None,
                 axis_name: str = DATA_AXIS,
                 devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    """1-D mesh over (a prefix of) the available devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} available")
        devices = devices[:num_devices]
    return jax.sharding.Mesh(np.asarray(devices), (axis_name,))


def mesh_2d(num_data: int, num_feature: int,
            devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    """(data, feature) mesh for combined row+feature sharding."""
    if devices is None:
        devices = jax.devices()
    n = num_data * num_feature
    if n > len(devices):
        raise ValueError(f"mesh {num_data}x{num_feature} needs {n} devices, "
                         f"only {len(devices)} available")
    arr = np.asarray(devices[:n]).reshape(num_data, num_feature)
    return jax.sharding.Mesh(arr, (DATA_AXIS, FEATURE_AXIS))


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     timeout_secs: Optional[int] = None) -> None:
    """Multi-host bring-up (replaces ``LGBM_NetworkInit`` + machine lists,
    ``c_api.cpp`` / ``application.cpp:167-202``).  On TPU pods all arguments
    are discovered from the environment."""
    kw = {}
    if timeout_secs is not None:
        kw["initialization_timeout"] = int(timeout_secs)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kw)


def set_network(machines, local_listen_port: int = 12400,
                listen_time_out: int = 120,
                num_machines: Optional[int] = None) -> None:
    """Reference ``Booster.set_network`` analog: bring up the
    ``jax.distributed`` client from a machine list.

    ``machines`` is a list/set or a comma-separated string of
    ``host[:port]`` entries — the FIRST entry becomes the coordinator
    (the reference's rank-0 socket hub).  This process's rank is the
    index of its entry, resolved by matching a local interface address
    or hostname; pass ``host:port`` entries whose hosts are resolvable.
    ``listen_time_out`` maps to the coordinator connect timeout.
    """
    import socket

    if isinstance(machines, str):
        entries = [m.strip() for m in machines.split(",") if m.strip()]
    else:
        entries = [str(m).strip() for m in machines]
        if isinstance(machines, (set, frozenset)):
            # per-process hash randomization would make each rank see a
            # different entry order (different coordinator!) — sort for a
            # deterministic shared view
            entries = sorted(entries)
    if num_machines is None:
        num_machines = len(entries)
    hosts = [e.split(":")[0] for e in entries]
    coord_host = hosts[0]
    coord_port = (int(entries[0].split(":")[1]) if ":" in entries[0]
                  else local_listen_port)

    local_names = {socket.gethostname(), "localhost", "127.0.0.1"}
    try:
        local_names.add(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass

    def _is_local_addr(addr: str) -> bool:
        """A bind() to addr succeeds exactly when addr belongs to a local
        interface — robust where hostname mapping is not (e.g. Debian's
        127.0.1.1 /etc/hosts entry hides the real NIC address)."""
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.bind((addr, 0))
            return True
        except OSError:
            return False

    addrs = []
    for h in hosts:
        try:
            addrs.append(socket.gethostbyname(h))
        except OSError:
            addrs.append(h)
    matches = [i for i, (h, a) in enumerate(zip(hosts, addrs))
               if h in local_names or a in local_names]
    if not matches:
        # fallback for hosts whose hostname does not map to the NIC
        # address (Debian's 127.0.1.1 /etc/hosts entry): bind-probe each
        # entry.  Only as a fallback — the whole 127/8 block is bindable,
        # so loopback multi-entry lists must resolve by name above.
        matches = [i for i, a in enumerate(addrs) if _is_local_addr(a)]
    if len(matches) > 1:
        # same host listed multiple times (multi-process-per-box layout):
        # hostname matching cannot tell the processes apart
        raise ValueError(
            f"set_network: machine entries {[entries[i] for i in matches]} "
            "all resolve to this host; assign ranks explicitly with "
            "init_distributed(coordinator_address, num_processes, "
            "process_id)")
    rank = matches[0] if matches else None
    if rank is None:
        raise ValueError(
            f"set_network: none of the machine entries {hosts} resolves to "
            "this host; use init_distributed(coordinator_address, "
            "num_processes, process_id) to assign the rank explicitly")
    init_distributed(coordinator_address=f"{coord_host}:{coord_port}",
                     num_processes=num_machines, process_id=rank,
                     timeout_secs=int(listen_time_out) * 60)  # ref: minutes


def free_network() -> None:
    """Reference ``LGBM_NetworkFree`` analog."""
    jax.distributed.shutdown()
