"""Voting-parallel GBDT training step: data parallel with ~constant comm.

TPU-native re-design of ``VotingParallelTreeLearner``
(``src/treelearner/voting_parallel_tree_learner.cpp``): rows are sharded;
each shard proposes its local top-k split features (``top_k`` config), a
global vote elects 2k features per leaf (``GlobalVoting``, ``:151``), and
only the elected features' histograms are reduced (``CopyLocalHistogram``
+ ReduceScatter, ``:184,345``) — shrinking per-split communication from
``F×B`` to ``2k×B`` histogram rows.

Here the vote is a psum of one-hot ballots, the election is a replicated
``top_k`` over vote counts, and the elected histograms ride one gathered
psum (see ``ops.grower`` voting mode).  Local min-data/min-hessian gates are
scaled by ``1/num_shards`` like the reference (``:61-63``).
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.grower import GrowerConfig, grow_tree
from .mesh import DATA_AXIS, shard_map


def make_voting_train_step(grower_cfg: GrowerConfig,
                           feature_meta: dict,
                           grad_fn: Callable,
                           learning_rate: float,
                           mesh: jax.sharding.Mesh,
                           top_k: int = 20,
                           axis_name: str = DATA_AXIS):
    """Build a jitted voting-parallel one-iteration training step.

    Same calling convention as ``make_dp_train_step`` (rows sharded over
    ``axis_name``); only elected histograms cross the interconnect.
    """
    n_shards = mesh.shape[axis_name]
    cfg = grower_cfg._replace(axis_name=axis_name, parallel_mode="voting",
                              top_k=top_k, num_shards=n_shards)
    fm = feature_meta

    def step(bins, label, score, row_weight, fmask, key):
        # shared grad_fn convention with make_dp_train_step:
        # (score, label, weight); sample weights are not
        # wired through this learner's step
        grad, hess = grad_fn(score, label, None)
        tree, node_assign = grow_tree(
            bins, grad, hess, row_weight, fmask,
            fm["num_bins"], fm["default_bins"], fm["nan_bins"],
            fm["is_categorical"], fm["monotone"], key, cfg)
        delta = tree.leaf_value * learning_rate
        has_split = tree.num_leaves > 1
        new_score = score + jnp.where(has_split, delta[node_assign], 0.0)
        return new_score, tree

    sharded = shard_map(
        step, mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(axis_name), P(axis_name),
                  P(), P()),
        out_specs=(P(axis_name), P()),
        check_vma=False)
    jitted = jax.jit(sharded)

    @functools.wraps(jitted)
    def checked(bins, label, score, row_weight, fmask, key):
        if bins.shape[0] % n_shards:
            raise ValueError(
                f"row count {bins.shape[0]} is not divisible by the "
                f"{n_shards}-way '{axis_name}' mesh axis")
        return jitted(bins, label, score, row_weight, fmask, key)
    return checked
