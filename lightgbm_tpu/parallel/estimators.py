"""One-liner distributed estimators: the Dask-package analog.

Reference analog: ``python-package/lightgbm/dask.py`` ``DaskLGBMClassifier``
/ ``DaskLGBMRegressor`` — sklearn-style estimators whose ``fit`` runs the
distributed trainer over each worker's local partition.  Here the cluster
is a ``jax.distributed`` process group and ``fit`` routes through
``parallel.trainer.train_distributed`` (which itself picks streaming
per-rank when the local bin shard exceeds the device budget, so
``DistLGBMClassifier(...).fit(X_local, y_local)`` is the one-liner for
"larger-than-HBM AND multi-host").

Cluster/port auto-discovery, in priority order (ROADMAP item 5c):

1. an already-initialized ``jax.distributed`` process group is used as-is;
2. the ``machines`` constructor param / ``machines`` entry in params — a
   ``host[:port],host[:port]`` list, wired via ``parallel.set_network``
   (rank = index of the local host, first entry is the coordinator);
3. the ``LGBM_TPU_MACHINES`` environment variable, same format;
4. none of the above: single-process training (``train_distributed``
   degrades to the ordinary engine).
"""
from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

from ..sklearn import LGBMClassifier, LGBMRegressor
from ..utils.log import Log, LightGBMError
from .trainer import train_distributed

__all__ = ["DistLGBMClassifier", "DistLGBMRegressor"]


def _distributed_active() -> bool:
    try:
        from jax._src import distributed as _dist
        return getattr(_dist.global_state, "client", None) is not None
    except Exception:
        return False


def _resolve_network(machines, local_listen_port: int,
                     time_out: int) -> None:
    """Bring up the process group if a machine list is known and no group
    exists yet; otherwise leave topology alone."""
    if _distributed_active():
        return
    machines = machines or os.environ.get("LGBM_TPU_MACHINES") or ""
    if not machines:
        return                      # single process
    from .mesh import set_network
    set_network(machines, local_listen_port=local_listen_port,
                listen_time_out=time_out)


class _DistMixin:
    """fit() plumbing shared by the distributed estimators."""

    def _dist_fit(self, X, y, sample_weight=None, group=None,
                  eval_set=None, eval_group=None,
                  early_stopping_rounds=None,
                  feature_name=None, categorical_feature=None):
        params = self._lgb_params()
        machines = params.pop("machines", None) or getattr(
            self, "machines", None)
        port = int(params.pop("local_listen_port", 0) or
                   getattr(self, "local_listen_port", 12400))
        time_out = int(params.pop("time_out", 0) or 120)
        # strip aliases train_distributed's engine would re-parse
        for k in ("num_machines", "num_machine"):
            params.pop(k, None)
        _resolve_network(machines, port, time_out)

        valid = None
        vgroup = None
        if eval_set:
            if len(eval_set) > 1:
                Log.warning("Dist estimators pool ONE validation shard; "
                            "using eval_set[0] and ignoring %d more",
                            len(eval_set) - 1)
            vX, vy = eval_set[0]
            valid = (vX, np.asarray(self._prep_eval_label(
                np.asarray(vy).ravel())).ravel())
            if eval_group:
                vgroup = eval_group[0]

        self._evals_result = {}
        booster = train_distributed(
            params, X, y, num_boost_round=self.n_estimators,
            weight=sample_weight, group=group, valid_data=valid,
            valid_group=vgroup,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=self._evals_result,
            feature_name=feature_name,
            categorical_feature=categorical_feature)
        self._Booster = booster
        self._best_iteration = getattr(booster, "best_iteration", -1)
        self._n_features = (int(X.shape[1]) if hasattr(X, "shape")
                            else len(X[0]))
        self.fitted_ = True
        return self


class DistLGBMRegressor(_DistMixin, LGBMRegressor):
    """Distributed (multi-process, streaming-aware) LGBMRegressor."""

    def __init__(self, machines: Optional[Any] = None,
                 local_listen_port: int = 12400, **kwargs):
        self.machines = machines
        self.local_listen_port = local_listen_port
        super().__init__(**kwargs)

    def fit(self, X, y, sample_weight=None, eval_set=None,
            early_stopping_rounds=None, feature_name=None,
            categorical_feature=None, **_ignored):
        y = np.asarray(y, np.float64).ravel()
        return self._dist_fit(
            X, y, sample_weight=sample_weight, eval_set=eval_set,
            early_stopping_rounds=early_stopping_rounds,
            feature_name=feature_name,
            categorical_feature=categorical_feature)


class DistLGBMClassifier(_DistMixin, LGBMClassifier):
    """Distributed (multi-process, streaming-aware) LGBMClassifier.

    Class discovery pools the label sets across ranks (a rank whose shard
    misses a class must still agree on the global code mapping).
    """

    def __init__(self, machines: Optional[Any] = None,
                 local_listen_port: int = 12400, **kwargs):
        self.machines = machines
        self.local_listen_port = local_listen_port
        super().__init__(**kwargs)

    def fit(self, X, y, sample_weight=None, eval_set=None,
            early_stopping_rounds=None, feature_name=None,
            categorical_feature=None, **_ignored):
        import jax
        y = np.asarray(y).ravel()
        local = np.unique(y)
        if _distributed_active() and jax.process_count() > 1:
            if not np.issubdtype(local.dtype, np.number):
                raise LightGBMError(
                    "multi-process DistLGBMClassifier needs numeric labels "
                    "(cross-rank class pooling rides float collectives); "
                    "encode string labels before sharding")
            from jax.experimental import multihost_utils as mhu
            local_f = local.astype(np.float64)
            n_max = int(np.asarray(mhu.process_allgather(
                np.int64(len(local_f)))).max())
            padded = np.pad(local_f, (0, n_max - len(local_f)),
                            constant_values=local_f[0] if len(local_f)
                            else 0.0)
            pooled = np.asarray(mhu.process_allgather(padded)).ravel()
            self._classes = np.unique(pooled)
        else:
            self._classes = local
        self._n_classes = len(self._classes)
        y_enc = np.searchsorted(self._classes, y).astype(np.float64)
        self._resolve_classification_objective()
        return self._dist_fit(
            X, y_enc, sample_weight=sample_weight, eval_set=eval_set,
            early_stopping_rounds=early_stopping_rounds,
            feature_name=feature_name,
            categorical_feature=categorical_feature)
