"""Multi-process end-to-end training: the Dask-package analog.

Reference analog: ``python-package/lightgbm/dask.py`` — each worker holds a
partition, `LGBM_NetworkInit` wires the ranks, and every rank runs the same
training loop with collective histogram merges, producing identical models.

Here the ranks are ``jax.distributed`` processes: ingest is
``io.distributed.distributed_dataset`` (pooled-sample binning → identical
mappers), the per-iteration step is ``make_dp_train_step``'s shard_map
program whose psum/pmax collectives cross process boundaries over the
global device mesh, and every process assembles the identical model from
the replicated tree output.

Feature coverage mirrors the reference's distributed training
(``src/boosting/gbdt.cpp:228-262`` bagging on the shared row partition,
``src/objective/rank_objective.hpp:25-67`` rank-local queries,
``src/boosting/gbdt.cpp:517-575`` synced validation metrics):

- **bagging** (incl. pos/neg fractions): the Bernoulli mask is drawn from
  the seeded iteration key over the GLOBAL row order, so every rank agrees
  and a multi-process run grows the same trees as a single process over
  the concatenated rows;
- **GOSS**: the top-rate cut is a global ``top_k`` over the sharded
  |g·h| importance (XLA inserts the collectives), matching the
  single-process exact-top-k semantics;
- **feature_fraction**: the per-tree column mask derives from the seeded
  numpy stream — identical on every rank by construction;
- **lambdarank / rank_xendcg**: queries are rank-local (the reference's
  distributed contract), gradients are computed per process on its local
  rows and fed to the sharded grower as precomputed inputs;
- **EFB**: the bundle layout is planned from the pooled binned sample
  (identical on every rank, io/distributed.py), the shard_map step trains
  directly in bundle space, and validation traverses unbundled columns;
- **validation metrics**: additive metrics pool (sum, count); AUC pools
  the raw (score, label) pairs exactly; NDCG@k / MAP@k pool per-query
  means weighted by local query counts.  Early stopping follows the first
  metric's higher/lower-better direction, rank-consistently.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..config import Config
from ..io.distributed import distributed_dataset
from ..utils.log import Log, LightGBMError, check
from ..utils.random_gen import key_for_iteration
from .data_parallel import make_dp_train_step
from .mesh import DATA_AXIS


def train_distributed(params, data, label, num_boost_round: Optional[int] = None,
                      weight=None, group=None, valid_data=None,
                      valid_group=None,
                      early_stopping_rounds: Optional[int] = None,
                      evals_result: Optional[dict] = None,
                      feature_name=None, categorical_feature=None):
    """Train over every ``jax.distributed`` process's local partition and
    return a ``Booster`` (identical on every process).

    ``data``/``label``/``weight``/``group`` are THIS process's rows (and
    rank-local queries); ``valid_data`` an optional ``(X_local, y_local)``
    validation shard with ``valid_group`` its local query sizes.  Requires
    ``parallel.mesh.init_distributed`` to have run.  Single-process calls
    degrade to the ordinary engine.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    cfg = Config.from_params(dict(params or {}))
    rounds = (num_boost_round if num_boost_round is not None
              else cfg.num_iterations)

    from ..io.dataset import (_df_has_category_columns, _is_dataframe,
                              _require_pandas_mapping)
    pandas_categorical = None
    valid_is_df = valid_data is not None and _is_dataframe(valid_data[0])
    valid_has_cats = valid_is_df and _df_has_category_columns(valid_data[0])
    if _is_dataframe(data):
        # category-dtype columns -> training codes, like Dataset.construct;
        # the category lists ride to the returned Booster so predict on a
        # DataFrame re-codes against them.  The lists come from THIS
        # process's shard; cross-rank consistency is verified below.
        from ..io.dataset import _pandas_to_numpy
        data, df_names, cat_spec, pandas_categorical = _pandas_to_numpy(
            data, categorical_feature if categorical_feature is not None
            else "auto", None)
        feature_name = feature_name or df_names
        categorical_feature = None if cat_spec == "auto" else cat_spec
    if jax.process_count() > 1:
        # Shards whose category dtypes differ (levels cast per-shard, or a
        # level absent on one rank) would silently produce different codes
        # for the same value on different ranks.  Gather a digest of the
        # lists and fail loudly on divergence instead.
        import hashlib
        import json as _json
        from jax.experimental import multihost_utils as _mhu
        # the no-mapping guard's raise PREDICATE rides in the digest so it
        # fires on EVERY rank or none (a rank-local raise would leave the
        # others blocked in the next collective); the raw flag would reject
        # legitimate mixed container types when a mapping exists
        valid_would_raise = pandas_categorical is None and valid_has_cats
        digest = hashlib.sha256(
            _json.dumps([pandas_categorical, valid_would_raise], default=str)
            .encode()).digest()[:8]
        # int32 chunks: jax default x64-disabled would silently truncate int64
        mine = np.frombuffer(digest, dtype=np.int32)
        everyone = np.asarray(_mhu.process_allgather(mine))
        if not (everyone == mine[None, :]).all():
            raise LightGBMError(
                "pandas categorical levels differ across processes: every "
                "rank must see identical category dtypes (same levels, same "
                "order). Cast columns to a shared CategoricalDtype before "
                "sharding.")
    if valid_is_df:
        from ..io.dataset import _pandas_to_numpy
        # after the digest gather, every rank agrees on both inputs to this
        # guard, so it raises everywhere or nowhere
        _require_pandas_mapping(valid_data[0], pandas_categorical,
                                "validation DataFrame")
        valid_data = (_pandas_to_numpy(valid_data[0], "auto",
                                       pandas_categorical)[0],
                      valid_data[1])

    ds = distributed_dataset(data, cfg, label=label, weight=weight,
                             group=group,
                             categorical_feature=categorical_feature,
                             feature_names=feature_name)
    if jax.process_count() == 1:
        from ..basic import Booster, Dataset
        wrapper = Dataset(None, params=dict(params or {}))
        wrapper._inner = ds
        wrapper.pandas_categorical = pandas_categorical
        valid_sets = None
        if valid_data is not None:
            vw = Dataset(valid_data[0], label=valid_data[1],
                         group=valid_group, reference=wrapper,
                         params=dict(params or {}))
            valid_sets = [vw]
        from ..engine import train as _train
        return _train(dict(params or {}), wrapper, num_boost_round=rounds,
                      valid_sets=valid_sets,
                      early_stopping_rounds=early_stopping_rounds,
                      evals_result=evals_result)

    from jax.experimental import multihost_utils as mhu
    from ..objective import create_objective
    from ..models.gbdt import GBDT
    from ..models.tree import Tree

    objective = create_objective(cfg)
    check(objective is not None,
          "train_distributed requires a built-in objective")
    K = objective.num_model_per_iteration
    is_ranking = getattr(objective, "is_ranking", False)
    check(cfg.boosting in ("gbdt", "goss"),
          "train_distributed supports boosting=gbdt/goss")
    check(cfg.feature_fraction_bynode >= 1.0,
          "train_distributed does not support feature_fraction_bynode")
    check(not cfg.is_unbalance and cfg.scale_pos_weight == 1.0,
          "train_distributed does not support is_unbalance/"
          "scale_pos_weight (class stats would be per-shard, not global)")
    if is_ranking:
        check(group is not None,
              "ranking objectives need rank-local `group` sizes")

    # --- host-side shard geometry (shared with the streaming branch) ----
    n_local = ds.num_data
    d_local = jax.local_device_count()
    n_locals = np.asarray(mhu.process_allgather(np.int64(n_local))).reshape(-1)
    n_global = int(n_locals.sum())
    my_off = int(n_locals[: jax.process_index()].sum())
    label_np = np.asarray(ds.metadata.label, np.float32)
    w_np = (np.asarray(ds.metadata.weight, np.float32)
            if ds.metadata.weight is not None else np.ones(n_local, np.float32))

    # --- GLOBAL boost-from-average: only the weighted label sum/count
    # crosses processes (two scalars), then the objective's own formula
    # applies.  A per-process mean would give each rank a different init.
    inits = [0.0] * K
    if cfg.boost_from_average and not is_ranking:
        if cfg.objective == "regression":
            sums = np.asarray(mhu.process_allgather(np.asarray(
                [float((w_np * label_np).sum()), float(w_np.sum())])))
            inits = [float(sums[:, 0].sum()) / max(float(sums[:, 1].sum()),
                                                   1e-12)]
        elif cfg.objective in ("binary", "multiclass", "multiclassova"):
            # class-frequency objectives: pool the per-class WEIGHTED
            # counts (a [C] vector), then feed a C-point weighted
            # surrogate through the objective's own initscore formula —
            # exact, because these formulas depend only on class
            # frequencies
            C = max(2, cfg.num_class)
            local = np.bincount(label_np.astype(np.int64), weights=w_np,
                                minlength=C).astype(np.float64)
            pooled = np.asarray(
                mhu.process_allgather(local)).reshape(-1, C).sum(axis=0)
            from ..io.dataset import Metadata
            surrogate = Metadata(C)
            surrogate.set_field("label", np.arange(C, dtype=np.float64))
            surrogate.set_field("weight", np.maximum(pooled, 1e-12))
            obj2 = create_objective(cfg)
            obj2.init(surrogate, C)
            inits = [obj2.boost_from_score(k) for k in range(K)]
        else:
            Log.warning("train_distributed: boost_from_average for "
                        "objective %s is not pooled globally; starting "
                        "from 0", cfg.objective)

    objective.init(ds.metadata, n_local)     # local stats for gradients

    # --- per-rank out-of-core choice (docs/STREAMING.md): when THIS rank's
    # bin shard exceeds the device budget, train it host-resident with
    # streamed blocks; the cross-rank histogram reduction happens on the
    # block-accumulated [F, B, 3] store, so ranks that stream and ranks
    # that don't would still agree — v1 keeps one code path per run and
    # streams everywhere once any config budget is set (the EFB gate is
    # config-only for the same reason, io/dataset._efb_config_allows)
    plan = ds.stream_plan()
    if plan is not None:
        return _train_distributed_stream(
            cfg, ds, plan, objective, K, rounds, inits, label_np, w_np,
            n_locals, n_global, my_off, valid_data, valid_group,
            early_stopping_rounds, evals_result, mhu,
            pandas_categorical)

    # --- equal per-process row blocks (pad rows ride weight 0) ----------
    per_proc = int(n_locals.max())
    per_proc = -(-per_proc // d_local) * d_local
    pad = per_proc - n_local
    bins_l = np.pad(np.asarray(ds.bins), ((0, pad), (0, 0)))
    label_l = np.pad(label_np, (0, pad))
    rw_l = np.pad(np.ones(n_local, np.float32), (0, pad))
    w_l = np.pad(w_np, (0, pad))
    N = per_proc * jax.process_count()
    # TRUE global row index of every local (padded) position: bagging/GOSS
    # draw per-row uniforms over the UNPADDED global order, so the masks
    # match a single-process run over the concatenated rows even when
    # shards are padded (pad rows point at 0 and ride weight 0)
    gidx_l = np.pad(my_off + np.arange(n_local, dtype=np.int32), (0, pad))

    mesh = Mesh(np.array(jax.devices()), (DATA_AXIS,))
    sh = NamedSharding(mesh, P(DATA_AXIS))
    mk = lambda a: jax.make_array_from_process_local_data(  # noqa: E731
        sh, a, (N,) + a.shape[1:])
    bins_g, label_g, rw_g, w_g = mk(bins_l), mk(label_l), mk(rw_l), mk(w_l)
    gidx_g = mk(gidx_l)
    ksh = NamedSharding(mesh, P(None, DATA_AXIS))
    mk_k = lambda a: jax.make_array_from_process_local_data(  # noqa: E731
        ksh, a, (a.shape[0], N))

    dd = ds.device_data()
    tmp = GBDT(cfg)
    tmp.train_data = ds
    tmp._dd = dd
    gcfg = tmp._make_grower_cfg()._replace(
        num_shards=jax.device_count(), parallel_mode="data")
    meta = dict(num_bins=dd.num_bins, default_bins=dd.default_bins,
                nan_bins=dd.nan_bins, is_categorical=dd.is_categorical,
                monotone=dd.monotone)

    step = make_dp_train_step(gcfg, meta, None, cfg.learning_rate, mesh,
                              num_class=K, external_grads=True, efb=dd.efb)
    if K == 1:
        score_l = np.full((per_proc,), inits[0], np.float32)
        score = mk(score_l)
    else:
        score_l = np.tile(np.asarray(inits, np.float32)[:, None],
                          (1, per_proc))
        score = mk_k(score_l)

    # --- per-iteration gradients (global sharded for elementwise
    # objectives; host-local for rank objectives whose queries are
    # rank-local by the reference's distributed contract) ----------------
    if not is_ranking:
        if K == 1:
            grad_jit = jax.jit(
                lambda sc, lab, w: objective.get_gradients(sc, lab, w))
        else:
            grad_jit = jax.jit(
                lambda sc, lab, w: objective.get_gradients_multi(sc, lab, w))

        def compute_grads(score, it):
            g, h = grad_jit(score, label_g, w_g)
            return g, h
    else:
        def _local_rows(arr):
            shards = sorted(arr.addressable_shards,
                            key=lambda s: s.index[-1].start or 0)
            return np.concatenate([np.asarray(s.data, np.float32).reshape(-1)
                                   for s in shards])

        def compute_grads(score, it):
            sc_local = _local_rows(score)[:n_local]
            g, h = objective.get_gradients(jnp.asarray(sc_local),
                                           jnp.asarray(label_np),
                                           (jnp.asarray(w_np)
                                            if ds.metadata.weight is not None
                                            else None))
            g = np.pad(np.asarray(g, np.float32), (0, pad))
            h = np.pad(np.asarray(h, np.float32), (0, pad))
            return mk(g), mk(h)

    # --- row sampling: bagging (seeded global Bernoulli — every rank
    # draws the identical mask) or GOSS (global top-k over |g*h|) --------
    use_bagging = (cfg.boosting == "gbdt" and cfg.bagging_freq > 0
                   and (cfg.bagging_fraction < 1.0
                        or cfg.pos_bagging_fraction < 1.0
                        or cfg.neg_bagging_fraction < 1.0))
    use_goss = (cfg.boosting == "goss"
                and cfg.top_rate + cfg.other_rate < 1.0)

    if use_bagging:
        from ..models.gbdt import bag_mask_from_uniform

        @jax.jit
        def bag_mask_fn(key, lab, gidx):
            # draw over the UNPADDED global order, gather to padded layout
            u = jnp.take(jax.random.uniform(key, (n_global,)), gidx)
            return bag_mask_from_uniform(cfg, u, lab)
        _bag_state = {}

    if use_goss:
        from ..models.goss import goss_mask_from_importance
        k_top = max(1, int(cfg.top_rate * n_global))

        @jax.jit
        def goss_fn(g, h, base_rw, key, gidx):
            imp = (jnp.abs(g * h) if K == 1
                   else jnp.sum(jnp.abs(g * h), axis=0))
            imp = imp * (base_rw > 0)
            u = jnp.take(jax.random.uniform(key, (n_global,)), gidx)
            mask, amplify = goss_mask_from_importance(cfg, imp, u, k_top)
            return mask * base_rw, amplify

    def sample(it, g, h):
        """(row_weight, g, h) for this iteration after bagging/GOSS."""
        if use_bagging:
            if it % cfg.bagging_freq == 0:
                key = key_for_iteration(cfg.bagging_seed,
                                        it // cfg.bagging_freq)
                _bag_state["mask"] = bag_mask_fn(key, label_g, gidx_g)
            m = _bag_state["mask"]
            rw = rw_g * m
            mm = m if K == 1 else m[None, :]
            return rw, g * mm, h * mm
        if use_goss:
            key = key_for_iteration(cfg.bagging_seed, it)
            rw, amplify = goss_fn(g, h, rw_g, key, gidx_g)
            am = amplify if K == 1 else amplify[None, :]
            return rw, g * am, h * am
        return rw_g, g, h

    # --- local validation shard, binned with the SHARED mappers ---------
    vbins = vlabel = None
    vscore = None
    metrics = []
    check(valid_data is not None or not early_stopping_rounds,
          "early_stopping_rounds requires valid_data")
    if valid_data is not None:
        from ..io.dataset import Dataset as InnerDataset
        vds = InnerDataset.from_data(valid_data[0], cfg,
                                     label=valid_data[1], reference=ds)
        if valid_group is not None:
            vds.metadata.set_field("group", valid_group)
        vbins = jnp.asarray(vds.unbundled_bins())
        vlabel = np.asarray(vds.metadata.label, np.float64)
        vscore = np.tile(np.asarray(inits, np.float64)[:, None],
                         (1, vds.num_data))
        vnan = dd.nan_bins

        from ..ops.predict import predict_leaf_binned
        vpredict = jax.jit(lambda ta, b: predict_leaf_binned(ta, b, vnan))
        metrics = _pooled_metrics(cfg, objective, vds, vlabel, mhu)

    trees = []
    completed = rounds
    ev_state = _EvalState(metrics, rounds)
    for it in range(rounds):
        key = key_for_iteration(cfg.seed, it, salt=1)
        g, h = compute_grads(score, it)
        rw_it, g, h = sample(it, g, h)
        fmask = jnp.asarray(tmp._feature_mask(it))
        score, tree_arrays = step(bins_g, g, h, score, rw_it, fmask, key)
        host = jax.device_get(tree_arrays)
        for k in range(K):
            hk = (host if K == 1
                  else jax.tree.map(lambda a: a[k], host))
            t = Tree.from_arrays(hk, ds, learning_rate=1.0)
            t.shrink(cfg.learning_rate)
            # valid scores start AT the init, so they accumulate the
            # shrunk-but-UNBIASED leaf values (the bias below exists only
            # for the standalone model file)
            vals_unbiased = np.asarray(t.leaf_value, np.float64).copy()
            if it == 0 and inits[k] != 0.0:
                if int(hk.num_leaves) > 1:
                    t.add_bias(inits[k])
                else:
                    t.leaf_value = np.full_like(t.leaf_value, inits[k])
            trees.append(t)
            if vbins is not None and int(hk.num_leaves) > 1:
                ta_local = jax.tree.map(
                    lambda a: jnp.asarray(a) if hasattr(a, "shape") else a,
                    hk)
                leaf = np.asarray(vpredict(ta_local, vbins))
                vscore[k] += vals_unbiased[leaf]
        if vbins is not None:
            ev_state.update(metrics, vscore, it)
            if ev_state.should_stop(early_stopping_rounds):
                Log.info("train_distributed: early stop at iter %d "
                         "(best %.6f @ %d)", it + 1,
                         ev_state.best_metric, ev_state.best_iter_num)
                completed = it + 1
                break
    return _assemble_booster(cfg, ds, objective, trees, inits, K, completed,
                             ev_state, evals_result, early_stopping_rounds,
                             pandas_categorical)


class _EvalState:
    """Per-iteration validation bookkeeping shared by the in-HBM and
    streaming distributed loops (one copy of the first-metric early-stop
    state machine — two drifting copies would silently diverge the paths'
    best_iteration semantics)."""

    def __init__(self, metrics, rounds):
        self.history: dict = {}
        self.first_hib = metrics[0]["higher_better"] if metrics else False
        self.best_metric = -np.inf if self.first_hib else np.inf
        self.best_iter_num = rounds
        self.since_best = 0

    def update(self, metrics, vscore, it):
        first = True
        for m in metrics:
            for name, val in m["eval"](vscore):
                self.history.setdefault(name, []).append(val)
                if first:
                    better = (val > self.best_metric + 1e-12
                              if self.first_hib
                              else val < self.best_metric - 1e-12)
                    if better:
                        self.best_metric = val
                        self.best_iter_num = it + 1
                        self.since_best = 0
                    else:
                        self.since_best += 1
                    first = False

    def should_stop(self, early_stopping_rounds) -> bool:
        return bool(early_stopping_rounds) and \
            self.since_best >= early_stopping_rounds


def _assemble_booster(cfg, ds, objective, trees, inits, K, completed,
                      ev_state, evals_result, early_stopping_rounds,
                      pandas_categorical):
    """Identical Booster on every process (shared by both loops)."""
    from ..basic import Booster
    from ..models import model_io
    from ..models.gbdt import GBDT
    if evals_result is not None and ev_state.history:
        evals_result.setdefault("valid", {}).update(ev_state.history)
    gbdt = GBDT(cfg)
    gbdt.train_data = ds
    gbdt.objective = objective
    gbdt.models = trees
    gbdt.init_scores = list(inits)
    gbdt.num_tree_per_iteration = K
    gbdt.max_feature_idx = ds.num_total_features - 1
    gbdt.iter_ = completed
    bst = Booster(model_str=model_io.save_model_to_string(gbdt))
    bst.pandas_categorical = pandas_categorical
    if ev_state.history and early_stopping_rounds:
        bst.best_iteration = ev_state.best_iter_num  # sklearn hooks
    return bst


def _train_distributed_stream(cfg, ds, plan, objective, K, rounds, inits,
                              label_np, w_np, n_locals, n_global, my_off,
                              valid_data, valid_group,
                              early_stopping_rounds, evals_result, mhu,
                              pandas_categorical):
    """Data-parallel training over per-rank HOST-RESIDENT bin shards.

    Each rank streams its local row blocks through the
    ``stream.StreamTreeGrower``; the per-leaf ``[F, B, 3]`` histogram
    partials accumulated block-wise on each rank are joined by an
    allgather-sum ``cross_reduce`` — the streaming analog of
    ``DataParallelTreeLearner``'s histogram allreduce — after which every
    rank takes the identical split decision and repartitions its local
    leaf vectors.  Bagging/GOSS masks are drawn over the UNPADDED global
    row order with the same iteration keying as the in-HBM trainer, so a
    streamed multi-process run grows the same trees as a single process
    over the concatenated rows (tests/test_stream.py verifies the 2-shard
    virtual-mesh analog on CPU).
    """
    import jax
    import jax.numpy as jnp
    from ..models.gbdt import GBDT
    from ..models.tree import Tree
    from ..ops.predict import predict_leaf_binned
    from ..stream.booster import (predict_leaf_blocks, stream_bag_mask,
                                  stream_goss_sample, stream_gradients)
    from ..stream.grower import StreamTreeGrower, make_shards
    from ..stream.pipeline import PipelineStats

    check(not getattr(objective, "is_ranking", False),
          "distributed streaming does not support ranking objectives")
    check(not cfg.linear_tree and not cfg.interaction_constraints
          and not cfg.forcedsplits_filename,
          "distributed streaming does not support linear_tree/"
          "interaction_constraints/forced splits")

    n_local = ds.num_data
    nprocs = jax.process_count()

    tmp = GBDT(cfg)
    tmp.train_data = ds
    tmp._dd = ds.device_meta()
    gcfg = tmp._make_grower_cfg()
    meta = {k: np.asarray(getattr(tmp._dd, k)) for k in
            ("num_bins", "default_bins", "nan_bins", "is_categorical",
             "monotone")}

    from ..obs import metrics as obs_metrics
    _m_calls = obs_metrics.counter("comm.allgather_calls")
    _m_payload = obs_metrics.counter("comm.payload_bytes")
    _m_wire = obs_metrics.counter("comm.wire_bytes")

    def cross_reduce(arr):
        if nprocs == 1:
            return arr
        a = np.asarray(arr)
        # wire-volume ledger: an allgather of P bytes per rank receives
        # (nprocs - 1) * P remote bytes at this rank (EQuARX-style wire
        # accounting — counts what crossed the interconnect, not the copy
        # of our own shard)
        _m_calls.inc()
        _m_payload.inc(a.nbytes)
        _m_wire.inc(a.nbytes * (nprocs - 1))
        pooled = np.asarray(mhu.process_allgather(a))
        return pooled.reshape((nprocs,) + a.shape).sum(axis=0)

    stats = PipelineStats()
    grower = StreamTreeGrower(
        make_shards([ds.host_bin_matrix(plan)], plan.prefetch, stats),
        meta, gcfg, cross_reduce=cross_reduce)
    Log.info("train_distributed: rank %d streams %d blocks of %d rows "
             "(local bins %.1f MB, budget %s)", jax.process_index(),
             plan.num_blocks, plan.block_rows, plan.total_bytes / 1e6,
             plan.budget_bytes or "stream_rows")

    score = np.tile(np.asarray(inits, np.float32)[:, None], (1, n_local))
    has_weight = ds.metadata.weight is not None

    def local_grads():
        # per-block objective eval from the host scores (shared helper:
        # full [K, n_local] device score/grad residency would sit outside
        # the streaming budget)
        return stream_gradients(objective, score, label_np,
                                w_np if has_weight else None,
                                plan.block_rows)

    # --- global-order row sampling (same keying as the in-HBM trainer) --
    use_bagging = (cfg.boosting == "gbdt" and cfg.bagging_freq > 0
                   and (cfg.bagging_fraction < 1.0
                        or cfg.pos_bagging_fraction < 1.0
                        or cfg.neg_bagging_fraction < 1.0))
    use_goss = (cfg.boosting == "goss"
                and cfg.top_rate + cfg.other_rate < 1.0)
    _bag_state = {}

    def sample(it, g, h):
        if use_bagging:
            if it % cfg.bagging_freq == 0 or "mask" not in _bag_state:
                # this rank's window of the GLOBAL seeded draw (shared
                # keying helper — see stream.booster.stream_bag_mask)
                _bag_state["mask"] = stream_bag_mask(
                    cfg, it, n_global, label_np, my_off, my_off + n_local)
            m = _bag_state["mask"]
            return m, g * m[None, :], h * m[None, :]
        if use_goss:
            imp = np.sum(np.abs(g * h), axis=0)
            # global exact top-k: pool the (small, 4 B/row) importance
            # vector; rank-padded gather keeps the global order, then the
            # shared helper draws the mask over it
            n_max = int(n_locals.max())
            if nprocs > 1:
                padded = np.pad(imp, (0, n_max - n_local))
                _m_calls.inc()
                _m_payload.inc(padded.nbytes)
                _m_wire.inc(padded.nbytes * (nprocs - 1))
                pooled = np.asarray(
                    mhu.process_allgather(padded)).reshape(nprocs, n_max)
            else:
                pooled = imp[None, :]
            imp_g = np.concatenate(
                [pooled[r, :int(n_locals[r])] for r in range(nprocs)])
            m, a = stream_goss_sample(cfg, it, imp_g, my_off,
                                      my_off + n_local)
            return m, g * a[None], h * a[None]
        return np.ones(n_local, np.float32), g, h

    # --- local validation shard, pooled metrics (shared helper) ---------
    vbins = vlabel = None
    vscore = None
    metrics = []
    check(valid_data is not None or not early_stopping_rounds,
          "early_stopping_rounds requires valid_data")
    if valid_data is not None:
        from ..io.dataset import Dataset as InnerDataset
        vds = InnerDataset.from_data(valid_data[0], cfg,
                                     label=valid_data[1], reference=ds)
        if valid_group is not None:
            vds.metadata.set_field("group", valid_group)
        vlabel = np.asarray(vds.metadata.label, np.float64)
        vscore = np.tile(np.asarray(inits, np.float64)[:, None],
                         (1, vds.num_data))
        vnan = tmp._dd.nan_bins
        vjit = jax.jit(lambda ta, b: predict_leaf_binned(ta, b, vnan))
        vplan = vds.stream_plan()
        if vplan is None:
            vbins = jnp.asarray(vds.bins)

            def vpredict(ta):
                return np.asarray(vjit(ta, vbins))
        else:
            # an over-budget validation shard streams block-wise too —
            # putting it whole would break the HBM budget this branch
            # exists to honor (shared helper with StreamGBDT's valid path)
            vmat = vds.host_bin_matrix(vplan)

            def vpredict(ta):
                return predict_leaf_blocks(
                    lambda blk: vjit(ta, jnp.asarray(blk)), vmat)
        vbins_ready = True
        metrics = _pooled_metrics(cfg, objective, vds, vlabel, mhu)
    else:
        vbins_ready = False

    trees = []
    completed = rounds
    ev_state = _EvalState(metrics, rounds)
    for it in range(rounds):
        g, h = local_grads()
        rw, g, h = sample(it, g, h)
        fmask = np.asarray(tmp._feature_mask(it), np.float32)
        for k in range(K):
            ta, assign = grower.grow(
                g[k], h[k], rw, fmask,
                key_for_iteration(cfg.seed, it, salt=k + 1))
            nl = int(ta.num_leaves)
            t = Tree.from_arrays(ta, ds, learning_rate=1.0)
            t.shrink(cfg.learning_rate)
            vals_unbiased = np.asarray(t.leaf_value, np.float64).copy()
            if it == 0 and inits[k] != 0.0:
                if nl > 1:
                    t.add_bias(inits[k])
                else:
                    t.leaf_value = np.full_like(t.leaf_value, inits[k])
            trees.append(t)
            if nl > 1:
                delta = (np.asarray(ta.leaf_value, np.float32)
                         * np.float32(cfg.learning_rate))
                score[k] += delta[assign]
                if vbins_ready:
                    ta_dev = jax.tree.map(jnp.asarray, ta)
                    vscore[k] += vals_unbiased[vpredict(ta_dev)]
        if vbins_ready:
            ev_state.update(metrics, vscore, it)
            if ev_state.should_stop(early_stopping_rounds):
                Log.info("train_distributed(stream): early stop at iter %d "
                         "(best %.6f @ %d)", it + 1, ev_state.best_metric,
                         ev_state.best_iter_num)
                completed = it + 1
                break
    bst = _assemble_booster(cfg, ds, objective, trees, inits, K, completed,
                            ev_state, evals_result, early_stopping_rounds,
                            pandas_categorical)
    bst.stream_stats = stats
    return bst


def _pooled_metrics(cfg, objective, vds, vlabel, mhu):
    """Build the rank-consistent pooled validation metrics.

    Each entry: ``{"name", "higher_better", "eval": vscore -> [(name,
    value), ...]}`` where ``eval`` performs the cross-process pooling:

    - additive metrics (l2/logloss/multi_logloss): (sum, count) pairs;
    - auc: the raw (score, label, weight) triples allgather (valid shards
      are small) and every rank runs the exact tie-corrected AUC;
    - ndcg@k / map@k: queries are rank-local, so the local per-query mean
      pools weighted by the local query count.
    """
    import numpy as np

    names = list(cfg.metric) if cfg.metric else []
    if not names:
        names = [{"regression": "l2", "binary": "binary_logloss",
                  "multiclass": "multi_logloss", "multiclassova":
                  "multi_logloss", "lambdarank": "ndcg",
                  "rank_xendcg": "ndcg"}.get(cfg.objective, "l2")]

    def additive(fn, name):
        def ev(vscore):
            s, c = fn(vscore)
            pooled = np.asarray(mhu.process_allgather(
                np.asarray([s, c], np.float64))).reshape(-1, 2)
            return [(name, float(pooled[:, 0].sum()
                                 / max(pooled[:, 1].sum(), 1.0)))]
        return ev

    out = []
    for name in names:
        base = name.split("@")[0]
        if base in ("l2", "mse", "regression"):
            out.append({"name": "l2", "higher_better": False,
                        "eval": additive(
                            lambda sc: (float(np.sum((sc[0] - vlabel) ** 2)),
                                        len(vlabel)), "l2")})
        elif base in ("binary_logloss", "logloss"):
            def bl(sc):
                p1 = np.clip(np.asarray(objective.convert_output(sc[0]),
                                        np.float64), 1e-15, 1 - 1e-15)
                ll = -(vlabel * np.log(p1) + (1 - vlabel) * np.log(1 - p1))
                return float(ll.sum()), len(vlabel)
            out.append({"name": "binary_logloss", "higher_better": False,
                        "eval": additive(bl, "binary_logloss")})
        elif base in ("multi_logloss", "multiclass"):
            def ml(sc):
                prob = np.clip(np.asarray(objective.convert_output(sc),
                                          np.float64), 1e-15, 1.0)
                ll = -np.log(prob[vlabel.astype(np.int64),
                                  np.arange(len(vlabel))])
                return float(ll.sum()), len(vlabel)
            out.append({"name": "multi_logloss", "higher_better": False,
                        "eval": additive(ml, "multi_logloss")})
        elif base == "auc":
            # labels and shard sizes never change: pool them ONCE; each
            # iteration only allgathers the scores
            from ..metric.base import AUCMetric
            from ..io.dataset import Metadata
            n_here = len(vlabel)
            n_max = int(np.asarray(mhu.process_allgather(
                np.int64(n_here))).max())

            def pads(a):
                return np.pad(np.asarray(a, np.float64),
                              (0, n_max - n_here))
            lab_keep = np.asarray(mhu.process_allgather(np.stack(
                [pads(vlabel), pads(np.ones(n_here))]))).reshape(-1, 2, n_max)
            keep = lab_keep[:, 1].ravel() > 0
            nkeep = int(keep.sum())
            md = Metadata(nkeep)
            md.set_field("label", lab_keep[:, 0].ravel()[keep])
            auc_m = AUCMetric(cfg)
            auc_m.init(md, nkeep)

            def auc_ev(vscore, pads=pads, keep=keep, auc_m=auc_m):
                from ..obs import metrics as obs_metrics
                padded = pads(vscore[0])
                import jax as _jax
                _np = _jax.process_count() - 1
                obs_metrics.counter("comm.allgather_calls").inc()
                obs_metrics.counter("comm.payload_bytes").inc(padded.nbytes)
                obs_metrics.counter("comm.wire_bytes").inc(
                    padded.nbytes * _np)
                pooled = np.asarray(mhu.process_allgather(
                    padded)).reshape(-1)[keep]
                (_, val, _), = auc_m.eval(pooled)
                return [("auc", float(val))]
            out.append({"name": "auc", "higher_better": True,
                        "eval": auc_ev})
        elif base in ("ndcg", "map"):
            from ..metric.rank import MapMetric, NDCGMetric
            cls = NDCGMetric if base == "ndcg" else MapMetric
            m = cls(cfg)
            m.init(vds.metadata, vds.num_data)
            qb = vds.metadata.query_boundaries
            nq_local = len(qb) - 1 if qb is not None else 1

            def rank_ev(vscore, m=m, nq_local=nq_local):
                rows = m.eval(np.asarray(vscore[0], np.float64))
                outv = []
                for mname, val, _ in rows:
                    pooled = np.asarray(mhu.process_allgather(np.asarray(
                        [val * nq_local, nq_local], np.float64)))
                    pooled = pooled.reshape(-1, 2)
                    outv.append((mname, float(pooled[:, 0].sum()
                                              / max(pooled[:, 1].sum(), 1))))
                return outv
            out.append({"name": base, "higher_better": True,
                        "eval": rank_ev})
        else:
            Log.warning("train_distributed: metric '%s' is not pooled "
                        "across processes; skipping", name)
    check(bool(out), "no poolable validation metric")
    return out
