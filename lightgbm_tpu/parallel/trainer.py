"""Multi-process end-to-end training: the Dask-package analog.

Reference analog: ``python-package/lightgbm/dask.py`` — each worker holds a
partition, `LGBM_NetworkInit` wires the ranks, and every rank runs the same
training loop with collective histogram merges, producing identical models.

Here the ranks are ``jax.distributed`` processes: ingest is
``io.distributed.distributed_dataset`` (pooled-sample binning → identical
mappers), the per-iteration step is ``make_dp_train_step``'s shard_map
program whose psum/pmax collectives cross process boundaries over the
global device mesh, and every process assembles the identical model from
the replicated tree output.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..config import Config
from ..io.distributed import distributed_dataset
from ..utils.log import Log, check
from ..utils.random_gen import key_for_iteration
from .data_parallel import make_dp_train_step
from .mesh import DATA_AXIS


def train_distributed(params, data, label, num_boost_round: Optional[int] = None,
                      weight=None, valid_data=None,
                      early_stopping_rounds: Optional[int] = None,
                      evals_result: Optional[dict] = None,
                      feature_name=None, categorical_feature=None):
    """Train over every ``jax.distributed`` process's local partition and
    return a ``Booster`` (identical on every process).

    ``data``/``label``/``weight`` are THIS process's rows; ``valid_data``
    an optional ``(X_local, y_local)`` validation shard.  Requires
    ``parallel.mesh.init_distributed`` to have run.  Single-process calls
    degrade to the ordinary engine.  Supports regression/binary/multiclass
    objectives (globally pooled boost_from_average), sample weights, and
    validation with GLOBALLY POOLED additive metrics (l2 / logloss /
    multi_logloss — per-process sums allgathered, so every rank sees the
    same curve and early stopping is rank-consistent); per-iteration
    row/feature sampling is rejected explicitly.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    cfg = Config.from_params(dict(params or {}))
    rounds = (num_boost_round if num_boost_round is not None
              else cfg.num_iterations)
    if jax.process_count() > 1:
        # v1: the shard_map step runs bins as plain per-feature columns
        cfg.enable_bundle = False

    ds = distributed_dataset(data, cfg, label=label, weight=weight,
                             categorical_feature=categorical_feature,
                             feature_names=feature_name)
    if jax.process_count() == 1:
        from ..basic import Booster, Dataset
        wrapper = Dataset(None, params=dict(params or {}))
        wrapper._inner = ds
        valid_sets = None
        if valid_data is not None:
            vw = Dataset(valid_data[0], label=valid_data[1],
                         reference=wrapper, params=dict(params or {}))
            valid_sets = [vw]
        from ..engine import train as _train
        return _train(dict(params or {}), wrapper, num_boost_round=rounds,
                      valid_sets=valid_sets,
                      early_stopping_rounds=early_stopping_rounds,
                      evals_result=evals_result)

    from jax.experimental import multihost_utils as mhu
    from ..objective import create_objective
    from ..models.gbdt import GBDT
    from ..models.tree import Tree

    objective = create_objective(cfg)
    check(objective is not None,
          "train_distributed requires a built-in objective")
    K = objective.num_model_per_iteration
    # reject configs the fixed-ones row/feature masks would silently ignore
    # (the per-iteration sampling machinery lives in the full GBDT loop)
    check(cfg.bagging_freq == 0 or (cfg.bagging_fraction >= 1.0
                                    and cfg.pos_bagging_fraction >= 1.0
                                    and cfg.neg_bagging_fraction >= 1.0),
          "train_distributed v1 does not support bagging")
    check(cfg.feature_fraction >= 1.0 and cfg.feature_fraction_bynode >= 1.0,
          "train_distributed v1 does not support feature_fraction")
    check(cfg.boosting == "gbdt",
          "train_distributed v1 supports boosting=gbdt only")
    check(not cfg.is_unbalance and cfg.scale_pos_weight == 1.0,
          "train_distributed v1 does not support is_unbalance/"
          "scale_pos_weight (class stats would be per-shard, not global)")

    # --- equal per-process row blocks (pad rows ride weight 0) ----------
    n_local = ds.num_data
    d_local = jax.local_device_count()
    per_proc = int(np.asarray(mhu.process_allgather(np.int64(n_local))).max())
    per_proc = -(-per_proc // d_local) * d_local
    pad = per_proc - n_local
    bins_l = np.pad(np.asarray(ds.bins), ((0, pad), (0, 0)))
    label_np = np.asarray(ds.metadata.label, np.float32)
    label_l = np.pad(label_np, (0, pad))
    rw_l = np.pad(np.ones(n_local, np.float32), (0, pad))
    w_np = (np.asarray(ds.metadata.weight, np.float32)
            if ds.metadata.weight is not None else np.ones(n_local, np.float32))
    w_l = np.pad(w_np, (0, pad))
    N = per_proc * jax.process_count()

    mesh = Mesh(np.array(jax.devices()), (DATA_AXIS,))
    sh = NamedSharding(mesh, P(DATA_AXIS))
    mk = lambda a: jax.make_array_from_process_local_data(  # noqa: E731
        sh, a, (N,) + a.shape[1:])
    bins_g, label_g, rw_g, w_g = mk(bins_l), mk(label_l), mk(rw_l), mk(w_l)

    # --- GLOBAL boost-from-average: only the weighted label sum/count
    # crosses processes (two scalars), then the objective's own formula
    # applies.  A per-process mean would give each rank a different init.
    inits = [0.0] * K
    if cfg.boost_from_average:
        if cfg.objective == "regression":
            sums = np.asarray(mhu.process_allgather(np.asarray(
                [float((w_np * label_np).sum()), float(w_np.sum())])))
            inits = [float(sums[:, 0].sum()) / max(float(sums[:, 1].sum()),
                                                   1e-12)]
        elif cfg.objective in ("binary", "multiclass", "multiclassova"):
            # class-frequency objectives: pool the per-class WEIGHTED
            # counts (a [C] vector), then feed a C-point weighted
            # surrogate through the objective's own initscore formula —
            # exact, because these formulas depend only on class
            # frequencies
            C = max(2, cfg.num_class)
            local = np.bincount(label_np.astype(np.int64), weights=w_np,
                                minlength=C).astype(np.float64)
            pooled = np.asarray(
                mhu.process_allgather(local)).reshape(-1, C).sum(axis=0)
            from ..io.dataset import Metadata
            surrogate = Metadata(C)
            surrogate.set_field("label", np.arange(C, dtype=np.float64))
            surrogate.set_field("weight", np.maximum(pooled, 1e-12))
            obj2 = create_objective(cfg)
            obj2.init(surrogate, C)
            inits = [obj2.boost_from_score(k) for k in range(K)]
        else:
            Log.warning("train_distributed: boost_from_average for "
                        "objective %s is not pooled globally; starting "
                        "from 0", cfg.objective)

    objective.init(ds.metadata, n_local)     # local stats for gradients

    dd = ds.device_data()
    tmp = GBDT(cfg)
    tmp.train_data = ds
    tmp._dd = dd
    gcfg = tmp._make_grower_cfg()._replace(
        num_shards=jax.device_count(), parallel_mode="data")
    meta = dict(num_bins=dd.num_bins, default_bins=dd.default_bins,
                nan_bins=dd.nan_bins, is_categorical=dd.is_categorical,
                monotone=dd.monotone)

    if K == 1:
        def grad_fn(score, lab, w):
            return objective.get_gradients(score, lab, w)
    else:
        def grad_fn(score, lab, w):
            return objective.get_gradients_multi(score, lab, w)

    step = make_dp_train_step(gcfg, meta, grad_fn, cfg.learning_rate, mesh,
                              num_class=K)
    fmask = jnp.ones(ds.num_features, jnp.float32)
    if K == 1:
        score_l = np.full((per_proc,), inits[0], np.float32)
        score = mk(score_l)
    else:
        score_l = np.tile(np.asarray(inits, np.float32)[:, None],
                          (1, per_proc))
        score = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P(None, DATA_AXIS)), score_l, (K, N))

    # --- local validation shard, binned with the SHARED mappers ---------
    vbins = vlabel = None
    vscore = None
    check(valid_data is not None or not early_stopping_rounds,
          "early_stopping_rounds requires valid_data")
    if valid_data is not None:
        check(cfg.objective in ("regression", "binary", "multiclass"),
              "train_distributed pooled valid metrics support "
              "regression/binary/multiclass (softmax) objectives")
        from ..io.dataset import Dataset as InnerDataset
        vds = InnerDataset.from_data(valid_data[0], cfg,
                                     label=valid_data[1], reference=ds)
        vbins = jnp.asarray(vds.unbundled_bins())
        vlabel = np.asarray(vds.metadata.label, np.float64)
        vscore = np.tile(np.asarray(inits, np.float64)[:, None],
                         (1, vds.num_data))
        vnan = dd.nan_bins

        from ..ops.predict import predict_leaf_binned
        vpredict = jax.jit(lambda ta, b: predict_leaf_binned(ta, b, vnan))

    def pooled_metric(sc):
        """Globally pooled additive metric on the valid shard: every
        process contributes (sum, count) — identical value on all ranks."""
        if cfg.objective == "regression":
            local = np.asarray([np.sum((sc[0] - vlabel) ** 2),
                                len(vlabel)], np.float64)
            name = "l2"
        elif cfg.objective == "binary":
            # the objective's OWN transform (sigmoid scaling included) —
            # a hand-rolled formula here drifted from convert_output once
            p1 = np.clip(np.asarray(objective.convert_output(sc[0]),
                                    np.float64), 1e-15, 1 - 1e-15)
            ll = -(vlabel * np.log(p1) + (1 - vlabel) * np.log(1 - p1))
            local = np.asarray([ll.sum(), len(vlabel)], np.float64)
            name = "binary_logloss"
        else:                                   # multiclass softmax
            prob = np.clip(np.asarray(objective.convert_output(sc),
                                      np.float64), 1e-15, 1.0)
            ll = -np.log(prob[vlabel.astype(np.int64),
                              np.arange(len(vlabel))])
            local = np.asarray([ll.sum(), len(vlabel)], np.float64)
            name = "multi_logloss"
        pooled = np.asarray(mhu.process_allgather(local)).reshape(-1, 2)
        return name, float(pooled[:, 0].sum() / max(pooled[:, 1].sum(), 1.0))

    trees = []
    history: list = []
    metric_name = None
    completed = rounds
    best_metric, best_iter_num, since_best = np.inf, rounds, 0
    for it in range(rounds):
        key = key_for_iteration(cfg.seed, it, salt=1)
        score, tree_arrays = step(bins_g, label_g, score, rw_g, fmask, key,
                                  weight=w_g)
        host = jax.device_get(tree_arrays)
        for k in range(K):
            hk = (host if K == 1
                  else jax.tree.map(lambda a: a[k], host))
            t = Tree.from_arrays(hk, ds, learning_rate=1.0)
            t.shrink(cfg.learning_rate)
            # valid scores start AT the init, so they accumulate the
            # shrunk-but-UNBIASED leaf values (the bias below exists only
            # for the standalone model file)
            vals_unbiased = np.asarray(t.leaf_value, np.float64).copy()
            if it == 0 and inits[k] != 0.0:
                if int(hk.num_leaves) > 1:
                    t.add_bias(inits[k])
                else:
                    t.leaf_value = np.full_like(t.leaf_value, inits[k])
            trees.append(t)
            if vbins is not None and int(hk.num_leaves) > 1:
                ta_local = jax.tree.map(
                    lambda a: jnp.asarray(a) if hasattr(a, "shape") else a,
                    hk)
                leaf = np.asarray(vpredict(ta_local, vbins))
                vscore[k] += vals_unbiased[leaf]
        if vbins is not None:
            metric_name, mval = pooled_metric(vscore)
            history.append(mval)
            if mval < best_metric - 1e-12:
                best_metric, best_iter_num, since_best = mval, it + 1, 0
            else:
                since_best += 1
            if (early_stopping_rounds
                    and since_best >= early_stopping_rounds):
                Log.info("train_distributed: early stop at iter %d "
                         "(best %s=%.6f @ %d)", it + 1, metric_name,
                         best_metric, best_iter_num)
                completed = it + 1
                break
    if evals_result is not None and history:
        evals_result.setdefault("valid", {})[metric_name] = history

    # --- identical Booster on every process -----------------------------
    gbdt = GBDT(cfg)
    gbdt.train_data = ds
    gbdt.objective = objective
    gbdt.models = trees
    gbdt.init_scores = list(inits)
    gbdt.num_tree_per_iteration = K
    gbdt.max_feature_idx = ds.num_total_features - 1
    gbdt.iter_ = completed
    from ..models import model_io
    from ..basic import Booster
    bst = Booster(model_str=model_io.save_model_to_string(gbdt))
    if history and early_stopping_rounds:
        bst.best_iteration = best_iter_num     # sklearn/num_iteration hooks
    return bst
