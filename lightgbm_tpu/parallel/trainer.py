"""Multi-process end-to-end training: the Dask-package analog.

Reference analog: ``python-package/lightgbm/dask.py`` — each worker holds a
partition, `LGBM_NetworkInit` wires the ranks, and every rank runs the same
training loop with collective histogram merges, producing identical models.

Here the ranks are ``jax.distributed`` processes: ingest is
``io.distributed.distributed_dataset`` (pooled-sample binning → identical
mappers), the per-iteration step is ``make_dp_train_step``'s shard_map
program whose psum/pmax collectives cross process boundaries over the
global device mesh, and every process assembles the identical model from
the replicated tree output.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..config import Config
from ..io.distributed import distributed_dataset
from ..utils.log import Log, check
from ..utils.random_gen import key_for_iteration
from .data_parallel import make_dp_train_step
from .mesh import DATA_AXIS


def train_distributed(params, data, label, num_boost_round: Optional[int] = None,
                      feature_name=None, categorical_feature=None):
    """Train over every ``jax.distributed`` process's local partition and
    return a ``Booster`` (identical on every process).

    ``data``/``label`` are THIS process's rows.  Requires
    ``parallel.mesh.init_distributed`` to have run.  Single-process calls
    degrade to the ordinary engine.  v1 scope: one model per iteration
    objectives with mean-based boost_from_average (regression l2, binary);
    sample weights and valid sets are not yet wired through the
    multi-process loop.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    cfg = Config.from_params(dict(params or {}))
    rounds = (num_boost_round if num_boost_round is not None
              else cfg.num_iterations)
    if jax.process_count() > 1:
        # v1: the shard_map step runs bins as plain per-feature columns
        cfg.enable_bundle = False

    ds = distributed_dataset(data, cfg, label=label,
                             categorical_feature=categorical_feature,
                             feature_names=feature_name)
    if jax.process_count() == 1:
        from ..basic import Booster, Dataset
        wrapper = Dataset(None, params=dict(params or {}))
        wrapper._inner = ds
        from ..engine import train as _train
        return _train(dict(params or {}), wrapper, num_boost_round=rounds)

    from jax.experimental import multihost_utils as mhu
    from ..objective import create_objective
    from ..models.gbdt import GBDT
    from ..models.tree import Tree

    check(cfg.num_class <= 1 or cfg.objective in ("regression", "binary"),
          "train_distributed v1 supports single-model-per-iteration "
          "objectives")
    objective = create_objective(cfg)
    check(objective is not None and objective.num_model_per_iteration == 1,
          "train_distributed v1 supports one tree per iteration")
    # reject configs the fixed-ones row/feature masks would silently ignore
    # (the per-iteration sampling machinery lives in the full GBDT loop)
    check(cfg.bagging_freq == 0 or (cfg.bagging_fraction >= 1.0
                                    and cfg.pos_bagging_fraction >= 1.0
                                    and cfg.neg_bagging_fraction >= 1.0),
          "train_distributed v1 does not support bagging")
    check(cfg.feature_fraction >= 1.0 and cfg.feature_fraction_bynode >= 1.0,
          "train_distributed v1 does not support feature_fraction")
    check(cfg.boosting == "gbdt",
          "train_distributed v1 supports boosting=gbdt only")
    check(not cfg.is_unbalance and cfg.scale_pos_weight == 1.0,
          "train_distributed v1 does not support is_unbalance/"
          "scale_pos_weight (class stats would be per-shard, not global)")

    # --- equal per-process row blocks (pad rows ride weight 0) ----------
    n_local = ds.num_data
    d_local = jax.local_device_count()
    per_proc = int(np.asarray(mhu.process_allgather(np.int64(n_local))).max())
    per_proc = -(-per_proc // d_local) * d_local
    pad = per_proc - n_local
    bins_l = np.pad(np.asarray(ds.bins), ((0, pad), (0, 0)))
    label_np = np.asarray(ds.metadata.label, np.float32)
    label_l = np.pad(label_np, (0, pad))
    rw_l = np.pad(np.ones(n_local, np.float32), (0, pad))
    N = per_proc * jax.process_count()

    mesh = Mesh(np.array(jax.devices()), (DATA_AXIS,))
    sh = NamedSharding(mesh, P(DATA_AXIS))
    mk = lambda a: jax.make_array_from_process_local_data(  # noqa: E731
        sh, a, (N,) + a.shape[1:])
    bins_g, label_g, rw_g = mk(bins_l), mk(label_l), mk(rw_l)

    # --- GLOBAL boost-from-average: only the weighted label sum/count
    # crosses processes (two scalars), then the objective's own formula
    # applies.  A per-process mean would give each rank a different init.
    init = 0.0
    if cfg.boost_from_average:
        sums = np.asarray(mhu.process_allgather(
            np.asarray([float(label_np.sum()), float(n_local)])))
        wl, w = float(sums[:, 0].sum()), float(sums[:, 1].sum())
        if cfg.objective == "regression":
            init = wl / max(w, 1.0)          # pooled mean (RegressionL2)
        elif cfg.objective == "binary":
            # binary labels are 0/1, so a two-point weighted surrogate
            # reproduces the pooled pavg exactly and reuses the
            # objective's own initscore formula (sigmoid scaling etc.)
            from ..io.dataset import Metadata
            surrogate = Metadata(2)
            surrogate.set_field("label", np.asarray([0.0, 1.0]))
            surrogate.set_field("weight",
                                np.asarray([max(w - wl, 1e-12),
                                            max(wl, 1e-12)]))
            obj2 = create_objective(cfg)
            obj2.init(surrogate, 2)
            init = obj2.boost_from_score(0)
        else:
            Log.warning("train_distributed: boost_from_average for "
                        "objective %s is not pooled globally; starting "
                        "from 0", cfg.objective)

    objective.init(ds.metadata, n_local)     # local stats for gradients

    dd = ds.device_data()
    tmp = GBDT(cfg)
    tmp.train_data = ds
    tmp._dd = dd
    gcfg = tmp._make_grower_cfg()._replace(
        num_shards=jax.device_count(), parallel_mode="data")
    meta = dict(num_bins=dd.num_bins, default_bins=dd.default_bins,
                nan_bins=dd.nan_bins, is_categorical=dd.is_categorical,
                monotone=dd.monotone)

    def grad_fn(score, lab):
        return objective.get_gradients(score, lab, None)

    step = make_dp_train_step(gcfg, meta, grad_fn, cfg.learning_rate, mesh)
    fmask = jnp.ones(ds.num_features, jnp.float32)
    score = jax.make_array_from_process_local_data(
        sh, np.full((per_proc,), init, np.float32), (N,))

    trees = []
    for it in range(rounds):
        key = key_for_iteration(cfg.seed, it, salt=1)
        score, tree_arrays = step(bins_g, label_g, score, rw_g, fmask, key)
        host = jax.device_get(tree_arrays)
        t = Tree.from_arrays(host, ds, learning_rate=1.0)
        t.shrink(cfg.learning_rate)
        if it == 0 and init != 0.0:
            if int(host.num_leaves) > 1:
                t.add_bias(init)
            else:
                t.leaf_value = np.full_like(t.leaf_value, init)
        trees.append(t)

    # --- identical Booster on every process -----------------------------
    gbdt = GBDT(cfg)
    gbdt.train_data = ds
    gbdt.objective = objective
    gbdt.models = trees
    gbdt.init_scores = [init]
    gbdt.num_tree_per_iteration = 1
    gbdt.max_feature_idx = ds.num_total_features - 1
    gbdt.iter_ = rounds
    from ..models import model_io
    from ..basic import Booster
    return Booster(model_str=model_io.save_model_to_string(gbdt))
