"""One-hot build variants for the histogram MXU kernels — the single registry.

The histogram build is a one-hot matmul on the MXU (ops/histogram.py), and
the one-hot construction is the kernel's bound: the production build is an
iota-compare-select over ``f*Bp*BR`` elements per block on the VPU, ~6 MXU
MACs of useful work per VPU-built element, which caps the kernel at ~12% MFU
(docs/PERF.md "ceiling attack").  Each registry entry changes how the
one-hot tile is built — or what rides the dot — so the production kernels,
the timing shootout (scripts/bench_onehot_variants.py) and the perf suite
(scripts/tpu_perf_suite.py) all draw from ONE set of kernel bodies that
cannot drift apart.  This registry plus ``pick_variant`` replaces the
reference's col-wise/row-wise histogram auto-tuner (``train_share_states.h``)
with a TPU-native equivalent: the candidate axes are one-hot build
strategies, and the timed election runs once on device at first fit.

Variant families (``VARIANTS``):

  base      int32 iota compare -> bf16 select (the production shape)
  bf16cmp   bf16 iota + bf16 bins compare (2-byte compare lanes)
  i16cmp    int16 iota + int16 bins compare
  u8cmp     uint8 iota + raw u8 bins compare (1-byte compare lanes)
  sub1abs   onehot = max(0, 1 - |b - j|) in bf16 (no select, all-arith)
  staged    hierarchical hi/lo one-hot: outer product of a ``Bp/16``-wide
            hi-digit one-hot and a 16-wide lo-digit one-hot — ~Bp/16 + 16
            VPU compares per element instead of Bp, one multiply to combine
  packed    multi-feature lane packing (``128 % B == 0``, ``B <= 64``):
            k = 128//B features share one 128-lane group via the
            ``bin + f_local*B`` lane offset, cutting both the VPU one-hot
            element count and the MXU N-dim by k (at ``max_bin=64`` the
            unpacked kernel wastes 2x lanes on Bp=128 padding outright)
  int8      int8-MXU with f32 fixup: the one-hot is exact in int8 and the
            (g,h,m) rows are per-block three-level quantized (primary +
            two residual int8 fixups, per-row f32 scales) with int32
            accumulation — rides the int8 MXU rate at the same parity bar
            as the production bf16 (hi, lo) pair

Every variant is interchangeable at the ``build_histogram`` call site and
parity-checks against the exact scatter-add in Pallas interpret mode on CPU
(tests/test_onehot_variants.py), so no variant can land or drift without
tier-1 coverage; hardware pricing comes from the shootout under the watcher.

jax is imported inside the kernel-body/prep functions (the idiom the
shootout always used): registry METADATA — names, geometry, the VPU-work
model — is plain-int machinery, and nothing heavier loads until a kernel
is actually built.  (Importing THIS MODULE still runs the package
``__init__``, which imports jax — callers that must stay jax-free, like
the watcher's supervisor, load ``bench``/``supervise`` package-init-free
instead and never touch the registry.)
"""
from __future__ import annotations

from typing import Callable, NamedTuple


def padded_bins(max_bin: int) -> int:
    """Lane-tile-aligned bin width Bp (128-multiple)."""
    return -(-max_bin // 128) * 128


def pack_k(max_bin: int) -> int:
    """Features per 128-lane group for the lane-packing variant, or 0 when
    packing does not apply.  Packing slots are exactly ``max_bin`` lanes wide
    (the ``bin + f_local*B`` offset), so groups must tile 128 lanes with no
    remainder — otherwise the per-group pad would need an in-kernel lane
    concatenate, which Mosaic relayouts.  Supported widths are the divisors
    of 128 up to 64 (2/4/8/16/32/64); other kernel widths are reachable
    (gbdt rounds the kernel width to a 4-multiple, e.g. 60) and an explicit
    ``hist_variant=packed`` there falls back to 'base' with a warning via
    ``resolve``."""
    if max_bin <= 0 or max_bin > 64 or 128 % max_bin:
        return 0
    return 128 // max_bin


class VariantSpec(NamedTuple):
    """One one-hot build strategy, pluggable into every histogram kernel.

    The kernel shells (grid/BlockSpec plumbing in ops/histogram.py and the
    shootout's single-block bench kernel) stay generic; everything
    variant-specific lives here:

      prep(grad, hess, mask) -> [R, N] rows for the dot's LHS (R and dtype
          set the MXU rate: 6 bf16 rows for the split-precision pair, 3 f32
          rows for int8 — quantized per block inside the kernel).
      group_lanes/group_feats: output-lane geometry.  ``group_feats``
          features share one ``group_lanes``-wide lane group (1/Bp for the
          unpacked variants, k/128 for lane packing); feature-block sizes
          must be ``group_feats``-multiples.
      contrib(b, gh, fc, B, Bp, BR) -> [6, fc//group_feats*group_lanes] f32
          in-kernel per-block contribution (one-hot build + dot), to be
          accumulated by the shell (plain ``+=`` or the batched-leaf
          kernel's slot-select).  Rows are the (hi, lo) triple pairs that
          ``finish_hist`` sums.
      supports(B): static eligibility for a kernel bin width.
      vpu_compares(f, B, BR): per-row-block VPU compare count — the work
          model behind the predicted MFU bounds in docs/PERF.md.
    """
    name: str
    description: str
    prep: Callable
    group_lanes: Callable      # (B, Bp) -> int
    group_feats: Callable      # (B, Bp) -> int
    contrib: Callable          # kernel-side
    supports: Callable         # (B) -> bool
    vpu_compares: Callable     # (f, B, BR) -> int


# --------------------------------------------------------------------------
# shared pieces
# --------------------------------------------------------------------------

def _prep_bf16_pair(grad, hess, mask):
    """The production LHS: (g·m, h·m, m) split into a fenced bf16 (hi, lo)
    pair — see histogram._split_bf16_pair for why the fence is load-bearing."""
    from .histogram import _gh6
    return _gh6(grad, hess, mask)


def _prep_f32(grad, hess, mask):
    """Raw f32 channel rows; the int8 variant quantizes them per block
    INSIDE the kernel (scales are per row-block, so they cannot be baked
    outside the grid loop)."""
    import jax.numpy as jnp
    return jnp.stack([grad * mask, hess * mask, mask],
                     axis=0).astype(jnp.float32)


def _dot6(gh, onehot):
    """[R, BR] x [lanes, BR]^T -> [R, lanes] f32 (rows on M: <=8 sublanes
    ride free; lanes on N)."""
    import jax
    import jax.numpy as jnp
    return jax.lax.dot_general(
        gh, onehot,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)


def feat_geometry(spec: "VariantSpec", f: int, B: int, Bp: int):
    """(f_pad, lanes): the feature count padded to a lane-group multiple
    and the resulting output lane count (= MXU N-dim).  THE forward lane
    mapping — every kernel shell sizes its blocks through this one
    function, and ``finish_hist`` is its inverse.  Pure int math."""
    gf = spec.group_feats(B, Bp)
    f_pad = -(-f // gf) * gf
    return f_pad, (f_pad // gf) * spec.group_lanes(B, Bp)


def total_lanes(name: str, f: int, max_bin: int) -> int:
    """Output lane count (= MXU N-dim) a variant needs for ``f`` features —
    the structural size the lane-packing variant shrinks."""
    spec = VARIANTS[name]
    return feat_geometry(spec, f, max_bin, padded_bins(max_bin))[1]


#: VPU:MXU throughput ratio at the bf16 rate (8x128 VPU lanes vs the
#: 128x128 MXU) — the normalization of the docs/PERF.md VPU-work model
VPU_MXU_RATIO = 42.0


def predicted_mfu(name: str, f: int, max_bin: int) -> float:
    """Analytical MFU bound from the VPU-work model (docs/PERF.md
    "ceiling attack"): per row the kernel does ``6 * lanes`` useful MXU
    MACs against ``vpu_compares`` one-hot VPU ops at a ~1:42 throughput
    disadvantage, so the bound is ``MACs / (MACs + 42 * compares)`` —
    fewer compares per useful MAC raises the roof.  The perf suite and
    shootout report this next to the achieved MFU so the next window
    prices each variant's headroom automatically."""
    macs = 6.0 * total_lanes(name, f, max_bin)
    compares = float(VARIANTS[name].vpu_compares(f, max_bin, 1))
    return macs / (macs + VPU_MXU_RATIO * compares)


# --------------------------------------------------------------------------
# contrib implementations (kernel-side bodies)
# --------------------------------------------------------------------------

def _contrib_base(b, gh, *, fc, B, Bp, BR):
    import jax
    import jax.numpy as jnp
    bi = b.astype(jnp.int32)
    bin_id = jax.lax.broadcasted_iota(jnp.int32, (fc, Bp, BR), 1)
    onehot = (bi[:, None, :] == bin_id).astype(jnp.bfloat16)
    return _dot6(gh, onehot.reshape(fc * Bp, BR))


def _contrib_bf16cmp(b, gh, *, fc, B, Bp, BR):
    import jax
    import jax.numpy as jnp
    bb = b.astype(jnp.bfloat16)                  # bins < 256: exact in bf16
    bin_id = jax.lax.broadcasted_iota(jnp.bfloat16, (fc, Bp, BR), 1)
    onehot = (bb[:, None, :] == bin_id).astype(jnp.bfloat16)
    return _dot6(gh, onehot.reshape(fc * Bp, BR))


def _contrib_i16cmp(b, gh, *, fc, B, Bp, BR):
    import jax
    import jax.numpy as jnp
    bi = b.astype(jnp.int16)
    bin_id = jax.lax.broadcasted_iota(jnp.int16, (fc, Bp, BR), 1)
    onehot = (bi[:, None, :] == bin_id).astype(jnp.bfloat16)
    return _dot6(gh, onehot.reshape(fc * Bp, BR))


def _contrib_u8cmp(b, gh, *, fc, B, Bp, BR):
    # 1-byte compare domain (u8 lanes pack 4x vs i32; Bp=256 spans u8 exactly)
    import jax
    import jax.numpy as jnp
    bin_id = jax.lax.broadcasted_iota(jnp.uint8, (fc, Bp, BR), 1)
    onehot = (b.astype(jnp.uint8)[:, None, :] == bin_id).astype(jnp.bfloat16)
    return _dot6(gh, onehot.reshape(fc * Bp, BR))


def _contrib_sub1abs(b, gh, *, fc, B, Bp, BR):
    import jax
    import jax.numpy as jnp
    bb = b.astype(jnp.bfloat16)
    bin_id = jax.lax.broadcasted_iota(jnp.bfloat16, (fc, Bp, BR), 1)
    d = bb[:, None, :] - bin_id
    onehot = jnp.maximum(jnp.bfloat16(1.0) - jnp.abs(d), jnp.bfloat16(0.0))
    return _dot6(gh, onehot.reshape(fc * Bp, BR))


_STAGED_LO = 16           # lo-digit width (Bp is a 128-multiple, so 16 | Bp)


def _contrib_staged(b, gh, *, fc, B, Bp, BR):
    # hierarchical one-hot: bin = hi*16 + lo, so
    #   onehot[f, hi*16+lo, r] = onehot_hi[f, hi, r] * onehot_lo[f, lo, r]
    # — (Bp/16 + 16) VPU compares per element instead of Bp, one bf16
    # multiply to combine (the outer product over disjoint digit supports
    # reproduces the one-hot EXACTLY: both factors are 0/1, exact in bf16).
    # Out-of-range bins (B <= bin < 256-domain garbage) get hi >= Bp/16 and
    # match nothing, same drop-by-compare semantics as base.
    import jax
    import jax.numpy as jnp
    W = _STAGED_LO
    H = Bp // W
    bi = b.astype(jnp.int32)
    hi = bi >> (W.bit_length() - 1)        # bin // W (W is a power of two)
    lo = bi & (W - 1)
    hi_id = jax.lax.broadcasted_iota(jnp.int32, (fc, H, BR), 1)
    lo_id = jax.lax.broadcasted_iota(jnp.int32, (fc, W, BR), 1)
    oh_hi = (hi[:, None, :] == hi_id).astype(jnp.bfloat16)      # [fc, H, BR]
    oh_lo = (lo[:, None, :] == lo_id).astype(jnp.bfloat16)      # [fc, W, BR]
    onehot = (oh_hi[:, :, None, :] * oh_lo[:, None, :, :])      # [fc,H,W,BR]
    return _dot6(gh, onehot.reshape(fc * Bp, BR))


def _contrib_packed(b, gh, *, fc, B, Bp, BR):
    # k = 128//B features share one 128-lane group: feature j of a group
    # owns lanes [j*B, (j+1)*B).  Rows land on k DISJOINT lanes per group
    # (one per feature), so the "one-hot" is a k-hot whose dot still yields
    # per-(feature, bin) sums — and it is built with fc*B*BR compares
    # instead of fc*Bp*BR: only each feature's OWN B lanes are compared,
    # a k-fold VPU cut on top of the k-fold MXU N-dim cut.
    import jax
    import jax.numpy as jnp
    k = 128 // B
    ng = fc // k                       # shell guarantees fc % k == 0
    bi = b.astype(jnp.int32).reshape(ng, k, BR)
    bin_id = jax.lax.broadcasted_iota(jnp.int32, (ng, k, B, BR), 2)
    khot = (bi[:, :, None, :] == bin_id).astype(jnp.bfloat16)   # [ng,k,B,BR]
    return _dot6(gh, khot.reshape(ng * 128, BR))


def _contrib_int8(b, gh, *, fc, B, Bp, BR):
    # int8 MXU with f32 fixup: the one-hot is exactly representable in int8;
    # the f32 (g,h,m) rows are per-block THREE-level quantized — primary
    # q1 = round(x/s1) plus two residual fixups q2, q3, each capturing the
    # previous level's rounding with its own per-row f32 scale — and all
    # nine rows ride ONE int8 dot with int32 accumulation (M = 9 is still
    # under the MXU sublane granularity, so the extra residual rows are
    # free).  Two levels alone leave ~1.5e-5·max|x| per element — 4x the
    # bf16 (hi, lo) pair's floor, which measured right AT HIST_PARITY_TOL
    # on dense 64-bin histograms; the third level drops the floor to
    # ~6e-8·max|x|, comfortably inside the shared parity bar.
    import jax
    import jax.numpy as jnp
    bi = b.astype(jnp.int32)
    bin_id = jax.lax.broadcasted_iota(jnp.int32, (fc, Bp, BR), 1)
    onehot = (bi[:, None, :] == bin_id).astype(jnp.int8).reshape(fc * Bp, BR)

    def level(x):
        s = jnp.maximum(jnp.max(jnp.abs(x), axis=1, keepdims=True) / 127.0,
                        jnp.float32(1e-30))
        q = jnp.round(x / s)
        return s, q, x - q * s

    s1, q1, r1 = level(gh)                                     # [3, BR] f32
    s2, q2, r2 = level(r1)
    s3, q3, _ = level(r2)
    q = jnp.concatenate([q1, q2, q3], axis=0).astype(jnp.int8)  # [9, BR]
    acc = jax.lax.dot_general(
        q, onehot,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32).astype(jnp.float32)  # [9, lanes]
    # fold to the (hi, lo) triple-pair layout finish_hist expects: the two
    # residual levels sum into the lo triple
    hi = acc[:3] * s1
    lo = acc[3:6] * s2 + acc[6:9] * s3
    return jnp.concatenate([hi, lo], axis=0)                   # [6, lanes]


# --------------------------------------------------------------------------
# the registry
# --------------------------------------------------------------------------

def _geom_plain(B, Bp):
    return Bp


def _one(B, Bp):
    return 1


VARIANTS = {
    "base": VariantSpec(
        "base", "int32 iota compare -> bf16 select (production shape)",
        _prep_bf16_pair, _geom_plain, _one, _contrib_base,
        lambda B: True,
        lambda f, B, BR: f * padded_bins(B) * BR),
    "bf16cmp": VariantSpec(
        "bf16cmp", "bf16 iota + bf16 bins compare (2-byte lanes)",
        _prep_bf16_pair, _geom_plain, _one, _contrib_bf16cmp,
        lambda B: B <= 256,            # integers exact in bf16 up to 256
        lambda f, B, BR: f * padded_bins(B) * BR),
    "i16cmp": VariantSpec(
        "i16cmp", "int16 iota + int16 bins compare",
        _prep_bf16_pair, _geom_plain, _one, _contrib_i16cmp,
        lambda B: B <= 32768,          # int16 iota domain
        lambda f, B, BR: f * padded_bins(B) * BR),
    "u8cmp": VariantSpec(
        "u8cmp", "uint8 iota + raw u8 bins compare (1-byte lanes)",
        _prep_bf16_pair, _geom_plain, _one, _contrib_u8cmp,
        lambda B: B <= 256,            # u8 compare domain
        lambda f, B, BR: f * padded_bins(B) * BR),
    "sub1abs": VariantSpec(
        "sub1abs", "onehot = max(0, 1 - |b - j|) in bf16 (all-arith)",
        _prep_bf16_pair, _geom_plain, _one, _contrib_sub1abs,
        lambda B: B <= 256,
        lambda f, B, BR: f * padded_bins(B) * BR),
    "staged": VariantSpec(
        "staged", "hi/lo-digit outer-product one-hot (~Bp/16+16 compares/elt)",
        _prep_bf16_pair, _geom_plain, _one, _contrib_staged,
        lambda B: True,
        lambda f, B, BR: f * (padded_bins(B) // _STAGED_LO + _STAGED_LO) * BR),
    "packed": VariantSpec(
        "packed", "k=128//B features per 128-lane group (B <= 64, B | 128)",
        _prep_bf16_pair,
        lambda B, Bp: 128,
        lambda B, Bp: 128 // B,
        _contrib_packed,
        lambda B: pack_k(B) >= 2,
        lambda f, B, BR: f * B * BR),
    "int8": VariantSpec(
        "int8", "int8-MXU one-hot, per-block quantized gh + residual fixups",
        _prep_f32, _geom_plain, _one, _contrib_int8,
        lambda B: True,
        lambda f, B, BR: f * padded_bins(B) * BR),
}

VARIANT_NAMES = tuple(VARIANTS)

# candidates the first-fit auto-tuner times (pick_variant): one entrant per
# family that can plausibly win on hardware — the pure-compare-dtype
# variants share base's work model, so only the cheapest (u8cmp) runs
AUTO_CANDIDATES = ("base", "u8cmp", "staged", "packed", "int8")


def resolve(name: str, max_bin: int):
    """Validate ``name`` against the registry and the kernel bin width;
    returns a supported variant name (falling back to 'base' with a warning
    when the requested family cannot serve this width)."""
    if name not in VARIANTS:
        raise ValueError(f"unknown hist_variant {name!r}; "
                         f"known: {', '.join(VARIANT_NAMES)}")
    if not VARIANTS[name].supports(max_bin):
        from ..utils.log import Log
        Log.warning("hist_variant=%s does not support max_bin=%d; "
                    "using 'base'", name, max_bin)
        return "base"
    return name


def finish_hist(out, f, B, Bp, spec: VariantSpec):
    """[..., 6, n_lanes] kernel output -> [..., f, B, 3] histograms: sum the
    (hi, lo) triples and undo the lane layout (plain Bp-wide slots, or the
    packed ``group*128 + f_local*B + bin`` layout).  Shared by every kernel
    shell so the lane mapping exists exactly once."""
    gl = spec.group_lanes(B, Bp)
    gf = spec.group_feats(B, Bp)
    lead = out.shape[:-2]
    ng = out.shape[-1] // gl
    o = out.reshape(lead + (2, 3, ng, gl))
    hist = o[..., 0, :, :, :] + o[..., 1, :, :, :]       # [..., 3, ng, gl]
    hist = hist[..., :gf * B].reshape(lead + (3, ng * gf, B))
    hist = hist[..., :f, :]
    # [..., 3, f, B] -> [..., f, B, 3]
    import jax.numpy as jnp
    return jnp.moveaxis(hist, -3, -1)


# --------------------------------------------------------------------------
# single-feature-block bench kernel (the shootout's shell)
# --------------------------------------------------------------------------

def make_bench_kernel(variant: str, f: int, max_bin: int, BR: int, *,
                      interpret: bool = False):
    """(prep, run) for the timing shootout: ``rows = jit(prep)(g, h, m)``
    once outside the timed loop, then ``run(bins_t [f, N] u8, rows)`` is the
    timed kernel — feature-major single-block, bins pre-transposed OUTSIDE
    (the production layout; the in-kernel transpose benched 35x slower).
    Returns finished ``[f, B, 3]`` histograms so parity checks read off the
    same surface the production kernels expose."""
    import jax
    from jax.experimental import pallas as pl

    spec = VARIANTS[variant]
    B = max_bin
    Bp = padded_bins(B)
    fc, lanes = feat_geometry(spec, f, B, Bp)

    def kernel(bins_ref, gh_ref, out_ref):
        import jax.numpy as jnp

        @pl.when(pl.program_id(0) == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        out_ref[:] += spec.contrib(bins_ref[:], gh_ref[:],
                                   fc=fc, B=B, Bp=Bp, BR=BR)

    def run(bins_t, rows):
        import jax.numpy as jnp
        n = bins_t.shape[1]
        assert n % BR == 0
        if fc > f:
            bins_t = jnp.pad(bins_t, ((0, fc - f), (0, 0)))
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((6, lanes), jnp.float32),
            grid=(n // BR,),
            in_specs=[pl.BlockSpec((fc, BR), lambda i: (0, i)),
                      pl.BlockSpec((rows.shape[0], BR), lambda i: (0, i))],
            out_specs=pl.BlockSpec((6, lanes), lambda i: (0, 0)),
            interpret=interpret,
        )(bins_t, rows)
        return finish_hist(out, f, B, Bp, spec)

    return spec.prep, run


# --------------------------------------------------------------------------
# first-fit auto-tuner (the reference train_share_states analog)
# --------------------------------------------------------------------------

_AUTO_CACHE: dict = {}


def _auto_bench_data(max_bin: int, f: int, rows: int = 262144):
    """Synthetic (bins, g, h, m) for the election micro-bench.  The width
    is capped: the RANKING is what matters, and a Criteo-wide first fit
    must not spend its budget timing a 13k-column micro-bench."""
    import jax.numpy as jnp
    import numpy as np
    f = max(8, min(f, 128))
    rng = np.random.default_rng(0)
    bins = jnp.asarray(rng.integers(0, max_bin, size=(rows, f),
                                    dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=rows).astype(np.float32))
    h = jnp.asarray(np.full(rows, 0.25, np.float32))
    m = jnp.ones(rows, jnp.float32)
    return bins, g, h, m


def _time_auto_candidate(variant, bins, g, h, m, max_bin, ref,
                         iters: int = 5):
    """(seconds-per-pass, relerr-vs-ref) for one candidate ON DEVICE.

    The parity number is load-bearing, not diagnostic: a Mosaic miscompile
    is frequently FASTER than the correct lowering (this kernel family
    miscompiled data-dependently on real v5e twice in round 4, caught only
    by hardware parity gates), so an election by speed alone would crown
    exactly the broken candidate.  _run_auto_bench disqualifies on relerr
    before looking at the clock."""
    import time

    import jax
    import jax.numpy as jnp
    from .histogram import _hist_pallas

    jfn = jax.jit(lambda b_, g_: _hist_pallas(
        b_, g_, h, m, max_bin, variant=variant))
    out = jfn(bins, g).block_until_ready()         # compile + warm
    err = float(jnp.max(jnp.abs(out - ref) / (jnp.abs(ref) + 1.0)))
    t0 = time.perf_counter()
    for _ in range(iters):
        r = jfn(bins, g + 1e-12)
    r.block_until_ready()
    return (time.perf_counter() - t0) / iters, err


def pick_variant(max_bin: int, num_features: int, *,
                 backend: "str | None" = None) -> str:
    """``hist_variant=auto``: one-time on-device micro-bench electing the
    fastest supported variant for this (device kind, bin width) — cached at
    module scope so later fits (and every tree of this fit) reuse the
    winner without re-timing or retracing.  Off-TPU the Pallas kernels are
    not the production path, so 'base' is returned without timing."""
    import jax
    backend = backend or jax.default_backend()
    if backend != "tpu":
        return "base"
    key = (jax.devices()[0].device_kind, int(max_bin))
    if key in _AUTO_CACHE:
        return _AUTO_CACHE[key]
    choice = _run_auto_bench(max_bin, num_features)
    _AUTO_CACHE[key] = choice
    return choice


def _run_auto_bench(max_bin: int, num_features: int) -> str:
    """Elect the production variant: every supported AUTO_CANDIDATE must
    FIRST parity-check on device against the true-f32 XLA one-hot
    (precision-pinned — the same reference the hardware dual gate uses)
    before its timing counts; the fastest parity-clean candidate wins.  A
    candidate that fails to lower or fails parity is skipped with a
    warning, never fatal — 'base' (itself covered by bench_dual's hardware
    gate) is the floor."""
    from ..utils.log import Log
    from .histogram import HIST_PARITY_TOL, _hist_onehot
    import jax

    bins, g, h, m = _auto_bench_data(max_bin, max(1, num_features))
    ref = jax.jit(lambda b_, g_: _hist_onehot(b_, g_, h, m, max_bin,
                                              65536))(bins, g)
    ref = ref.block_until_ready()
    best, best_t = "base", float("inf")
    for name in AUTO_CANDIDATES:
        if not VARIANTS[name].supports(max_bin):
            continue
        try:
            t, err = _time_auto_candidate(name, bins, g, h, m, max_bin, ref)
        except Exception as e:             # noqa: BLE001 — lowering failures
            Log.warning("hist_variant auto-tune: %s failed (%s)", name,
                        str(e)[:120])
            continue
        if err > HIST_PARITY_TOL:
            Log.warning("hist_variant auto-tune: %s FAILED on-device parity "
                        "(relerr %.2e > %.0e) — disqualified", name, err,
                        HIST_PARITY_TOL)
            continue
        Log.info("hist_variant auto-tune: %s %.3f ms (relerr %.2e)", name,
                 t * 1e3, err)
        if t < best_t:
            best, best_t = name, t
    Log.info("hist_variant auto-tune: picked %s for max_bin=%d", best,
             max_bin)
    return best
