"""Leaf-wise linear model fitting for linear trees (``linear_tree=true``).

TPU-native re-design of the reference's ``LinearTreeLearner::CalculateLinear``
(``src/treelearner/linear_tree_learner.cpp:170-380``): per leaf, a ridge
regression of the Newton step on the raw values of the leaf's branch features
— coefficients ``-(XᵀHX + λI)⁻¹ Xᵀg`` (Eq. 3 of arXiv:1802.05640), rows with
NaN in any branch feature excluded.  The reference accumulates per-thread
triangular XᵀHX buffers and solves with vendored Eigen; here each leaf's
normal equations are built with masked matmuls and solved with a batched
``jnp.linalg.solve`` over a ``lax.map`` of leaves.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def fit_leaf_linear(raw: jax.Array, grad: jax.Array, hess: jax.Array,
                    node_assign: jax.Array, row_weight: jax.Array,
                    feat_mat: jax.Array, num_leaves: int,
                    linear_lambda: float):
    """Fit per-leaf linear models.

    Args:
      raw: ``[n, F_total]`` raw feature values (may contain NaN).
      grad, hess: ``[n]`` f32.
      node_assign: ``[n]`` i32 leaf of each row.
      row_weight: ``[n]`` f32 (0 = bagged out).
      feat_mat: ``[L, K]`` i32 real-feature ids on each leaf's branch path,
        -1 padded.
      linear_lambda: ridge term (applied to feature dims, not the intercept —
        linear_tree_learner.cpp:343).

    Returns (coeffs [L, K] f64, consts [L] f64, ok [L] bool) — ``ok`` is the
    reference's non-NaN-row-count gate (rows >= num_feats + 1).
    """
    n, _ = raw.shape
    L, K = feat_mat.shape

    def one(l):
        feats = feat_mat[l]
        fvalid = feats >= 0
        cols = jnp.where(fvalid, feats, 0)
        Xl = jnp.take(raw, cols, axis=1)                       # [n, K]
        row_nan = jnp.any(jnp.isnan(Xl) & fvalid[None, :], axis=1)
        w = ((node_assign == l) & (row_weight > 0) & ~row_nan)
        wf = w.astype(jnp.float32)
        Xa = jnp.concatenate(
            [jnp.where(fvalid[None, :], jnp.nan_to_num(Xl), 0.0),
             jnp.ones((n, 1), jnp.float32)], axis=1)           # [n, K+1]
        Xw = Xa * wf[:, None]
        XTHX = (Xw * hess[:, None]).T @ Xw                     # [K+1, K+1]
        XTg = Xw.T @ (grad * wf)
        # ridge on feature dims; unit diag on padded dims keeps the system
        # nonsingular (their rows are zero, so their coefficients solve to 0)
        diag = jnp.concatenate(
            [jnp.where(fvalid, linear_lambda, 1.0), jnp.zeros(1)])
        # tiny jitter on active dims guards exact singularity (the reference's
        # fullPivLu inverse of a singular system is equally meaningless and
        # gated by `ok` below)
        A = XTHX + jnp.diag(diag.astype(jnp.float32)) + 1e-10 * jnp.eye(K + 1)
        beta = -jnp.linalg.solve(A, XTg)
        nnz = jnp.sum(w)
        ok = nnz >= (jnp.sum(fvalid) + 1)
        return beta[:K], beta[K], ok

    coeffs, consts, oks = jax.lax.map(one, jnp.arange(L, dtype=jnp.int32))
    return coeffs, consts, oks


def linear_leaf_delta(raw: jax.Array, leaf: jax.Array,
                      coeffs: jax.Array, consts: jax.Array,
                      feat_mat: jax.Array, fallback: jax.Array) -> jax.Array:
    """Per-row linear leaf output: ``const[leaf] + Σ coef·x``; rows with NaN
    in any of their leaf's features take ``fallback[leaf]`` (the constant
    leaf value — reference ``PredictionFunLinear``, tree.cpp:127-136)."""
    feats = feat_mat[leaf]                                     # [n, K]
    fvalid = feats >= 0
    cols = jnp.where(fvalid, feats, 0)
    vals = jnp.take_along_axis(raw, cols, axis=1)              # [n, K]
    nan_found = jnp.any(jnp.isnan(vals) & fvalid, axis=1)
    lin = consts[leaf] + jnp.sum(
        jnp.where(fvalid, coeffs[leaf] * jnp.nan_to_num(vals), 0.0), axis=1)
    return jnp.where(nan_found, fallback[leaf], lin)
