"""TreeSHAP feature contributions.

Analog of the reference ``Tree::TreeSHAP`` (``src/io/tree.cpp:887``, per-row
recursive path algorithm from Lundberg et al.).  Re-designed for batch
execution: the DFS visit order and the feature layout of the "unique path"
are row-independent — only the hot/cold choice at each internal node varies
per row — so the path state carries a leading row axis and every row is
processed in one numpy pass per tree node (``[n, depth+1]`` path arrays
instead of the reference's per-row recursion).

Output convention matches ``PredictContrib`` (``c_api.cpp`` predict with
``pred_contrib``): per-row ``[num_features + 1]`` where the last column is
the expected value (bias) of the ensemble.
"""
from __future__ import annotations

import numpy as np


def _extend(pz, po, pw, pfeat, depth, zero_frac, one_frac, feat):
    """ExtendPath (tree.cpp:823-840), vectorized over rows.

    pz/po/pw: [n, max_depth+2] path arrays (mutated in place);
    zero_frac: scalar; one_frac: [n] or scalar.
    """
    pz[:, depth] = zero_frac
    po[:, depth] = one_frac
    pw[:, depth] = 1.0 if depth == 0 else 0.0
    pfeat[depth] = feat
    for i in range(depth - 1, -1, -1):
        pw[:, i + 1] += po[:, depth] * pw[:, i] * (i + 1.0) / (depth + 1.0)
        pw[:, i] = pz[:, depth] * pw[:, i] * (depth - i) / (depth + 1.0)


def _unwind(pz, po, pw, pfeat, depth, path_index):
    """UnwindPath (tree.cpp:842-862), vectorized over rows."""
    one_frac = po[:, path_index].copy()
    zero_frac = pz[:, path_index].copy()
    next_one_portion = pw[:, depth].copy()
    for i in range(depth - 1, -1, -1):
        nonzero = one_frac != 0
        tmp = pw[:, i].copy()
        pw[:, i] = np.where(
            nonzero,
            np.divide(next_one_portion * (depth + 1.0), (i + 1.0) * one_frac,
                      out=np.zeros_like(next_one_portion),
                      where=nonzero),
            np.divide(tmp, zero_frac * (depth - i) / (depth + 1.0),
                      out=np.zeros_like(tmp),
                      where=(zero_frac * (depth - i)) != 0))
        next_one_portion = np.where(
            nonzero, tmp - pw[:, i] * zero_frac * (depth - i) / (depth + 1.0),
            next_one_portion)
    for i in range(path_index, depth):
        pz[:, i] = pz[:, i + 1]
        po[:, i] = po[:, i + 1]
        pfeat[i] = pfeat[i + 1]


def _unwound_sum(pz, po, pw, depth, path_index):
    """UnwoundPathSum (tree.cpp:864-884), vectorized over rows → [n]."""
    one_frac = po[:, path_index]
    zero_frac = pz[:, path_index]
    next_one_portion = pw[:, depth].copy()
    total = np.zeros(pz.shape[0])
    for i in range(depth - 1, -1, -1):
        nonzero = one_frac != 0
        tmp = np.divide(next_one_portion * (depth + 1.0), (i + 1.0) * one_frac,
                        out=np.zeros_like(next_one_portion), where=nonzero)
        with_one = tmp
        denom = zero_frac * (depth - i) / (depth + 1.0)
        with_zero = np.divide(pw[:, i], denom, out=np.zeros_like(total),
                              where=denom != 0)
        total += np.where(nonzero, with_one, with_zero)
        next_one_portion = np.where(
            nonzero, pw[:, i] - tmp * zero_frac * (depth - i) / (depth + 1.0),
            next_one_portion)
    return total


def tree_shap(tree, X: np.ndarray) -> np.ndarray:
    """SHAP values for one tree over a batch: returns ``[n, F]`` phi
    (feature contributions only; the caller adds the expected value)."""
    n, F = X.shape
    phi = np.zeros((n, F))
    if tree.num_leaves <= 1:
        return phi
    max_path = _max_depth(tree) + 2
    pz = np.zeros((n, max_path))
    po = np.zeros((n, max_path))
    pw = np.zeros((n, max_path))
    pfeat = np.full(max_path, -1, np.int64)

    # precompute per-node per-row decisions once
    goes_left = {}
    for node in range(tree.num_internal):
        goes_left[node] = tree._decide(node, X[:, tree.split_feature[node]])

    def counts(idx: int) -> float:
        if idx < 0:
            return float(tree.leaf_count[~idx])
        return float(tree.internal_count[idx])

    def visit(node, depth, zero_frac, one_frac, feat,
              pz, po, pw, pfeat):
        pz, po, pw, pfeat = pz.copy(), po.copy(), pw.copy(), pfeat.copy()
        _extend(pz, po, pw, pfeat, depth, zero_frac, one_frac, feat)
        if node < 0:                                     # leaf
            leaf_val = float(tree.leaf_value[~node])
            for i in range(1, depth + 1):
                w = _unwound_sum(pz, po, pw, depth, i)
                phi[:, pfeat[i]] += w * (po[:, i] - pz[:, i]) * leaf_val
            return
        f = int(tree.split_feature[node])
        left, right = int(tree.left_child[node]), int(tree.right_child[node])
        w = counts(node)
        left_zero = counts(left) / w
        right_zero = counts(right) / w
        gl = goes_left[node]

        incoming_zero = 1.0
        incoming_one = np.ones(n)
        path_index = 0
        while path_index <= depth:
            if pfeat[path_index] == f:
                break
            path_index += 1
        if path_index != depth + 1:
            incoming_zero = pz[:, path_index].copy()
            incoming_one = po[:, path_index].copy()
            _unwind(pz, po, pw, pfeat, depth, path_index)
            depth -= 1
        else:
            incoming_zero = np.ones(n)

        # left child: hot for rows going left, cold otherwise
        visit(left, depth + 1, left_zero * incoming_zero,
              np.where(gl, incoming_one, 0.0), f, pz, po, pw, pfeat)
        visit(right, depth + 1, right_zero * incoming_zero,
              np.where(gl, 0.0, incoming_one), f, pz, po, pw, pfeat)

    # zero_frac at root slot is unused in sums; mirror the reference's
    # initial call with fractions 1 and feature -1 (tree.cpp:147,226 callers)
    visit(0, 0, np.ones(n), np.ones(n), -1, pz, po, pw, pfeat)
    return phi


def expected_value(tree) -> float:
    """Reference ``Tree::ExpectedValue`` (tree.cpp:991)."""
    if tree.num_leaves == 1:
        return float(tree.leaf_value[0]) if len(tree.leaf_value) else 0.0
    total = float(tree.internal_count[0])
    if total <= 0:
        return 0.0
    return float(np.sum(tree.leaf_count[:tree.num_leaves] / total
                        * tree.leaf_value[:tree.num_leaves]))


def _max_depth(tree) -> int:
    depth = np.zeros(tree.num_internal, np.int64)
    md = 1
    for node in range(tree.num_internal):
        for child in (tree.left_child[node], tree.right_child[node]):
            if child >= 0:
                depth[child] = depth[node] + 1
                md = max(md, int(depth[child]) + 1)
            else:
                md = max(md, int(depth[node]) + 1)
    return md


__all__ = ["tree_shap", "expected_value"]
