from .histogram import build_histogram, subtract_histogram
from .split import SplitParams, SplitResult, find_best_split
from .grower import GrowerConfig, TreeArrays, grow_tree
from .predict import predict_leaf_binned, add_score_from_leaves

__all__ = ["build_histogram", "subtract_histogram", "SplitParams", "SplitResult",
           "find_best_split", "GrowerConfig", "TreeArrays", "grow_tree",
           "predict_leaf_binned", "add_score_from_leaves"]
