"""Device-side tree traversal over binned data.

Used for training/validation score updates: validation sets are binned with
the training set's mappers, so bin-threshold comparison is exactly equivalent
to the reference's raw-value traversal (``tree.h:133``), but vectorized over
all rows with a ``lax.while_loop`` instead of per-row recursion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .grower import TreeArrays


def predict_leaf_binned(tree: TreeArrays, bins: jax.Array, nan_bins: jax.Array,
                        efb=None) -> jax.Array:
    """Leaf index per row for binned features ``[N, F]``.

    ``efb``: optional static ``(feat_bundle, feat_off, num_bins)`` arrays
    when ``bins`` is an EFB bundle matrix (io/efb.py) — the per-feature bin
    decodes through the uniform ``col - off + 1`` range mapping."""
    n = bins.shape[0]
    if efb is not None:
        fb = jnp.asarray(efb[0].astype("int32"))
        fo = jnp.asarray(efb[1].astype("int32"))
        fnb = jnp.asarray(efb[2].astype("int32"))

    def cond(cur):
        return jnp.any(cur >= 0)

    def body(cur):
        node = jnp.maximum(cur, 0)
        feat = tree.split_feature[node]                      # [N]
        col_id = jnp.take(fb, feat) if efb is not None else feat
        col = jnp.take_along_axis(bins, col_id[:, None].astype(jnp.int32),
                                  axis=1)[:, 0].astype(jnp.int32)  # [N]
        if efb is not None:
            from ..io.efb import decode_bundle_column
            col = decode_bundle_column(col, jnp.take(fo, feat),
                                       jnp.take(fnb, feat)).astype(jnp.int32)
        thr = tree.threshold[node]
        is_cat = tree.is_cat_split[node]
        dleft = tree.default_left[node]
        nb = nan_bins[feat]
        is_miss = (col == nb) & (nb >= 0)
        # categorical: bin-bitset membership (one-hot and sorted subsets)
        bits = tree.cat_bits[node]                           # [N, CW]
        word = jnp.take_along_axis(bits, (col >> 5)[:, None], axis=1)[:, 0]
        cat_left = ((word >> (col & 31)) & 1) == 1
        goes_left = jnp.where(is_cat, cat_left,
                              jnp.where(is_miss, dleft, col <= thr))
        nxt = jnp.where(goes_left, tree.left_child[node], tree.right_child[node])
        return jnp.where(cur >= 0, nxt, cur)

    has_splits = tree.num_leaves > 1
    init = jnp.where(has_splits, jnp.zeros(n, jnp.int32), jnp.full(n, -1, jnp.int32))
    final = jax.lax.while_loop(cond, body, init)
    return (~final).astype(jnp.int32)


def add_score_from_leaves(score: jax.Array, leaf_idx: jax.Array,
                          leaf_value: jax.Array) -> jax.Array:
    """Score update by leaf gather (the reference's by-partition
    ``ScoreUpdater::AddScore``, ``score_updater.hpp:88``)."""
    return score + leaf_value[leaf_idx]
