"""Level-batched best-first tree growth — the fast path of the grower.

Re-designs ``SerialTreeLearner::Train``'s one-split-at-a-time loop
(``src/treelearner/serial_tree_learner.cpp:158-209``) into rounds that grow
**k leaves per compiled step** while preserving exact best-first semantics.
The enabling observation: in the best-first priority-queue process a node's
pop position is the descending order of

    g_hat(v) = min(gain(v), g_hat(parent(v)))

— children enter the queue only after their parent pops, so a node's
effective priority is the minimum gain along its root path (non-increasing
down any path).  Therefore:

- expanding the top-k pending leaves by ``g_hat`` each round visits splits
  in a superset of the true best-first prefix,
- growth can stop exactly when every pending ``g_hat`` is below the
  ``(num_leaves-1)``-th largest applied ``g_hat`` (no pending split can
  displace an applied one), and
- ONE sort by ``(g_hat desc, creation seq asc)`` at the end reproduces the
  sequential grower's split order — and with it the reference's node/leaf
  numbering (left child keeps the parent's leaf id, right child takes the
  next fresh id) — with no sequential priority queue anywhere.

Splits applied beyond the budget ("overshoot") revert for free: a dropped
split's two child segments are contiguous inside the parent's recorded row
range, so the parent simply remains a leaf over that range.

Per round the heavy work is batched: ONE element-gather decides every
selected leaf's split column, ONE pass of segmented cumsums stable-partitions
all k segments of the row permutation, ONE leaf-grouped row gather feeds the
batched Pallas histogram kernel (``build_histogram_leaves``), and the 2k
child split searches ride a single vmapped ``find_best_split``.  This
amortizes the sequential tail (per-split small-op overhead, ~33% of round-3
tree time) and halves gather traffic (only smaller-sibling rows are ever
row-gathered; partition decisions ride a byte-sized element gather).

Scope: serial, data-, feature- and voting-parallel modes without
cross-leaf-COUPLED features.  Monotone constraints, CEGB, interaction
constraints and forced splits couple leaves to the sequential split order
and take the sequential grower (``grower.grow_tree``);
``grower._frontier_eligible`` is the gate.  Per-node RNG features
(``feature_fraction_bynode``, ``extra_trees``) ARE served here: their draws
are re-keyed by split-record index (see ``node_mask_for``), giving a valid
stream of the same structure as the sequential grower's step-keyed one.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .histogram import build_histogram, build_histogram_leaves, unrolled_rank
from .split import (NEG_INF, SplitResult, cat_words, find_best_split,
                    pack_bin_bitset)

POS_INF = -NEG_INF


def grow_tree_frontier(bins, grad, hess, row_weight, feature_mask,
                       num_bins, default_bins, nan_bins, is_categorical,
                       monotone, key, cfg, efb=None, feature_contri=None
                       ) -> Tuple["TreeArrays", jax.Array]:
    """Grow one tree with round-batched best-first expansion.

    Same contract as ``grower.grow_tree`` (returns ``(TreeArrays,
    node_assignment)``) for the eligible feature subset; trees are
    identical to the sequential grower's up to float-summation order in
    histograms and tie-breaks between exactly-equal gains.
    """
    from .grower import TreeArrays, _BestSplits

    n, n_cols = bins.shape
    if efb is not None:
        efb_bundle_np, efb_off_np, efb_nb_np = efb
        f = int(efb_bundle_np.shape[0])
    else:
        f = n_cols
    L = cfg.num_leaves
    B = cfg.max_bin
    Bb = cfg.bundle_bins or B
    cw = cat_words(B)
    p = cfg.split
    axis = cfg.axis_name
    mode = cfg.parallel_mode or ("data" if axis is not None else None)
    k = max(1, min(cfg.frontier_k, L - 1))
    BR = cfg.frontier_block_rows
    S = (L - 1) + 2 * k              # split-record capacity (overshoot slack)
    LS = L + 2 * k                   # leaf-slot capacity

    # ---- EFB decode tables (identity when efb is None); see grower.py -----
    if efb is not None:
        col_of_feat = jnp.asarray(efb_bundle_np.astype(np.int32))
        off_of_feat = jnp.asarray(efb_off_np.astype(np.int32))
        _spans = efb_nb_np.astype(np.int64) - 1
        _bidx = np.arange(B - 1, dtype=np.int64)[None, :]
        _valid = _bidx < _spans[:, None]
        _idx = (efb_bundle_np.astype(np.int64)[:, None] * Bb
                + efb_off_np.astype(np.int64)[:, None] + _bidx)
        _idx = np.where(_valid, _idx, 0)
        _efb_idx = jnp.asarray(_idx.reshape(-1).astype(np.int32))
        _efb_valid = jnp.asarray(_valid.astype(np.float32))
        _efb_bundle = jnp.asarray(efb_bundle_np.astype(np.int32))

        def expand_hist(hb):
            flat = hb.reshape(-1, 3)
            g = jnp.take(flat, _efb_idx, axis=0).reshape(f, B - 1, 3)
            g = g * _efb_valid[:, :, None]
            totals = jnp.sum(hb, axis=1)
            bin0 = jnp.take(totals, _efb_bundle, axis=0) - jnp.sum(g, axis=1)
            return jnp.concatenate([bin0[:, None, :], g], axis=1)

        def decode_col(colv, feat):
            off = off_of_feat[feat]
            nbf = num_bins[feat]
            return jnp.where((colv >= off) & (colv < off + nbf - 1),
                             colv - off + 1, 0)
    else:
        col_of_feat = None

        def expand_hist(hb):
            return hb

        def decode_col(colv, feat):
            return colv

    # ---- combined row payload: (grad, hess, row_weight) packed as trailing
    # bin-typed columns so one row gather moves everything (see grower.py) --
    _gh_cols = 12 // bins.dtype.itemsize
    _gh_packed = jax.lax.bitcast_convert_type(
        jnp.stack([grad, hess, row_weight], axis=1), bins.dtype
    ).reshape(n, _gh_cols)
    comb = jnp.concatenate([bins, _gh_packed], axis=1)    # [N, NC + gh_cols]
    ncc = comb.shape[1]
    comb_flat = comb.reshape(-1)

    def _unpack_gh(combb):
        cap = combb.shape[0]
        raw = combb[:, n_cols:].reshape(cap, 3, _gh_cols // 3)
        return jax.lax.bitcast_convert_type(raw, jnp.float32)

    # --- shard-local feature metadata + mode-dispatched search ------------
    # Mirrors the sequential grower's learner dispatch (grower.py find /
    # _find_voting / _reduce_split_global = the reference's per-learner
    # FindBestSplitsFromHistograms + SyncUpGlobalBestSplit).
    if mode == "feature":
        dev = jax.lax.axis_index(axis)
        f_start = dev * f

        def lslice(a):
            return jax.lax.dynamic_slice_in_dim(a, f_start, f)
        num_bins_l = lslice(num_bins)
        default_bins_l = lslice(default_bins)
        nan_bins_l = lslice(nan_bins)
        is_cat_l = lslice(is_categorical)
        mono_l = lslice(monotone)
        contri_l = (lslice(feature_contri) if feature_contri is not None
                    else None)

    def reduce_hist(h):
        # data: full-histogram allreduce; feature/voting keep shard-local
        # stores (voting reduces only ELECTED slices inside the search)
        return jax.lax.psum(h, axis) if mode == "data" else h

    # --- per-node RNG streams (feature_fraction_bynode, extra_trees) ------
    # The sequential grower keys both draws by the split-step index; the
    # frontier keys them by the expansion's split-record index s_idx (root =
    # step 0, children of record i = step i+1) — a deterministic, replay-
    # stable stream with the same structure (siblings share a draw, every
    # split event gets a fresh one), though not bit-identical to the
    # sequential grower's stream (the pop order differs, so no keying can
    # reproduce it without sequentializing).
    bynode = cfg.feature_fraction_bynode < 1.0
    _nb_r = None
    if cfg.extra_trees:
        _nb_r = num_bins_l if mode == "feature" else num_bins
        _nanb_r = nan_bins_l if mode == "feature" else nan_bins

    def node_mask_for(step):
        if not bynode:
            return feature_mask
        from .grower import node_feature_mask_for
        return node_feature_mask_for(key, step, feature_mask,
                                     cfg.feature_fraction_bynode)

    def rand_thr_for(step):
        if not cfg.extra_trees:
            return None
        from .grower import rand_thresholds_for
        return rand_thresholds_for(key, step, cfg.extra_seed, _nb_r, _nanb_r)

    # --- monotone-basic: output bounds pinch at the midpoint down the root
    # path (grower.py apply_split basic branch), which is per-leaf state the
    # frontier already carries — intermediate/advanced (cross-leaf
    # propagation) stay on the sequential grower (_frontier_eligible)
    use_mono = cfg.has_monotone
    use_pen = cfg.has_monotone and cfg.monotone_penalty > 0.0

    def mult_for(depth):
        if not use_pen:
            return None
        from .grower import monotone_gain_mult
        return monotone_gain_mult(depth, monotone, cfg.monotone_penalty)

    @jax.named_scope("lgbm/split_search")
    def find(hist_fb, sum_g, sum_h, count, fmask=None, rand=None,
             lo=NEG_INF, hi=POS_INF, mult=None):
        fmask = feature_mask if fmask is None else fmask
        if mode == "feature":
            from .grower import _reduce_split_global
            s = find_best_split(hist_fb, num_bins_l, default_bins_l,
                                nan_bins_l, is_cat_l, mono_l, sum_g, sum_h,
                                count, p, lslice(fmask),
                                output_lo=lo, output_hi=hi,
                                rand_threshold=rand,
                                sorted_cat=cfg.sorted_cat,
                                gain_mult=(lslice(mult) if mult is not None
                                           else None),
                                contri=contri_l)
            s = s._replace(feature=s.feature + f_start)
            return _reduce_split_global(s, axis)
        if mode == "voting":
            return _find_voting(hist_fb, sum_g, sum_h, count, fmask, rand,
                                lo, hi, mult)
        return find_best_split(hist_fb, num_bins, default_bins, nan_bins,
                               is_categorical, monotone, sum_g, sum_h, count,
                               p, fmask, output_lo=lo, output_hi=hi,
                               rand_threshold=rand,
                               sorted_cat=cfg.sorted_cat, gain_mult=mult,
                               contri=feature_contri)

    def _find_voting(hist, sum_g, sum_h, count, fmask, rand=None,
                     lo=NEG_INF, hi=POS_INF, mult=None):
        """Local top-k proposal -> global vote -> reduce only elected
        histograms (the election dataflow lives once in split.voting_elect,
        shared with the sequential grower)."""
        from .split import voting_elect
        hist_e, emask = voting_elect(
            hist, num_bins, nan_bins, is_categorical, monotone, sum_g,
            sum_h, count, p, fmask, axis, cfg.top_k, cfg.num_shards,
            output_lo=lo, output_hi=hi,
            sorted_cat=cfg.sorted_cat, gain_mult=mult,
            contri=feature_contri)
        return find_best_split(hist_e, num_bins, default_bins, nan_bins,
                               is_categorical, monotone, sum_g, sum_h, count,
                               p, emask, output_lo=lo, output_hi=hi,
                               rand_threshold=rand,
                               sorted_cat=cfg.sorted_cat, gain_mult=mult,
                               contri=feature_contri)

    # ---- degenerate: no usable features -> single-leaf tree ---------------
    if f == 0:
        cnt = jnp.sum(row_weight)
        wgt = jnp.sum(hess * row_weight)
        if mode in ("data", "voting"):
            cnt = jax.lax.psum(cnt, axis)
            wgt = jax.lax.psum(wgt, axis)
        empty = TreeArrays(
            split_feature=jnp.full(L - 1, -1, jnp.int32),
            threshold=jnp.zeros(L - 1, jnp.int32),
            default_left=jnp.zeros(L - 1, bool),
            is_cat_split=jnp.zeros(L - 1, bool),
            cat_bits=jnp.zeros((L - 1, cw), jnp.int32),
            split_gain=jnp.zeros(L - 1, jnp.float32),
            left_child=jnp.full(L - 1, -1, jnp.int32),
            right_child=jnp.full(L - 1, -1, jnp.int32),
            leaf_value=jnp.zeros(L, jnp.float32),
            leaf_count=jnp.zeros(L, jnp.float32).at[0].set(cnt),
            leaf_weight=jnp.zeros(L, jnp.float32).at[0].set(wgt),
            internal_value=jnp.zeros(L - 1, jnp.float32),
            internal_count=jnp.zeros(L - 1, jnp.float32),
            num_leaves=jnp.int32(1))
        return empty, jnp.zeros(n, jnp.int32)

    # ---- root -------------------------------------------------------------
    root_hist = reduce_hist(
        build_histogram(bins, grad, hess, row_weight, Bb,
                        method=cfg.hist_method,
                        chunk_rows=cfg.hist_chunk_rows,
                        variant=cfg.hist_variant))
    tot = jnp.stack([jnp.sum(grad * row_weight), jnp.sum(hess * row_weight),
                     jnp.sum(row_weight)])
    if mode in ("data", "voting"):
        # feature mode replicates rows, so local sums are already global
        tot = jax.lax.psum(tot, axis)
    root_split = find(expand_hist(root_hist), tot[0], tot[1], tot[2],
                      fmask=node_mask_for(0), rand=rand_thr_for(0),
                      mult=mult_for(0))

    # histogram blocks ladder: rungs over the per-round leaf-grouped gather
    # capacity (block-aligned); every rung a BR multiple
    cap_max = -(-(n // 2 + k * BR) // BR) * BR
    caps2: "list[int]" = []
    c = max(8 * BR, min(16384, cap_max))
    c = -(-c // BR) * BR
    while c < cap_max:
        caps2.append(c)
        c = -(-(c * 4) // BR) * BR
    caps2.append(cap_max)

    pend0 = _BestSplits.empty(LS, cw)
    pend0 = _batch_set(pend0, jnp.array([0]), _as_batch(root_split, 1),
                       jnp.array([True]))

    state = dict(
        perm=jnp.arange(n, dtype=jnp.int32),
        pos_leaf=jnp.zeros(n, jnp.int32),
        leaf_begin=jnp.zeros(LS, jnp.int32),
        leaf_nrows=jnp.zeros(LS, jnp.int32).at[0].set(n),
        leaf_depth=jnp.zeros(LS, jnp.int32),
        leaf_sum_g=jnp.zeros(LS, jnp.float32).at[0].set(tot[0]),
        leaf_weight=jnp.zeros(LS, jnp.float32).at[0].set(tot[1]),
        leaf_count=jnp.zeros(LS, jnp.float32).at[0].set(tot[2]),
        leaf_cghat=jnp.full(LS, POS_INF, jnp.float32),   # creator split g_hat
        leaf_cs=jnp.full(LS, -1, jnp.int32),             # creator split idx
        leaf_il=jnp.zeros(LS, bool),                     # was left child
        pend=pend0,
        pend_ghat=jnp.full(LS, NEG_INF, jnp.float32).at[0].set(
            jnp.minimum(root_split.gain, POS_INF)),
        hist=jnp.zeros((LS, n_cols, Bb, 3), jnp.float32).at[0].set(root_hist),
        # split records
        sp_ghat=jnp.full(S, NEG_INF, jnp.float32),
        sp_parent=jnp.full(S, -1, jnp.int32),
        sp_is_left=jnp.zeros(S, bool),
        sp_feature=jnp.zeros(S, jnp.int32),
        sp_threshold=jnp.zeros(S, jnp.int32),
        sp_dleft=jnp.zeros(S, bool),
        sp_iscat=jnp.zeros(S, bool),
        sp_catbits=jnp.zeros((S, cw), jnp.int32),
        sp_gain=jnp.zeros(S, jnp.float32),
        sp_lout=jnp.zeros(S, jnp.float32), sp_rout=jnp.zeros(S, jnp.float32),
        sp_lsumg=jnp.zeros(S, jnp.float32), sp_rsumg=jnp.zeros(S, jnp.float32),
        sp_lweight=jnp.zeros(S, jnp.float32),
        sp_rweight=jnp.zeros(S, jnp.float32),
        sp_lcount=jnp.zeros(S, jnp.float32),
        sp_rcount=jnp.zeros(S, jnp.float32),
        sp_value=jnp.zeros(S, jnp.float32),   # split-leaf output (internal)
        sp_count=jnp.zeros(S, jnp.float32),   # split-leaf weighted count
        sp_begin=jnp.zeros(S, jnp.int32),     # split-leaf row range (local)
        sp_nrows=jnp.zeros(S, jnp.int32),
        sp_nleft=jnp.zeros(S, jnp.int32),     # raw left row count (local)
        n_applied=jnp.int32(0),
    )
    if use_mono:
        # per-leaf monotone output bounds (basic mode: root-path state only)
        state["leaf_lo"] = jnp.full(LS, NEG_INF, jnp.float32)
        state["leaf_hi"] = jnp.full(LS, POS_INF, jnp.float32)

    from .split import leaf_output

    # one named scope per frontier round so device traces show the
    # per-round cost of the batched partition+hist+search program
    @jax.named_scope("lgbm/frontier_round")
    def round_body(st):
        applied = st["n_applied"]
        # expansion priority: g_hat primary, RAW gain secondary.  Structural
        # g_hat ties (child gain > parent gain caps the child at the parent's
        # g_hat) are popped by the true process in raw-gain cascade order, so
        # expanding tie classes in raw order keeps the applied set a superset
        # of the true prefix without blowing the overshoot slack.
        sel = jnp.lexsort((-st["pend"].gain, -st["pend_ghat"]))[:k]
        ghat_sel = st["pend_ghat"][sel]
        i_ar = jnp.arange(k, dtype=jnp.int32)
        t_full = jax.lax.top_k(st["sp_ghat"], L - 1)[0][-1]
        # >= on the threshold: when a child's raw gain exceeds its parent's,
        # g_hat(child) == g_hat(parent) EXACTLY (structural tie), and such a
        # child can pop before an applied record with the same g_hat — it
        # must be expanded so the replay can consider it
        valid = ((ghat_sel > 0.0)
                 & (applied + i_ar < S)
                 & ((applied + i_ar < L - 1) | (ghat_sel >= t_full)))
        v = jnp.sum(valid.astype(jnp.int32))

        b = st["pend"]
        sel_feat = b.feature[sel]
        sel_thr = b.threshold[sel]
        sel_dleft = b.default_left[sel]
        sel_cbits = b.cat_bits[sel]                       # [k, CW]
        sel_iscat = is_categorical[sel_feat]
        sel_gain = b.gain[sel]
        sp_ghat_i = jnp.minimum(sel_gain, st["leaf_cghat"][sel])
        right_slot = applied + 1 + i_ar                   # leaf slot of right child
        s_idx = applied + i_ar                            # split record index
        # the weighted-count comparison is GLOBAL (identical on every shard),
        # so all shards histogram the same side (grower.py apply_split)
        left_smaller = b.lc[sel] <= b.rc[sel]

        # ---- [N]-pass: decide + segmented stable partition ----------------
        slot_of_leaf = jnp.full(LS, -1, jnp.int32).at[
            jnp.where(valid, sel, LS)].set(i_ar, mode="drop")
        lf = st["pos_leaf"]
        si = slot_of_leaf[lf]
        act = si >= 0
        sic = jnp.maximum(si, 0)
        feat_p = sel_feat[sic]
        rowid = st["perm"]
        if mode == "feature":
            # columns are sharded: the owner shard selects its local column
            # and ONE [N] psum broadcasts it (rows are replicated, so every
            # shard's perm/selection state is identical; grower.py
            # partition_and_hist does the same per split — here it is once
            # per ROUND)
            local_ix = jnp.clip(feat_p - f_start, 0, f - 1)
            owns = (feat_p >= f_start) & (feat_p < f_start + f)
            colv_loc = jnp.take(comb_flat,
                                rowid * ncc + local_ix).astype(jnp.int32)
            colv = jax.lax.psum(jnp.where(owns & act, colv_loc, 0), axis)
        else:
            col_id_p = col_of_feat[feat_p] if efb is not None else feat_p
            colv = jnp.take(comb_flat,
                            rowid * ncc + col_id_p).astype(jnp.int32)
            colv = decode_col(colv, feat_p)
        nb_p = nan_bins[feat_p]
        is_miss = (colv == nb_p) & (nb_p >= 0)
        wsel = jnp.take(sel_cbits.reshape(-1),
                        sic * cw + jnp.clip(colv >> 5, 0, cw - 1))
        gl_cat = ((wsel >> (colv & 31)) & 1) > 0
        gl = jnp.where(sel_iscat[sic], gl_cat,
                       jnp.where(is_miss, sel_dleft[sic],
                                 colv <= sel_thr[sic]))
        gl_a = gl & act
        cumL = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                jnp.cumsum(gl_a.astype(jnp.int32))])
        cumA = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                jnp.cumsum(act.astype(jnp.int32))])
        beg_p = st["leaf_begin"][lf]
        baseL = jnp.take(cumL, beg_p)
        baseA = jnp.take(cumA, beg_p)
        rankL = cumL[1:] - gl_a.astype(jnp.int32) - baseL     # exclusive
        rankA = cumA[1:] - act.astype(jnp.int32) - baseA
        rankR = rankA - rankL
        sel_beg = st["leaf_begin"][sel]
        sel_rows = st["leaf_nrows"][sel]
        nl_i = (jnp.take(cumL, sel_beg + sel_rows)
                - jnp.take(cumL, sel_beg))                    # [k] raw left
        nl_p = nl_i[sic]
        pos_idx = jnp.arange(n, dtype=jnp.int32)
        new_pos = jnp.where(act,
                            beg_p + jnp.where(gl, rankL, nl_p + rankR),
                            pos_idx)
        perm_new = jnp.zeros(n, jnp.int32).at[new_pos].set(rowid)
        pos_leaf_new = jnp.zeros(n, jnp.int32).at[new_pos].set(
            jnp.where(gl | ~act, lf, right_slot[sic]))

        # ---- leaf bookkeeping --------------------------------------------
        def upd(arr, idx, val, pred):
            return arr.at[jnp.where(pred, idx, LS)].set(val, mode="drop")
        nr_i = sel_rows - nl_i
        depth_c = st["leaf_depth"][sel] + 1
        leaf_begin = upd(st["leaf_begin"], right_slot, sel_beg + nl_i, valid)
        leaf_nrows = upd(upd(st["leaf_nrows"], sel, nl_i, valid),
                         right_slot, nr_i, valid)
        leaf_depth = upd(upd(st["leaf_depth"], sel, depth_c, valid),
                         right_slot, depth_c, valid)
        leaf_sum_g = upd(upd(st["leaf_sum_g"], sel, b.lg[sel], valid),
                         right_slot, b.rg[sel], valid)
        leaf_weight = upd(upd(st["leaf_weight"], sel, b.lh[sel], valid),
                          right_slot, b.rh[sel], valid)
        leaf_count = upd(upd(st["leaf_count"], sel, b.lc[sel], valid),
                         right_slot, b.rc[sel], valid)
        leaf_cghat = upd(upd(st["leaf_cghat"], sel, sp_ghat_i, valid),
                         right_slot, sp_ghat_i, valid)
        leaf_cs = upd(upd(st["leaf_cs"], sel, s_idx, valid),
                      right_slot, s_idx, valid)
        leaf_il = upd(upd(st["leaf_il"], sel, jnp.ones(k, bool), valid),
                      right_slot, jnp.zeros(k, bool), valid)

        extra_mono = {}
        if use_mono:
            # basic mode: pinch both children at the midpoint of the child
            # outputs (grower.py apply_split, reference BasicConstraint) —
            # depends only on the expansion's own path, so batching k
            # expansions cannot reorder it
            mono_sel = monotone[sel_feat]
            lo_p, hi_p = st["leaf_lo"][sel], st["leaf_hi"][sel]
            mid = (b.lout[sel] + b.rout[sel]) * 0.5
            l_lo = jnp.where(mono_sel < 0, jnp.maximum(lo_p, mid), lo_p)
            l_hi = jnp.where(mono_sel > 0, jnp.minimum(hi_p, mid), hi_p)
            r_lo = jnp.where(mono_sel > 0, jnp.maximum(lo_p, mid), lo_p)
            r_hi = jnp.where(mono_sel < 0, jnp.minimum(hi_p, mid), hi_p)
            extra_mono = dict(
                leaf_lo=upd(upd(st["leaf_lo"], sel, l_lo, valid),
                            right_slot, r_lo, valid),
                leaf_hi=upd(upd(st["leaf_hi"], sel, l_hi, valid),
                            right_slot, r_hi, valid))

        # ---- split records ------------------------------------------------
        def rec(arr, val):
            return arr.at[jnp.where(valid, s_idx, S)].set(val, mode="drop")
        sp_value_i = leaf_output(st["leaf_sum_g"][sel], st["leaf_weight"][sel],
                                 p, 0.0, st["leaf_count"][sel])
        recs = dict(
            sp_ghat=rec(st["sp_ghat"], sp_ghat_i),
            sp_parent=rec(st["sp_parent"], st["leaf_cs"][sel]),
            sp_is_left=rec(st["sp_is_left"], st["leaf_il"][sel]),
            sp_feature=rec(st["sp_feature"], sel_feat),
            sp_threshold=rec(st["sp_threshold"], sel_thr),
            sp_dleft=rec(st["sp_dleft"], sel_dleft),
            sp_iscat=rec(st["sp_iscat"], sel_iscat),
            sp_catbits=rec(st["sp_catbits"], sel_cbits),
            sp_gain=rec(st["sp_gain"], sel_gain),
            sp_lout=rec(st["sp_lout"], b.lout[sel]),
            sp_rout=rec(st["sp_rout"], b.rout[sel]),
            sp_lsumg=rec(st["sp_lsumg"], b.lg[sel]),
            sp_rsumg=rec(st["sp_rsumg"], b.rg[sel]),
            sp_lweight=rec(st["sp_lweight"], b.lh[sel]),
            sp_rweight=rec(st["sp_rweight"], b.rh[sel]),
            sp_lcount=rec(st["sp_lcount"], b.lc[sel]),
            sp_rcount=rec(st["sp_rcount"], b.rc[sel]),
            sp_value=rec(st["sp_value"], sp_value_i),
            sp_count=rec(st["sp_count"], st["leaf_count"][sel]),
            sp_begin=rec(st["sp_begin"], sel_beg),
            sp_nrows=rec(st["sp_nrows"], sel_rows),
            sp_nleft=rec(st["sp_nleft"], nl_i),
        )

        # ---- batched smaller-child histograms -----------------------------
        small_n = jnp.where(valid, jnp.where(left_smaller, nl_i, nr_i), 0)
        small_beg = jnp.where(left_smaller, sel_beg, sel_beg + nl_i)
        nblocks = jnp.maximum(-(-small_n // BR), 1)   # >=1: every slot inits
        blk_start = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                     jnp.cumsum(nblocks)])[:-1]
        nb_tot = blk_start[-1] + nblocks[-1]

        def mk_branch(C2):
            NB = C2 // BR

            def br(perm_arg):
                blk = jnp.arange(NB, dtype=jnp.int32)
                i_of_blk = jnp.clip(
                    unrolled_rank(blk_start, blk, strict=False) - 1, 0, k - 1)
                q = jnp.arange(C2, dtype=jnp.int32)
                qb = q // BR
                i_of_q = i_of_blk[qb]
                local = (qb - blk_start[i_of_q]) * BR + (q % BR)
                okrow = (local < small_n[i_of_q]) & (qb < nb_tot)
                row_pos = jnp.clip(small_beg[i_of_q] + local, 0, n - 1)
                rid = jnp.take(perm_arg, row_pos)
                combb = jnp.take(comb, jnp.where(okrow, rid, 0), axis=0)
                ghb = _unpack_gh(combb)
                m = jnp.where(okrow, ghb[:, 2], 0.0)
                return build_histogram_leaves(
                    combb, ghb[:, 0], ghb[:, 1], m, i_of_blk, k, Bb,
                    method=cfg.hist_method, block_rows=BR,
                    f_limit=n_cols,
                    variant=cfg.hist_variant)[:, :n_cols]
            return br

        idx = jnp.searchsorted(jnp.asarray(caps2, jnp.int32), nb_tot * BR)
        hist_small = jax.lax.switch(idx, [mk_branch(c) for c in caps2],
                                    perm_new)
        hist_small = reduce_hist(hist_small)              # [k, NC, Bb, 3]

        parent_hist = st["hist"][sel]
        large_hist = parent_hist - hist_small
        ls4 = left_smaller[:, None, None, None]
        lhist = jnp.where(ls4, hist_small, large_hist)
        rhist = parent_hist - lhist
        v4 = valid[:, None, None, None]
        hist = st["hist"].at[sel].set(jnp.where(v4, lhist, parent_hist))
        hist = hist.at[jnp.where(valid, right_slot, LS)].set(
            rhist, mode="drop")

        # ---- 2k child split searches (one vmapped program) ----------------
        hist2 = jnp.concatenate([lhist, rhist])           # [2k, NC, Bb, 3]
        g2 = jnp.concatenate([b.lg[sel], b.rg[sel]])
        h2 = jnp.concatenate([b.lh[sel], b.rh[sel]])
        c2 = jnp.concatenate([b.lc[sel], b.rc[sel]])
        if use_mono:
            # bounds per child, penalty factor per child depth; the step
            # keying rides along (node_mask_for/rand_thr_for ignore the
            # step when their feature is off)
            steps2 = jnp.concatenate([s_idx, s_idx]) + 1
            lo2 = jnp.concatenate([l_lo, r_lo])
            hi2 = jnp.concatenate([l_hi, r_hi])
            d2 = jnp.concatenate([depth_c, depth_c])
            s2 = jax.vmap(lambda hc, g_, h_, c_, st_, lo_, hi_, d_: find(
                expand_hist(hc), g_, h_, c_,
                fmask=node_mask_for(st_), rand=rand_thr_for(st_),
                lo=lo_, hi=hi_, mult=mult_for(d_)))(
                hist2, g2, h2, c2, steps2, lo2, hi2, d2)
        elif bynode or cfg.extra_trees:
            # children of the expansion recorded at s_idx draw their mask /
            # random thresholds from step s_idx+1 (both siblings share it,
            # like the sequential grower's per-step draw)
            steps2 = jnp.concatenate([s_idx, s_idx]) + 1
            s2 = jax.vmap(lambda hc, g_, h_, c_, st_: find(
                expand_hist(hc), g_, h_, c_,
                fmask=node_mask_for(st_), rand=rand_thr_for(st_)))(
                hist2, g2, h2, c2, steps2)
        else:
            s2 = jax.vmap(lambda hc, g_, h_, c_: find(expand_hist(hc),
                                                      g_, h_, c_))(
                hist2, g2, h2, c2)
        depth_ok = (cfg.max_depth <= 0) | (depth_c < cfg.max_depth)
        dok2 = jnp.concatenate([depth_ok, depth_ok])
        s2 = s2._replace(gain=jnp.where(dok2, s2.gain, NEG_INF))
        sl = jax.tree.map(lambda a: a[:k], s2)
        sr = jax.tree.map(lambda a: a[k:], s2)
        pend = _batch_set(st["pend"], sel, sl, valid)
        pend = _batch_set(pend, jnp.where(valid, right_slot, LS), sr, valid)
        pend_ghat = upd(upd(st["pend_ghat"], sel,
                            jnp.minimum(sl.gain, sp_ghat_i), valid),
                        right_slot, jnp.minimum(sr.gain, sp_ghat_i), valid)

        return dict(
            perm=perm_new, pos_leaf=pos_leaf_new,
            leaf_begin=leaf_begin, leaf_nrows=leaf_nrows,
            leaf_depth=leaf_depth, leaf_sum_g=leaf_sum_g,
            leaf_weight=leaf_weight, leaf_count=leaf_count,
            leaf_cghat=leaf_cghat, leaf_cs=leaf_cs, leaf_il=leaf_il,
            pend=pend, pend_ghat=pend_ghat, hist=hist,
            **extra_mono,
            **recs,
            n_applied=applied + v,
        )

    def round_cond(st):
        applied = st["n_applied"]
        t_full = jax.lax.top_k(st["sp_ghat"], L - 1)[0][-1]
        mx = jnp.max(st["pend_ghat"])
        return ((mx > 0.0) & (applied < S)
                & ((applied < L - 1) | (mx >= t_full)))

    if L > 1:
        state = jax.lax.while_loop(round_cond, round_body, state)

    # ---- exact best-first selection + numbering: tiny PQ replay -----------
    # The applied records are a superset of the true best-first prefix.  A
    # replay over ONLY leaf-slot argmaxes — the very operation the
    # sequential grower's loop performs, including its lowest-leaf-id
    # tie-break — recovers the exact split order and with it the reference
    # numbering (left child keeps the parent's leaf id, right child of the
    # j-th split is leaf j+1).  [L]-sized ops per step: ~L x 8 tiny ops
    # total, vs the full histogram+search pipeline the sequential loop
    # pays per step.
    appl = jnp.arange(S, dtype=jnp.int32) < state["n_applied"]
    rec_ids = jnp.arange(S, dtype=jnp.int32)
    child_left = jnp.full(S, -1, jnp.int32).at[
        jnp.where(appl & (state["sp_parent"] >= 0) & state["sp_is_left"],
                  jnp.clip(state["sp_parent"], 0), S)].set(
        rec_ids, mode="drop")
    child_right = jnp.full(S, -1, jnp.int32).at[
        jnp.where(appl & (state["sp_parent"] >= 0) & ~state["sp_is_left"],
                  jnp.clip(state["sp_parent"], 0), S)].set(
        rec_ids, mode="drop")

    def gain_of(r):
        return jnp.where(r >= 0, state["sp_gain"][jnp.clip(r, 0)], NEG_INF)

    have_root = state["n_applied"] > 0      # record 0 is always the root split
    cur_rec0 = jnp.full(L, -1, jnp.int32).at[0].set(
        jnp.where(have_root, 0, -1))
    gains0 = jnp.full(L, NEG_INF, jnp.float32).at[0].set(
        gain_of(cur_rec0[0]))

    def replay_step(j, carry):
        cur_rec, gains, order, leaf_of_node, cnt = carry
        pop = jnp.argmax(gains).astype(jnp.int32)
        ok = gains[pop] > 0.0
        rec = cur_rec[pop]
        order = order.at[j].set(jnp.where(ok, rec, -1))
        leaf_of_node = leaf_of_node.at[j].set(jnp.where(ok, pop, -1))
        lc = child_left[jnp.clip(rec, 0)]
        rc = child_right[jnp.clip(rec, 0)]
        new_id = jnp.minimum(j + 1, L - 1)
        cur_rec = cur_rec.at[pop].set(jnp.where(ok, lc, cur_rec[pop]))
        cur_rec = cur_rec.at[new_id].set(
            jnp.where(ok, rc, cur_rec[new_id]))
        gains = gains.at[pop].set(jnp.where(ok, gain_of(lc), NEG_INF))
        gains = gains.at[new_id].set(
            jnp.where(ok, gain_of(rc), gains[new_id]))
        return cur_rec, gains, order, leaf_of_node, cnt + ok.astype(jnp.int32)

    _, _, order, leaf_of_node, nsel = jax.lax.fori_loop(
        0, L - 1, replay_step,
        (cur_rec0, gains0,
         jnp.full(L - 1, -1, jnp.int32), jnp.full(L - 1, -1, jnp.int32),
         jnp.int32(0)))

    node_on = order >= 0
    src = jnp.clip(order, 0)                                  # node j <- record
    leaf_id_of_node = jnp.maximum(leaf_of_node, 0)
    node_ids = jnp.arange(L - 1, dtype=jnp.int32)

    # children pointers: a selected child record overwrites the leaf default
    pos_of_rec = jnp.full(S, -1, jnp.int32).at[
        jnp.where(node_on, src, S)].set(node_ids, mode="drop")

    def child_ptr(crec, default_leaf):
        c = crec[src]                                          # child record
        cpos = pos_of_rec[jnp.clip(c, 0)]
        return jnp.where(node_on,
                         jnp.where((c >= 0) & (cpos >= 0), cpos,
                                   ~default_leaf),
                         -1)

    left_child = child_ptr(child_left, leaf_id_of_node)
    right_child = child_ptr(child_right, node_ids + 1)

    # leaf stats: node j writes its left/right child's final-leaf slot when
    # that child was not (selected-)split
    lleaf = node_on & (left_child < 0)
    rleaf = node_on & (right_child < 0)
    lids = jnp.clip(leaf_id_of_node, 0, L - 1)
    rids = jnp.clip(node_ids + 1, 0, L - 1)

    def leafset(init, vl, vr):
        a = jnp.zeros(L, init.dtype) + init
        a = a.at[jnp.where(lleaf, lids, L)].set(vl, mode="drop")
        a = a.at[jnp.where(rleaf, rids, L)].set(vr, mode="drop")
        return a

    no_split = nsel == 0
    leaf_value = leafset(jnp.zeros(L, jnp.float32),
                         state["sp_lout"][src], state["sp_rout"][src])
    leaf_count = leafset(jnp.zeros(L, jnp.float32),
                         state["sp_lcount"][src], state["sp_rcount"][src])
    leaf_count = leaf_count.at[0].set(
        jnp.where(no_split, tot[2], leaf_count[0]))
    leaf_weight = leafset(jnp.zeros(L, jnp.float32),
                          state["sp_lweight"][src], state["sp_rweight"][src])
    leaf_weight = leaf_weight.at[0].set(
        jnp.where(no_split, tot[1], leaf_weight[0]))

    tree = TreeArrays(
        split_feature=jnp.where(node_on, state["sp_feature"][src], -1),
        threshold=jnp.where(node_on, state["sp_threshold"][src], 0),
        default_left=node_on & state["sp_dleft"][src],
        is_cat_split=node_on & state["sp_iscat"][src],
        cat_bits=jnp.where(node_on[:, None], state["sp_catbits"][src], 0),
        split_gain=jnp.where(node_on, state["sp_gain"][src], 0.0),
        left_child=left_child,
        right_child=right_child,
        leaf_value=leaf_value,
        leaf_count=leaf_count,
        leaf_weight=leaf_weight,
        internal_value=jnp.where(node_on, state["sp_value"][src], 0.0),
        internal_count=jnp.where(node_on, state["sp_count"][src], 0.0),
        num_leaves=(nsel + 1).astype(jnp.int32),
    )

    # ---- node assignment from final leaf row ranges ------------------------
    lbeg = state["sp_begin"][src]
    lnl = state["sp_nleft"][src]
    leaf_beg = leafset(jnp.zeros(L, jnp.int32), lbeg, lbeg + lnl)
    leaf_nr = leafset(jnp.zeros(L, jnp.int32), lnl,
                      state["sp_nrows"][src] - lnl)
    leaf_nr = leaf_nr.at[0].set(jnp.where(no_split, n, leaf_nr[0]))
    begins = jnp.where(leaf_nr > 0, leaf_beg,
                       n + 1 + jnp.arange(L, dtype=jnp.int32))
    lorder = jnp.argsort(begins)
    sorted_begin = begins[lorder]
    pos = jnp.arange(n, dtype=jnp.int32)
    rank = unrolled_rank(sorted_begin, pos, strict=False)
    leaf_of_pos = jnp.take(lorder, jnp.maximum(rank - 1, 0))
    node_assign = jnp.zeros(n, jnp.int32).at[state["perm"]].set(leaf_of_pos)
    return tree, node_assign


def _as_batch(s: SplitResult, m: int) -> SplitResult:
    """Broadcast a scalar SplitResult to a [m]-batched one."""
    def bc(x):
        x = jnp.asarray(x)
        return jnp.broadcast_to(x, (m,) + x.shape)
    return SplitResult(*[bc(c) for c in s])


def _batch_set(best, idx, s: SplitResult, pred):
    """Scatter a [m]-batched SplitResult into per-leaf _BestSplits slots
    ``idx``, predicated by ``pred`` (dropped via out-of-range index)."""
    from .grower import _BestSplits
    n_slots = best.gain.shape[0]
    tgt = jnp.where(pred, idx, n_slots)

    def u(arr, val):
        return arr.at[tgt].set(val, mode="drop")
    return _BestSplits(
        gain=u(best.gain, s.gain),
        feature=u(best.feature, s.feature),
        threshold=u(best.threshold, s.threshold),
        default_left=u(best.default_left, s.default_left),
        lg=u(best.lg, s.left_sum_g), lh=u(best.lh, s.left_sum_h),
        lc=u(best.lc, s.left_count),
        rg=u(best.rg, s.right_sum_g), rh=u(best.rh, s.right_sum_h),
        rc=u(best.rc, s.right_count),
        lout=u(best.lout, s.left_output), rout=u(best.rout, s.right_output),
        cat_bits=u(best.cat_bits, s.cat_bits))
