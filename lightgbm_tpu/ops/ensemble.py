"""Device batched ensemble prediction over raw feature values.

Replaces the per-tree host loop for ``GBDT::PredictRaw`` (reference
``src/boosting/gbdt_prediction.cpp:20-72``, per-row ``Tree::Predict``
recursion ``tree.h:133``) with ONE compiled program: every tree's flat arrays
are stacked into ``[T, ...]`` device tensors and a ``lax.scan`` over trees
runs a vectorized ``while_loop`` traversal for all rows at once.

Exactness: raw inputs are compared in float32.  Each f64 node threshold ``t``
is rounded DOWN to the nearest f32 (``nextafter`` if the cast rounded up), so
for any f32-representable input ``x``: ``x <= t  <=>  f32(x) <= t32`` — the
device decision matches the host f64 decision exactly for f32 data (the
common case; f64 inputs with sub-f32 resolution may differ at the ulp).
"""
from __future__ import annotations

from typing import List, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.common import K_ZERO_THRESHOLD

_MT_NONE, _MT_ZERO, _MT_NAN = 0, 1, 2


class EnsembleArrays(NamedTuple):
    """Stacked flat trees (device layout of ``List[Tree]``)."""
    split_feature: jax.Array    # [T, M] i32 real feature ids
    threshold: jax.Array        # [T, M] f32 (f32-down-rounded reals)
    is_cat: jax.Array           # [T, M] bool
    default_left: jax.Array     # [T, M] bool
    missing_type: jax.Array     # [T, M] i32
    left_child: jax.Array       # [T, M] i32 (~leaf encoding)
    right_child: jax.Array      # [T, M] i32
    leaf_value: jax.Array       # [T, L] f32
    has_split: jax.Array        # [T] bool
    # categorical bitsets, flattened across all trees
    cat_lo: jax.Array           # [T, M] i32 word offset into cat_words
    cat_nwords: jax.Array       # [T, M] i32
    cat_words: jax.Array        # [W] u32
    # linear trees (K=1 zero-filled when no linear trees in the slice;
    # whether to apply them is the STATIC any_linear argument of
    # predict_raw_ensemble, kept out of this pytree so jit doesn't trace it)
    leaf_const: jax.Array       # [T, L] f32
    leaf_coeff: jax.Array       # [T, L, K] f32
    leaf_feats: jax.Array       # [T, L, K] i32 (-1 = unused)


def _f32_down(t: np.ndarray) -> np.ndarray:
    """Largest f32 <= t (so f32 compares reproduce the f64 decision)."""
    t32 = t.astype(np.float32)
    up = t32.astype(np.float64) > t
    return np.where(up, np.nextafter(t32, np.float32(-np.inf)), t32)


def stack_trees(models: List) -> EnsembleArrays:
    """Stack host ``Tree`` objects into device arrays (pad to max sizes)."""
    T = len(models)
    M = max(1, max(t.num_internal for t in models))
    L = max(1, max(t.num_leaves for t in models))
    sf = np.zeros((T, M), np.int32)
    th = np.zeros((T, M), np.float32)
    ic = np.zeros((T, M), bool)
    dl = np.zeros((T, M), bool)
    mt = np.zeros((T, M), np.int32)
    lc = np.full((T, M), -1, np.int32)
    rc = np.full((T, M), -1, np.int32)
    lv = np.zeros((T, L), np.float32)
    hs = np.zeros(T, bool)
    clo = np.zeros((T, M), np.int32)
    cnw = np.zeros((T, M), np.int32)
    words: List[int] = []
    any_linear = any(getattr(t, "is_linear", False) for t in models)
    K = 1
    if any_linear:
        K = max([1] + [len(fs) for t in models if t.is_linear
                       for fs in t.leaf_features])
    const = np.zeros((T, L), np.float32)
    coeff = np.zeros((T, L, K), np.float32)
    feats = np.full((T, L, K), -1, np.int32)

    for ti, t in enumerate(models):
        m = t.num_internal if t.num_leaves > 1 else 0
        hs[ti] = t.num_leaves > 1
        if m:
            sf[ti, :m] = t.split_feature[:m]
            lc[ti, :m] = t.left_child[:m]
            rc[ti, :m] = t.right_child[:m]
            for j in range(m):
                if t.is_categorical_split(j):
                    ic[ti, j] = True
                    cidx = int(t.threshold[j])
                    lo, hi = t.cat_boundaries[cidx], t.cat_boundaries[cidx + 1]
                    clo[ti, j] = len(words)
                    cnw[ti, j] = hi - lo
                    words.extend(int(w) for w in t.cat_threshold[lo:hi])
                else:
                    th[ti, j] = _f32_down(np.float64(t.threshold[j]))
                    dl[ti, j] = t.default_left(j)
                    mt[ti, j] = t.missing_type(j)
        nl = max(1, t.num_leaves)
        lv[ti, :nl] = t.leaf_value[:nl] if len(t.leaf_value) >= nl else 0.0
        if any_linear and getattr(t, "is_linear", False):
            ncl = min(nl, len(t.leaf_const))
            const[ti, :ncl] = t.leaf_const[:ncl]
            for li in range(min(nl, len(t.leaf_features))):
                fs, cs = t.leaf_features[li], t.leaf_coeff[li]
                feats[ti, li, :len(fs)] = fs
                coeff[ti, li, :len(cs)] = cs
        elif any_linear:
            const[ti, :nl] = lv[ti, :nl]

    return EnsembleArrays(
        split_feature=jnp.asarray(sf), threshold=jnp.asarray(th),
        is_cat=jnp.asarray(ic), default_left=jnp.asarray(dl),
        missing_type=jnp.asarray(mt),
        left_child=jnp.asarray(lc), right_child=jnp.asarray(rc),
        leaf_value=jnp.asarray(lv), has_split=jnp.asarray(hs),
        cat_lo=jnp.asarray(clo), cat_nwords=jnp.asarray(cnw),
        cat_words=jnp.asarray(np.asarray(words or [0], np.uint32)),
        leaf_const=jnp.asarray(const), leaf_coeff=jnp.asarray(coeff),
        leaf_feats=jnp.asarray(feats))


def predict_leaf_raw(ens: EnsembleArrays, X: jax.Array, ti) -> jax.Array:
    """Leaf index per row of raw-valued ``X [N, F]`` for tree ``ti``."""
    n = X.shape[0]
    sf = ens.split_feature[ti]
    th = ens.threshold[ti]
    ic = ens.is_cat[ti]
    dl = ens.default_left[ti]
    mt = ens.missing_type[ti]
    lch = ens.left_child[ti]
    rch = ens.right_child[ti]
    clo = ens.cat_lo[ti]
    cnw = ens.cat_nwords[ti]
    words = ens.cat_words

    def cond(cur):
        return jnp.any(cur >= 0)

    def body(cur):
        node = jnp.maximum(cur, 0)
        feat = sf[node]
        x = jnp.take_along_axis(X, feat[:, None], axis=1)[:, 0]
        is_nan = jnp.isnan(x)
        x0 = jnp.where(is_nan, 0.0, x)
        node_mt = mt[node]
        is_miss = jnp.where(
            node_mt == _MT_ZERO,
            is_nan | (jnp.abs(x) <= K_ZERO_THRESHOLD),
            jnp.where(node_mt == _MT_NAN, is_nan, False))
        numeric = jnp.where(is_miss, dl[node], x0 <= th[node])
        # categorical bitset membership (reference Tree::CategoricalDecision)
        iv = jnp.where(jnp.isfinite(x) & (x >= 0), x, -1.0).astype(jnp.int32)
        wi = iv // 32
        in_range = (iv >= 0) & (wi < cnw[node])
        widx = jnp.clip(clo[node] + wi, 0, words.shape[0] - 1)
        bit = (words[widx] >> (iv % 32).astype(jnp.uint32)) & 1
        cat_left = in_range & (bit == 1)
        goes_left = jnp.where(ic[node], cat_left, numeric)
        nxt = jnp.where(goes_left, lch[node], rch[node])
        return jnp.where(cur >= 0, nxt, cur)

    init = jnp.where(ens.has_split[ti],
                     jnp.zeros(n, jnp.int32), jnp.full(n, -1, jnp.int32))
    final = jax.lax.while_loop(cond, body, init)
    return (~final).astype(jnp.int32)


def predict_raw_ensemble(ens: EnsembleArrays, X: jax.Array,
                         num_class: int, any_linear: bool = False) -> jax.Array:
    """Summed raw scores ``[K, N]`` over all stacked trees (trees are
    interleaved per class: tree ``t`` belongs to class ``t % K``).

    Accumulation is float32 with Kahan compensation, so the sum over trees
    carries ~1 ulp of error vs the host loop's float64 accumulation (for
    in-session models the leaf values themselves are exactly f32)."""
    T = ens.leaf_value.shape[0]
    n = X.shape[0]
    K = num_class

    def body(carry, ti):
        acc, comp = carry
        leaf = predict_leaf_raw(ens, X, ti)
        delta = ens.leaf_value[ti][leaf]
        if any_linear:
            lin = ens.leaf_const[ti][leaf]
            fs = ens.leaf_feats[ti][leaf]                    # [N, Kc]
            cs = ens.leaf_coeff[ti][leaf]                    # [N, Kc]
            used = fs >= 0
            xv = jnp.take_along_axis(X, jnp.maximum(fs, 0), axis=1)
            nan_found = jnp.any(used & jnp.isnan(xv), axis=1)
            lin = lin + jnp.sum(jnp.where(used, jnp.nan_to_num(xv) * cs, 0.0),
                                axis=1)
            delta = jnp.where(nan_found, delta, lin)
        k = ti % K
        y = delta - comp[k]
        t = acc[k] + y
        comp_k = (t - acc[k]) - y
        return (acc.at[k].set(t), comp.at[k].set(comp_k)), None

    zero = jnp.zeros((K, n), jnp.float32)
    (acc, _), _ = jax.lax.scan(body, (zero, zero),
                               jnp.arange(T, dtype=jnp.int32))
    return acc
