"""Gradient/hessian histogram construction — the hot op.

This replaces the reference's CPU histogram loops (``dense_bin.hpp:97-142``),
its col-wise/row-wise auto-tuner (``train_share_states.h``) and its three
OpenCL/CUDA kernels (``src/treelearner/ocl/histogram{16,64,256}.cl``).

TPUs have no fast scatter atomics, so the scatter-add is reformulated as a
**one-hot matmul on the MXU**: for each feature, ``hist[f] = onehotᵀ @ [g,h,m]``
where the one-hot is built per row-chunk and never materialized in HBM
(``lax.scan`` over chunks; a Pallas kernel with VMEM-resident one-hot is the
planned fast path).  An XLA scatter-add variant is kept for CPU tests and as a
fallback (``method='scatter'``).

Output layout: ``[num_features, max_bin, 3]`` float32 with channels
(sum_grad, sum_hess, count) — dense and uniform so the whole tree learner is
one compiled program (features with fewer bins simply leave the tail at zero).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Shared parity bar for every one-hot/Pallas histogram kernel vs the exact
# scatter-add (or the true-f32 XLA one-hot): the kernels accumulate a bf16
# (hi, lo) split-precision pair — or the int8 variant's multi-level
# quantized pair — whose lo-residual rounding is ~2^-18 per row; summed over ~N/B rows
# per bin this measures 1.2e-4 at 200k rows on v5e
# (scripts/debug_bf16_fence2.py).  5e-4 gives shape headroom while still
# rejecting bare-bf16 accumulation by >200x (the lo-collapse bug class
# measures ~1e-1 against a true-f32 reference).  The reference side MUST be
# true f32: _hist_onehot pins precision=HIGHEST internally — at DEFAULT TPU
# matmul precision it is itself bf16-grade (relerr 0.13 vs the exact
# scatter-add), which once masked that very bug.  Import this constant
# everywhere a kernel parity check lives (scripts/bench_dual.py,
# scripts/bench_onehot_variants.py, tests/test_dual.py,
# tests/test_onehot_variants.py) — a tolerance re-derived in one place and
# drifted in another is how the round-4 incident stayed hidden.
HIST_PARITY_TOL = 5e-4


def _pallas_interpret_default() -> bool:
    """Off-TPU the Pallas kernels run in interpret mode (pure-XLA
    emulation): the CPU tier-1 suite can parity-check every variant of the
    PRODUCTION kernels without hardware.  On TPU they lower for real."""
    return jax.default_backend() != "tpu"


def build_histogram(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                    mask: jax.Array, max_bin: int, *,
                    method: str = "onehot", chunk_rows: int = 65536,
                    f_limit: "int | None" = None,
                    variant: str = "base") -> jax.Array:
    """Dispatch over histogram kernels; see module docstring.

    method: 'pallas' (fused VMEM one-hot, TPU), 'onehot' (XLA matmul),
    'scatter' (XLA scatter-add, CPU tests).

    f_limit: only the first ``f_limit`` columns carry real bins (the grower
    packs gradient bytes into trailing columns); the pallas kernel skips the
    rest at one-hot build time, the XLA fallbacks return them as garbage for
    the caller to slice off.

    variant: one-hot build strategy for the pallas kernels (a registry name
    from ops/onehot_variants.py — lane packing, staged compare, int8 MXU,
    ...); ignored by the XLA fallbacks."""
    if method == "pallas":
        return _hist_pallas(bins, grad, hess, mask, max_bin, f_limit=f_limit,
                            variant=variant)
    return _build_histogram_xla(bins, grad, hess, mask, max_bin,
                                method=method, chunk_rows=chunk_rows)


def _build_histogram_xla(bins, grad, hess, mask, max_bin, *,
                         method="onehot", chunk_rows=65536):
    """Compute per-feature (grad, hess, count) histograms over masked rows.

    Args:
      bins: ``[N, F]`` uint8/uint16 binned features.
      grad, hess: ``[N]`` float32.
      mask: ``[N]`` float32 row weights (0.0 excludes a row; bagging uses
        fractional weights for GOSS-style scaling of the count channel too).
      max_bin: static histogram width ``B``.
      method: 'onehot' (MXU matmul) or 'scatter' (XLA scatter-add).

    Returns: ``[F, B, 3]`` float32.
    """
    if method == "scatter":
        return _hist_scatter(bins, grad, hess, mask, max_bin)
    return _hist_onehot(bins, grad, hess, mask, max_bin, chunk_rows)


def _hist_scatter(bins, grad, hess, mask, max_bin):
    n, f = bins.shape
    gh = jnp.stack([grad * mask, hess * mask, mask], axis=-1)        # [N, 3]
    # clip keeps out-of-range values (e.g. the grower's packed gh byte-columns)
    # inside their own column's space; the one-hot paths drop them by compare
    clipped = jnp.minimum(bins.astype(jnp.int32), max_bin - 1)
    flat = clipped + max_bin * jnp.arange(f, dtype=jnp.int32)[None, :]
    out = jnp.zeros((f * max_bin, 3), dtype=jnp.float32)
    vals = jnp.broadcast_to(gh[:, None, :], (n, f, 3)).reshape(n * f, 3)
    out = out.at[flat.reshape(-1)].add(vals)
    return out.reshape(f, max_bin, 3)


def _hist_onehot(bins, grad, hess, mask, max_bin, chunk_rows):
    # gh on the LEFT of the dot: [3, chunk] @ [chunk, F*B].  The tiny "3" dim
    # lands on M (MXU sublane granularity 8) instead of N (lane granularity
    # 128), which benched 2.5x faster on v5e than the [F*B, chunk] @
    # [chunk, 3] orientation (scripts/bench_hist.py).
    #
    # precision=HIGHEST: on TPU the DEFAULT matmul precision rounds f32
    # inputs to bf16 (one MXU pass), which silently degrades this "f32
    # fallback" to bare-bf16 histograms — measured relerr 0.13 vs the exact
    # scatter-add on v5e (scripts/debug_bf16_fence2.py).  This path is the
    # CPU fallback and the accuracy reference for the Pallas kernels, so it
    # must be truly f32; HIGHEST is a no-op on CPU and costs extra MXU
    # passes only where this non-hot path runs on TPU.
    n, f = bins.shape
    gh = jnp.stack([grad * mask, hess * mask, mask], axis=0).astype(jnp.float32)  # [3, N]
    chunk = min(chunk_rows, n)
    pad = (-n) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, 0), (0, pad)))
    n_chunks = (n + pad) // chunk
    bins_c = bins.reshape(n_chunks, chunk, f)
    gh_c = gh.reshape(3, n_chunks, chunk).transpose(1, 0, 2)        # [nc, 3, chunk]

    def body(acc, xs):
        b, g = xs                                   # [chunk, F], [3, chunk]
        onehot = (b.astype(jnp.int32)[:, :, None] ==
                  jnp.arange(max_bin, dtype=jnp.int32)[None, None, :])
        onehot = onehot.astype(jnp.float32).reshape(chunk, f * max_bin)
        h = jax.lax.dot_general(
            g, onehot,
            dimension_numbers=(((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)     # [3, F*B]
        return acc + h, None

    init = jnp.zeros((3, f * max_bin), dtype=jnp.float32)
    if n_chunks == 1:
        hist, _ = body(init, (bins_c[0], gh_c[0]))
    else:
        hist, _ = jax.lax.scan(body, init, (bins_c, gh_c))
    return hist.reshape(3, f, max_bin).transpose(1, 2, 0)


def _split_bf16_pair(gh: jax.Array) -> jax.Array:
    """Split-precision prep for the bf16 histogram matmuls: stack the f32
    channel rows into (hi, lo) bf16 halves with hi = bf16(x),
    lo = bf16(x - f32(hi)) so the pair carries ~16 mantissa bits.

    The rounding MUST be fenced with ``optimization_barrier``: under jit,
    XLA's excess-precision simplification rewrites ``f32(bf16(x))`` back to
    ``x`` (allowed by ``xla_allow_excess_precision``, default on), which
    collapses ``lo`` to exactly zero and silently degrades every histogram
    to bare-bf16 accuracy (relerr ~1e-2 — caught on v5e hardware by
    ``scripts/bench_dual.py``'s batched-leaf parity gate, round 4; the
    repro is ``lo == 0`` in-jit but not eagerly)."""
    hi = jax.lax.optimization_barrier(gh.astype(jnp.bfloat16))
    lo = (gh - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return jnp.concatenate([hi, lo], axis=0)


def _gh6(grad, hess, mask):
    """Channel prologue shared by the Pallas kernels: stack the three f32
    channels (g·m, h·m, m) and split each into the bf16 (hi, lo) pair."""
    gh = jnp.stack([grad * mask, hess * mask, mask], axis=0).astype(jnp.float32)
    return _split_bf16_pair(gh)


def build_histogram_leaves(comb: jax.Array, grad: jax.Array, hess: jax.Array,
                           mask: jax.Array, block_leaf: jax.Array,
                           num_slots: int, max_bin: int, *,
                           method: str = "onehot", block_rows: int = 512,
                           f_limit: "int | None" = None,
                           variant: str = "base") -> jax.Array:
    """Per-leaf histograms of leaf-grouped row blocks — the frontier grower's
    batched analog of ``build_histogram``.

    ``comb`` is ``[C, NC]`` gathered rows laid out as consecutive
    ``block_rows``-sized blocks, each block belonging to ONE leaf slot
    (``block_leaf[C // block_rows]`` i32, sorted ascending); padded rows
    carry ``mask == 0``.  Returns ``[num_slots, F, B, 3]`` where
    ``F = f_limit or NC`` on every path (both the Pallas kernel and the XLA
    fallback slice the trailing packed-gradient columns off before any
    histogramming, so neither pays for columns the caller discards).

    The Pallas path transposes the gathered rows ONCE in XLA and feeds the
    one-hot MXU kernel ``(f, BR)`` feature-major blocks, with the whole
    ``[num_slots, 6, F*Bp]`` accumulator VMEM-resident for the full grid;
    each row block accumulates into its ``block_leaf``-indexed slot row and
    the buffer flushes to HBM once (the reference GPU kernels' per-workgroup
    shared-memory accumulation, ``histogram256.cl:100``, with the slot index
    replacing the workgroup->feature-group map).  ``block_leaf`` need not be
    sorted and slots may be empty (they come back zero).
    """
    n, nc = comb.shape
    f = min(f_limit, nc) if f_limit is not None else nc
    _lanes = f * (-(-max_bin // 128) * 128)
    if method == "pallas" and _lanes <= _PALLAS_ROWMAJOR_MAX_LANES \
            and num_slots * 6 * _lanes * 4 <= _PALLAS_LEAFACC_BYTES:
        return _hist_leaves_pallas(comb, grad, hess, mask, block_leaf,
                                   num_slots, max_bin, block_rows, f,
                                   variant=variant)
    # XLA fallback: one scatter-add with the leaf slot folded into the flat
    # bin index (fast on CPU, correct everywhere).  The packed-gradient tail
    # columns are sliced off BEFORE the flat index is built: scattering them
    # too made the CPU test path pay num_slots * gh_cols * max_bin extra
    # scatter targets for garbage the caller discarded anyway.
    comb_f = comb[:, :f] if f < nc else comb
    row_leaf = jnp.repeat(block_leaf, block_rows, total_repeat_length=n)
    gh = jnp.stack([grad * mask, hess * mask, mask], axis=-1)       # [C, 3]
    clipped = jnp.minimum(comb_f.astype(jnp.int32), max_bin - 1)
    flat = (row_leaf[:, None] * (f * max_bin)
            + jnp.arange(f, dtype=jnp.int32)[None, :] * max_bin + clipped)
    out = jnp.zeros((num_slots * f * max_bin, 3), jnp.float32)
    vals = jnp.broadcast_to(gh[:, None, :], (n, f, 3)).reshape(n * f, 3)
    out = out.at[flat.reshape(-1)].add(vals)
    return out.reshape(num_slots, f, max_bin, 3)


def _hist_leaves_pallas(comb, grad, hess, mask, block_leaf, num_slots,
                        max_bin, block_rows, f, variant="base",
                        interpret=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from .onehot_variants import VARIANTS, feat_geometry, finish_hist

    spec = VARIANTS[variant]
    n, nc = comb.shape
    B = max_bin
    Bp = -(-B // 128) * 128
    BR = block_rows
    assert n % BR == 0 and BR % 128 == 0
    nb = n // BR
    if interpret is None:
        interpret = _pallas_interpret_default()

    f_pad, lanes = feat_geometry(spec, f, B, Bp)   # lane-pack group align

    rows = spec.prep(grad, hess, mask)                        # [R, C]
    # transpose ONCE in XLA (a fixed ~0.7ms u8 relayout), NOT per block in
    # the kernel: an in-kernel [BR, f].T benched ~35x slower over a full
    # pass on v5e — Mosaic lowers the small-tile transpose to lane/sublane
    # shuffles that dominate the whole kernel (measured 128ms vs 3.7ms at
    # 1M x 28 x 255, scripts/tpu_perf_suite.py round 4)
    comb_t = comb[:, :f].T                                        # [f, C] u8
    if f_pad > f:
        # padded features histogram real rows at bin 0 of their own lane
        # slot, which finish_hist's [:f] slice drops
        comb_t = jnp.pad(comb_t, ((0, f_pad - f), (0, 0)))

    # The WHOLE [num_slots, 6, f*Bp] accumulator rides one constant-index
    # output block: it stays VMEM-resident across the entire grid (k=16
    # slots x 28 feats x 256 bins f32 = 2.8MB) and flushes to HBM once.
    # This zeroes every slot up front — a slot with no row blocks is
    # well-defined zeros, not stale HBM.  The per-block accumulate routes
    # through a SLOT ONE-HOT broadcast (sel * acc) rather than any dynamic
    # index into out_ref: both dynamic-index formulations miscompiled
    # data-dependently on real v5e hardware (a [1,6,f*Bp] output block
    # keyed on bl[i], and an out_ref[pl.ds(sl,1)] += store whose 6-sublane
    # slot slabs are not (8,128)-tile aligned, each dropped the lo-half
    # bf16-residual contributions for some block_leaf patterns: relerr
    # ~1.8e-2 vs the ~3e-5 this split-precision design gives — caught twice
    # by scripts/bench_dual.py's hardware parity gate, round 4).  The
    # select costs num_slots*6*f*Bp VPU mult-adds per block and benched
    # FASTER than the aligned dynamic store on v5e.
    def kernel(bl_ref, bins_ref, gh_ref, out_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        # the one-hot build + dot live in the variant registry
        # (ops/onehot_variants.py) — ONE set of kernel bodies shared with
        # _hist_pallas and the shootout
        acc = spec.contrib(bins_ref[:], gh_ref[:],
                           fc=f_pad, B=B, Bp=Bp, BR=BR)           # [6, lanes]
        slot_id = jax.lax.broadcasted_iota(jnp.int32, (num_slots, 1, 1), 0)
        # where, not sel*acc: 0.0 * inf would leak one bad block's NaNs
        # into every slot's histogram instead of only its own
        out_ref[:] += jnp.where(slot_id == bl_ref[i], acc[None], 0.0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=[pl.BlockSpec((f_pad, BR), lambda i, bl: (0, i)),
                  pl.BlockSpec((rows.shape[0], BR), lambda i, bl: (0, i))],
        out_specs=pl.BlockSpec((num_slots, 6, lanes),
                               lambda i, bl: (0, 0, 0)),
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_slots, 6, lanes), jnp.float32),
        interpret=interpret,
    )(block_leaf.astype(jnp.int32), comb_t, rows)

    return finish_hist(out, f, B, Bp, spec)                   # [k, f, B, 3]


def unrolled_rank(sorted_vals: jax.Array, targets: jax.Array,
                  strict: bool) -> jax.Array:
    """Per-target count of entries in ``sorted_vals`` that are ``< target``
    (strict) or ``<= target``.  A statically-unrolled batched binary search:
    no while-loop sync overhead, and the probe is clamped so a span reaching
    past the array can never advance the count (the overshoot bug class)."""
    m = sorted_vals.shape[0]
    lo = jnp.zeros(targets.shape, jnp.int32)
    span = 1 << max(0, (m - 1).bit_length())
    while span >= 1:
        idx = lo + span - 1
        v = jnp.take(sorted_vals, jnp.minimum(idx, m - 1))
        cmp = (v < targets) if strict else (v <= targets)
        lo = jnp.where((idx < m) & cmp, lo + span, lo)
        span >>= 1
    return lo


_PALLAS_BLOCK_ROWS = 1024
# lane budget per feature block: FC features of Bp padded bins ride the MXU
# as one [6, BR] x [FC*Bp, BR]^T dot.  FC has an 8-sublane floor (the bins
# block is (FC, BR)), so for wide bins (Bp > 256) the lane budget alone
# cannot bound the one-hot tile — _hist_pallas also shrinks BR to keep
# FC*Bp*BR bf16 within _PALLAS_ONEHOT_BYTES of VMEM.
_PALLAS_BLOCK_LANES = 2048
# v5e VMEM is ~128MB; 8MB keeps the tile comfortably resident alongside the
# in/out blocks while letting BR (grid-step row count) stay large enough to
# amortize per-step overheads
_PALLAS_ONEHOT_BYTES = 8 * 1024 * 1024


# cap on single-feature-block kernels (the opt-in rowmajor layout and the
# batched-leaf kernel, whose bins block spans all f at once) so that the
# 128-row BR floor never busts _PALLAS_ONEHOT_BYTES:
# f*Bp*128 bf16 <= 8MiB  =>  f*Bp <= 32768
_PALLAS_ROWMAJOR_MAX_LANES = 32768

# the batched-leaf kernel keeps its whole [num_slots, 6, f*Bp] f32
# accumulator VMEM-resident for the full grid; cap it so accumulator +
# one-hot tile + I/O blocks stay well inside v5e's ~128MB VMEM
_PALLAS_LEAFACC_BYTES = 48 * 1024 * 1024


def _hist_pallas(bins, grad, hess, mask, max_bin, block_rows=None,
                 f_limit=None, layout="featmajor", variant="base",
                 interpret=None):
    """Fused histogram: Pallas TPU kernel, bf16 split-precision one-hot matmul.

    TPUs have no fast scatter atomics, so the scatter-add is a one-hot matmul
    on the MXU.  The key design point vs a naive formulation:

    - **bf16 at f32 accuracy**: the one-hot is exactly representable in bf16,
      and each f32 channel value is split into hi = bf16(x) plus
      lo = bf16(x - hi), giving ~16 mantissa bits across the pair.  The six
      rows (g_hi, h_hi, m_hi, g_lo, h_lo, m_lo) ride the SAME matmul (M <= 8
      sublanes is free) with f32 accumulation, so the whole histogram runs at
      the MXU's bf16 rate — ~4x the f32 rate — with ~1e-5 relative error.
    The default layout is **feature-major blocked**: bins are transposed
    ONCE in XLA to ``[f_pad, Npad]`` (a fixed ~0.7 ms u8 relayout at the
    bench shape) and the block is ``(FC, BR)`` — FC on sublanes
    (8-aligned), BR on lanes (128-aligned) — with grid (feature_blocks,
    row_blocks), rows minor, so each [6, FC*Bp] output block accumulates
    in VMEM while the one-hot only ever exists as a [FC*Bp, BR] tile.

    A **row-major** variant (``layout='rowmajor'``, needs ``f*Bp <= 32k``
    lanes) feeds the dataset layout straight in as ``(BR, f)`` blocks and
    transposes each tile INSIDE the kernel.  It exists to amortize the
    fixed external-transpose latency over small per-leaf segments, but on
    real v5e the in-kernel small-tile transpose lowers to lane/sublane
    shuffles that cost ~35x the whole feature-major pass at the bench
    shape (128 ms vs 3.7 ms at 1M x 28 x 255, round-4
    ``scripts/tpu_perf_suite.py``), so it is opt-in for benchmarking
    only, never picked automatically.

    The one-hot build + dot bodies live in the variant registry
    (``ops/onehot_variants.py``) — ``variant`` selects the build strategy
    (lane packing, staged compare, int8 MXU, ...); this function owns only
    the grid/BlockSpec shells and the fixed layout lessons above.

    This replaces the reference's CPU hot loop (``dense_bin.hpp:97-142``) and
    its per-workgroup local-memory GPU kernels
    (``src/treelearner/ocl/histogram256.cl:100``).
    """
    from jax.experimental import pallas as pl

    from .onehot_variants import VARIANTS, finish_hist

    spec = VARIANTS[variant]
    n, f_cols = bins.shape
    f = min(f_limit, f_cols) if f_limit is not None else f_cols
    B = max_bin
    Bp = -(-B // 128) * 128                      # lane-tile aligned bin width
    if not spec.supports(B):
        raise ValueError(
            f"hist variant {variant!r} does not support max_bin={B} "
            "(resolve the variant with onehot_variants.resolve first)")
    gf = spec.group_feats(B, Bp)                 # features per lane group
    lpf = spec.group_lanes(B, Bp) // gf          # output lanes per feature
    if interpret is None:
        interpret = _pallas_interpret_default()

    if layout not in ("featmajor", "rowmajor"):
        raise ValueError(f"unknown histogram layout {layout!r}")
    if layout == "rowmajor" and f * Bp > _PALLAS_ROWMAJOR_MAX_LANES:
        raise ValueError(
            f"layout='rowmajor' needs f*Bp <= {_PALLAS_ROWMAJOR_MAX_LANES} "
            f"lanes (got {f * Bp}); the benchmark comparison would silently "
            "run the featmajor kernel instead")
    rows = spec.prep(grad, hess, mask)           # [R, N]: bf16 pair or f32

    if layout == "rowmajor":
        # ---- row-major path: one feature block spans all features ----------
        if f % gf:
            raise ValueError(
                f"layout='rowmajor' with variant {variant!r} needs the "
                f"feature count to be a multiple of {gf} (got {f})")
        f_pad = f
        lanes = f_pad * lpf
        # BR is the bins block's sublane dim AND the gh block's lane dim, so
        # it must be a 128-multiple
        br_cap = max(128, (_PALLAS_ONEHOT_BYTES // (2 * f_pad * lpf)) // 128 * 128)
        BR = max(128, min(block_rows or _PALLAS_BLOCK_ROWS, br_cap,
                          -(-n // 128) * 128))
        pad = (-n) % BR
        if pad:
            bins = jnp.pad(bins, ((0, pad), (0, 0)))
            rows = jnp.pad(rows, ((0, 0), (0, pad)))
            # padded rows carry zero weight in every channel
        n_rb = (n + pad) // BR

        def kernel_rm(bins_ref, gh_ref, out_ref):
            @pl.when(pl.program_id(0) == 0)
            def _init():
                out_ref[:] = jnp.zeros_like(out_ref)

            # transpose the small [BR, f_cols] tile in VMEM so the one-hot
            # can be built as [f, Bp, BR] and reshaped [f*Bp, BR] by merging
            # LEADING dims (layout-free).  A [BR, f, Bp] -> [BR, f*Bp]
            # reshape would merge a non-lane-aligned dim into lanes — a
            # per-step relayout that benched ~10x slower.  Trailing f_limit
            # columns (packed gradient bytes) are dropped by the sublane
            # slice after the transpose.
            b = bins_ref[:].T[:f_pad]                         # [f_pad, BR]
            out_ref[:] += spec.contrib(b, gh_ref[:],
                                       fc=f_pad, B=B, Bp=Bp, BR=BR)

        out = pl.pallas_call(
            kernel_rm,
            out_shape=jax.ShapeDtypeStruct((6, lanes), jnp.float32),
            grid=(n_rb,),
            in_specs=[pl.BlockSpec((BR, bins.shape[1]), lambda i: (i, 0)),
                      pl.BlockSpec((rows.shape[0], BR), lambda i: (0, i))],
            out_specs=pl.BlockSpec((6, lanes), lambda i: (0, 0)),
            interpret=interpret,
        )(bins, rows)
    else:
        # ---- feature-major blocked path (wide features) --------------------
        if f < f_cols:
            bins = bins[:, :f]                   # drop packed-gradient cols
        # features per block: 8-sublane floor, lane-pack group multiple
        align = max(8, gf)
        FC = max(align, (_PALLAS_BLOCK_LANES // lpf) // align * align)
        n_fb = -(-f // FC)
        f_pad = n_fb * FC
        lanes = FC * lpf                         # output lanes per block
        # bound the VMEM-resident one-hot tile: FC*lpf*BR (2-byte worst
        # case; the int8 variant's tile is half that) <= budget
        br_cap = max(128, (_PALLAS_ONEHOT_BYTES // (2 * FC * lpf)) // 128 * 128)
        BR = max(128, min(block_rows or _PALLAS_BLOCK_ROWS, br_cap,
                          -(-n // 128) * 128))
        pad = (-n) % BR
        if pad:
            rows = jnp.pad(rows, ((0, 0), (0, pad)))
        bins_t = jnp.pad(bins.T, ((0, f_pad - f), (0, pad)))  # [f_pad, Npad]
        n_rb = (n + pad) // BR

        def kernel_fm(bins_ref, gh_ref, out_ref):
            @pl.when(pl.program_id(1) == 0)
            def _init():
                out_ref[:] = jnp.zeros_like(out_ref)

            out_ref[:] += spec.contrib(bins_ref[:], gh_ref[:],
                                       fc=FC, B=B, Bp=Bp, BR=BR)

        out = pl.pallas_call(
            kernel_fm,
            out_shape=jax.ShapeDtypeStruct((6, n_fb * lanes), jnp.float32),
            grid=(n_fb, n_rb),
            in_specs=[pl.BlockSpec((FC, BR), lambda fb, i: (fb, i)),
                      pl.BlockSpec((rows.shape[0], BR), lambda fb, i: (0, i))],
            out_specs=pl.BlockSpec((6, lanes), lambda fb, i: (0, fb)),
            interpret=interpret,
        )(bins_t, rows)

    return finish_hist(out, f, B, Bp, spec)


def gather_rows(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                mask: jax.Array, cap: int):
    """Compact the rows with ``mask > 0`` into fixed-capacity buffers.

    The TPU analog of the reference's per-leaf index ranges
    (``data_partition.hpp:21-170``): instead of histogramming all N rows with
    a mask, gather the (≤ cap) active rows so downstream cost is O(cap).
    Rows beyond ``cap`` would be silently dropped — callers must guarantee
    ``sum(mask > 0) <= cap``.

    Returns (bins[cap, F], grad[cap], hess[cap], mask[cap]).
    """
    n = bins.shape[0]
    active = mask > 0
    # scatter-free compaction: the k-th active row is the first index whose
    # running count reaches k+1 — a batched binary search over the monotone
    # cumsum.  (A scatter formulation benched 5x slower on TPU: scatters
    # serialize; jnp.searchsorted's while-loop benched ~1ms of per-step sync
    # overhead, so the search is unrolled; scripts/profile_gather.py.)
    cs = jnp.cumsum(active.astype(jnp.int32))
    targets = jnp.arange(1, cap + 1, dtype=jnp.int32)         # [cap]
    row_ids = jnp.minimum(unrolled_rank(cs, targets, strict=True), n - 1)
    filled = targets <= cs[-1]
    return (jnp.take(bins, row_ids, axis=0),
            jnp.take(grad, row_ids),
            jnp.take(hess, row_ids),
            jnp.where(filled, jnp.take(mask, row_ids), 0.0))


def subtract_histogram(parent: jax.Array, child: jax.Array) -> jax.Array:
    """Sibling histogram via subtraction (reference ``FeatureHistogram::Subtract``,
    ``feature_histogram.hpp:79``)."""
    return parent - child


def accumulate_histogram(acc: jax.Array, bins: jax.Array, grad: jax.Array,
                         hess: jax.Array, mask: jax.Array, max_bin: int, *,
                         method: str = "onehot", chunk_rows: int = 65536,
                         variant: str = "base") -> jax.Array:
    """Block-accumulating entry point: ``acc + histogram(block)``.

    The out-of-core trainer (lightgbm_tpu/stream, docs/STREAMING.md) folds
    one streamed row block into a running ``[F, B, 3]`` accumulator with
    this op — the same shape/kernels as ``build_histogram``, so the
    accumulated result feeds ``split.find_best_split`` /
    ``subtract_histogram`` unchanged, and the same structure the quantized
    histogram collectives of ROADMAP item 4 will reduce over the wire.
    Accumulation order is block-major (f32 adds reassociate vs the
    single-pass kernels — the sharded-learner noise class, ~2^-23 relative
    per add)."""
    return acc + build_histogram(bins, grad, hess, mask, max_bin,
                                 method=method, chunk_rows=chunk_rows,
                                 variant=variant)
