"""Gradient/hessian histogram construction — the hot op.

This replaces the reference's CPU histogram loops (``dense_bin.hpp:97-142``),
its col-wise/row-wise auto-tuner (``train_share_states.h``) and its three
OpenCL/CUDA kernels (``src/treelearner/ocl/histogram{16,64,256}.cl``).

TPUs have no fast scatter atomics, so the scatter-add is reformulated as a
**one-hot matmul on the MXU**: for each feature, ``hist[f] = onehotᵀ @ [g,h,m]``
where the one-hot is built per row-chunk and never materialized in HBM
(``lax.scan`` over chunks; a Pallas kernel with VMEM-resident one-hot is the
planned fast path).  An XLA scatter-add variant is kept for CPU tests and as a
fallback (``method='scatter'``).

Output layout: ``[num_features, max_bin, 3]`` float32 with channels
(sum_grad, sum_hess, count) — dense and uniform so the whole tree learner is
one compiled program (features with fewer bins simply leave the tail at zero).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def build_histogram(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                    mask: jax.Array, max_bin: int, *,
                    method: str = "onehot", chunk_rows: int = 65536) -> jax.Array:
    """Compute per-feature (grad, hess, count) histograms over masked rows.

    Args:
      bins: ``[N, F]`` uint8/uint16 binned features.
      grad, hess: ``[N]`` float32.
      mask: ``[N]`` float32 row weights (0.0 excludes a row; bagging uses
        fractional weights for GOSS-style scaling of the count channel too).
      max_bin: static histogram width ``B``.
      method: 'onehot' (MXU matmul) or 'scatter' (XLA scatter-add).

    Returns: ``[F, B, 3]`` float32.
    """
    if method == "scatter":
        return _hist_scatter(bins, grad, hess, mask, max_bin)
    return _hist_onehot(bins, grad, hess, mask, max_bin, chunk_rows)


def _hist_scatter(bins, grad, hess, mask, max_bin):
    n, f = bins.shape
    gh = jnp.stack([grad * mask, hess * mask, mask], axis=-1)        # [N, 3]
    flat = bins.astype(jnp.int32) + max_bin * jnp.arange(f, dtype=jnp.int32)[None, :]
    out = jnp.zeros((f * max_bin, 3), dtype=jnp.float32)
    vals = jnp.broadcast_to(gh[:, None, :], (n, f, 3)).reshape(n * f, 3)
    out = out.at[flat.reshape(-1)].add(vals)
    return out.reshape(f, max_bin, 3)


def _hist_onehot(bins, grad, hess, mask, max_bin, chunk_rows):
    n, f = bins.shape
    gh = jnp.stack([grad * mask, hess * mask, mask], axis=-1).astype(jnp.float32)  # [N, 3]
    chunk = min(chunk_rows, n)
    pad = (-n) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
    n_chunks = (n + pad) // chunk
    bins_c = bins.reshape(n_chunks, chunk, f)
    gh_c = gh.reshape(n_chunks, chunk, 3)

    def body(acc, xs):
        b, g = xs                                   # [chunk, F], [chunk, 3]
        onehot = (b.astype(jnp.int32)[:, :, None] ==
                  jnp.arange(max_bin, dtype=jnp.int32)[None, None, :])
        onehot = onehot.astype(jnp.float32)         # [chunk, F, B]
        # batched matmul over F: [F, B, chunk] @ [chunk, 3] -> [F, B, 3]
        h = jax.lax.dot_general(
            onehot, g,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [F, B, 3]
        return acc + h, None

    init = jnp.zeros((f, max_bin, 3), dtype=jnp.float32)
    if n_chunks == 1:
        hist, _ = body(init, (bins_c[0], gh_c[0]))
        return hist
    hist, _ = jax.lax.scan(body, init, (bins_c, gh_c))
    return hist


def subtract_histogram(parent: jax.Array, child: jax.Array) -> jax.Array:
    """Sibling histogram via subtraction (reference ``FeatureHistogram::Subtract``,
    ``feature_histogram.hpp:79``)."""
    return parent - child
