"""Gradient/hessian histogram construction — the hot op.

This replaces the reference's CPU histogram loops (``dense_bin.hpp:97-142``),
its col-wise/row-wise auto-tuner (``train_share_states.h``) and its three
OpenCL/CUDA kernels (``src/treelearner/ocl/histogram{16,64,256}.cl``).

TPUs have no fast scatter atomics, so the scatter-add is reformulated as a
**one-hot matmul on the MXU**: for each feature, ``hist[f] = onehotᵀ @ [g,h,m]``
where the one-hot is built per row-chunk and never materialized in HBM
(``lax.scan`` over chunks; a Pallas kernel with VMEM-resident one-hot is the
planned fast path).  An XLA scatter-add variant is kept for CPU tests and as a
fallback (``method='scatter'``).

Output layout: ``[num_features, max_bin, 3]`` float32 with channels
(sum_grad, sum_hess, count) — dense and uniform so the whole tree learner is
one compiled program (features with fewer bins simply leave the tail at zero).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def build_histogram(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                    mask: jax.Array, max_bin: int, *,
                    method: str = "onehot", chunk_rows: int = 65536) -> jax.Array:
    """Dispatch over histogram kernels; see module docstring.

    method: 'pallas' (fused VMEM one-hot, TPU), 'onehot' (XLA matmul),
    'scatter' (XLA scatter-add, CPU tests)."""
    if method == "pallas":
        if bins.shape[1] * max_bin <= _PALLAS_MAX_FLAT_BINS:
            return _hist_pallas(bins, grad, hess, mask, max_bin)
        method = "onehot"   # too wide for the VMEM-resident accumulator
    return _build_histogram_xla(bins, grad, hess, mask, max_bin,
                                method=method, chunk_rows=chunk_rows)


def _build_histogram_xla(bins, grad, hess, mask, max_bin, *,
                         method="onehot", chunk_rows=65536):
    """Compute per-feature (grad, hess, count) histograms over masked rows.

    Args:
      bins: ``[N, F]`` uint8/uint16 binned features.
      grad, hess: ``[N]`` float32.
      mask: ``[N]`` float32 row weights (0.0 excludes a row; bagging uses
        fractional weights for GOSS-style scaling of the count channel too).
      max_bin: static histogram width ``B``.
      method: 'onehot' (MXU matmul) or 'scatter' (XLA scatter-add).

    Returns: ``[F, B, 3]`` float32.
    """
    if method == "scatter":
        return _hist_scatter(bins, grad, hess, mask, max_bin)
    return _hist_onehot(bins, grad, hess, mask, max_bin, chunk_rows)


def _hist_scatter(bins, grad, hess, mask, max_bin):
    n, f = bins.shape
    gh = jnp.stack([grad * mask, hess * mask, mask], axis=-1)        # [N, 3]
    flat = bins.astype(jnp.int32) + max_bin * jnp.arange(f, dtype=jnp.int32)[None, :]
    out = jnp.zeros((f * max_bin, 3), dtype=jnp.float32)
    vals = jnp.broadcast_to(gh[:, None, :], (n, f, 3)).reshape(n * f, 3)
    out = out.at[flat.reshape(-1)].add(vals)
    return out.reshape(f, max_bin, 3)


def _hist_onehot(bins, grad, hess, mask, max_bin, chunk_rows):
    # gh on the LEFT of the dot: [3, chunk] @ [chunk, F*B].  The tiny "3" dim
    # lands on M (MXU sublane granularity 8) instead of N (lane granularity
    # 128), which benched 2.5x faster on v5e than the [F*B, chunk] @
    # [chunk, 3] orientation (scripts/bench_hist.py).
    n, f = bins.shape
    gh = jnp.stack([grad * mask, hess * mask, mask], axis=0).astype(jnp.float32)  # [3, N]
    chunk = min(chunk_rows, n)
    pad = (-n) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, 0), (0, pad)))
    n_chunks = (n + pad) // chunk
    bins_c = bins.reshape(n_chunks, chunk, f)
    gh_c = gh.reshape(3, n_chunks, chunk).transpose(1, 0, 2)        # [nc, 3, chunk]

    def body(acc, xs):
        b, g = xs                                   # [chunk, F], [3, chunk]
        onehot = (b.astype(jnp.int32)[:, :, None] ==
                  jnp.arange(max_bin, dtype=jnp.int32)[None, None, :])
        onehot = onehot.astype(jnp.float32).reshape(chunk, f * max_bin)
        h = jax.lax.dot_general(
            g, onehot,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [3, F*B]
        return acc + h, None

    init = jnp.zeros((3, f * max_bin), dtype=jnp.float32)
    if n_chunks == 1:
        hist, _ = body(init, (bins_c[0], gh_c[0]))
    else:
        hist, _ = jax.lax.scan(body, init, (bins_c, gh_c))
    return hist.reshape(3, f, max_bin).transpose(1, 2, 0)


def unrolled_rank(sorted_vals: jax.Array, targets: jax.Array,
                  strict: bool) -> jax.Array:
    """Per-target count of entries in ``sorted_vals`` that are ``< target``
    (strict) or ``<= target``.  A statically-unrolled batched binary search:
    no while-loop sync overhead, and the probe is clamped so a span reaching
    past the array can never advance the count (the overshoot bug class)."""
    m = sorted_vals.shape[0]
    lo = jnp.zeros(targets.shape, jnp.int32)
    span = 1 << max(0, (m - 1).bit_length())
    while span >= 1:
        idx = lo + span - 1
        v = jnp.take(sorted_vals, jnp.minimum(idx, m - 1))
        cmp = (v < targets) if strict else (v <= targets)
        lo = jnp.where((idx < m) & cmp, lo + span, lo)
        span >>= 1
    return lo


_PALLAS_BLOCK_ROWS = 512
# beyond this, the (3, F*B) VMEM-resident accumulator (plus bins + one-hot
# tiles) no longer fits the ~16MB VMEM budget — fall back to the chunked XLA
# one-hot path
_PALLAS_MAX_FLAT_BINS = 512 * 1024


def _hist_pallas(bins, grad, hess, mask, max_bin):
    """Fused one-hot histogram: Pallas TPU kernel.

    The XLA one-hot path materializes the ``[chunk, F*B]`` one-hot in HBM
    (~235MB per 8k-row pass at F=28, B=256) — pure bandwidth waste.  Here the
    one-hot lives only as VMEM tiles: each grid step loads a row block's bins
    + (g, h, m) and accumulates ``gh @ onehot`` per feature into the
    VMEM-resident output, which every grid step revisits (TPU grid is
    sequential, so the accumulation is race-free).  This is the analog of the
    reference's per-workgroup local-memory sub-histograms
    (``src/treelearner/ocl/histogram256.cl:100``) without the atomics.
    """
    from jax.experimental import pallas as pl

    n, f = bins.shape
    B = max_bin
    BR = min(_PALLAS_BLOCK_ROWS, max(8, n))
    pad = (-n) % BR
    gh = jnp.stack([grad * mask, hess * mask, mask], axis=0).astype(jnp.float32)
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, 0), (0, pad)))
        # padded bin value 0 contributes 0 weight: gh columns are zero there
    n_blocks = (n + pad) // BR

    def kernel(bins_ref, gh_ref, out_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        b = bins_ref[:].astype(jnp.int32)                     # [BR, F]
        g = gh_ref[:]                                         # [3, BR]
        iota = jax.lax.broadcasted_iota(jnp.int32, (BR, B), 1)
        for fi in range(f):                                   # static unroll
            onehot = (b[:, fi][:, None] == iota).astype(jnp.float32)
            out_ref[:, fi * B:(fi + 1) * B] += jax.lax.dot_general(
                g, onehot, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)           # [3, B]

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((3, f * B), jnp.float32),
        grid=(n_blocks,),
        in_specs=[pl.BlockSpec((BR, f), lambda i: (i, 0)),
                  pl.BlockSpec((3, BR), lambda i: (0, i))],
        out_specs=pl.BlockSpec((3, f * B), lambda i: (0, 0)),
    )(bins, gh)
    return out.reshape(3, f, B).transpose(1, 2, 0)


def gather_rows(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                mask: jax.Array, cap: int):
    """Compact the rows with ``mask > 0`` into fixed-capacity buffers.

    The TPU analog of the reference's per-leaf index ranges
    (``data_partition.hpp:21-170``): instead of histogramming all N rows with
    a mask, gather the (≤ cap) active rows so downstream cost is O(cap).
    Rows beyond ``cap`` would be silently dropped — callers must guarantee
    ``sum(mask > 0) <= cap``.

    Returns (bins[cap, F], grad[cap], hess[cap], mask[cap]).
    """
    n = bins.shape[0]
    active = mask > 0
    # scatter-free compaction: the k-th active row is the first index whose
    # running count reaches k+1 — a batched binary search over the monotone
    # cumsum.  (A scatter formulation benched 5x slower on TPU: scatters
    # serialize; jnp.searchsorted's while-loop benched ~1ms of per-step sync
    # overhead, so the search is unrolled; scripts/profile_gather.py.)
    cs = jnp.cumsum(active.astype(jnp.int32))
    targets = jnp.arange(1, cap + 1, dtype=jnp.int32)         # [cap]
    row_ids = jnp.minimum(unrolled_rank(cs, targets, strict=True), n - 1)
    filled = targets <= cs[-1]
    return (jnp.take(bins, row_ids, axis=0),
            jnp.take(grad, row_ids),
            jnp.take(hess, row_ids),
            jnp.where(filled, jnp.take(mask, row_ids), 0.0))


def subtract_histogram(parent: jax.Array, child: jax.Array) -> jax.Array:
    """Sibling histogram via subtraction (reference ``FeatureHistogram::Subtract``,
    ``feature_histogram.hpp:79``)."""
    return parent - child
