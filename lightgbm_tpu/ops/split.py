"""Best-split search over histograms.

Replaces the reference's sequential per-bin sweeps
(``FeatureHistogram::FindBestThresholdSequentially``,
``src/treelearner/feature_histogram.hpp:856-1050``) with vectorized cumulative
sums over the whole ``[F, B]`` histogram — both missing-value directions are
evaluated as two cumsum variants instead of two sequential passes.

Semantics preserved from the reference:
- leaf output / gain closed forms with L1 thresholding, L2, ``max_delta_step``
  clipping and path smoothing (``CalculateSplittedLeafOutput:743``,
  ``GetSplitGains:785``, ``GetLeafGain:826``);
- missing handling: NaN-bin or zero-bin contents are assigned to either side,
  the better direction wins, reported as ``default_left``
  (the REVERSE / NA_AS_MISSING / SKIP_DEFAULT_BIN template lattice);
- gates: ``min_data_in_leaf``, ``min_sum_hessian_in_leaf``,
  ``min_gain_to_split`` (as the ``min_gain_shift`` on parent gain);
- categorical one-hot splits (``FindBestThresholdCategoricalInner:278``
  one-hot branch; the sorted many-category scan is in the grower roadmap);
- monotone constraint (basic): candidate rejected when child outputs violate
  the feature's direction, with per-leaf output bounds applied.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


class SplitParams(NamedTuple):
    """Static gain-formula parameters (subset of Config)."""
    lambda_l1: float
    lambda_l2: float
    min_data_in_leaf: int
    min_sum_hessian_in_leaf: float
    min_gain_to_split: float
    max_delta_step: float
    path_smooth: float
    cat_smooth: float
    cat_l2: float
    max_cat_to_onehot: int
    max_cat_threshold: int = 32
    min_data_per_group: int = 100


class SplitResult(NamedTuple):
    """Best split of one leaf (the analog of ``SplitInfo``,
    ``src/treelearner/split_info.hpp:51``)."""
    gain: jax.Array          # f32 — improvement over parent (NEG_INF if none)
    feature: jax.Array       # i32 inner feature index
    threshold: jax.Array     # i32 bin threshold (<=: left); category bin for cat
    default_left: jax.Array  # bool — missing goes left
    left_sum_g: jax.Array
    left_sum_h: jax.Array
    left_count: jax.Array    # f32 (weighted count)
    right_sum_g: jax.Array
    right_sum_h: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array
    # categorical membership bitset over BIN ids ([ceil(B/32)] int32): for a
    # categorical split, bins with a set bit go LEFT (one-hot = single bit;
    # sorted many-category subsets = the elected prefix).  Zeros for numeric
    # splits.  The analog of SplitInfo::cat_threshold.
    cat_bits: jax.Array


def threshold_l1(s, l1):
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(sum_g, sum_h, p: SplitParams, parent_output=0.0, count=None,
                lo=None, hi=None):
    """Closed-form leaf output with L1/L2/max_delta_step/path smoothing and
    optional monotone bounds (reference ``CalculateSplittedLeafOutput``)."""
    raw = -threshold_l1(sum_g, p.lambda_l1) / (sum_h + p.lambda_l2 + 1e-35)
    if p.max_delta_step > 0:
        raw = jnp.clip(raw, -p.max_delta_step, p.max_delta_step)
    if p.path_smooth > 0 and count is not None:
        smooth = count / (count + p.path_smooth)
        raw = raw * smooth + parent_output * (1.0 - smooth)
    if lo is not None:
        raw = jnp.clip(raw, lo, hi)
    return raw


def leaf_gain_given_output(sum_g, sum_h, out, p: SplitParams):
    """Reference ``GetLeafGainGivenOutput``: -(2·G̃·w + (H+λ₂)·w²)."""
    g1 = threshold_l1(sum_g, p.lambda_l1)
    return -(2.0 * g1 * out + (sum_h + p.lambda_l2) * out * out)


def leaf_gain(sum_g, sum_h, p: SplitParams, parent_output=0.0, count=None,
              lo=None, hi=None):
    if p.max_delta_step > 0 or p.path_smooth > 0 or lo is not None:
        out = leaf_output(sum_g, sum_h, p, parent_output, count, lo, hi)
        return leaf_gain_given_output(sum_g, sum_h, out, p)
    g1 = threshold_l1(sum_g, p.lambda_l1)
    return g1 * g1 / (sum_h + p.lambda_l2 + 1e-35)


def _split_gain_matrix(hist, num_bins, nan_bins, is_categorical, monotone,
                       total, p: SplitParams, feature_mask,
                       parent_output, output_lo, output_hi,
                       gain_penalty=None, rand_threshold=None, contri=None):
    """Candidate gains over all (feature, threshold) pairs.

    Returns (gain_fb [F, B], use_left [F, B], cum [F, B, 3], miss [F, 3]).
    """
    f, b, _ = hist.shape
    bin_ids = jnp.arange(b, dtype=jnp.int32)[None, :]                  # [1, B]

    # --- extract "missing" bin per feature, zero it out of the sweep ---
    # NaN-missing features: the trailing NaN bin; zero-as-missing features:
    # the zero bin (mid-range in general).  Either way the bin is excluded
    # from the ordered sweep and trialed on both sides (the reference's
    # REVERSE/NA_AS_MISSING + SKIP_DEFAULT_BIN cases in one formulation).
    miss_bin = nan_bins                                                # [F]
    has_miss = miss_bin >= 0
    miss_sel = (bin_ids == miss_bin[:, None]) & has_miss[:, None]      # [F, B]
    miss = jnp.sum(jnp.where(miss_sel[:, :, None], hist, 0.0), axis=1) # [F, 3]
    swept = jnp.where(miss_sel[:, :, None], 0.0, hist)                 # [F, B, 3]

    cum = jnp.cumsum(swept, axis=1)                                    # [F, B, 3]

    # threshold t means: bins <= t go left (t in [0, num_bin-2]); when the
    # missing bin is the TRAILING bin the last real threshold drops with it,
    # but a mid-range missing bin (zero_as_missing) keeps the full range
    trailing_miss = has_miss & (miss_bin == num_bins - 1)
    valid_t = bin_ids < (num_bins[:, None] - 1 - trailing_miss[:, None])

    def eval_direction(missing_left):
        left = cum + jnp.where(missing_left, miss[:, None, :], 0.0)    # [F, B, 3]
        right = total[None, None, :] - left
        return _gain_at(left, right, total, monotone, p,
                        parent_output, output_lo, output_hi, valid_t)

    gain_r, out_r = eval_direction(False)   # missing -> right
    gain_l, out_l = eval_direction(True)    # missing -> left
    use_left = gain_l > gain_r
    num_gain = jnp.where(use_left, gain_l, gain_r)                     # [F, B]

    # --- categorical one-hot: left = (bin == k) -------------------------------
    # only for low-cardinality features (reference use_onehot dispatch,
    # feature_histogram.hpp:316); larger cardinalities use the sorted scan
    # bin 0 is the unseen/other/NaN catch-all (io/bin.py categorical layout):
    # it cannot be expressed in a category-VALUE bitset, so it is never a
    # left-set member — those rows always go right, like unseen categories
    # at predict time
    cat_left = hist                                                     # [F, B, 3]
    cat_right = total[None, None, :] - cat_left
    cat_valid = (bin_ids >= 1) & (bin_ids < num_bins[:, None]) & \
        (num_bins[:, None] <= p.max_cat_to_onehot)
    cat_gain, cat_out = _gain_at(cat_left, cat_right, total, monotone, p,
                                 parent_output, output_lo, output_hi, cat_valid,
                                 extra_l2=p.cat_l2)
    is_cat = is_categorical[:, None]
    gain_fb = jnp.where(is_cat, cat_gain, num_gain)                    # [F, B]
    if contri is not None:
        # feature_contri scales the min_gain-shifted improvement BEFORE the
        # CEGB delta-gain is subtracted (reference order: FindBestThreshold
        # applies meta_->penalty internally, feature_histogram.hpp:94, and
        # serial_tree_learner.cpp:740 subtracts CEGB after)
        pivot = leaf_gain(total[0], total[1], p, parent_output, total[2],
                          output_lo, output_hi) + p.min_gain_to_split
        gain_fb = jnp.where(gain_fb > NEG_INF / 2,
                            pivot + (gain_fb - pivot) * contri[:, None],
                            gain_fb)
    if gain_penalty is not None:
        # CEGB: per-feature penalty subtracted from the candidate gain before
        # the argmax (reference ``new_split.gain -= cegb_->DetlaGain(...)``,
        # serial_tree_learner.cpp:740-744)
        gain_fb = jnp.where(gain_fb > NEG_INF / 2,
                            gain_fb - gain_penalty[:, None], gain_fb)
    if rand_threshold is not None:
        # extra_trees: each feature offers exactly ONE random threshold
        # (reference USE_RAND specialization, feature_histogram.hpp:115-217);
        # categorical features keep the full scan like the reference
        keep = (bin_ids == rand_threshold[:, None]) | is_cat
        gain_fb = jnp.where(keep, gain_fb, NEG_INF)
    gain_fb = jnp.where(feature_mask[:, None] > 0, gain_fb, NEG_INF)
    return gain_fb, use_left, cum, miss


def cat_words(b: int) -> int:
    """Bitset words needed for ``b`` bins."""
    return max(1, -(-b // 32))


def pack_bin_bitset(member: jax.Array) -> jax.Array:
    """Pack a ``[..., B]`` membership mask into ``[..., ceil(B/32)]`` i32."""
    b = member.shape[-1]
    cw = cat_words(b)
    pad = cw * 32 - b
    if pad:
        member = jnp.pad(member, [(0, 0)] * (member.ndim - 1) + [(0, pad)])
    m = member.reshape(member.shape[:-1] + (cw, 32)).astype(jnp.uint32)
    packed = jnp.sum(m << jnp.arange(32, dtype=jnp.uint32), axis=-1,
                     dtype=jnp.uint32)
    return jax.lax.bitcast_convert_type(packed, jnp.int32)


def bitset_contains(bits: jax.Array, idx: jax.Array) -> jax.Array:
    """Test bit ``idx`` of a ``[CW]`` i32 bitset (vectorized over ``idx``)."""
    word = jnp.take(bits, idx >> 5, mode="clip")
    return ((word >> (idx & 31)) & 1) == 1


def _sorted_cat_best(hist, num_bins, is_categorical, monotone, total,
                     p: SplitParams, feature_mask, parent_output,
                     output_lo, output_hi, gain_penalty=None, contri=None):
    """Sorted many-category split scan, vectorized over features.

    Reference ``FindBestThresholdCategoricalInner`` sorted branch
    (``feature_histogram.hpp:378-474``): bins with enough data are sorted by
    ``sum_grad/(sum_hess + cat_smooth)`` and prefixes from BOTH ends (up to
    ``min(max_cat_threshold, (used+1)/2)`` categories) are candidate left
    sets, with ``min_data_per_group`` gating candidate prefixes.  One
    deviation: the reference estimates bin counts from hessians
    (``cnt_factor``); the count channel here is exact.

    Returns ``(gain [F], bits [F, CW] i32, left_sums [F, 3])`` with
    ``NEG_INF`` gain for features where the sorted scan does not apply.
    """
    f, b, _ = hist.shape
    cw = cat_words(b)
    if f == 0:
        z = jnp.zeros((0,), jnp.float32)
        return z, jnp.zeros((0, cw), jnp.int32), jnp.zeros((0, 3), jnp.float32)
    maxT = max(1, min(p.max_cat_threshold, b))
    g, h, c = hist[..., 0], hist[..., 1], hist[..., 2]
    bin_ids = jnp.arange(b, dtype=jnp.int32)[None, :]
    active = (is_categorical & (num_bins > p.max_cat_to_onehot)
              & (feature_mask > 0))                                 # [F]
    # bin 0 (unseen/other/NaN catch-all) is excluded from left-set
    # membership — see the one-hot branch in _split_gain_matrix
    elig = ((c >= p.cat_smooth) & (bin_ids >= 1)
            & (bin_ids < num_bins[:, None]))                        # [F, B]
    used_bin = jnp.sum(elig, axis=1)                                # [F]
    max_num_cat = jnp.minimum(p.max_cat_threshold, (used_bin + 1) // 2)
    score = jnp.where(elig, g / (h + p.cat_smooth), jnp.inf)
    p_eff = p._replace(lambda_l2=p.lambda_l2 + p.cat_l2)
    pen = gain_penalty if gain_penalty is not None else jnp.zeros(f, jnp.float32)
    mono = monotone

    def scan_dir(order_score):
        idx = jnp.argsort(order_score, axis=1, stable=True)         # [F, B]
        tk = lambda a: jnp.take_along_axis(jnp.where(elig, a, 0.0), idx, axis=1)
        cum_g = jnp.cumsum(tk(g), axis=1)[:, :maxT]
        cum_h = jnp.cumsum(tk(h), axis=1)[:, :maxT] + 1e-15         # kEpsilon
        cum_c = jnp.cumsum(tk(c), axis=1)[:, :maxT]
        sc_step = tk(c)[:, :maxT]

        def body(i, carry):
            cnt_grp, best_gain, best_i = carry
            lg, lh, lc = cum_g[:, i], cum_h[:, i], cum_c[:, i]
            rg, rh, rc = total[0] - lg, total[1] - lh, total[2] - lc
            cnt_grp = cnt_grp + sc_step[:, i]
            in_range = i < jnp.minimum(used_bin, max_num_cat)
            gate1 = (lc >= p.min_data_in_leaf) & (lh >= p.min_sum_hessian_in_leaf)
            nobrk = ((rc >= p.min_data_in_leaf) & (rc >= p.min_data_per_group)
                     & (rh >= p.min_sum_hessian_in_leaf))
            grp_ok = cnt_grp >= p.min_data_per_group
            considered = active & in_range & gate1 & nobrk & grp_ok
            cnt_grp = jnp.where(in_range & gate1 & nobrk & grp_ok,
                                0.0, cnt_grp)
            lo_out = leaf_output(lg, lh, p_eff, parent_output, lc,
                                 output_lo, output_hi)
            ro_out = leaf_output(rg, rh, p_eff, parent_output, rc,
                                 output_lo, output_hi)
            bad = ((mono > 0) & (lo_out > ro_out)) | ((mono < 0) & (lo_out < ro_out))
            raw = (leaf_gain(lg, lh, p_eff, parent_output, lc,
                             output_lo, output_hi)
                   + leaf_gain(rg, rh, p_eff, parent_output, rc,
                               output_lo, output_hi))
            if contri is not None:
                pivot = leaf_gain(total[0], total[1], p, parent_output,
                                  total[2], output_lo, output_hi) \
                    + p.min_gain_to_split
                raw = pivot + (raw - pivot) * contri
            gain = raw - pen
            gain = jnp.where(considered & ~bad, gain, NEG_INF)
            better = gain > best_gain
            return (cnt_grp,
                    jnp.where(better, gain, best_gain),
                    jnp.where(better, i, best_i))

        init = (jnp.zeros(f, jnp.float32), jnp.full(f, NEG_INF, jnp.float32),
                jnp.zeros(f, jnp.int32))
        _, best_gain, best_i = jax.lax.fori_loop(0, maxT, body, init)
        return best_gain, best_i, idx

    g_asc, i_asc, idx_asc = scan_dir(score)
    g_dsc, i_dsc, idx_dsc = scan_dir(jnp.where(elig, -score, jnp.inf))
    use_dsc = g_dsc > g_asc
    best_gain = jnp.where(use_dsc, g_dsc, g_asc)
    best_i = jnp.where(use_dsc, i_dsc, i_asc)
    idx = jnp.where(use_dsc[:, None], idx_dsc, idx_asc)

    memb_sorted = jnp.arange(b, dtype=jnp.int32)[None, :] <= best_i[:, None]
    memb_bins = jnp.zeros((f, b), bool).at[
        jnp.arange(f, dtype=jnp.int32)[:, None], idx].set(memb_sorted)
    bits = pack_bin_bitset(memb_bins)                               # [F, CW]
    left = jnp.sum(jnp.where(memb_bins[:, :, None], hist, 0.0), axis=1)
    return best_gain, bits, left


def per_feature_gains(hist, num_bins, nan_bins, is_categorical, monotone,
                      sum_g, sum_h, count, p: SplitParams, feature_mask,
                      parent_output=0.0, output_lo=NEG_INF, output_hi=-NEG_INF,
                      sorted_cat: bool = True, gain_mult=None,
                      contri=None) -> jax.Array:
    """Best candidate gain per feature — ``[F]``.  Used by the voting-parallel
    learner's local top-k proposal (reference ``VotingParallelTreeLearner``,
    ``voting_parallel_tree_learner.cpp:151``).  Penalty-aware: the election
    must rank features by PENALIZED gains (the reference votes on
    SplitInfo gains that already include FeatureMetainfo::penalty), else a
    muted feature could crowd the elected set."""
    total = jnp.stack([sum_g, sum_h, count]).astype(jnp.float32)
    gain_fb, _, _, _ = _split_gain_matrix(
        hist, num_bins, nan_bins, is_categorical, monotone, total, p,
        feature_mask, parent_output, output_lo, output_hi, contri=contri)
    best = jnp.max(gain_fb, axis=1)
    if sorted_cat:
        gain_sorted, _, _ = _sorted_cat_best(
            hist, num_bins, is_categorical, monotone, total, p, feature_mask,
            parent_output, output_lo, output_hi, contri=contri)
        best = jnp.maximum(best, gain_sorted)
    if gain_mult is not None:
        pivot = leaf_gain(total[0], total[1], p, parent_output, total[2],
                          output_lo, output_hi) + p.min_gain_to_split
        best = jnp.where(best > NEG_INF / 2,
                         pivot + (best - pivot) * gain_mult, best)
    return best


def find_best_split(hist: jax.Array, num_bins: jax.Array, default_bins: jax.Array,
                    nan_bins: jax.Array, is_categorical: jax.Array,
                    monotone: jax.Array, sum_g, sum_h, count,
                    p: SplitParams, feature_mask: jax.Array,
                    parent_output=0.0, output_lo=NEG_INF, output_hi=-NEG_INF,
                    gain_penalty=None, rand_threshold=None,
                    sorted_cat: bool = True, gain_mult=None,
                    contri=None) -> SplitResult:
    """Find the best split of a leaf given its histogram.

    Args:
      hist: ``[F, B, 3]`` (grad, hess, count) histogram of the leaf.
      num_bins/default_bins/nan_bins/is_categorical/monotone: ``[F]`` feature
        metadata from ``Dataset.device_data``.
      sum_g/sum_h/count: leaf totals (scalars).
      feature_mask: ``[F]`` f32/bool — column sampling / interaction constraints.
      output_lo/output_hi: monotone bounds for this leaf's subtree.
    """
    f, b, _ = hist.shape
    cw = cat_words(b)
    total = jnp.stack([sum_g, sum_h, count]).astype(jnp.float32)       # [3]
    gain_fb, use_left, cum, miss = _split_gain_matrix(
        hist, num_bins, nan_bins, is_categorical, monotone, total, p,
        feature_mask, parent_output, output_lo, output_hi, gain_penalty,
        rand_threshold, contri=contri)
    # statically no many-category feature in the dataset (sorted_cat=False):
    # the sorted scan (2 argsorts + 2 maxT-step fori_loops of tiny ops) is
    # pure per-split overhead — skip it at trace time, and trace NO
    # placeholder candidate arrays either: constant NEG_INF candidates fed
    # through argmax/where under a vmapped shard_map crash XLA:CPU's
    # sharding propagation (TileAssignment::Reshape 0-element CHECK,
    # jaxlib 0.4.37) besides being dead weight
    if sorted_cat:
        gain_sorted, bits_sorted, left_sorted = _sorted_cat_best(
            hist, num_bins, is_categorical, monotone, total, p, feature_mask,
            parent_output, output_lo, output_hi, gain_penalty,
            contri=contri)

    if gain_mult is not None:
        # monotone split penalty (ComputeMonotoneSplitGainPenalty,
        # monotone_constraints.hpp:355) scales the min_gain-shifted
        # improvement AFTER any CEGB subtraction (serial_tree_learner.cpp:
        # 745-749); rebasing around parent_gain + min_gain makes the final
        # ``best - parent - min_gain`` exactly the reference's scaled gain
        pivot = leaf_gain(total[0], total[1], p, parent_output, total[2],
                          output_lo, output_hi) + p.min_gain_to_split
        gain_fb = jnp.where(gain_fb > NEG_INF / 2,
                            pivot + (gain_fb - pivot) * gain_mult[:, None],
                            gain_fb)
        if sorted_cat:
            gain_sorted = jnp.where(
                gain_sorted > NEG_INF / 2,
                pivot + (gain_sorted - pivot) * gain_mult, gain_sorted)

    # --- argmax over (feature, threshold) ------------------------------------
    flat = gain_fb.reshape(-1)
    best_idx = jnp.argmax(flat)
    grid_gain = flat[best_idx]
    if sorted_cat:
        # sorted-subset candidates compete per feature
        sorted_f = (jnp.argmax(gain_sorted).astype(jnp.int32) if f
                    else jnp.int32(0))
        use_sorted = ((gain_sorted[sorted_f] > grid_gain) if f
                      else jnp.asarray(False))
        best_gain = jnp.where(use_sorted, gain_sorted[sorted_f], grid_gain)
        best_f = jnp.where(use_sorted, sorted_f,
                           (best_idx // b).astype(jnp.int32))
        best_t = jnp.where(use_sorted, 0, (best_idx % b).astype(jnp.int32))
    else:
        best_gain = grid_gain
        best_f = (best_idx // b).astype(jnp.int32)
        best_t = (best_idx % b).astype(jnp.int32)
    bf_cat = is_categorical[best_f]
    bf_missing_left = jnp.where(bf_cat, False, use_left[best_f, best_t])

    # categorical membership bitset: sorted prefix, or the one-hot bin's bit
    onehot_bits = pack_bin_bitset(
        jnp.arange(b, dtype=jnp.int32) == best_t)                      # [CW]
    cat_bits = jnp.where(bf_cat, onehot_bits, jnp.zeros(cw, jnp.int32))
    if sorted_cat:
        cat_bits = jnp.where(use_sorted, bits_sorted[sorted_f], cat_bits)

    # recompute chosen split's child sums
    def pick(arr):
        return arr[best_f, best_t]
    left_num = pick(cum) + jnp.where(bf_missing_left, miss[best_f], 0.0)
    left_cat = pick(hist)
    left = jnp.where(bf_cat, left_cat, left_num)
    if sorted_cat:
        left = jnp.where(use_sorted, left_sorted[sorted_f], left)
    right = total - left

    # categorical outputs use the categorical L2 (reference computes
    # CalculateSplittedLeafOutput with l2 += cat_l2 for cat splits)
    p_cat = p._replace(lambda_l2=p.lambda_l2 + p.cat_l2)

    def out_of(s):
        return jnp.where(
            bf_cat,
            leaf_output(s[0], s[1], p_cat, parent_output, s[2],
                        output_lo, output_hi),
            leaf_output(s[0], s[1], p, parent_output, s[2],
                        output_lo, output_hi))
    lo_out = out_of(left)
    hi_out = out_of(right)

    # parent gain baseline: reported gain is improvement over parent
    parent_gain = leaf_gain(total[0], total[1], p, parent_output, total[2],
                            output_lo, output_hi)
    improvement = best_gain - parent_gain - p.min_gain_to_split
    ok = improvement > 0.0
    return SplitResult(
        gain=jnp.where(ok, improvement + p.min_gain_to_split, NEG_INF),
        feature=best_f,
        threshold=best_t,
        default_left=bf_missing_left,
        left_sum_g=left[0], left_sum_h=left[1], left_count=left[2],
        right_sum_g=right[0], right_sum_h=right[1], right_count=right[2],
        left_output=lo_out, right_output=hi_out,
        cat_bits=cat_bits,
    )


def _gain_at(left, right, total, monotone, p: SplitParams,
             parent_output, output_lo, output_hi, valid, extra_l2=0.0):
    """Gain of candidate (left, right) sums [..., 3]; returns ([F,B] gain,
    ([F,B] left_out, [F,B] right_out) is folded into monotone check only)."""
    p_eff = p._replace(lambda_l2=p.lambda_l2 + extra_l2) if extra_l2 else p
    gl, hl, cl = left[..., 0], left[..., 1], left[..., 2]
    gr, hr, cr = right[..., 0], right[..., 1], right[..., 2]
    gain = (leaf_gain(gl, hl, p_eff, parent_output, cl, output_lo, output_hi) +
            leaf_gain(gr, hr, p_eff, parent_output, cr, output_lo, output_hi))
    ok = (valid
          & (cl >= p.min_data_in_leaf) & (cr >= p.min_data_in_leaf)
          & (hl >= p.min_sum_hessian_in_leaf) & (hr >= p.min_sum_hessian_in_leaf))
    mono = monotone[:, None]
    if True:  # monotone basic mode: reject direction violations
        lo = leaf_output(gl, hl, p_eff, parent_output, cl, output_lo, output_hi)
        ro = leaf_output(gr, hr, p_eff, parent_output, cr, output_lo, output_hi)
        bad = ((mono > 0) & (lo > ro)) | ((mono < 0) & (lo < ro))
        ok = ok & ~bad
    return jnp.where(ok, gain, NEG_INF), None


def voting_elect(hist, num_bins, nan_bins, is_categorical, monotone,
                 sum_g, sum_h, count, p: SplitParams, feature_mask,
                 axis_name: str, top_k: int, num_shards: int,
                 parent_output=0.0, output_lo=NEG_INF, output_hi=-NEG_INF,
                 sorted_cat: bool = True, gain_mult=None, contri=None):
    """Voting-parallel election: local top-k proposal -> global vote ->
    psum only the ELECTED feature histograms
    (``voting_parallel_tree_learner.cpp:151-345``).  Returns
    ``(hist_elected, elected_mask)`` for the caller's final
    ``find_best_split`` — shared by the sequential grower and the frontier
    grower so the election dataflow lives exactly once.

    Local gains run with min-data/hessian gates scaled to the shard
    (reference scales by 1/num_machines, ``:61-63``); the election ranks
    PENALIZED gains (gain_mult/contri) like the reference's SplitInfo vote.
    """
    import jax

    ns = max(1, num_shards)
    p_loc = p._replace(
        min_data_in_leaf=max(1, p.min_data_in_leaf // ns),
        min_sum_hessian_in_leaf=p.min_sum_hessian_in_leaf / ns)
    fg = per_feature_gains(hist, num_bins, nan_bins, is_categorical,
                           monotone, sum_g / ns, sum_h / ns, count / ns,
                           p_loc, feature_mask, parent_output, output_lo,
                           output_hi, sorted_cat=sorted_cat,
                           gain_mult=gain_mult, contri=contri)
    f_full = feature_mask.shape[0]
    kv = min(top_k, f_full)
    topv, topi = jax.lax.top_k(fg, kv)
    votes = jnp.zeros(f_full, jnp.float32).at[topi].add(
        jnp.where(topv > NEG_INF / 2, 1.0, 0.0))
    votes = jax.lax.psum(votes, axis_name)
    # elect 2k features (GlobalVoting); deterministic tie-break by index
    score = votes * (f_full + 1.0) - jnp.arange(f_full, dtype=jnp.float32)
    k2 = min(2 * kv, f_full)
    _, elected = jax.lax.top_k(score, k2)
    h_glob = jax.lax.psum(hist[elected], axis_name)
    hist_e = jnp.zeros_like(hist).at[elected].set(h_glob)
    emask = jnp.zeros(f_full, jnp.float32).at[elected].set(1.0)
    emask = jnp.where(feature_mask > 0, emask, 0.0)
    return hist_e, emask
