"""Leaf-wise (best-first) tree growth as ONE compiled XLA program.

TPU-native re-design of the reference's ``SerialTreeLearner::Train``
(``src/treelearner/serial_tree_learner.cpp:158-209``).  Semantics preserved:

- best-first growth: each step splits the active leaf with the max split gain
  (``serial_tree_learner.cpp:194-201``);
- the smaller child's histogram is computed, the larger sibling's obtained by
  subtraction (the histogram-subtraction trick, ``:306-320``);
- the left child keeps the parent's leaf id, the right child gets the next
  fresh id (the reference ``Tree::Split`` leaf-numbering convention);
- depth / min-data / min-hessian / min-gain gates;
- monotone-constraint (basic mode) output-bound propagation
  (``monotone_constraints.hpp`` BasicConstraint).

Mechanics replaced: no per-leaf index partition (``data_partition.hpp``) — a
dense ``node_assignment[num_data]`` vector and masked histogram passes keep
every shape static so the whole ``num_leaves-1`` split loop is a single
``lax.fori_loop`` compiled once; no histogram LRU pool — a dense
``[num_leaves, F, B, 3]`` store (HBM is the pool).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .histogram import build_histogram
from .split import (NEG_INF, SplitParams, SplitResult, find_best_split,
                    leaf_output, per_feature_gains)


def _reduce_split_global(s: SplitResult, axis_name: str) -> SplitResult:
    """Allreduce-max of a per-shard best split: the TPU analog of the
    reference's ``SyncUpGlobalBestSplit`` serialized-SplitInfo allreduce
    (``parallel_tree_learner.h:191-214``) — a pmax on the gain picks the
    winner, ties break to the lowest shard, and the winner's scalar payload
    is broadcast by masked psum (no byte packing needed)."""
    gain_max = jax.lax.pmax(s.gain, axis_name)
    dev = jax.lax.axis_index(axis_name)
    n_dev = jax.lax.psum(1, axis_name)
    claim = jnp.where(s.gain >= gain_max, dev, n_dev)
    winner = jax.lax.pmin(claim, axis_name)
    mine = (dev == winner)

    def bc(x):
        xf = x.astype(jnp.float32)
        out = jax.lax.psum(jnp.where(mine, xf, jnp.zeros_like(xf)), axis_name)
        return out.astype(x.dtype) if x.dtype != jnp.float32 else out

    return SplitResult(
        gain=gain_max,
        feature=bc(s.feature), threshold=bc(s.threshold),
        default_left=bc(s.default_left.astype(jnp.int32)).astype(bool),
        left_sum_g=bc(s.left_sum_g), left_sum_h=bc(s.left_sum_h),
        left_count=bc(s.left_count),
        right_sum_g=bc(s.right_sum_g), right_sum_h=bc(s.right_sum_h),
        right_count=bc(s.right_count),
        left_output=bc(s.left_output), right_output=bc(s.right_output))


class GrowerConfig(NamedTuple):
    """Static (compile-time) grower parameters."""
    num_leaves: int
    max_depth: int            # <=0: unlimited
    max_bin: int              # histogram width B
    split: SplitParams
    feature_fraction_bynode: float
    hist_method: str          # 'onehot' | 'scatter'
    hist_chunk_rows: int
    # data-parallel mesh axis: rows are sharded across this axis and the
    # reference's histogram ReduceScatter + global-sum collectives
    # (data_parallel_tree_learner.cpp:155-173, network.h:168) become a psum
    axis_name: "str | None" = None
    # parallel strategy over axis_name (SURVEY.md §2.9):
    #   'data'    — rows sharded; full-histogram psum (DataParallelTreeLearner)
    #   'feature' — features sharded, rows replicated; split search sharded,
    #               winning SplitInfo reduced (FeatureParallelTreeLearner)
    #   'voting'  — rows sharded; local top-k vote elects 2k features, only
    #               their histograms are reduced (VotingParallelTreeLearner)
    # None with axis_name set defaults to 'data'.
    parallel_mode: "str | None" = None
    top_k: int = 20               # voting: local proposals per leaf
    num_shards: int = 1           # static axis size (gates scaling in voting)


class TreeArrays(NamedTuple):
    """Flat-array tree (device layout of the reference ``Tree``, ``tree.h:25``).

    Internal node ``j`` is created at split step ``j``; child pointers encode
    leaves as ``~leaf_id`` (the reference's negative-leaf convention).
    """
    split_feature: jax.Array   # [L-1] i32, -1 = unused node
    threshold: jax.Array       # [L-1] i32 bin threshold
    default_left: jax.Array    # [L-1] bool
    is_cat_split: jax.Array    # [L-1] bool
    split_gain: jax.Array      # [L-1] f32
    left_child: jax.Array      # [L-1] i32
    right_child: jax.Array     # [L-1] i32
    leaf_value: jax.Array      # [L] f32
    leaf_count: jax.Array      # [L] f32 (weighted)
    leaf_weight: jax.Array     # [L] f32 (sum of hessians)
    internal_value: jax.Array  # [L-1] f32 (node output, for model IO / SHAP)
    internal_count: jax.Array  # [L-1] f32
    num_leaves: jax.Array      # scalar i32 (actual leaves grown)


class _BestSplits(NamedTuple):
    """Per-leaf pending best split (SoA of SplitResult over leaves)."""
    gain: jax.Array; feature: jax.Array; threshold: jax.Array
    default_left: jax.Array
    lg: jax.Array; lh: jax.Array; lc: jax.Array
    rg: jax.Array; rh: jax.Array; rc: jax.Array
    lout: jax.Array; rout: jax.Array

    @classmethod
    def empty(cls, n: int) -> "_BestSplits":
        z = jnp.zeros(n, jnp.float32)
        return cls(gain=jnp.full(n, NEG_INF, jnp.float32),
                   feature=jnp.zeros(n, jnp.int32), threshold=jnp.zeros(n, jnp.int32),
                   default_left=jnp.zeros(n, bool),
                   lg=z, lh=z, lc=z, rg=z, rh=z, rc=z, lout=z, rout=z)

    def set_leaf(self, i, s: SplitResult) -> "_BestSplits":
        return _BestSplits(
            gain=self.gain.at[i].set(s.gain),
            feature=self.feature.at[i].set(s.feature),
            threshold=self.threshold.at[i].set(s.threshold),
            default_left=self.default_left.at[i].set(s.default_left),
            lg=self.lg.at[i].set(s.left_sum_g), lh=self.lh.at[i].set(s.left_sum_h),
            lc=self.lc.at[i].set(s.left_count),
            rg=self.rg.at[i].set(s.right_sum_g), rh=self.rh.at[i].set(s.right_sum_h),
            rc=self.rc.at[i].set(s.right_count),
            lout=self.lout.at[i].set(s.left_output),
            rout=self.rout.at[i].set(s.right_output))


def grow_tree(bins: jax.Array, grad: jax.Array, hess: jax.Array,
              row_weight: jax.Array, feature_mask: jax.Array,
              num_bins: jax.Array, default_bins: jax.Array, nan_bins: jax.Array,
              is_categorical: jax.Array, monotone: jax.Array,
              key: jax.Array, cfg: GrowerConfig
              ) -> Tuple[TreeArrays, jax.Array]:
    """Grow one tree.  Returns (tree, node_assignment[num_data])."""
    n, f = bins.shape
    L = cfg.num_leaves
    B = cfg.max_bin
    p = cfg.split
    axis = cfg.axis_name
    mode = cfg.parallel_mode or ("data" if axis is not None else None)

    # --- feature-parallel bookkeeping: features sharded over the axis -------
    # metadata arrays arrive FULL-width [F_total]; the histogram axis is the
    # local shard.  Local slices feed the split search, full arrays feed the
    # partition step (which sees the globally-reduced winning feature id).
    if mode == "feature":
        dev = jax.lax.axis_index(axis)
        f_start = dev * f

        def lslice(a):
            return jax.lax.dynamic_slice_in_dim(a, f_start, f)
        num_bins_l = lslice(num_bins)
        default_bins_l = lslice(default_bins)
        nan_bins_l = lslice(nan_bins)
        is_cat_l = lslice(is_categorical)
        mono_l = lslice(monotone)
        f_full = feature_mask.shape[0]
    else:
        num_bins_l, default_bins_l, nan_bins_l = num_bins, default_bins, nan_bins
        is_cat_l, mono_l = is_categorical, monotone
        f_full = f

    def hist_of(mask):
        h = build_histogram(bins, grad, hess, mask, B,
                            method=cfg.hist_method,
                            chunk_rows=cfg.hist_chunk_rows)
        if mode == "data":
            h = jax.lax.psum(h, axis)
        return h

    def node_feature_mask(step):
        if cfg.feature_fraction_bynode >= 1.0:
            return feature_mask
        k = jax.random.fold_in(key, step)
        frac = cfg.feature_fraction_bynode
        n_take = max(1, int(frac * f_full + 0.5))
        u = jax.random.uniform(k, (f_full,))
        u = jnp.where(feature_mask > 0, u, -jnp.inf)
        thresh = jax.lax.top_k(u, n_take)[0][-1]
        return jnp.where(u >= thresh, feature_mask, 0.0)

    def find(hist, sum_g, sum_h, count, fmask, parent_output=0.0,
             lo=NEG_INF, hi=-NEG_INF):
        """Mode-dispatched best-split search (the analog of the reference's
        learner-specific FindBestSplitsFromHistograms overrides)."""
        if mode == "feature":
            fmask_l = jax.lax.dynamic_slice_in_dim(fmask, f_start, f)
            s = find_best_split(hist, num_bins_l, default_bins_l, nan_bins_l,
                                is_cat_l, mono_l, sum_g, sum_h, count, p,
                                fmask_l, parent_output, lo, hi)
            # local winner carries a shard-local feature id; globalize and
            # allreduce-max the packed SplitInfo (parallel_tree_learner.h:191)
            s = s._replace(feature=s.feature + f_start)
            return _reduce_split_global(s, axis)
        if mode == "voting":
            return _find_voting(hist, sum_g, sum_h, count, fmask,
                                parent_output, lo, hi)
        return find_best_split(hist, num_bins_l, default_bins_l, nan_bins_l,
                               is_cat_l, mono_l, sum_g, sum_h, count, p,
                               fmask, parent_output, lo, hi)

    def _find_voting(hist, sum_g, sum_h, count, fmask, parent_output, lo, hi):
        """Local top-k proposal → global vote → reduce only elected
        histograms (voting_parallel_tree_learner.cpp:151-345)."""
        # local gains with min-data/hessian gates scaled to the shard
        # (reference scales by 1/num_machines, :61-63)
        ns = max(1, cfg.num_shards)
        p_loc = p._replace(
            min_data_in_leaf=max(1, p.min_data_in_leaf // ns),
            min_sum_hessian_in_leaf=p.min_sum_hessian_in_leaf / ns)
        fg = per_feature_gains(hist, num_bins_l, nan_bins_l, is_cat_l, mono_l,
                               sum_g / ns, sum_h / ns, count / ns, p_loc,
                               fmask, parent_output, lo, hi)
        k = min(cfg.top_k, f_full)
        topv, topi = jax.lax.top_k(fg, k)
        votes = jnp.zeros(f_full, jnp.float32).at[topi].add(
            jnp.where(topv > NEG_INF / 2, 1.0, 0.0))
        votes = jax.lax.psum(votes, axis)
        # elect 2k features (GlobalVoting); deterministic tie-break by index
        score = votes * (f_full + 1.0) - jnp.arange(f_full, dtype=jnp.float32)
        k2 = min(2 * k, f_full)
        _, elected = jax.lax.top_k(score, k2)                # [2k], replicated
        h_glob = jax.lax.psum(hist[elected], axis)           # [2k, B, 3]
        hist_e = jnp.zeros_like(hist).at[elected].set(h_glob)
        emask = jnp.zeros(f_full, jnp.float32).at[elected].set(1.0)
        emask = jnp.where(fmask > 0, emask, 0.0)
        return find_best_split(hist_e, num_bins_l, default_bins_l, nan_bins_l,
                               is_cat_l, mono_l, sum_g, sum_h, count, p,
                               emask, parent_output, lo, hi)

    # ---- degenerate case: no usable features -> single-leaf tree -----------
    if f == 0:
        cnt = jnp.sum(row_weight)
        wgt = jnp.sum(hess * row_weight)
        if mode in ("data", "voting"):
            cnt = jax.lax.psum(cnt, axis)
            wgt = jax.lax.psum(wgt, axis)
        empty = TreeArrays(
            split_feature=jnp.full(L - 1, -1, jnp.int32),
            threshold=jnp.zeros(L - 1, jnp.int32),
            default_left=jnp.zeros(L - 1, bool),
            is_cat_split=jnp.zeros(L - 1, bool),
            split_gain=jnp.zeros(L - 1, jnp.float32),
            left_child=jnp.full(L - 1, -1, jnp.int32),
            right_child=jnp.full(L - 1, -1, jnp.int32),
            leaf_value=jnp.zeros(L, jnp.float32),
            leaf_count=jnp.zeros(L, jnp.float32).at[0].set(cnt),
            leaf_weight=jnp.zeros(L, jnp.float32).at[0].set(wgt),
            internal_value=jnp.zeros(L - 1, jnp.float32),
            internal_count=jnp.zeros(L - 1, jnp.float32),
            num_leaves=jnp.int32(1))
        return empty, jnp.zeros(n, jnp.int32)

    # ---- root --------------------------------------------------------------
    root_hist = hist_of(row_weight)
    tot = jnp.stack([jnp.sum(grad * row_weight), jnp.sum(hess * row_weight),
                     jnp.sum(row_weight)])
    if mode in ("data", "voting"):
        # root grad/hess sums are global (reference Allreduce,
        # data_parallel_tree_learner.cpp:126-152); feature-parallel replicates
        # rows so local sums are already global
        tot = jax.lax.psum(tot, axis)
    root_split = find(root_hist, tot[0], tot[1], tot[2], node_feature_mask(0))

    hist_store = jnp.zeros((L, f, B, 3), jnp.float32).at[0].set(root_hist)
    best = _BestSplits.empty(L).set_leaf(0, root_split)
    # depth gate for root handled trivially (max_depth >= 1 always allows root)

    state = dict(
        node_assign=jnp.zeros(n, jnp.int32),
        hist=hist_store,
        best=best,
        leaf_depth=jnp.zeros(L, jnp.int32),
        leaf_value=jnp.zeros(L, jnp.float32),
        leaf_count=jnp.zeros(L, jnp.float32).at[0].set(tot[2]),
        leaf_weight=jnp.zeros(L, jnp.float32).at[0].set(tot[1]),
        leaf_sum_g=jnp.zeros(L, jnp.float32).at[0].set(tot[0]),
        leaf_lo=jnp.full(L, NEG_INF, jnp.float32),
        leaf_hi=jnp.full(L, -NEG_INF, jnp.float32),
        leaf_parent=jnp.full(L, -1, jnp.int32),     # node that created the leaf
        leaf_is_left=jnp.zeros(L, bool),
        node_feature=jnp.full(L - 1, -1, jnp.int32),
        node_threshold=jnp.zeros(L - 1, jnp.int32),
        node_default_left=jnp.zeros(L - 1, bool),
        node_is_cat=jnp.zeros(L - 1, bool),
        node_gain=jnp.zeros(L - 1, jnp.float32),
        node_parent=jnp.full(L - 1, -1, jnp.int32),  # parent internal node
        node_is_left=jnp.zeros(L - 1, bool),
        node_value=jnp.zeros(L - 1, jnp.float32),
        node_count=jnp.zeros(L - 1, jnp.float32),
        num_leaves=jnp.int32(1),
    )

    def split_step(j, st):
        bestg = jnp.where(jnp.arange(L) < st["num_leaves"], st["best"].gain, NEG_INF)
        leaf = jnp.argmax(bestg).astype(jnp.int32)
        gain = bestg[leaf]

        def do_split(st):
            b = st["best"]
            feat = b.feature[leaf]
            thr = b.threshold[leaf]
            dleft = b.default_left[leaf]
            f_is_cat = is_categorical[feat]
            new_id = st["num_leaves"]

            # --- update node arrays + parent linkage ---
            parent_node = st["leaf_parent"][leaf]
            st_nf = st["node_feature"].at[j].set(feat)
            st_nt = st["node_threshold"].at[j].set(thr)
            st_nd = st["node_default_left"].at[j].set(dleft)
            st_nc = st["node_is_cat"].at[j].set(f_is_cat)
            st_ng = st["node_gain"].at[j].set(gain)
            st_np = st["node_parent"].at[j].set(parent_node)
            st_nl = st["node_is_left"].at[j].set(st["leaf_is_left"][leaf])
            st_nv = st["node_value"].at[j].set(leaf_output(
                st["leaf_sum_g"][leaf], st["leaf_weight"][leaf], p,
                0.0, st["leaf_count"][leaf]))
            st_ncount = st["node_count"].at[j].set(st["leaf_count"][leaf])

            # --- partition rows of this leaf ---
            if mode == "feature":
                # only the shard owning the winning feature can decide; it
                # broadcasts the decision (the reference avoids this because
                # every rank holds every column — here columns are sharded,
                # so one [n] psum replaces replicated column storage)
                local_ix = jnp.clip(feat - f_start, 0, f - 1)
                owns = (feat >= f_start) & (feat < f_start + f)
                col = jnp.take(bins, local_ix, axis=1).astype(jnp.int32)
            else:
                col = jnp.take(bins, feat, axis=1).astype(jnp.int32)
            is_miss = (col == nan_bins[feat]) & (nan_bins[feat] >= 0)
            goes_left = jnp.where(
                f_is_cat, col == thr,
                jnp.where(is_miss, dleft, col <= thr))
            if mode == "feature":
                goes_left = jax.lax.psum(
                    jnp.where(owns, goes_left.astype(jnp.float32), 0.0),
                    axis) > 0.5
            in_leaf = st["node_assign"] == leaf
            node_assign = jnp.where(in_leaf & ~goes_left, new_id, st["node_assign"])

            # --- child histograms: compute smaller, subtract for larger ---
            left_smaller = b.lc[leaf] <= b.rc[leaf]
            small_mask = jnp.where(in_leaf & (goes_left == left_smaller),
                                   row_weight, 0.0)
            small_hist = hist_of(small_mask)
            parent_hist = st["hist"][leaf]
            large_hist = parent_hist - small_hist
            lhist = jnp.where(left_smaller, small_hist, large_hist)
            rhist = parent_hist - lhist
            hist = st["hist"].at[leaf].set(lhist).at[new_id].set(rhist)

            # --- child bookkeeping ---
            depth = st["leaf_depth"][leaf] + 1
            leaf_depth = st["leaf_depth"].at[leaf].set(depth).at[new_id].set(depth)
            leaf_value = st["leaf_value"].at[leaf].set(b.lout[leaf]).at[new_id].set(b.rout[leaf])
            leaf_count = st["leaf_count"].at[leaf].set(b.lc[leaf]).at[new_id].set(b.rc[leaf])
            leaf_weight = st["leaf_weight"].at[leaf].set(b.lh[leaf]).at[new_id].set(b.rh[leaf])
            leaf_sum_g = st["leaf_sum_g"].at[leaf].set(b.lg[leaf]).at[new_id].set(b.rg[leaf])
            leaf_parent = st["leaf_parent"].at[leaf].set(j).at[new_id].set(j)
            leaf_is_left = st["leaf_is_left"].at[leaf].set(True).at[new_id].set(False)

            # monotone (basic): children inherit bounds; split on a monotone
            # feature pinches them at the midpoint of the child outputs
            mono = monotone[feat]
            lo, hi = st["leaf_lo"][leaf], st["leaf_hi"][leaf]
            mid = (b.lout[leaf] + b.rout[leaf]) * 0.5
            l_lo = jnp.where(mono < 0, jnp.maximum(lo, mid), lo)
            l_hi = jnp.where(mono > 0, jnp.minimum(hi, mid), hi)
            r_lo = jnp.where(mono > 0, jnp.maximum(lo, mid), lo)
            r_hi = jnp.where(mono < 0, jnp.minimum(hi, mid), hi)
            leaf_lo = st["leaf_lo"].at[leaf].set(l_lo).at[new_id].set(r_lo)
            leaf_hi = st["leaf_hi"].at[leaf].set(l_hi).at[new_id].set(r_hi)

            # --- new best splits for both children ---
            fmask = node_feature_mask(j + 1)
            depth_ok = (cfg.max_depth <= 0) | (depth < cfg.max_depth)

            def child_best(hist_c, g, h, c, lo_, hi_):
                s = find(hist_c, g, h, c, fmask, 0.0, lo_, hi_)
                return s._replace(gain=jnp.where(depth_ok, s.gain, NEG_INF))

            sl = child_best(lhist, b.lg[leaf], b.lh[leaf], b.lc[leaf], l_lo, l_hi)
            sr = child_best(rhist, b.rg[leaf], b.rh[leaf], b.rc[leaf], r_lo, r_hi)
            best = st["best"].set_leaf(leaf, sl).set_leaf(new_id, sr)

            return dict(
                node_assign=node_assign, hist=hist, best=best,
                leaf_depth=leaf_depth, leaf_value=leaf_value,
                leaf_count=leaf_count, leaf_weight=leaf_weight,
                leaf_sum_g=leaf_sum_g, leaf_lo=leaf_lo, leaf_hi=leaf_hi,
                leaf_parent=leaf_parent, leaf_is_left=leaf_is_left,
                node_feature=st_nf, node_threshold=st_nt,
                node_default_left=st_nd, node_is_cat=st_nc, node_gain=st_ng,
                node_parent=st_np, node_is_left=st_nl, node_value=st_nv,
                node_count=st_ncount,
                num_leaves=st["num_leaves"] + 1,
            )

        return jax.lax.cond(gain > 0.0, do_split, lambda s: s, st)

    state = jax.lax.fori_loop(0, L - 1, split_step, state)

    # ---- reconstruct child pointers ----------------------------------------
    # node j's children: initially leaves (~leaf ids); later splits of those
    # leaves overwrite with internal node ids.
    left_child = jnp.full(L - 1, -1, jnp.int32)
    right_child = jnp.full(L - 1, -1, jnp.int32)

    def scatter_claims(child, idx, cond, val):
        # route non-claiming writes out of bounds so they are dropped —
        # each (node, side) slot has exactly one final claimant
        return child.at[jnp.where(cond, idx, L)].set(val, mode="drop")

    # leaves claim the slot of their creating node
    leaf_ids = jnp.arange(L, dtype=jnp.int32)
    lp = state["leaf_parent"]
    valid_leaf = lp >= 0
    left_child = scatter_claims(left_child, lp, valid_leaf & state["leaf_is_left"], ~leaf_ids)
    right_child = scatter_claims(right_child, lp, valid_leaf & ~state["leaf_is_left"], ~leaf_ids)
    # internal nodes overwrite the slot they were grown from
    node_ids = jnp.arange(L - 1, dtype=jnp.int32)
    npar = state["node_parent"]
    valid_node = (npar >= 0) & (state["node_feature"] >= 0)
    left_child = scatter_claims(left_child, npar, valid_node & state["node_is_left"], node_ids)
    right_child = scatter_claims(right_child, npar, valid_node & ~state["node_is_left"], node_ids)

    tree = TreeArrays(
        split_feature=state["node_feature"],
        threshold=state["node_threshold"],
        default_left=state["node_default_left"],
        is_cat_split=state["node_is_cat"],
        split_gain=state["node_gain"],
        left_child=left_child,
        right_child=right_child,
        leaf_value=state["leaf_value"],
        leaf_count=state["leaf_count"],
        leaf_weight=state["leaf_weight"],
        internal_value=state["node_value"],
        internal_count=state["node_count"],
        num_leaves=state["num_leaves"],
    )
    return tree, state["node_assign"]
