"""Leaf-wise (best-first) tree growth as ONE compiled XLA program.

TPU-native re-design of the reference's ``SerialTreeLearner::Train``
(``src/treelearner/serial_tree_learner.cpp:158-209``).  Semantics preserved:

- best-first growth: each step splits the active leaf with the max split gain
  (``serial_tree_learner.cpp:194-201``);
- the smaller child's histogram is computed, the larger sibling's obtained by
  subtraction (the histogram-subtraction trick, ``:306-320``);
- the left child keeps the parent's leaf id, the right child gets the next
  fresh id (the reference ``Tree::Split`` leaf-numbering convention);
- depth / min-data / min-hessian / min-gain gates;
- monotone-constraint (basic mode) output-bound propagation
  (``monotone_constraints.hpp`` BasicConstraint).

Mechanics replaced: no per-leaf index partition (``data_partition.hpp``) — a
dense ``node_assignment[num_data]`` vector and masked histogram passes keep
every shape static so the whole ``num_leaves-1`` split loop is a single
``lax.fori_loop`` compiled once; no histogram LRU pool — a dense
``[num_leaves, F, B, 3]`` store (HBM is the pool).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .histogram import build_histogram, gather_rows, unrolled_rank
from .split import (NEG_INF, SplitParams, SplitResult, bitset_contains,
                    cat_words, find_best_split, leaf_gain, leaf_output,
                    pack_bin_bitset, per_feature_gains)


def _reduce_split_global(s: SplitResult, axis_name: str) -> SplitResult:
    """Allreduce-max of a per-shard best split: the TPU analog of the
    reference's ``SyncUpGlobalBestSplit`` serialized-SplitInfo allreduce
    (``parallel_tree_learner.h:191-214``) — a pmax on the gain picks the
    winner, ties break to the lowest shard, and the winner's scalar payload
    is broadcast by masked psum (no byte packing needed)."""
    gain_max = jax.lax.pmax(s.gain, axis_name)
    dev = jax.lax.axis_index(axis_name)
    n_dev = jax.lax.psum(1, axis_name)
    claim = jnp.where(s.gain >= gain_max, dev, n_dev)
    winner = jax.lax.pmin(claim, axis_name)
    mine = (dev == winner)

    def bc(x):
        if jnp.issubdtype(x.dtype, jnp.integer) or x.dtype == bool:
            # integer payloads (ids, bitsets) ride an exact integer psum —
            # a float cast would corrupt bitset words above 2^24
            xi = x.astype(jnp.int32)
            out = jax.lax.psum(jnp.where(mine, xi, jnp.zeros_like(xi)),
                               axis_name)
            return out.astype(x.dtype)
        xf = x.astype(jnp.float32)
        out = jax.lax.psum(jnp.where(mine, xf, jnp.zeros_like(xf)), axis_name)
        return out.astype(x.dtype) if x.dtype != jnp.float32 else out

    return SplitResult(
        gain=gain_max,
        feature=bc(s.feature), threshold=bc(s.threshold),
        default_left=bc(s.default_left),
        left_sum_g=bc(s.left_sum_g), left_sum_h=bc(s.left_sum_h),
        left_count=bc(s.left_count),
        right_sum_g=bc(s.right_sum_g), right_sum_h=bc(s.right_sum_h),
        right_count=bc(s.right_count),
        left_output=bc(s.left_output), right_output=bc(s.right_output),
        cat_bits=bc(s.cat_bits))


def _rect_comparability(rect_lo, rect_hi, c_lo_row, c_hi_row, mono_f):
    """Monotone comparability masks of every leaf rect vs one child rect.

    Two leaves are comparable along monotone dim k when their rects overlap
    in every other dim and are strictly ordered along k (in an axis-aligned
    partition, all-but-k overlap implies strict k-ordering).  Returns
    ``(upper, lower)`` ``[L, F]`` masks: ``upper[m, k]`` — leaf m sits on
    the child's greater side along k (so ``out_child <= out_m``),
    ``lower`` mirrored."""
    ovl_d = ((rect_lo <= c_hi_row[None, :])
             & (rect_hi >= c_lo_row[None, :]))               # [L, F]
    miss_cnt = jnp.sum(~ovl_d, axis=1)                       # [L]
    # overlap in all dims except k: no misses, or the only miss is k itself
    ovl_exc = ((miss_cnt == 0)[:, None]
               | ((miss_cnt == 1)[:, None] & ~ovl_d))        # [L, F]
    m_right = rect_lo > c_hi_row[None, :]                    # [L, F]
    m_left = rect_hi < c_lo_row[None, :]
    upper = ovl_exc & (((mono_f > 0)[None, :] & m_right)
                       | ((mono_f < 0)[None, :] & m_left))
    lower = ovl_exc & (((mono_f > 0)[None, :] & m_left)
                       | ((mono_f < 0)[None, :] & m_right))
    return upper, lower


class GrowerConfig(NamedTuple):
    """Static (compile-time) grower parameters."""
    num_leaves: int
    max_depth: int            # <=0: unlimited
    max_bin: int              # histogram width B
    split: SplitParams
    feature_fraction_bynode: float
    hist_method: str          # 'pallas' (TPU) | 'onehot' | 'scatter'
    hist_chunk_rows: int
    # one-hot build strategy for the pallas kernels: a registry name from
    # ops/onehot_variants.py (resolved from the user-facing
    # ``hist_variant`` param — 'auto' is resolved to a concrete name by a
    # one-time cached on-device micro-bench BEFORE this config is built, so
    # the compiled tree program never retraces over it)
    hist_variant: str = "base"
    # data-parallel mesh axis: rows are sharded across this axis and the
    # reference's histogram ReduceScatter + global-sum collectives
    # (data_parallel_tree_learner.cpp:155-173, network.h:168) become a psum
    axis_name: "str | None" = None
    # parallel strategy over axis_name (SURVEY.md §2.9):
    #   'data'    — rows sharded; full-histogram psum (DataParallelTreeLearner)
    #   'feature' — features sharded, rows replicated; split search sharded,
    #               winning SplitInfo reduced (FeatureParallelTreeLearner)
    #   'voting'  — rows sharded; local top-k vote elects 2k features, only
    #               their histograms are reduced (VotingParallelTreeLearner)
    # None with axis_name set defaults to 'data'.
    parallel_mode: "str | None" = None
    top_k: int = 20               # voting: local proposals per leaf
    num_shards: int = 1           # static axis size (gates scaling in voting)
    # CEGB (cost_effective_gradient_boosting.hpp): per-split penalty scaled by
    # leaf row count, pre-multiplied by cegb_tradeoff
    cegb_split_penalty: float = 0.0
    # adaptive leaf compaction (see Config.hist_compact): gather the smaller
    # sibling's rows into the tightest power-of-4 bucket before histogramming
    hist_compact: bool = True
    hist_compact_min_cap: int = 8192
    # capacity-ladder growth factor: smaller factors shrink the average
    # bucket round-up waste (expected waste ~ (ladder-1)/2 of every gathered
    # segment) at the cost of more switch branches to compile; fractional
    # values are allowed (caps round up to 1024-multiples)
    hist_compact_ladder: float = 2
    # extremely-randomized trees: one random threshold per feature per node
    # (reference USE_RAND, feature_histogram.hpp:115-217)
    extra_trees: bool = False
    # static: dataset has a many-category feature (num_bins > max_cat_to_onehot)
    # — when False the sorted-categorical scan is skipped at trace time,
    # removing ~128 sequential tiny ops + 4 argsorts from every split step
    sorted_cat: bool = True
    extra_seed: int = 0       # extra-trees threshold stream (Config::extra_seed)
    # depth-scaled gain penalty for splits on monotone features
    # (reference ComputeMonotoneSplitGainPenalty)
    monotone_penalty: float = 0.0
    # EFB (io/efb.py): histogram width of the BUNDLE columns the kernel sees;
    # 0 = bins are plain per-feature columns.  Feature-space histograms of
    # width max_bin are expanded from bundle space before each split search.
    bundle_bins: int = 0
    # monotone constraint mode (reference monotone_constraints.hpp):
    # 'basic' pinches child output bounds at the midpoint;
    # 'intermediate' bounds children with the ACTUAL sibling outputs and
    # propagates to overlapping leaves (see apply_split), re-validating each
    # chosen split against current bounds at apply time.  Only takes effect
    # when has_monotone is True (static, so unconstrained models trace none
    # of the machinery).
    monotone_mode: str = "basic"
    has_monotone: bool = False
    # round-batched best-first growth (ops/frontier.py): 'auto' takes the
    # frontier grower whenever the feature set allows (see
    # _frontier_eligible), 'serial' forces the one-split-at-a-time loop,
    # 'frontier' asks for batching and warns+falls back when ineligible
    grower_mode: str = "auto"
    frontier_k: int = 16          # leaves expanded per round
    frontier_block_rows: int = 512  # rows per kernel block (128-multiple)


class TreeArrays(NamedTuple):
    """Flat-array tree (device layout of the reference ``Tree``, ``tree.h:25``).

    Internal node ``j`` is created at split step ``j``; child pointers encode
    leaves as ``~leaf_id`` (the reference's negative-leaf convention).
    """
    split_feature: jax.Array   # [L-1] i32, -1 = unused node
    threshold: jax.Array       # [L-1] i32 bin threshold
    default_left: jax.Array    # [L-1] bool
    is_cat_split: jax.Array    # [L-1] bool
    cat_bits: jax.Array        # [L-1, CW] i32 bin-bitset for cat splits
    split_gain: jax.Array      # [L-1] f32
    left_child: jax.Array      # [L-1] i32
    right_child: jax.Array     # [L-1] i32
    leaf_value: jax.Array      # [L] f32
    leaf_count: jax.Array      # [L] f32 (weighted)
    leaf_weight: jax.Array     # [L] f32 (sum of hessians)
    internal_value: jax.Array  # [L-1] f32 (node output, for model IO / SHAP)
    internal_count: jax.Array  # [L-1] f32
    num_leaves: jax.Array      # scalar i32 (actual leaves grown)


class _BestSplits(NamedTuple):
    """Per-leaf pending best split (SoA of SplitResult over leaves)."""
    gain: jax.Array; feature: jax.Array; threshold: jax.Array
    default_left: jax.Array
    lg: jax.Array; lh: jax.Array; lc: jax.Array
    rg: jax.Array; rh: jax.Array; rc: jax.Array
    lout: jax.Array; rout: jax.Array
    cat_bits: jax.Array       # [n, CW] i32

    @classmethod
    def empty(cls, n: int, cw: int) -> "_BestSplits":
        z = jnp.zeros(n, jnp.float32)
        return cls(gain=jnp.full(n, NEG_INF, jnp.float32),
                   feature=jnp.zeros(n, jnp.int32), threshold=jnp.zeros(n, jnp.int32),
                   default_left=jnp.zeros(n, bool),
                   lg=z, lh=z, lc=z, rg=z, rh=z, rc=z, lout=z, rout=z,
                   cat_bits=jnp.zeros((n, cw), jnp.int32))

    def set_leaf(self, i, s: SplitResult, ok=None) -> "_BestSplits":
        def u(arr, v):
            if ok is None:
                return arr.at[i].set(v)
            return arr.at[i].set(jnp.where(ok, v, arr[i]))
        return _BestSplits(
            gain=u(self.gain, s.gain),
            feature=u(self.feature, s.feature),
            threshold=u(self.threshold, s.threshold),
            default_left=u(self.default_left, s.default_left),
            lg=u(self.lg, s.left_sum_g), lh=u(self.lh, s.left_sum_h),
            lc=u(self.lc, s.left_count),
            rg=u(self.rg, s.right_sum_g), rh=u(self.rh, s.right_sum_h),
            rc=u(self.rc, s.right_count),
            lout=u(self.lout, s.left_output),
            rout=u(self.rout, s.right_output),
            cat_bits=u(self.cat_bits, s.cat_bits))


def node_feature_mask_for(key, step, feature_mask, frac: float):
    """Per-node feature subset (reference ``col_sampler.hpp:91`` GetByNode):
    keep ``max(1, round(frac * n_allowed))`` of the still-allowed
    (bytree-selected) features — the fraction applies to the ALLOWED count,
    not the full width — keyed by ``fold_in(key, step)``.  ONE
    implementation shared by the sequential grower (step = split index) and
    the frontier grower (step = split-record index) so their streams cannot
    silently desynchronize in structure."""
    k = jax.random.fold_in(key, step)
    f_full = feature_mask.shape[0]
    allowed = feature_mask > 0
    # the fraction applies to the STILL-ALLOWED (bytree-selected) subset,
    # not the full feature count (col_sampler.hpp:94 draws from
    # used_feature_indices_): sizing from f_full made bynode a silent
    # no-op whenever feature_fraction < 1 already thinned the mask
    n_allowed = jnp.sum(allowed.astype(jnp.int32))
    n_take = jnp.clip(
        jnp.floor(frac * n_allowed.astype(jnp.float32) + 0.5).astype(
            jnp.int32), 1, f_full)
    u = jax.random.uniform(k, (f_full,))
    u = jnp.where(allowed, u, -jnp.inf)
    thresh = jax.lax.top_k(u, f_full)[0][n_take - 1]
    return jnp.where(u >= thresh, feature_mask, 0.0)


def rand_thresholds_for(key, step, extra_seed: int, num_bins, nan_bins):
    """extra_trees: one random valid numeric threshold per feature
    (reference ExtremelyRandomizedTrees path).  ``extra_seed`` decorrelates
    the stream from every other seeded draw (Config::extra_seed); a
    TRAILING missing bin removes the last real threshold (must stay in sync
    with split.py's valid_t).  Shared by both growers like
    ``node_feature_mask_for``."""
    k = jax.random.fold_in(
        jax.random.fold_in(jax.random.fold_in(key, 7919), step), extra_seed)
    hi = jnp.maximum(num_bins - 2 - (nan_bins == num_bins - 1), 0)
    u = jax.random.uniform(k, (num_bins.shape[0],))
    return jnp.floor(u * (hi + 1).astype(jnp.float32)).astype(jnp.int32)


def monotone_gain_mult(depth, monotone, pen: float):
    """[F] monotone-split gain penalty factor at a leaf of ``depth``
    (reference ``ComputeMonotoneSplitGainPenalty``,
    monotone_constraints.hpp:355-364).  ONE implementation shared by the
    sequential grower (closure ``gain_mult_for``) and the frontier grower
    so the two streams cannot drift."""
    d = jnp.asarray(depth, jnp.float32)
    factor = jnp.where(
        pen >= d + 1.0, 1e-15,
        jnp.where(pen <= 1.0, 1.0 - pen / jnp.exp2(d),
                  1.0 - jnp.exp2(pen - 1.0 - d)) + 1e-15)
    return jnp.where(monotone != 0, factor, 1.0)


def _frontier_eligible(cfg: "GrowerConfig", n_cols: int, interaction_sets,
                       cegb_coupled, cegb_lazy, forced,
                       efb=None) -> bool:
    """True when the round-batched frontier grower (ops/frontier.py) can
    serve this call.  Cross-leaf-coupled features (monotone intermediate/
    advanced bounds, CEGB refunds, interaction branch masks, forced-split
    prefixes) depend on the sequential split order and take the one-split
    loop; per-node RNG features (feature_fraction_bynode, extra_trees) are
    served by the frontier with a split-record-keyed stream, and
    monotone-BASIC is served natively: its output bounds pinch at the
    midpoint down the root path, which is exactly the per-leaf state the
    frontier already tracks (no cross-leaf propagation to order against)."""
    if cfg.grower_mode == "serial":
        return False
    mode = cfg.parallel_mode or ("data" if cfg.axis_name is not None else None)
    ok = ((not cfg.has_monotone or cfg.monotone_mode == "basic")
          and interaction_sets is None
          and cegb_coupled is None and cegb_lazy is None
          and not forced
          and cfg.cegb_split_penalty == 0.0
          and mode in (None, "data", "feature", "voting")
          and (efb is None or mode in (None, "data")))
    if ok and cfg.hist_method == "pallas":
        # the batched-leaf kernel's bins block spans all features at once
        # (single feature block); very wide feature sets exceed its lane
        # budget
        from .histogram import _PALLAS_ROWMAJOR_MAX_LANES
        bb = cfg.bundle_bins or cfg.max_bin
        ok = n_cols * (-(-bb // 128) * 128) <= _PALLAS_ROWMAJOR_MAX_LANES
    if not ok and cfg.grower_mode == "frontier":
        from ..utils.log import Log
        Log.warning("tree_grower=frontier is not compatible with the "
                    "requested features; using the serial grower")
    return ok


def grow_tree(bins: jax.Array, grad: jax.Array, hess: jax.Array,
              row_weight: jax.Array, feature_mask: jax.Array,
              num_bins: jax.Array, default_bins: jax.Array, nan_bins: jax.Array,
              is_categorical: jax.Array, monotone: jax.Array,
              key: jax.Array, cfg: GrowerConfig,
              interaction_sets: "jax.Array | None" = None,
              cegb_coupled: "jax.Array | None" = None,
              cegb_lazy: "jax.Array | None" = None,
              cegb_used_data: "jax.Array | None" = None,
              forced: "Tuple[Tuple[int, int, int], ...]" = (),
              efb: "tuple | None" = None,
              feature_contri: "jax.Array | None" = None,
              ) -> Tuple[TreeArrays, jax.Array]:
    """Grow one tree.  Returns (tree, node_assignment[num_data]).

    Optional feature-gating state:
      interaction_sets: ``[C, F]`` 0/1 — each row one interaction-constraint
        group; a leaf may only split on features in some group containing all
        its branch features (``col_sampler.hpp:91`` ``GetByNode``).
      cegb_coupled: ``[F]`` tradeoff×coupled-penalty, already zeroed for
        features used by earlier trees (``cegb_penalty_feature_coupled``).
      cegb_lazy: ``[F]`` tradeoff×lazy-penalty (``cegb_penalty_feature_lazy``).
      cegb_used_data: ``[N, F]`` bool — rows×features already "paid for" by
        earlier trees (the reference's ``feature_used_in_data_`` bitset).
      forced: static BFS-ordered forced splits as (side, inner_feature,
        threshold_bin, parent_forced_idx) tuples
        (``SerialTreeLearner::ForceSplits``, serial_tree_learner.cpp:450-562);
        ``side`` is 0 for the root/left child of the parent forced split and
        1 for its right child — target leaf ids are resolved at runtime so
        a forced split that fails its validity gates (skipped, as the
        reference erases negative-gain forced splits from forceSplitMap)
        does not shift later forced splits' leaf numbering.
      efb: static ``(feat_bundle [F], feat_off [F], num_bins [F])`` numpy
        arrays when ``bins`` is an EFB bundle matrix (io/efb.py): histograms
        are built and stored in bundle space and expanded to feature space
        for each split search; the split column decodes through the uniform
        ``col - off + 1`` mapping (identity for singleton bundles).
    """
    if _frontier_eligible(cfg, bins.shape[1], interaction_sets,
                          cegb_coupled, cegb_lazy, forced, efb):
        from .frontier import grow_tree_frontier
        return grow_tree_frontier(bins, grad, hess, row_weight, feature_mask,
                                  num_bins, default_bins, nan_bins,
                                  is_categorical, monotone, key, cfg,
                                  efb=efb, feature_contri=feature_contri)
    n, n_cols = bins.shape
    if efb is not None:
        efb_bundle_np, efb_off_np, efb_nb_np = efb
        f = int(efb_bundle_np.shape[0])
        if cfg.parallel_mode in ("feature", "voting"):
            raise NotImplementedError(
                "EFB is not supported with feature/voting parallel learners")
    else:
        f = n_cols
    L = cfg.num_leaves
    B = cfg.max_bin                    # feature-space histogram width
    Bb = cfg.bundle_bins or B          # kernel (bundle-column) width
    cw = cat_words(B)
    p = cfg.split
    axis = cfg.axis_name
    mode = cfg.parallel_mode or ("data" if axis is not None else None)

    # ---- EFB decode tables (identity when efb is None) ---------------------
    # split-column mapping: feature bin = col - off + 1 when
    # off <= col < off + (nb-1), else 0.  With off = 1 and col the feature's
    # own column this is the identity, so ONE code path serves both layouts.
    if efb is not None:
        col_of_feat = jnp.asarray(efb_bundle_np.astype(np.int32))
        off_of_feat = jnp.asarray(efb_off_np.astype(np.int32))
        # static gather indices: hist_f[f, b] = hist_b[bundle_f, off_f+b-1]
        _spans = efb_nb_np.astype(np.int64) - 1
        _bidx = np.arange(B - 1, dtype=np.int64)[None, :]
        _valid = _bidx < _spans[:, None]
        _idx = (efb_bundle_np.astype(np.int64)[:, None] * Bb
                + efb_off_np.astype(np.int64)[:, None] + _bidx)
        _idx = np.where(_valid, _idx, 0)
        _efb_idx = jnp.asarray(_idx.reshape(-1).astype(np.int32))
        _efb_valid = jnp.asarray(_valid.astype(np.float32))
        _efb_bundle = jnp.asarray(efb_bundle_np.astype(np.int32))

        def expand_hist(hb):
            """[n_cols, Bb, 3] bundle hists -> [F, B, 3] feature hists
            (bin 0 recovered as total-minus-rest: the reference's
            FixHistogram, dataset.cpp:1239)."""
            flat = hb.reshape(-1, 3)
            g = jnp.take(flat, _efb_idx, axis=0).reshape(f, B - 1, 3)
            g = g * _efb_valid[:, :, None]
            totals = jnp.sum(hb, axis=1)                       # [n_cols, 3]
            bin0 = jnp.take(totals, _efb_bundle, axis=0) - jnp.sum(g, axis=1)
            return jnp.concatenate([bin0[:, None, :], g], axis=1)
    else:
        col_of_feat = off_of_feat = None

        def expand_hist(hb):
            return hb

    def split_column_bins(colv_raw, feat):
        """Decode a gathered (bundle) column into feature bins for ``feat``."""
        if efb is None:
            return colv_raw
        from ..io.efb import decode_bundle_column
        return decode_bundle_column(colv_raw, off_of_feat[feat],
                                    num_bins[feat]).astype(jnp.int32)

    # --- data-parallel comm shape: reduce-scatter + sharded search ----------
    # Instead of allreducing the full [F, B, 3] histogram per split, each
    # shard receives (and stores, and searches) only its OWN feature block:
    # lax.psum_scatter moves F*B/ndev per device where a psum moved F*B, and
    # the winning SplitInfo rides the existing _reduce_split_global pmax —
    # the reference DataParallelTreeLearner dataflow (ReduceScatter +
    # SyncUpGlobalBestSplit, data_parallel_tree_learner.cpp:155-251).
    # Falls back to the full psum for the paths that need a full-width
    # histogram store on every shard (EFB bundles, forced splits, CEGB-lazy).
    dp_scatter = (mode == "data" and efb is None and not forced
                  and cegb_lazy is None and cfg.num_shards > 1)
    if dp_scatter:
        shard_w = -(-f // cfg.num_shards)        # owned features per shard
        shard_wp = shard_w * cfg.num_shards

    # --- sharded-search bookkeeping (feature-parallel + data-scatter) -------
    # metadata arrays arrive FULL-width [F_total]; the histogram axis is the
    # local shard.  Local slices feed the split search, full arrays feed the
    # partition step (which sees the globally-reduced winning feature id).
    if mode == "feature":
        dev = jax.lax.axis_index(axis)
        f_start = dev * f

        def lslice(a):
            return jax.lax.dynamic_slice_in_dim(a, f_start, f)
        num_bins_l = lslice(num_bins)
        default_bins_l = lslice(default_bins)
        nan_bins_l = lslice(nan_bins)
        is_cat_l = lslice(is_categorical)
        mono_l = lslice(monotone)
        f_full = feature_mask.shape[0]
    elif dp_scatter:
        dev = jax.lax.axis_index(axis)
        f_start = dev * shard_w

        def lslice(a, fill):
            ap = jnp.pad(a, (0, shard_wp - f), constant_values=fill)
            return jax.lax.dynamic_slice_in_dim(ap, f_start, shard_w)
        num_bins_l = lslice(num_bins, 1)
        default_bins_l = lslice(default_bins, 0)
        nan_bins_l = lslice(nan_bins, -1)
        is_cat_l = lslice(is_categorical, False)
        mono_l = lslice(monotone, 0)
        f_full = f
    else:
        num_bins_l, default_bins_l, nan_bins_l = num_bins, default_bins, nan_bins
        is_cat_l, mono_l = is_categorical, monotone
        f_full = f

    # capacity ladder for adaptive leaf compaction: per-split histogram cost
    # tracks the smaller sibling's size (the reference computes only over
    # per-leaf index ranges, data_partition.hpp; full-mask passes would make
    # every split O(N))
    caps: "list[int]" = []
    if cfg.hist_compact:
        c = min(cfg.hist_compact_min_cap, n)
        factor = max(1.2, float(cfg.hist_compact_ladder))
        while c < n:
            caps.append(c)
            c = max(c + 1024, -(-int(c * factor) // 1024) * 1024)
    caps.append(n)

    # Row-partition mode: maintain a permutation of local rows grouped by
    # leaf (the TPU analog of the reference's DataPartition index ranges,
    # data_partition.hpp:21-170).  Per split, only the parent's contiguous
    # segment is touched: every O(N)-per-split pass (leaf masks, decision
    # vectors, compaction searches) collapses to O(parent rows), bucketed by
    # the same capacity ladder.  Feature mode broadcasts the owner shard's
    # split column per segment (see partition_and_hist); voting partitions
    # its local row shard exactly like data mode.  Disabled only for
    # CEGB-lazy (its per-row cost bitset needs leaf masks).
    use_partition = (cfg.hist_compact and len(caps) > 1
                     and cegb_lazy is None)

    def _seg_window(begin, cap):
        """Clamped cap-sized window covering [begin, begin+cap) and the
        offset of ``begin`` inside it."""
        start = jnp.clip(begin, 0, max(n - cap, 0))
        return start, begin - start

    # Per-tree combined row payload for the fused partition+histogram pass:
    # the 12 bytes of (grad, hess, row_weight) ride INSIDE the bins rows as
    # extra bin-typed columns, so ONE row gather moves everything.  On v5e a
    # u8 [N, F] row is lane-padded to a 128-byte tile row for any F<=128, so
    # the extra byte-columns are free at gather time, while a separate f32
    # [N, 3] gather benched ~2x the bins gather (XLA lays [N, small] out
    # column-major, scattering each row's fields 4MB apart).
    _gh_cols = 12 // bins.dtype.itemsize          # 12 bytes as bin-typed cols
    _gh_packed = jax.lax.bitcast_convert_type(
        jnp.stack([grad, hess, row_weight], axis=1), bins.dtype
    ).reshape(n, _gh_cols)
    comb = jnp.concatenate([bins, _gh_packed], axis=1)    # [N, F + gh_cols]

    def _unpack_gh(combb):
        """[cap, 3] f32 (grad, hess, row_weight) back out of a gathered
        combined block."""
        cap = combb.shape[0]
        raw = combb[:, n_cols:].reshape(cap, 3, _gh_cols // 3)
        return jax.lax.bitcast_convert_type(raw, jnp.float32)

    def reduce_hist(h):
        """Join shard-local histograms: reduce-scatter to the owned feature
        block (dp_scatter) or full allreduce.  No-op outside data mode."""
        if mode != "data":
            return h
        if dp_scatter:
            hp = jnp.pad(h, ((0, shard_wp - n_cols), (0, 0), (0, 0)))
            return jax.lax.psum_scatter(hp, axis, scatter_dimension=0,
                                        tiled=True)
        return jax.lax.psum(h, axis)

    # lgbm/* named scopes label the phases inside the single fused program
    # so device traces (jax.profiler / obs_trace_device) decompose the
    # grower the way the host-paced streaming loop does naturally
    @jax.named_scope("lgbm/partition")
    def partition_and_hist(perm, begin, rows, feat, thr, dleft, f_is_cat,
                           cbits, ok, left_smaller):
        """One switch over the parent-cap ladder: gather the parent segment's
        rows ONCE, decide the split, stable-partition the perm segment, and
        histogram the smaller child from the gathered block with a side mask.

        Fuses the reference's ``DataPartition::Split`` + smaller-child
        ``ConstructHistograms`` (serial_tree_learner.cpp:324-372,564-682).
        The fusion is the point: a per-split flat ``bins.reshape(-1)`` column
        gather benched at a fixed ~0.7 ms relayout of the whole bins array,
        and the separate child histogram paid a second row gather — here the
        parent block is gathered once and both consumers read it from VMEM-
        friendly layout.  Returns (perm', nleft, small_hist)."""
        def mk(cap):
            def br(perm):
                start, off = _seg_window(begin, cap)
                seg = jax.lax.dynamic_slice(perm, (start,), (cap,))
                combb = jnp.take(comb, seg, axis=0)       # [cap, NC+gh_cols]
                ghb = _unpack_gh(combb)                   # [cap, 3]
                # split column via one-hot reduce — a dynamic minor-axis
                # take would relayout the whole block
                if mode == "feature":
                    # columns are sharded: the owner selects its local
                    # column, the psum broadcasts it.  The collective is
                    # safe INSIDE the cap switch only because feature mode
                    # replicates rows — begin/rows (hence the switch index)
                    # are identical on every shard.
                    local_ix = jnp.clip(feat - f_start, 0, f - 1)
                    fsel = ((jnp.arange(combb.shape[1], dtype=jnp.int32)
                             == local_ix)
                            & (feat >= f_start) & (feat < f_start + f))
                    colv = jax.lax.psum(
                        jnp.sum(combb.astype(jnp.int32) * fsel[None, :],
                                axis=1), axis)
                else:
                    col_id = col_of_feat[feat] if efb is not None else feat
                    fsel = (jnp.arange(combb.shape[1], dtype=jnp.int32)
                            == col_id)
                    colv = split_column_bins(
                        jnp.sum(combb.astype(jnp.int32) * fsel[None, :],
                                axis=1), feat)
                is_miss = (colv == nan_bins[feat]) & (nan_bins[feat] >= 0)
                gl = jnp.where(f_is_cat, bitset_contains(cbits, colv),
                               jnp.where(is_miss, dleft, colv <= thr))
                ar = jnp.arange(cap, dtype=jnp.int32)
                valid = (ar >= off) & (ar < off + rows)
                gl_v = gl & valid
                nleft = jnp.sum(gl_v.astype(jnp.int32))
                # stable partition via position scatter (a gather-based
                # double binary search benched 7x slower: large-array
                # gathers are the slow primitive on TPU)
                cl = jnp.cumsum(gl_v.astype(jnp.int32))
                cr = jnp.cumsum((valid & ~gl).astype(jnp.int32))
                pos = jnp.where(gl_v, off + cl - 1,
                                jnp.where(valid, off + nleft + cr - 1, ar))
                new_seg = jnp.zeros(cap, jnp.int32).at[pos].set(seg)
                if ok is not None:
                    new_seg = jnp.where(ok, new_seg, seg)
                    nleft = jnp.where(ok, nleft, 0)
                new_perm = jax.lax.dynamic_update_slice(perm, new_seg,
                                                        (start,))
                m = jnp.where(valid & (gl == left_smaller), ghb[:, 2], 0.0)
                # histogram the combined block in place: the pallas kernel
                # skips the gh byte-columns via f_limit, the XLA fallbacks
                # histogram them as garbage and the [:n_cols] slice drops it
                # — either way cheaper than a minor-axis slice relayout
                h = build_histogram(combb, ghb[:, 0], ghb[:, 1], m, Bb,
                                    method=cfg.hist_method,
                                    chunk_rows=cfg.hist_chunk_rows,
                                    f_limit=n_cols,
                                    variant=cfg.hist_variant)
                return new_perm, nleft, h[:n_cols]
            return br
        idx = jnp.searchsorted(jnp.asarray(caps, jnp.int32), rows)
        new_perm, nleft, h = jax.lax.switch(idx, [mk(c) for c in caps], perm)
        # collective stays OUTSIDE the data-dependent switch: shards may
        # pick different buckets, all join here
        return new_perm, nleft, reduce_hist(h)

    @jax.named_scope("lgbm/hist")
    def hist_of(mask, nrows=None):
        def full(m):
            return build_histogram(bins, grad, hess, m, Bb,
                                   method=cfg.hist_method,
                                   chunk_rows=cfg.hist_chunk_rows,
                                   variant=cfg.hist_variant)

        if nrows is None or len(caps) == 1:
            h = full(mask)
        else:
            def mk(cap):
                def br(m):
                    bc, gc, hc, mc = gather_rows(bins, grad, hess, m, cap)
                    return build_histogram(bc, gc, hc, mc, Bb,
                                           method=cfg.hist_method,
                                           chunk_rows=cfg.hist_chunk_rows,
                                           variant=cfg.hist_variant)
                return br
            branches = [mk(c) for c in caps[:-1]] + [full]
            idx = jnp.searchsorted(jnp.asarray(caps, jnp.int32),
                                   nrows.astype(jnp.int32))
            h = jax.lax.switch(idx, branches, mask)
        # collective stays OUTSIDE the data-dependent switch: shards may
        # pick different buckets, all join here
        return reduce_hist(h)

    def node_feature_mask(step):
        if cfg.feature_fraction_bynode >= 1.0:
            return feature_mask
        return node_feature_mask_for(key, step, feature_mask,
                                     cfg.feature_fraction_bynode)

    def rand_thresholds(step):
        if not cfg.extra_trees:
            return None
        return rand_thresholds_for(key, step, cfg.extra_seed,
                                   num_bins_l, nan_bins_l)

    def gain_mult_for(depth):
        """[F] monotone-split penalty factor at a leaf of ``depth``
        (ComputeMonotoneSplitGainPenalty, monotone_constraints.hpp:355-364);
        applied AFTER CEGB like the reference.  feature_contri flows
        separately (BEFORE CEGB) via find()'s ``contri``."""
        if not (cfg.has_monotone and cfg.monotone_penalty > 0.0):
            return None
        return monotone_gain_mult(depth, monotone, cfg.monotone_penalty)

    @jax.named_scope("lgbm/split_search")
    def find(hist, sum_g, sum_h, count, fmask, parent_output=0.0,
             lo=NEG_INF, hi=-NEG_INF, penalty=None, rand=None, mult=None):
        """Mode-dispatched best-split search (the analog of the reference's
        learner-specific FindBestSplitsFromHistograms overrides)."""
        if mode == "feature" or dp_scatter:
            w = f if mode == "feature" else shard_w

            def lsl(a):
                if dp_scatter:
                    a = jnp.pad(a, (0, shard_wp - a.shape[0]))
                return jax.lax.dynamic_slice_in_dim(a, f_start, w)
            fmask_l = lsl(fmask)
            pen_l = lsl(penalty) if penalty is not None else None
            mult_l = lsl(mult) if mult is not None else None
            contri_l = (lsl(feature_contri) if feature_contri is not None
                        else None)
            # rand_thresholds is built from num_bins_l: already shard-local
            s = find_best_split(hist, num_bins_l, default_bins_l, nan_bins_l,
                                is_cat_l, mono_l, sum_g, sum_h, count, p,
                                fmask_l, parent_output, lo, hi, pen_l, rand,
                                sorted_cat=cfg.sorted_cat, gain_mult=mult_l,
                                contri=contri_l)
            # local winner carries a shard-local feature id; globalize and
            # allreduce-max the packed SplitInfo (parallel_tree_learner.h:191)
            s = s._replace(feature=s.feature + f_start)
            return _reduce_split_global(s, axis)
        if mode == "voting":
            return _find_voting(hist, sum_g, sum_h, count, fmask,
                                parent_output, lo, hi, penalty, rand,
                                mult=mult)
        return find_best_split(hist, num_bins_l, default_bins_l, nan_bins_l,
                               is_cat_l, mono_l, sum_g, sum_h, count, p,
                               fmask, parent_output, lo, hi, penalty, rand,
                               sorted_cat=cfg.sorted_cat, gain_mult=mult,
                               contri=feature_contri)

    def _find_voting(hist, sum_g, sum_h, count, fmask, parent_output, lo, hi,
                     penalty=None, rand=None, mult=None):
        """Local top-k proposal → global vote → reduce only elected
        histograms (voting_parallel_tree_learner.cpp:151-345; the election
        dataflow lives once in split.voting_elect, shared with the frontier
        grower)."""
        from .split import voting_elect
        hist_e, emask = voting_elect(
            hist, num_bins_l, nan_bins_l, is_cat_l, mono_l, sum_g, sum_h,
            count, p, fmask, axis, cfg.top_k, cfg.num_shards, parent_output,
            lo, hi, sorted_cat=cfg.sorted_cat, gain_mult=mult,
            contri=feature_contri)
        return find_best_split(hist_e, num_bins_l, default_bins_l, nan_bins_l,
                               is_cat_l, mono_l, sum_g, sum_h, count, p,
                               emask, parent_output, lo, hi, penalty, rand,
                               sorted_cat=cfg.sorted_cat, gain_mult=mult,
                               contri=feature_contri)

    # monotone 'intermediate' (reference IntermediateLeafConstraints,
    # monotone_constraints.hpp:514): output bounds come from the ACTUAL
    # sibling outputs instead of the midpoint, and tighten OTHER leaves
    # whose bin-rectangles overlap the new children in every non-split
    # dimension.  The overlap test is a vectorized superset of the
    # reference's contiguity tree-walk (GoUpToFindLeavesToUpdate): sound —
    # every constraint it adds is implied by monotonicity — at worst
    # slightly more constraining, and it trades the data-dependent
    # recursion for one [L, F] broadcast per split.  Cached best splits can
    # go stale when bounds tighten, so the growth loop re-validates the
    # chosen leaf's split against current bounds before applying (the
    # analog of RecomputeBestSplitForLeaf, serial_tree_learner.cpp:673-681).
    # intermediate AND advanced share the rect-tracking machinery; advanced
    # additionally RE-DERIVES each new child's output bounds from current
    # rect comparability over all active leaves (see apply_split), instead
    # of inheriting the parent's pinched scalars — the analog of the
    # reference's AdvancedLeafConstraints precision
    # (monotone_constraints.hpp:230-375): a child created by a split on a
    # NON-monotone feature can shed comparable neighbors, and the inherited
    # whole-parent bound would over-tighten it.
    mono_inter = cfg.has_monotone and cfg.monotone_mode in ("intermediate",
                                                            "advanced")
    mono_adv = cfg.has_monotone and cfg.monotone_mode == "advanced"

    use_cegb = (cegb_coupled is not None or cegb_lazy is not None
                or cfg.cegb_split_penalty > 0.0)
    if cegb_lazy is not None and cegb_used_data is None:
        cegb_used_data = jnp.zeros((n, f_full), bool)
    rw_pos = (row_weight > 0).astype(jnp.float32)

    def interaction_allowed(branch):
        """[F] 0/1 mask of features a leaf with branch-feature indicator
        ``branch`` may split on: the union of constraint groups that contain
        every branch feature (``col_sampler.hpp:91`` ``GetByNode``)."""
        ok_c = ~jnp.any((branch[None, :] > 0) & (interaction_sets <= 0), axis=1)
        return jnp.any((interaction_sets > 0) & ok_c[:, None], axis=0) \
            .astype(jnp.float32)

    def cegb_penalty(leaf_mask, count, feat_used, used_data):
        """[F] CEGB gain penalty for splitting the leaf covered by
        ``leaf_mask`` (reference ``DetlaGain``,
        cost_effective_gradient_boosting.hpp:67-85)."""
        pen = jnp.full(f_full, cfg.cegb_split_penalty * count, jnp.float32)
        if cegb_coupled is not None:
            pen = pen + jnp.where(feat_used, 0.0, cegb_coupled)
        if cegb_lazy is not None:
            # on-demand cost: rows in the leaf that never paid for feature f
            unused = leaf_mask @ (1.0 - used_data.astype(jnp.float32))  # [F]
            if mode in ("data", "voting"):
                unused = jax.lax.psum(unused, axis)
            pen = pen + cegb_lazy * unused
        return pen

    # ---- degenerate case: no usable features -> single-leaf tree -----------
    if f == 0:
        cnt = jnp.sum(row_weight)
        wgt = jnp.sum(hess * row_weight)
        if mode in ("data", "voting"):
            cnt = jax.lax.psum(cnt, axis)
            wgt = jax.lax.psum(wgt, axis)
        empty = TreeArrays(
            split_feature=jnp.full(L - 1, -1, jnp.int32),
            threshold=jnp.zeros(L - 1, jnp.int32),
            default_left=jnp.zeros(L - 1, bool),
            is_cat_split=jnp.zeros(L - 1, bool),
            cat_bits=jnp.zeros((L - 1, cw), jnp.int32),
            split_gain=jnp.zeros(L - 1, jnp.float32),
            left_child=jnp.full(L - 1, -1, jnp.int32),
            right_child=jnp.full(L - 1, -1, jnp.int32),
            leaf_value=jnp.zeros(L, jnp.float32),
            leaf_count=jnp.zeros(L, jnp.float32).at[0].set(cnt),
            leaf_weight=jnp.zeros(L, jnp.float32).at[0].set(wgt),
            internal_value=jnp.zeros(L - 1, jnp.float32),
            internal_count=jnp.zeros(L - 1, jnp.float32),
            num_leaves=jnp.int32(1))
        return empty, jnp.zeros(n, jnp.int32)

    # ---- root --------------------------------------------------------------
    root_hist = hist_of(row_weight)
    tot = jnp.stack([jnp.sum(grad * row_weight), jnp.sum(hess * row_weight),
                     jnp.sum(row_weight)])
    if mode in ("data", "voting"):
        # root grad/hess sums are global (reference Allreduce,
        # data_parallel_tree_learner.cpp:126-152); feature-parallel replicates
        # rows so local sums are already global
        tot = jax.lax.psum(tot, axis)
    fmask0 = node_feature_mask(0)
    if interaction_sets is not None:
        fmask0 = fmask0 * interaction_allowed(jnp.zeros(f_full, jnp.float32))
    pen0 = None
    if use_cegb:
        pen0 = cegb_penalty(
            rw_pos, tot[2],
            jnp.zeros(f_full, bool) if cegb_coupled is not None else None,
            cegb_used_data)
    root_split = find(expand_hist(root_hist), tot[0], tot[1], tot[2], fmask0,
                      penalty=pen0, rand=rand_thresholds(0),
                      mult=gain_mult_for(0))

    # histogram store stays in BUNDLE space (subtraction is linear there);
    # searches expand to feature space on the fly.  Under dp_scatter each
    # shard stores only its owned feature block: memory / num_shards.
    store_w = shard_w if dp_scatter else n_cols
    hist_store = jnp.zeros((L, store_w, Bb, 3), jnp.float32).at[0].set(root_hist)
    best = _BestSplits.empty(L, cw).set_leaf(0, root_split)
    # depth gate for root handled trivially (max_depth >= 1 always allows root)

    state = dict(
        hist=hist_store,
        best=best,
        leaf_depth=jnp.zeros(L, jnp.int32),
        leaf_value=jnp.zeros(L, jnp.float32),
        leaf_count=jnp.zeros(L, jnp.float32).at[0].set(tot[2]),
        leaf_weight=jnp.zeros(L, jnp.float32).at[0].set(tot[1]),
        leaf_sum_g=jnp.zeros(L, jnp.float32).at[0].set(tot[0]),
        leaf_lo=jnp.full(L, NEG_INF, jnp.float32),
        leaf_hi=jnp.full(L, -NEG_INF, jnp.float32),
        leaf_parent=jnp.full(L, -1, jnp.int32),     # node that created the leaf
        leaf_is_left=jnp.zeros(L, bool),
        node_feature=jnp.full(L - 1, -1, jnp.int32),
        node_threshold=jnp.zeros(L - 1, jnp.int32),
        node_default_left=jnp.zeros(L - 1, bool),
        node_is_cat=jnp.zeros(L - 1, bool),
        node_cat_bits=jnp.zeros((L - 1, cw), jnp.int32),
        node_gain=jnp.zeros(L - 1, jnp.float32),
        node_parent=jnp.full(L - 1, -1, jnp.int32),  # parent internal node
        node_is_left=jnp.zeros(L - 1, bool),
        node_value=jnp.zeros(L - 1, jnp.float32),
        node_count=jnp.zeros(L - 1, jnp.float32),
        num_leaves=jnp.int32(1),
    )
    if use_partition:
        state["perm"] = jnp.arange(n, dtype=jnp.int32)
        state["leaf_begin"] = jnp.zeros(L, jnp.int32)
        state["leaf_nrows"] = jnp.zeros(L, jnp.int32).at[0].set(n)
    else:
        state["node_assign"] = jnp.zeros(n, jnp.int32)
    if mono_inter:
        # per-leaf bin rectangles for the overlap-propagation pass
        state["rect_lo"] = jnp.zeros((L, f_full), jnp.int32)
        state["rect_hi"] = jnp.full((L, f_full), B - 1, jnp.int32)
        # the step whose per-node feature mask / extra-trees thresholds the
        # leaf's cached best split was searched under: the re-validation
        # must re-key with the SAME step, not resample
        state["leaf_step"] = jnp.zeros(L, jnp.int32)
    if mono_adv:
        # current output of every active leaf (advanced bound derivation);
        # root output from the unconstrained totals
        root_out = leaf_output(state["leaf_sum_g"][0], state["leaf_weight"][0],
                               p, 0.0, state["leaf_count"][0])
        state["leaf_out"] = jnp.zeros(L, jnp.float32).at[0].set(root_out)
    if interaction_sets is not None:
        state["leaf_branch"] = jnp.zeros((L, f_full), jnp.float32)
    if cegb_coupled is not None:
        state["feat_used"] = jnp.zeros(f_full, bool)
    if cegb_lazy is not None:
        state["used_data"] = cegb_used_data

    def forced_split_info(st, leaf, feat, thr):
        """SplitInfo for a forced (feature, threshold-bin) split of a leaf,
        from its stored histogram (the reference's
        ``GatherInfoForThreshold``, feature_histogram.hpp).

        Parallel modes (``feat`` is a static global id): under feature
        parallel only the shard owning the feature's histogram computes the
        info and the result is pmax-broadcast; under voting parallel the
        histogram store is shard-local, so the forced feature's column is
        psum'd first and every shard computes identically (the reference
        runs ForceSplits on every rank over full local histograms —
        serial_tree_learner.cpp:543 — which feature-sharded storage here
        replaces)."""
        owns = None
        if mode == "feature":
            local_ix = jnp.clip(feat - f_start, 0, f - 1)
            owns = (feat >= f_start) & (feat < f_start + f)
            h = expand_hist(st["hist"][leaf])[local_ix]              # [B, 3]
        elif mode == "voting":
            h = jax.lax.psum(expand_hist(st["hist"][leaf])[feat], axis)
        else:
            h = expand_hist(st["hist"][leaf])[feat]                  # [B, 3]
        total = jnp.stack([st["leaf_sum_g"][leaf], st["leaf_weight"][leaf],
                           st["leaf_count"][leaf]])
        bin_ids = jnp.arange(B)
        miss_b = nan_bins[feat]
        # numeric: missing rows go LEFT, matching the reference's forced-split
        # gather which excludes the NaN bin from the RIGHT accumulation and
        # sets default_left=true (GatherInfoForThresholdNumericalInner,
        # feature_histogram.hpp)
        num_left = jnp.sum(
            jnp.where(((bin_ids <= thr) | (bin_ids == miss_b))[:, None], h, 0.0),
            axis=0)
        f_cat = is_categorical[feat]
        left = jnp.where(f_cat, h[thr], num_left)
        right = total - left
        lo, hi = st["leaf_lo"][leaf], st["leaf_hi"][leaf]
        lout = leaf_output(left[0], left[1], p, 0.0, left[2], lo, hi)
        rout = leaf_output(right[0], right[1], p, 0.0, right[2], lo, hi)
        gain = (leaf_gain(left[0], left[1], p, 0.0, left[2], lo, hi)
                + leaf_gain(right[0], right[1], p, 0.0, right[2], lo, hi)
                - leaf_gain(total[0], total[1], p, 0.0, total[2], lo, hi))
        # the reference gates forced splits only on the gain threshold
        # (min_gain_to_split), not on min-data/min-hessian
        ok = gain > p.min_gain_to_split
        if owns is not None:
            ok = ok & owns
        res = SplitResult(
            gain=jnp.where(ok, gain, NEG_INF),
            feature=jnp.int32(feat), threshold=jnp.int32(thr),
            default_left=~f_cat,
            left_sum_g=left[0], left_sum_h=left[1], left_count=left[2],
            right_sum_g=right[0], right_sum_h=right[1], right_count=right[2],
            left_output=lout, right_output=rout,
            cat_bits=jnp.where(
                f_cat, pack_bin_bitset(jnp.arange(B, dtype=jnp.int32) == thr),
                jnp.zeros(cw, jnp.int32)))
        if owns is not None:
            res = _reduce_split_global(res, axis)
        return res

    @jax.named_scope("lgbm/apply_split")
    def apply_split(j, st, leaf, gain, ok):
        """Apply the pending best split of ``leaf`` as node ``j``.

        ``ok is None`` means the caller guarantees the split is valid (the
        while-loop body, whose condition already checked gain > 0) and every
        write is unconditional — this keeps the loop free of ``lax.cond``,
        which would copy the multi-MB histogram store every step instead of
        updating it in place.  The forced-split prefix passes a traced ``ok``
        and all writes are predicated."""
        unconditional = ok is None

        def setw(arr, idx, val):
            if unconditional:
                return arr.at[idx].set(val)
            return arr.at[idx].set(jnp.where(ok, val, arr[idx]))

        def gate(cond):
            return cond if unconditional else (cond & ok)

        b = st["best"]
        feat = b.feature[leaf]
        thr = b.threshold[leaf]
        dleft = b.default_left[leaf]
        cbits = b.cat_bits[leaf]
        f_is_cat = is_categorical[feat]
        new_id = st["num_leaves"]

        # --- update node arrays + parent linkage ---
        parent_node = st["leaf_parent"][leaf]
        st_nf = setw(st["node_feature"], j, feat)
        st_nt = setw(st["node_threshold"], j, thr)
        st_nd = setw(st["node_default_left"], j, dleft)
        st_nc = setw(st["node_is_cat"], j, f_is_cat)
        st_ncb = setw(st["node_cat_bits"], j, cbits)
        st_ng = setw(st["node_gain"], j, gain)
        st_np = setw(st["node_parent"], j, parent_node)
        st_nl = setw(st["node_is_left"], j, st["leaf_is_left"][leaf])
        st_nv = setw(st["node_value"], j, leaf_output(
            st["leaf_sum_g"][leaf], st["leaf_weight"][leaf], p,
            0.0, st["leaf_count"][leaf]))
        st_ncount = setw(st["node_count"], j, st["leaf_count"][leaf])

        # --- partition rows of this leaf ---
        left_smaller = b.lc[leaf] <= b.rc[leaf]
        if use_partition:
            # reorder only the parent leaf's segment of the row permutation
            # (DataPartition::Split, data_partition.hpp) and histogram the
            # smaller child from the same gathered block: O(parent rows)
            pbegin = st["leaf_begin"][leaf]
            prows = st["leaf_nrows"][leaf]
            perm, nleft, small_hist = partition_and_hist(
                st["perm"], pbegin, prows, feat, thr, dleft, f_is_cat,
                cbits, ok, left_smaller)
            extra_part = dict(
                perm=perm,
                leaf_begin=setw(st["leaf_begin"], new_id, pbegin + nleft),
                leaf_nrows=setw(setw(st["leaf_nrows"], leaf, nleft),
                                new_id, prows - nleft))
            in_leaf = goes_left = None
        else:
            if mode == "feature":
                # only the shard owning the winning feature can decide; it
                # broadcasts the decision (the reference avoids this because
                # every rank holds every column — here columns are sharded,
                # so one [n] psum replaces replicated column storage)
                local_ix = jnp.clip(feat - f_start, 0, f - 1)
                owns = (feat >= f_start) & (feat < f_start + f)
                col = jnp.take(bins, local_ix, axis=1).astype(jnp.int32)
            else:
                col_id = col_of_feat[feat] if efb is not None else feat
                col = split_column_bins(
                    jnp.take(bins, col_id, axis=1).astype(jnp.int32), feat)
            is_miss = (col == nan_bins[feat]) & (nan_bins[feat] >= 0)
            goes_left = jnp.where(
                f_is_cat, bitset_contains(cbits, col),
                jnp.where(is_miss, dleft, col <= thr))
            if mode == "feature":
                goes_left = jax.lax.psum(
                    jnp.where(owns, goes_left.astype(jnp.float32), 0.0),
                    axis) > 0.5
            in_leaf = st["node_assign"] == leaf
            extra_part = dict(node_assign=jnp.where(
                gate(in_leaf & ~goes_left), new_id, st["node_assign"]))

            # --- child histograms: compute smaller, subtract for larger ---
            small_mask = jnp.where(in_leaf & (goes_left == left_smaller),
                                   row_weight, 0.0)
            small_hist = hist_of(small_mask, jnp.sum(small_mask > 0))
        parent_hist = st["hist"][leaf]
        large_hist = parent_hist - small_hist
        lhist = jnp.where(left_smaller, small_hist, large_hist)
        rhist = parent_hist - lhist
        hist = setw(setw(st["hist"], leaf, lhist), new_id, rhist)

        # --- child bookkeeping ---
        depth = st["leaf_depth"][leaf] + 1
        leaf_depth = setw(setw(st["leaf_depth"], leaf, depth), new_id, depth)
        leaf_value = setw(setw(st["leaf_value"], leaf, b.lout[leaf]),
                          new_id, b.rout[leaf])
        leaf_count = setw(setw(st["leaf_count"], leaf, b.lc[leaf]),
                          new_id, b.rc[leaf])
        leaf_weight = setw(setw(st["leaf_weight"], leaf, b.lh[leaf]),
                           new_id, b.rh[leaf])
        leaf_sum_g = setw(setw(st["leaf_sum_g"], leaf, b.lg[leaf]),
                          new_id, b.rg[leaf])
        leaf_parent = setw(setw(st["leaf_parent"], leaf, j), new_id, j)
        leaf_is_left = setw(setw(st["leaf_is_left"], leaf, True),
                            new_id, False)

        mono = monotone[feat]
        lo, hi = st["leaf_lo"][leaf], st["leaf_hi"][leaf]
        is_num = ~f_is_cat
        if mono_inter:
            # intermediate: children bounded by the ACTUAL sibling outputs
            # (UpdateConstraintsWithOutputs, monotone_constraints.hpp:543)
            lo_out, ro_out = b.lout[leaf], b.rout[leaf]
            l_lo = jnp.where(is_num & (mono < 0), jnp.maximum(lo, ro_out), lo)
            l_hi = jnp.where(is_num & (mono > 0), jnp.minimum(hi, ro_out), hi)
            r_lo = jnp.where(is_num & (mono > 0), jnp.maximum(lo, lo_out), lo)
            r_hi = jnp.where(is_num & (mono < 0), jnp.minimum(hi, lo_out), hi)
        else:
            # basic: pinch both children at the midpoint of the child outputs
            mid = (b.lout[leaf] + b.rout[leaf]) * 0.5
            l_lo = jnp.where(mono < 0, jnp.maximum(lo, mid), lo)
            l_hi = jnp.where(mono > 0, jnp.minimum(hi, mid), hi)
            r_lo = jnp.where(mono > 0, jnp.maximum(lo, mid), lo)
            r_hi = jnp.where(mono < 0, jnp.minimum(hi, mid), hi)
        leaf_lo = setw(setw(st["leaf_lo"], leaf, l_lo), new_id, r_lo)
        leaf_hi = setw(setw(st["leaf_hi"], leaf, l_hi), new_id, r_hi)

        extra_mono = {}
        if mono_inter:
            # children rectangles: a numeric split partitions dimension
            # `feat` at thr; categorical children conservatively keep the
            # parent rect (more overlaps -> never fewer constraints)
            fsel = jnp.arange(f_full, dtype=jnp.int32) == feat
            prl, prh = st["rect_lo"][leaf], st["rect_hi"][leaf]      # [F]
            l_rh = jnp.where(fsel & is_num, thr, prh)
            r_rl = jnp.where(fsel & is_num, thr + 1, prl)
            rect_lo = setw(setw(st["rect_lo"], leaf, prl), new_id, r_rl)
            rect_hi = setw(setw(st["rect_hi"], leaf, l_rh), new_id, prh)
            extra_mono = dict(rect_lo=rect_lo, rect_hi=rect_hi)

            if mono_adv:
                # ADVANCED: re-derive each child's bounds from current rect
                # comparability over all active leaves, instead of the
                # inherited parent scalars — a child of a split on a
                # non-monotone feature sheds comparable neighbors, and the
                # inherited bound would keep constraining it by them
                # (reference AdvancedLeafConstraints precision).
                new_out = setw(setw(st["leaf_out"], leaf, lo_out),
                               new_id, ro_out)
                lid = jnp.arange(L, dtype=jnp.int32)
                act = lid <= st["num_leaves"]        # old leaves + new slot
                mono_f = monotone.astype(jnp.int32)

                def derive(c_lo_row, c_hi_row, self_id):
                    upper, lower = _rect_comparability(
                        rect_lo, rect_hi, c_lo_row, c_hi_row, mono_f)
                    elig = (act & (lid != self_id))[:, None]
                    hi_c = jnp.min(jnp.where(upper & elig,
                                             new_out[:, None], -NEG_INF))
                    lo_c = jnp.max(jnp.where(lower & elig,
                                             new_out[:, None], NEG_INF))
                    return lo_c, hi_c

                al_lo, al_hi = derive(prl, l_rh, leaf)
                ar_lo, ar_hi = derive(r_rl, prh, new_id)
                leaf_lo = setw(setw(st["leaf_lo"], leaf, al_lo),
                               new_id, ar_lo)
                leaf_hi = setw(setw(st["leaf_hi"], leaf, al_hi),
                               new_id, ar_hi)
                extra_mono["leaf_out"] = new_out

            # Propagate the new child outputs to every active leaf that
            # overlaps a child in all dims except SOME monotone dim k and
            # sits strictly to one side of it along k — for ANY monotone k,
            # not just the split feature: the reference's up-walk crosses
            # every monotone ancestor boundary regardless of what feature
            # the triggering split used (GoUpToFindLeavesToUpdate).
            lid = jnp.arange(L, dtype=jnp.int32)
            is_active = lid <= st["num_leaves"]      # incl. the new leaf slot
            do_prop = gate(jnp.asarray(True))
            mono_f = monotone.astype(jnp.int32)                  # [F]

            def prop(llo, lhi, c_lo_row, c_hi_row, out_c):
                # upper[m]: m sits on the child's GREATER side (it bounds
                # the child's hi) — symmetrically the child's output is a
                # LOWER bound on m.  lower[m] mirrors.  prop updates the
                # NEIGHBORS; derive() uses the same masks to update the
                # child itself.
                upper, lower = _rect_comparability(
                    rect_lo, rect_hi, c_lo_row, c_hi_row, mono_f)
                in_upper = jnp.any(upper, axis=1)
                in_lower = jnp.any(lower, axis=1)
                llo = jnp.where(do_prop & is_active & in_upper,
                                jnp.maximum(llo, out_c), llo)
                lhi = jnp.where(do_prop & is_active & in_lower,
                                jnp.minimum(lhi, out_c), lhi)
                return llo, lhi

            leaf_lo, leaf_hi = prop(leaf_lo, leaf_hi, prl, l_rh, lo_out)
            leaf_lo, leaf_hi = prop(leaf_lo, leaf_hi, r_rl, prh, ro_out)

        # --- feature-gating state: interaction branch sets, CEGB ---
        extra = {}
        fmask = node_feature_mask(j + 1)
        if interaction_sets is not None:
            # both children share the branch = parent branch + this feature
            branch = jnp.where(jnp.arange(f_full) == feat, 1.0,
                               st["leaf_branch"][leaf])
            fmask = fmask * interaction_allowed(branch)
            extra["leaf_branch"] = setw(
                setw(st["leaf_branch"], leaf, branch), new_id, branch)
        cur_best = st["best"]
        feat_used = None
        if cegb_coupled is not None:
            # the coupled penalty is paid once per feature per model: mark
            # it used and refund the penalty in other leaves' cached best
            # gains that proposed the same feature.  This approximates the
            # reference's UpdateLeafBestSplits: leaves whose cached best used
            # a DIFFERENT feature are not re-searched here, so a refunded
            # feature that would now overtake a leaf's cached best is missed
            # until that leaf is next split (the reference re-runs the search
            # for such leaves)
            refund = jnp.where(st["feat_used"][feat], 0.0, cegb_coupled[feat])
            cur_best = cur_best._replace(gain=jnp.where(
                gate((cur_best.feature == feat)
                     & (cur_best.gain > NEG_INF / 2)),
                cur_best.gain + refund, cur_best.gain))
            feat_used = st["feat_used"].at[feat].set(
                st["feat_used"][feat] | (True if unconditional else ok))
            extra["feat_used"] = feat_used
        used_data = None
        if cegb_lazy is not None:
            # rows of the split leaf have now paid feature `feat`'s
            # on-demand cost (feature_used_in_data_ bitset insert)
            used_data = st["used_data"] | (
                gate(in_leaf & (row_weight > 0))[:, None]
                & (jnp.arange(f_full) == feat)[None, :])
            extra["used_data"] = used_data

        # --- new best splits for both children ---
        depth_ok = (cfg.max_depth <= 0) | (depth < cfg.max_depth)

        rand = rand_thresholds(j + 1)

        if use_partition:
            # CEGB-lazy (the only penalty needing row masks) is mask-path-only
            lmask = rmask = None
        else:
            lmask = jnp.where(in_leaf & goes_left, rw_pos, 0.0)
            rmask = jnp.where(in_leaf & ~goes_left, rw_pos, 0.0)

        # both children's split searches ride ONE vmapped call: the search is
        # dominated by fixed small-op overhead at [F, B] scale, so batching
        # the pair halves the per-split serial op count
        hist2 = jnp.stack([lhist, rhist])
        g2 = jnp.stack([b.lg[leaf], b.rg[leaf]])
        h2 = jnp.stack([b.lh[leaf], b.rh[leaf]])
        c2 = jnp.stack([b.lc[leaf], b.rc[leaf]])
        # search under the FINAL stored bounds: advanced re-derivation and
        # cross-leaf propagation may have moved them past the inherited
        # pinch (cached gains computed under stale-tighter bounds would
        # silently lose exactly the splits advanced mode admits)
        lo2 = jnp.stack([leaf_lo[leaf], leaf_lo[new_id]])
        hi2 = jnp.stack([leaf_hi[leaf], leaf_hi[new_id]])
        if use_cegb:
            pen2 = jnp.stack([cegb_penalty(lmask, c2[0], feat_used, used_data),
                              cegb_penalty(rmask, c2[1], feat_used, used_data)])
        mult2 = gain_mult_for(depth)        # both children share the depth
        if use_cegb:
            s2 = jax.vmap(
                lambda hc, g_, h_, c_, lo_, hi_, pen_: find(
                    expand_hist(hc), g_, h_, c_, fmask, 0.0, lo_, hi_,
                    penalty=pen_, rand=rand, mult=mult2)
            )(hist2, g2, h2, c2, lo2, hi2, pen2)
        else:
            s2 = jax.vmap(
                lambda hc, g_, h_, c_, lo_, hi_: find(
                    expand_hist(hc), g_, h_, c_, fmask, 0.0, lo_, hi_,
                    rand=rand, mult=mult2)
            )(hist2, g2, h2, c2, lo2, hi2)
        s2 = s2._replace(gain=jnp.where(depth_ok, s2.gain, NEG_INF))
        sl = jax.tree.map(lambda a: a[0], s2)
        sr = jax.tree.map(lambda a: a[1], s2)
        best = cur_best.set_leaf(leaf, sl, ok).set_leaf(new_id, sr, ok)
        if mono_inter:
            # both children's cached splits were searched under step j+1's
            # mask/thresholds (see fmask/rand above)
            jt = jnp.asarray(j, jnp.int32) + 1
            extra_mono["leaf_step"] = setw(
                setw(st["leaf_step"], leaf, jt), new_id, jt)

        return dict(
            **extra,
            **extra_part,
            **extra_mono,
            hist=hist, best=best,
            leaf_depth=leaf_depth, leaf_value=leaf_value,
            leaf_count=leaf_count, leaf_weight=leaf_weight,
            leaf_sum_g=leaf_sum_g, leaf_lo=leaf_lo, leaf_hi=leaf_hi,
            leaf_parent=leaf_parent, leaf_is_left=leaf_is_left,
            node_feature=st_nf, node_threshold=st_nt,
            node_default_left=st_nd, node_is_cat=st_nc, node_cat_bits=st_ncb,
            node_gain=st_ng,
            node_parent=st_np, node_is_left=st_nl, node_value=st_nv,
            node_count=st_ncount,
            num_leaves=st["num_leaves"] + (
                1 if unconditional else ok.astype(jnp.int32)),
        )

    # forced splits first (unrolled BFS prefix with runtime-tracked leaf ids
    # and node slots, so a forced split that fails its gates leaves no gap in
    # the node arrays and does not shift later siblings' leaf numbering),
    # then best-gain growth
    forced_ok = []
    forced_leaf_id = []      # traced leaf id each forced node targets
    forced_right_id = []     # traced leaf id of each forced node's right child
    for j in range(min(len(forced), L - 1)):
        fside, ffeat, fthr, fpar = forced[j]
        if fpar < 0:
            fleaf = jnp.int32(0)
        elif fside == 0:     # left child keeps the parent's leaf id
            fleaf = forced_leaf_id[fpar]
        else:                # right child got the fresh id at the parent split
            fleaf = forced_right_id[fpar]
        forced_leaf_id.append(fleaf)
        forced_right_id.append(state["num_leaves"])  # id if this split lands
        nl_before = state["num_leaves"]
        finfo = forced_split_info(state, fleaf, ffeat, fthr)
        if fpar >= 0:
            # a forced split whose forced ancestor failed is dropped (the
            # reference aborts the subtree, serial_tree_learner.cpp:543-553)
            finfo = finfo._replace(
                gain=jnp.where(forced_ok[fpar], finfo.gain, NEG_INF))
        natural = state["best"]
        state = dict(state, best=natural.set_leaf(fleaf, finfo))
        fgain = state["best"].gain[fleaf]
        # node slot = number of successful splits so far: failures leave the
        # node arrays gapless
        state = apply_split(state["num_leaves"] - 1, state, fleaf, fgain,
                            fgain > 0.0)
        ok = state["num_leaves"] > nl_before
        forced_ok.append(ok)
        # failed forced split: restore the leaf's natural best so the
        # best-gain phase can still split it (forceSplitMap erase)
        restored = _BestSplits(*[
            c.at[fleaf].set(jnp.where(ok, c[fleaf], nat[fleaf]))
            for c, nat in zip(state["best"], natural)])
        state = dict(state, best=restored)

    # best-gain growth: a while_loop that EXITS when no positive-gain split
    # remains, so finished trees don't pay for dead iterations, and whose
    # body is branch-free so XLA aliases the loop-carried histogram store
    # in place (a lax.cond here copied the multi-MB buffers every step)
    def loop_cond(carry):
        jj, st = carry
        active = jnp.where(jnp.arange(L) < st["num_leaves"],
                           st["best"].gain, NEG_INF)
        return (jj < L - 1) & (jnp.max(active) > 0.0)

    def loop_body(carry):
        jj, st = carry
        active = jnp.where(jnp.arange(L) < st["num_leaves"],
                           st["best"].gain, NEG_INF)
        leaf = jnp.argmax(active).astype(jnp.int32)
        if not mono_inter:
            st = apply_split(jj, st, leaf, active[leaf], None)
            return jj + 1, st
        # intermediate monotone mode: the cached split may violate bounds
        # tightened since it was found — re-search against CURRENT bounds
        # (RecomputeBestSplitForLeaf analog), with the same feature gates
        # the cached search had: per-node mask and extra-trees thresholds
        # re-keyed by the step the cache was built at (leaf_step), the
        # interaction branch mask, and CEGB penalties.  A leaf whose
        # re-search finds nothing is retired (gain -> NEG_INF) without
        # consuming a node slot.
        step0 = st["leaf_step"][leaf]
        fmask_j = node_feature_mask(step0)
        if interaction_sets is not None:
            fmask_j = fmask_j * interaction_allowed(st["leaf_branch"][leaf])
        pen_j = None
        if use_cegb:
            lm = None
            if cegb_lazy is not None:
                lm = jnp.where(st["node_assign"] == leaf, rw_pos, 0.0)
            pen_j = cegb_penalty(
                lm, st["leaf_count"][leaf],
                st["feat_used"] if cegb_coupled is not None else None,
                st["used_data"] if cegb_lazy is not None else None)
        s_new = find(expand_hist(st["hist"][leaf]), st["leaf_sum_g"][leaf],
                     st["leaf_weight"][leaf], st["leaf_count"][leaf],
                     fmask_j, 0.0,
                     st["leaf_lo"][leaf], st["leaf_hi"][leaf],
                     penalty=pen_j, rand=rand_thresholds(step0),
                     mult=gain_mult_for(st["leaf_depth"][leaf]))
        depth_ok = (cfg.max_depth <= 0) | (st["leaf_depth"][leaf]
                                           < cfg.max_depth)
        s_new = s_new._replace(gain=jnp.where(depth_ok, s_new.gain, NEG_INF))
        st = dict(st, best=st["best"].set_leaf(leaf, s_new))
        ok = s_new.gain > 0.0
        st = apply_split(jj, st, leaf, s_new.gain, ok)
        return jj + ok.astype(jnp.int32), st

    _, state = jax.lax.while_loop(
        loop_cond, loop_body, (state["num_leaves"] - 1, state))

    # ---- reconstruct child pointers ----------------------------------------
    # node j's children: initially leaves (~leaf ids); later splits of those
    # leaves overwrite with internal node ids.
    left_child = jnp.full(L - 1, -1, jnp.int32)
    right_child = jnp.full(L - 1, -1, jnp.int32)

    def scatter_claims(child, idx, cond, val):
        # route non-claiming writes out of bounds so they are dropped —
        # each (node, side) slot has exactly one final claimant
        return child.at[jnp.where(cond, idx, L)].set(val, mode="drop")

    # leaves claim the slot of their creating node
    leaf_ids = jnp.arange(L, dtype=jnp.int32)
    lp = state["leaf_parent"]
    valid_leaf = lp >= 0
    left_child = scatter_claims(left_child, lp, valid_leaf & state["leaf_is_left"], ~leaf_ids)
    right_child = scatter_claims(right_child, lp, valid_leaf & ~state["leaf_is_left"], ~leaf_ids)
    # internal nodes overwrite the slot they were grown from
    node_ids = jnp.arange(L - 1, dtype=jnp.int32)
    npar = state["node_parent"]
    valid_node = (npar >= 0) & (state["node_feature"] >= 0)
    left_child = scatter_claims(left_child, npar, valid_node & state["node_is_left"], node_ids)
    right_child = scatter_claims(right_child, npar, valid_node & ~state["node_is_left"], node_ids)

    tree = TreeArrays(
        split_feature=state["node_feature"],
        threshold=state["node_threshold"],
        default_left=state["node_default_left"],
        is_cat_split=state["node_is_cat"],
        cat_bits=state["node_cat_bits"],
        split_gain=state["node_gain"],
        left_child=left_child,
        right_child=right_child,
        leaf_value=state["leaf_value"],
        leaf_count=state["leaf_count"],
        leaf_weight=state["leaf_weight"],
        internal_value=state["node_value"],
        internal_count=state["node_count"],
        num_leaves=state["num_leaves"],
    )
    if not use_partition:
        return tree, state["node_assign"]

    # ---- node assignment from the partition (once per tree) ----------------
    # positions [begin_i, begin_i + nrows_i) belong to leaf i; empty leaves
    # get out-of-range sentinels so they never match.  Unrolled binary search
    # over the L sorted begins, then one scatter to row order.
    begins = jnp.where(state["leaf_nrows"] > 0, state["leaf_begin"],
                       n + 1 + jnp.arange(L, dtype=jnp.int32))
    order = jnp.argsort(begins)
    sorted_begin = begins[order]
    pos = jnp.arange(n, dtype=jnp.int32)
    rank = unrolled_rank(sorted_begin, pos, strict=False)
    leaf_of_pos = jnp.take(order, jnp.maximum(rank - 1, 0))
    node_assign = jnp.zeros(n, jnp.int32).at[state["perm"]].set(leaf_of_pos)
    return tree, node_assign
