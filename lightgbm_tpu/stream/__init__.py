"""Out-of-core training: host-resident bin matrix, streamed row blocks.

SCOPE.md's Criteo math (~86 GB of binned features per chip on v5e-16) puts
the flagship distributed workload far past HBM, so the device-resident
``Dataset.device_data()`` contract cannot serve it.  This subsystem keeps
the binned matrix in host RAM (``HostBinMatrix``), moves it through HBM in
double-buffered row blocks (``RowBlockPipeline`` — the ``jax.device_put``
of block k+1 overlaps the histogram/partition pass on block k, the TPU
analog of the GPU out-of-core block streamers of arxiv 1706.08359 /
1806.11248), and grows trees by accumulating per-leaf histograms
block-wise into the same ``[L, F, B, 3]`` layout ``ops/histogram.py``
produces, so the split search (``ops/split.find_best_split``) is shared
with the in-HBM growers unchanged.

Entry points:
- ``io.dataset.Dataset.stream_plan()`` — the budget decision
  (``max_bin_matrix_bytes`` / ``stream_rows`` / ``STREAM_FAKE_HBM_BYTES``);
- ``stream.booster.StreamGBDT`` / ``StreamGOSS`` — engine classes routed
  automatically by ``Booster`` when the plan says stream;
- ``stream.grower.StreamTreeGrower`` — one tree from host blocks, exact
  structural parity with the serial ``ops/grower.grow_tree`` semantics;
- ``parallel.trainer.train_distributed`` — chooses streaming per-rank
  before its data-parallel histogram reduction.

See docs/STREAMING.md for the block-size/prefetch model and the fake-HBM
testing seam.
"""
from .host_matrix import HostBinMatrix, StreamPlan, plan_streaming
from .pipeline import RowBlockPipeline
from .grower import StreamTreeGrower
from .booster import StreamGBDT, StreamGOSS

__all__ = ["HostBinMatrix", "StreamPlan", "plan_streaming",
           "RowBlockPipeline", "StreamTreeGrower", "StreamGBDT",
           "StreamGOSS"]
