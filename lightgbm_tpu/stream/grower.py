"""Streaming tree growth: the serial grower's semantics over host blocks.

One tree is grown with EXACTLY the structural semantics of the in-HBM
serial grower (``ops/grower.grow_tree``): best-first expansion of the
max-gain leaf, smaller-child histogram + sibling subtraction, left child
keeps the parent's leaf id, per-node feature sampling / extra-trees
thresholds keyed by the same split-step stream, basic monotone pinching —
so the streamed model is the same tree, verified structurally by
tests/test_stream.py.  What changes is WHERE the data lives:

- bins stay in host RAM (``HostBinMatrix``); each histogram pass streams
  row blocks through the ``RowBlockPipeline`` (H2D of block k+1 behind the
  pass on block k);
- per-leaf histograms accumulate block-wise into the same ``[F, B, 3]``
  layout ``ops/histogram.build_histogram`` produces, so the split search
  (``ops/split.find_best_split``) is byte-for-byte the shared one;
- leaf membership is a per-shard host ``leaf_vec`` int32 vector updated
  incrementally after each split (no device-resident permutation), and a
  per-(block, leaf) row-count table lets later passes SKIP blocks that
  hold no rows of the splitting leaf — deep-tree passes shrink toward the
  touched blocks only;
- the split loop itself runs on the host (the stream is host-paced
  anyway); each split costs one device sync to read the two children's
  candidate splits.

Multi-shard: ``shards`` may hold several host matrices (the data-parallel
row partition).  Histogram accumulation sums over all local shards'
blocks, then ``cross_reduce`` (optional) joins processes — the streaming
analog of ``DataParallelTreeLearner``'s histogram allreduce; split
DECISIONS are taken on the reduced histograms, so every rank applies the
identical split to its local rows.

Float caveat (shared with every sharded learner, see
tests/test_parallel.py): block/shard summation order differs from the
single-pass in-HBM kernels in final ulps, so split GAINS match to ~1e-5
relative and genuinely near-tied splits could in principle flip; split
features/thresholds/structure are asserted exact on tie-free data.
"""
from __future__ import annotations

import functools
import time
from typing import Callable, List, NamedTuple, Optional, Sequence

import numpy as np

from ..ops.grower import (GrowerConfig, TreeArrays, monotone_gain_mult,
                          node_feature_mask_for, rand_thresholds_for)
from ..ops.histogram import accumulate_histogram
from ..ops.split import (NEG_INF, bitset_contains, cat_words,
                         find_best_split)
from ..utils.log import LightGBMError, check
from .host_matrix import HostBinMatrix
from .pipeline import PipelineStats, RowBlockPipeline


class StreamShard(NamedTuple):
    """One host-resident row partition (a rank's local rows)."""
    matrix: HostBinMatrix
    pipeline: RowBlockPipeline


def make_shards(matrices: Sequence[HostBinMatrix], prefetch: int,
                stats: Optional[PipelineStats] = None) -> List[StreamShard]:
    stats = stats if stats is not None else PipelineStats()
    return [StreamShard(m, RowBlockPipeline(m, prefetch, stats))
            for m in matrices]


class StreamTreeGrower:
    """Grows trees from host-resident bin shards.

    Args:
      shards: local row partitions (one for single-host training).
      meta: numpy per-feature metadata — num_bins, default_bins, nan_bins,
        is_categorical, monotone (the ``Dataset.device_meta()`` fields).
      cfg: the shared ``GrowerConfig`` (serial semantics; parallel-mode
        fields are ignored — cross-rank joins ride ``cross_reduce``).
      cross_reduce: optional host-level reduction joining processes'
        histogram/total partials (data-parallel streaming).  Takes and
        returns a numpy array.
    """

    def __init__(self, shards: Sequence[StreamShard], meta: dict,
                 cfg: GrowerConfig,
                 cross_reduce: Optional[Callable] = None) -> None:
        import jax
        import jax.numpy as jnp

        check(len(shards) >= 1, "StreamTreeGrower needs >= 1 shard")
        widths = {s.matrix.num_cols for s in shards}
        check(len(widths) == 1, "stream shards must share the column width")
        self.shards = list(shards)
        self.cfg = cfg
        self.cross_reduce = cross_reduce
        self._f = int(widths.pop())
        self._B = cfg.max_bin
        self._cw = cat_words(self._B)
        self._L = cfg.num_leaves
        if cfg.bundle_bins:
            raise LightGBMError(
                "streaming training does not support EFB bundle columns; "
                "the Dataset disables bundling when a stream budget is "
                "configured")

        self._meta_host = {k: np.asarray(v) for k, v in meta.items()}
        self._meta_dev = {k: jnp.asarray(v)
                          for k, v in self._meta_host.items()}
        # per-(shard, block, leaf) row counts: blocks with zero rows of the
        # splitting leaf are skipped entirely (never transferred)
        self._counts = [np.zeros((s.matrix.num_blocks, self._L), np.int64)
                        for s in self.shards]
        # per-shard leaf membership, updated incrementally per split
        self._leaf_vecs = [np.zeros(s.matrix.num_data, np.int32)
                           for s in self.shards]
        # phase histograms (docs/OBSERVABILITY.md): the streamed loop is
        # host-paced, so these wall-clock spans are genuine per-phase cost
        # (unlike the fused in-HBM growers, which are one compiled program)
        from ..obs import metrics as _obs_metrics
        self._m_hist = _obs_metrics.histogram("stream.hist_seconds")
        self._m_partition = _obs_metrics.histogram("stream.partition_seconds")
        self._m_split = _obs_metrics.histogram("stream.split_seconds")
        self._build_jits()

    # ------------------------------------------------------------------
    def _build_jits(self) -> None:
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        md = self._meta_dev
        B = self._B
        p = cfg.split

        def hist_accum(acc, bins_blk, g, h, m):
            return accumulate_histogram(acc, bins_blk, g, h, m, B,
                                        method=cfg.hist_method,
                                        chunk_rows=cfg.hist_chunk_rows,
                                        variant=cfg.hist_variant)

        @jax.jit
        def root_pass(hist_acc, tot_acc, bins_blk, g, h, rw):
            tot = tot_acc + jnp.stack([jnp.sum(g * rw), jnp.sum(h * rw),
                                       jnp.sum(rw)])
            return hist_accum(hist_acc, bins_blk, g, h, rw), tot
        self._root_pass = root_pass

        @jax.jit
        def split_pass(hist_acc, bins_blk, leafv, g, h, rw, rows, leaf,
                       new_id, feat, thr, dleft, cbits, left_smaller):
            """Decide + repartition one block of the splitting leaf and
            accumulate the smaller child's histogram — the streamed fusion
            of the serial grower's partition_and_hist."""
            col = jnp.take(bins_blk, feat, axis=1).astype(jnp.int32)
            f_is_cat = md["is_categorical"][feat]
            nan_b = md["nan_bins"][feat]
            is_miss = (col == nan_b) & (nan_b >= 0)
            goes_left = jnp.where(f_is_cat, bitset_contains(cbits, col),
                                  jnp.where(is_miss, dleft, col <= thr))
            valid = jnp.arange(bins_blk.shape[0], dtype=jnp.int32) < rows
            in_leaf = (leafv == leaf) & valid
            new_vec = jnp.where(in_leaf & ~goes_left, new_id, leafv)
            small_mask = jnp.where(in_leaf & (goes_left == left_smaller),
                                   rw, 0.0)
            nl_blk = jnp.sum((in_leaf & goes_left).astype(jnp.int32))
            nin_blk = jnp.sum(in_leaf.astype(jnp.int32))
            return (hist_accum(hist_acc, bins_blk, g, h, small_mask),
                    new_vec, nl_blk, nin_blk)
        self._split_pass = split_pass

        use_pen = cfg.has_monotone and cfg.monotone_penalty > 0.0

        def find_inner(hist, sum_g, sum_h, count, fmask, key, step, depth,
                       lo, hi):
            if cfg.feature_fraction_bynode < 1.0:
                fmask = node_feature_mask_for(key, step, fmask,
                                              cfg.feature_fraction_bynode)
            rand = None
            if cfg.extra_trees:
                rand = rand_thresholds_for(key, step, cfg.extra_seed,
                                           md["num_bins"], md["nan_bins"])
            mult = None
            if use_pen:
                mult = monotone_gain_mult(depth, md["monotone"],
                                          cfg.monotone_penalty)
            return find_best_split(
                hist, md["num_bins"], md["default_bins"], md["nan_bins"],
                md["is_categorical"], md["monotone"], sum_g, sum_h, count,
                p, fmask, 0.0, lo, hi, rand_threshold=rand,
                sorted_cat=cfg.sorted_cat, gain_mult=mult)

        @jax.jit
        def root_find(hist, tot, fmask, key):
            return find_inner(hist, tot[0], tot[1], tot[2], fmask, key,
                              jnp.int32(0), jnp.int32(0),
                              jnp.float32(NEG_INF), jnp.float32(-NEG_INF))
        self._root_find = root_find

        # donate the [L, F, B, 3] store (the largest device resident) so
        # the functional .at[].set updates alias in place instead of
        # transiently doubling it every split; CPU doesn't implement
        # donation and would warn per call, so only donate off-CPU
        _donate = (0,) if jax.default_backend() != "cpu" else ()

        @functools.partial(jax.jit, donate_argnums=_donate)
        def child_step(store, small_hist, leaf, new_id, left_smaller,
                       sums2, lo2, hi2, step, depth, fmask, key):
            """Histogram subtraction + both children's split searches in one
            program (one device sync per split reads the pair).

            sums2: [2, 3] child (sum_g, sum_h, count); lo2/hi2: [2] bounds.
            """
            from ..ops.histogram import subtract_histogram
            parent = store[leaf]
            large = subtract_histogram(parent, small_hist)
            lhist = jnp.where(left_smaller, small_hist, large)
            rhist = subtract_histogram(parent, lhist)
            store = store.at[leaf].set(lhist).at[new_id].set(rhist)
            hist2 = jnp.stack([lhist, rhist])
            s2 = jax.vmap(
                lambda hc, s_, lo_, hi_: find_inner(
                    hc, s_[0], s_[1], s_[2], fmask, key, step, depth,
                    lo_, hi_))(hist2, sums2, lo2, hi2)
            return store, s2
        self._child_step = child_step

    # ------------------------------------------------------------------
    def _reduce(self, arr):
        out = np.asarray(arr, np.float32)
        if self.cross_reduce is not None:
            out = np.asarray(self.cross_reduce(out), np.float32)
        return out

    def _accumulate_root(self, g, h, rw):
        """Root histogram + totals over every shard's blocks."""
        import jax.numpy as jnp
        hist = jnp.zeros((self._f, self._B, 3), jnp.float32)
        tot = jnp.zeros(3, jnp.float32)
        for si, sh in enumerate(self.shards):
            off = self._shard_offsets[si]
            extras = {"g": g[off:off + sh.matrix.num_data],
                      "h": h[off:off + sh.matrix.num_data],
                      "rw": rw[off:off + sh.matrix.num_data]}
            for blk in sh.pipeline.blocks(extras):
                hist, tot = self._root_pass(hist, tot, blk.bins,
                                            blk.extras["g"],
                                            blk.extras["h"],
                                            blk.extras["rw"])
            self._counts[si][:, :] = 0
            for b in range(sh.matrix.num_blocks):
                self._counts[si][b, 0] = sh.matrix.block_rows_actual(b)
        return self._reduce(hist), self._reduce(tot)

    def _accumulate_split(self, si_extras, leaf, new_id, feat, thr, dleft,
                          cbits, left_smaller):
        """One streamed pass applying the chosen split: updates every
        shard's leaf_vec + count table, returns the smaller child's
        (locally accumulated) histogram."""
        import jax.numpy as jnp
        hist = jnp.zeros((self._f, self._B, 3), jnp.float32)
        cbits_dev = jnp.asarray(cbits)
        for si, sh in enumerate(self.shards):
            touched = np.nonzero(self._counts[si][:, leaf] > 0)[0]
            extras = dict(si_extras[si])
            extras["leafv"] = self._leaf_vecs[si]
            for blk in sh.pipeline.blocks(extras, only=touched):
                hist, new_vec, nl, nin = self._split_pass(
                    hist, blk.bins, blk.extras["leafv"], blk.extras["g"],
                    blk.extras["h"], blk.extras["rw"], np.int32(blk.rows),
                    np.int32(leaf), np.int32(new_id), np.int32(feat),
                    np.int32(thr), np.bool_(dleft), cbits_dev,
                    np.bool_(left_smaller))
                self._leaf_vecs[si][blk.start:blk.start + blk.rows] = \
                    np.asarray(new_vec)[:blk.rows]
                nl = int(nl)
                self._counts[si][blk.index, leaf] = nl
                self._counts[si][blk.index, new_id] = int(nin) - nl
        return hist

    # ------------------------------------------------------------------
    def grow(self, g: np.ndarray, h: np.ndarray, rw: np.ndarray,
             feature_mask, key):
        """Grow one tree from host gradients; returns
        ``(TreeArrays-of-numpy, node_assign[num_data] int32)``.

        ``g``/``h``/``rw`` are host float32 vectors over the concatenated
        shard rows (shard 0's rows first).
        """
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        L, cw, f = self._L, self._cw, self._f
        p = cfg.split
        self._shard_offsets = np.concatenate(
            [[0], np.cumsum([s.matrix.num_data for s in self.shards])]
        ).astype(np.int64)
        n_local = int(self._shard_offsets[-1])
        g = np.ascontiguousarray(np.asarray(g, np.float32))
        h = np.ascontiguousarray(np.asarray(h, np.float32))
        rw = np.ascontiguousarray(np.asarray(rw, np.float32))
        for vec in self._leaf_vecs:
            vec[:] = 0

        # ---- host-side tree state (mirrors grow_tree's state dict) -------
        best = dict(
            gain=np.full(L, NEG_INF, np.float32),
            feature=np.zeros(L, np.int32), threshold=np.zeros(L, np.int32),
            default_left=np.zeros(L, bool),
            lg=np.zeros(L, np.float32), lh=np.zeros(L, np.float32),
            lc=np.zeros(L, np.float32),
            rg=np.zeros(L, np.float32), rh=np.zeros(L, np.float32),
            rc=np.zeros(L, np.float32),
            lout=np.zeros(L, np.float32), rout=np.zeros(L, np.float32),
            cat_bits=np.zeros((L, cw), np.int32))
        leaf_depth = np.zeros(L, np.int32)
        leaf_value = np.zeros(L, np.float32)
        leaf_count = np.zeros(L, np.float32)
        leaf_weight = np.zeros(L, np.float32)
        leaf_sum_g = np.zeros(L, np.float32)
        leaf_lo = np.full(L, NEG_INF, np.float32)
        leaf_hi = np.full(L, -NEG_INF, np.float32)
        leaf_parent = np.full(L, -1, np.int32)
        leaf_is_left = np.zeros(L, bool)
        node_feature = np.full(L - 1, -1, np.int32)
        node_threshold = np.zeros(L - 1, np.int32)
        node_default_left = np.zeros(L - 1, bool)
        node_is_cat = np.zeros(L - 1, bool)
        node_cat_bits = np.zeros((L - 1, cw), np.int32)
        node_gain = np.zeros(L - 1, np.float32)
        node_value = np.zeros(L - 1, np.float32)
        node_count = np.zeros(L - 1, np.float32)
        left_child = np.full(L - 1, -1, np.int32)
        right_child = np.full(L - 1, -1, np.int32)

        def assemble(num_leaves: int):
            return TreeArrays(
                split_feature=node_feature, threshold=node_threshold,
                default_left=node_default_left, is_cat_split=node_is_cat,
                cat_bits=node_cat_bits, split_gain=node_gain,
                left_child=left_child, right_child=right_child,
                leaf_value=leaf_value, leaf_count=leaf_count,
                leaf_weight=leaf_weight, internal_value=node_value,
                internal_count=node_count,
                num_leaves=np.int32(num_leaves))

        node_assign = np.concatenate(self._leaf_vecs) if n_local else \
            np.zeros(0, np.int32)

        # ---- degenerate: no usable features -> single-leaf tree ----------
        if f == 0:
            tot = self._reduce(np.asarray(
                [np.sum(g * rw), np.sum(h * rw), np.sum(rw)], np.float32))
            leaf_count[0], leaf_weight[0] = tot[2], tot[1]
            return assemble(1), node_assign

        fmask_dev = jnp.asarray(np.asarray(feature_mask, np.float32))

        # ---- root --------------------------------------------------------
        t0 = time.perf_counter()
        root_hist, tot = self._accumulate_root(g, h, rw)
        self._m_hist.observe(time.perf_counter() - t0)
        store = jnp.zeros((L, f, self._B, 3), jnp.float32
                          ).at[0].set(jnp.asarray(root_hist))
        leaf_count[0], leaf_weight[0], leaf_sum_g[0] = tot[2], tot[1], tot[0]
        s0 = jax.device_get(self._root_find(jnp.asarray(root_hist),
                                            jnp.asarray(tot), fmask_dev, key))
        _set_best(best, 0, s0)

        si_extras = []
        for si, sh in enumerate(self.shards):
            off = self._shard_offsets[si]
            end = off + sh.matrix.num_data
            si_extras.append({"g": g[off:end], "h": h[off:end],
                              "rw": rw[off:end]})

        # ---- best-first growth (grow_tree's while loop, host-paced) ------
        num_leaves = 1
        while num_leaves < L:
            active = best["gain"][:num_leaves]
            leaf = int(np.argmax(active))
            gain = float(active[leaf])
            if not gain > 0.0:
                break
            j = num_leaves - 1                     # node slot of this split
            new_id = num_leaves
            feat = int(best["feature"][leaf])
            thr = int(best["threshold"][leaf])
            dleft = bool(best["default_left"][leaf])
            f_is_cat = bool(self._meta_host["is_categorical"][feat])
            cbits = best["cat_bits"][leaf]
            left_smaller = bool(best["lc"][leaf] <= best["rc"][leaf])

            # --- node arrays + parent linkage (scatter_claims, host form)
            node_feature[j] = feat
            node_threshold[j] = thr
            node_default_left[j] = dleft
            node_is_cat[j] = f_is_cat
            node_cat_bits[j] = cbits
            node_gain[j] = gain
            node_value[j] = _leaf_output_np(
                leaf_sum_g[leaf], leaf_weight[leaf], leaf_count[leaf], p)
            node_count[j] = leaf_count[leaf]
            par = leaf_parent[leaf]
            if par >= 0:
                if leaf_is_left[leaf]:
                    left_child[par] = j
                else:
                    right_child[par] = j
            left_child[j] = ~leaf
            right_child[j] = ~new_id

            # --- streamed partition + smaller-child histogram -------------
            t0 = time.perf_counter()
            small_local = self._accumulate_split(
                si_extras, leaf, new_id, feat, thr, dleft, cbits,
                left_smaller)
            small_hist = jnp.asarray(self._reduce(small_local))
            self._m_partition.observe(time.perf_counter() - t0)

            # --- child bookkeeping (apply_split, host form) ---------------
            depth = leaf_depth[leaf] + 1
            leaf_depth[leaf] = leaf_depth[new_id] = depth
            leaf_value[leaf] = best["lout"][leaf]
            leaf_value[new_id] = best["rout"][leaf]
            lsums = np.asarray([best["lg"][leaf], best["lh"][leaf],
                                best["lc"][leaf]], np.float32)
            rsums = np.asarray([best["rg"][leaf], best["rh"][leaf],
                                best["rc"][leaf]], np.float32)
            leaf_sum_g[leaf], leaf_weight[leaf], leaf_count[leaf] = lsums
            leaf_sum_g[new_id], leaf_weight[new_id], leaf_count[new_id] = \
                rsums
            leaf_parent[leaf] = leaf_parent[new_id] = j
            leaf_is_left[leaf], leaf_is_left[new_id] = True, False

            # basic monotone: pinch children at the midpoint (f32 math
            # matches the device op bit-for-bit)
            lo, hi = leaf_lo[leaf], leaf_hi[leaf]
            if cfg.has_monotone:
                mono = int(self._meta_host["monotone"][feat])
                mid = np.float32(
                    (best["lout"][leaf] + best["rout"][leaf])
                    * np.float32(0.5))
                l_lo = max(lo, mid) if mono < 0 else lo
                l_hi = min(hi, mid) if mono > 0 else hi
                r_lo = max(lo, mid) if mono > 0 else lo
                r_hi = min(hi, mid) if mono < 0 else hi
            else:
                l_lo = r_lo = lo
                l_hi = r_hi = hi
            leaf_lo[leaf], leaf_hi[leaf] = l_lo, l_hi
            leaf_lo[new_id], leaf_hi[new_id] = r_lo, r_hi

            # --- both children's next best splits (one device sync) -------
            t0 = time.perf_counter()
            store, s2 = self._child_step(
                store, small_hist, np.int32(leaf), np.int32(new_id),
                np.bool_(left_smaller),
                jnp.asarray(np.stack([lsums, rsums])),
                jnp.asarray(np.asarray([l_lo, r_lo], np.float32)),
                jnp.asarray(np.asarray([l_hi, r_hi], np.float32)),
                np.int32(j + 1), np.int32(depth), fmask_dev, key)
            s2 = jax.device_get(s2)
            self._m_split.observe(time.perf_counter() - t0)
            depth_ok = cfg.max_depth <= 0 or depth < cfg.max_depth
            sl = jax.tree.map(lambda a: a[0], s2)
            sr = jax.tree.map(lambda a: a[1], s2)
            if not depth_ok:
                sl = sl._replace(gain=np.float32(NEG_INF))
                sr = sr._replace(gain=np.float32(NEG_INF))
            _set_best(best, leaf, sl)
            _set_best(best, new_id, sr)
            num_leaves += 1

        node_assign = (np.concatenate(self._leaf_vecs) if n_local
                       else node_assign)
        return assemble(num_leaves), node_assign


def _leaf_output_np(sum_g, sum_h, count, p) -> np.float32:
    """Host float32 replica of ``ops.split.leaf_output`` (unbounded,
    parent_output=0) for the per-split node_value — a device call here
    would add one sync per split to the host-paced loop.  Same IEEE f32
    ops as the device version, so model-text internal_value matches."""
    g = np.float32(sum_g)
    h = np.float32(sum_h)
    thr = np.float32(np.sign(g)) * np.maximum(
        np.abs(g) - np.float32(p.lambda_l1), np.float32(0.0))
    raw = -thr / (h + np.float32(p.lambda_l2) + np.float32(1e-35))
    if p.max_delta_step > 0:
        raw = np.clip(raw, np.float32(-p.max_delta_step),
                      np.float32(p.max_delta_step))
    if p.path_smooth > 0:
        c = np.float32(count)
        smooth = c / (c + np.float32(p.path_smooth))
        raw = raw * smooth          # parent_output = 0 at the split leaf
    return np.float32(raw)


def _set_best(best: dict, i: int, s) -> None:
    """Record a SplitResult (host pytree) as leaf ``i``'s pending split."""
    best["gain"][i] = s.gain
    best["feature"][i] = s.feature
    best["threshold"][i] = s.threshold
    best["default_left"][i] = s.default_left
    best["lg"][i] = s.left_sum_g
    best["lh"][i] = s.left_sum_h
    best["lc"][i] = s.left_count
    best["rg"][i] = s.right_sum_g
    best["rh"][i] = s.right_sum_h
    best["rc"][i] = s.right_count
    best["lout"][i] = s.left_output
    best["rout"][i] = s.right_output
    best["cat_bits"][i] = s.cat_bits
