"""Double-buffered host->device row-block pipeline.

The consumer iterates blocks; the pipeline keeps up to ``prefetch`` blocks
in flight beyond the one being consumed, issuing each ``jax.device_put``
BEFORE the previous block's compute is drained — on TPU the H2D copy of
block k+1 runs behind the histogram/partition pass on block k (async
dispatch), on CPU the same structure degrades to eager copies so tier-1
tests exercise identical ordering/eviction behavior.

Every block is padded to the uniform ``block_rows`` shape (pad rows ride
row-weight 0, so they vanish from every histogram and sum) — one compiled
program shape serves all blocks.  Device-byte accounting
(``PipelineStats``) is the measurement surface for the synthetic-HBM-cap
tests and ``scripts/bench_stream.py``.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Iterator, NamedTuple, Optional, Sequence

import numpy as np

from ..obs import costs as obs_costs
from ..obs import metrics as obs_metrics
from .host_matrix import HostBinMatrix


@dataclass
class PipelineStats:
    """Cumulative transfer accounting across passes (shared per trainer)."""
    puts: int = 0                  # device_put calls (blocks)
    bytes_h2d: int = 0             # bytes moved host -> device
    peak_block_bytes: int = 0      # max bytes of blocks live at once
    passes: int = 0                # full sweeps over the matrix
    blocks_skipped: int = 0        # blocks never transferred (empty leaves)

    def as_dict(self) -> dict:
        return dict(puts=self.puts, bytes_h2d=self.bytes_h2d,
                    peak_block_bytes=self.peak_block_bytes,
                    passes=self.passes, blocks_skipped=self.blocks_skipped)


class Block(NamedTuple):
    """One in-flight row block."""
    index: int
    rows: int                # actual rows (<= block_rows; rest is padding)
    start: int               # global row offset of the block
    bins: object             # [block_rows, C] device array
    extras: Dict[str, object]   # name -> [block_rows] device array (padded)


class RowBlockPipeline:
    """Bounded-prefetch iterator over a ``HostBinMatrix``'s row blocks.

    ``extras`` are per-row host arrays (float32/int32) sliced, padded and
    device-put alongside each bins block — gradients/hessians/row-weights
    and per-block leaf-index vectors ride here, so ONE put per block moves
    everything the pass consumes.
    """

    def __init__(self, matrix: HostBinMatrix, prefetch: int = 2,
                 stats: Optional[PipelineStats] = None) -> None:
        self.matrix = matrix
        self.prefetch = max(1, int(prefetch))
        self.stats = stats if stats is not None else PipelineStats()
        # process-wide mirrors of the per-trainer PipelineStats, so
        # obs-report sees H2D volume without a handle on the trainer
        self._m_puts = obs_metrics.counter("stream.h2d_puts")
        self._m_bytes = obs_metrics.counter("stream.h2d_bytes")
        self._m_passes = obs_metrics.counter("stream.passes")
        self._m_skipped = obs_metrics.counter("stream.blocks_skipped")
        self._m_peak = obs_metrics.gauge("stream.peak_block_bytes")

    # ------------------------------------------------------------------
    def _put(self, i: int, extras: Dict[str, np.ndarray]) -> Block:
        import jax

        m = self.matrix
        sl = m.block_slice(i)
        rows = sl.stop - sl.start
        pad = m.block_rows - rows
        blk = m.bins[sl]
        if pad:
            blk = np.pad(blk, ((0, pad), (0, 0)))
        dev_extras = {}
        nbytes = blk.nbytes
        for name, arr in extras.items():
            a = arr[sl.start:sl.stop]
            if pad:
                a = np.pad(a, (0, pad))
            d = jax.device_put(a)
            nbytes += a.nbytes
            dev_extras[name] = d
        bins_dev = jax.device_put(blk)
        self.stats.puts += 1
        self.stats.bytes_h2d += nbytes
        self._m_puts.inc()
        self._m_bytes.inc(nbytes)
        # HBM watermark per transfer (local stats read, no sync; {} on CPU)
        obs_costs.record_watermarks("stream")
        return Block(index=i, rows=rows, start=sl.start, bins=bins_dev,
                     extras=dev_extras)

    def blocks(self, extras: Optional[Dict[str, np.ndarray]] = None,
               only: Optional[Sequence[int]] = None) -> Iterator[Block]:
        """Yield blocks in index order with bounded prefetch.

        ``only``: optional block-index subset (sorted) — blocks whose
        target leaf is empty are never transferred at all (the skip is
        recorded, so bench/tests can assert the eviction math).
        """
        extras = extras or {}
        m = self.matrix
        order = list(range(m.num_blocks)) if only is None else sorted(only)
        if only is not None:
            self.stats.blocks_skipped += m.num_blocks - len(order)
            self._m_skipped.inc(m.num_blocks - len(order))
        self.stats.passes += 1
        self._m_passes.inc()
        q: deque = deque()
        nxt = 0
        first = True
        while nxt < len(order) or q:
            # issue the H2D of upcoming blocks BEFORE consuming the oldest:
            # on an async backend these copies overlap the caller's compute.
            # Refill only to `prefetch`: during this refill the CONSUMER
            # still references the previously yielded block (its loop
            # variable is rebound only after next() returns), so total
            # device residency is len(q) + 1 — refilling to prefetch+1 here
            # would transiently pin prefetch+2 blocks, silently overshooting
            # the (prefetch+1)-block budget model of plan_streaming
            while nxt < len(order) and len(q) < self.prefetch:
                q.append(self._put(order[nxt], extras))
                nxt += 1
            per_block = (m.block_nbytes
                         + sum(4 * m.block_rows for _ in extras))
            held = 0 if first else 1          # the consumer-held block
            self.stats.peak_block_bytes = max(
                self.stats.peak_block_bytes, (len(q) + held) * per_block)
            self._m_peak.set_max(self.stats.peak_block_bytes)
            blk = q.popleft()
            first = False
            yield blk
            # the yielded block's device buffers die with the last reference
            # (the consumer drops them when it moves on) — eviction is
            # reference-counted, nothing pins more than prefetch + 1 blocks
            del blk
