"""Streaming boosting engines: GBDT/GOSS over a host-resident bin matrix.

``StreamGBDT`` keeps the training loop's per-row state on the HOST — raw
scores ``[K, N]`` float32, gradients/hessians, bagging masks, leaf
assignments — and drives ``StreamTreeGrower`` for tree growth, so the only
device residents are the streamed row blocks (bounded by the
``max_bin_matrix_bytes`` budget), the ``[L, F, B, 3]`` histogram store and
the per-feature metadata.  Gradients are computed per row block from the
host scores (one compiled objective program per block shape), matching the
in-HBM engine's elementwise objective math row-for-row.

Scope (v1, checked loudly in ``init_train``): serial single-process
training (multi-process streaming goes through
``parallel.trainer.train_distributed``), built-in elementwise or
renew-style objectives plus custom fobj, bagging (incl. pos/neg) and GOSS,
categorical features, basic monotone constraints, feature_fraction
(bytree + bynode), extra_trees, max_depth.  Not served: linear trees,
CEGB, interaction constraints, forced splits, monotone
intermediate/advanced, ranking objectives (query-coupled gradients), DART
and RF boosting.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..config import Config
from ..io.dataset import Dataset
from ..metric import create_metrics
from ..models.gbdt import GBDT, bag_mask_from_uniform
from ..obs import health as obs_health
from ..models.goss import goss_mask_from_importance
from ..models.tree import Tree
from ..objective import create_objective
from ..utils.log import Log, LightGBMError, check
from ..utils.random_gen import key_for_iteration
from ..utils.timer import global_timer
from .grower import StreamTreeGrower, make_shards
from .pipeline import PipelineStats


def stream_gradients(objective, score: np.ndarray, label_np, weight_np,
                     block_rows: int):
    """Per-block objective gradients from host-resident scores.

    THE streaming gradient loop (single-process booster AND distributed
    trainer — one copy, so the chunking/objective math cannot drift
    between the paths whose parity the subsystem guarantees).  ``score``
    is host ``[K, n]`` float32; returns host ``(g, h)`` of the same shape.
    """
    import jax.numpy as jnp
    if objective is None:
        raise LightGBMError("objective is None; provide custom grad/hess")
    K, n = score.shape
    g = np.empty((K, n), np.float32)
    h = np.empty((K, n), np.float32)
    for s in range(0, n, block_rows):
        e = min(s + block_rows, n)
        sc = jnp.asarray(score[:, s:e])
        lab = jnp.asarray(label_np[s:e]) if label_np is not None else None
        w = jnp.asarray(weight_np[s:e]) if weight_np is not None else None
        if K > 1:
            gg, hh = objective.get_gradients_multi(sc, lab, w)
        else:
            gg, hh = objective.get_gradients(sc[0], lab, w)
            gg, hh = gg[None, :], hh[None, :]
        g[:, s:e] = np.asarray(gg, np.float32)
        h[:, s:e] = np.asarray(hh, np.float32)
    return g, h


def stream_goss_sample(cfg: Config, iteration: int, imp: np.ndarray,
                       lo: int = 0, hi: "int | None" = None):
    """(mask, amplify) host arrays for rows ``[lo:hi)`` of the global
    order, from the GLOBAL per-row importance ``imp`` — the one streaming
    implementation of the in-HBM GOSS keying (exact global top-k +
    seeded tail draw, ``goss_mask_from_importance``)."""
    import jax
    import jax.numpy as jnp
    n_total = imp.shape[0]
    key = key_for_iteration(cfg.bagging_seed, iteration)
    mask, amplify = goss_mask_from_importance(
        cfg, jnp.asarray(imp), jax.random.uniform(key, (n_total,)),
        max(1, int(cfg.top_rate * n_total)))
    mask = np.asarray(mask, np.float32)
    amplify = np.asarray(amplify, np.float32)
    if lo or hi is not None:
        mask, amplify = mask[lo:hi], amplify[lo:hi]
    return mask, amplify


def predict_leaf_blocks(predict_fn, matrix) -> np.ndarray:
    """Leaf index per row of a host-resident matrix, one block at a time
    (over-budget validation sets — shared by the booster and the
    distributed trainer)."""
    out = np.empty(matrix.num_data, np.int32)
    for b in range(matrix.num_blocks):
        sl = matrix.block_slice(b)
        out[sl] = np.asarray(predict_fn(matrix.block(b)))
    return out


def stream_bag_mask(cfg: Config, iteration: int, n_global: int, label_np,
                    lo: int = 0, hi: "int | None" = None) -> np.ndarray:
    """Host bagging mask over rows ``[lo:hi)`` of the GLOBAL row order.

    THE one streaming implementation of the in-HBM keying
    (``key_for_iteration(bagging_seed, it // bagging_freq)`` ->
    ``bag_mask_from_uniform``): the single-process booster draws over its
    whole dataset (lo=0, hi=None) and the distributed trainer slices its
    rank's window of the same global draw — both must stay byte-identical
    to the device path for multi-process parity, so the formula lives
    once here."""
    import jax
    import jax.numpy as jnp
    key = key_for_iteration(cfg.bagging_seed, iteration // cfg.bagging_freq)
    u = jax.random.uniform(key, (n_global,))
    if lo or hi is not None:
        u = u[lo:hi]
    lab = jnp.asarray(label_np) if label_np is not None else None
    return np.asarray(bag_mask_from_uniform(cfg, u, lab), np.float32)


def _finite_stats(a) -> dict:
    """Host-side sentinel stats (the streaming twin of the device
    reductions in ``GBDT._health_stats_fn``)."""
    a = np.asarray(a, np.float32).ravel()
    finite = np.isfinite(a)
    mx = float(np.abs(a[finite]).max()) if finite.any() else 0.0
    return {"finite_frac": float(finite.mean()), "max_abs": mx}


class StreamGBDT(GBDT):
    """Out-of-core GBDT engine (see module docstring)."""

    # ------------------------------------------------------------------
    def init_train(self, train_data: Dataset) -> None:
        cfg = self.config
        self.train_data = train_data
        plan = train_data.stream_plan()
        check(plan is not None,
              "StreamGBDT needs a Dataset whose stream_plan() streams "
              "(set max_bin_matrix_bytes/stream_rows)")
        self._plan = plan
        self._check_supported(cfg)

        if self.objective is None:
            self.objective = create_objective(cfg)
        if self.objective is not None:
            if getattr(self.objective, "is_ranking", False):
                raise LightGBMError(
                    "out-of-core streaming does not support ranking "
                    "objectives (query-coupled gradients cannot be computed "
                    "per row block)")
            self.objective.init(train_data.metadata, train_data.num_data)
            self.num_tree_per_iteration = \
                self.objective.num_model_per_iteration
        else:
            self.num_tree_per_iteration = max(1, cfg.num_class)
        self.max_feature_idx = train_data.num_total_features - 1
        self.train_metrics = create_metrics(cfg)
        for m in self.train_metrics:
            m.init(train_data.metadata, train_data.num_data)

        # feature metadata WITHOUT bins: the matrix stays in host RAM
        self._dd = train_data.device_meta()
        md = train_data.metadata
        self._label_np = (np.asarray(md.label, np.float32)
                          if md.label is not None else None)
        self._weight_np = (np.asarray(md.weight, np.float32)
                           if md.weight is not None else None)
        K = self.num_tree_per_iteration
        n = train_data.num_data

        # boost from average / init_score (host scores)
        init = np.zeros((K, n), dtype=np.float32)
        md_init = md.init_score
        self.init_scores = [0.0] * K
        if md_init is not None:
            init += md_init.reshape(-1, n).astype(np.float32)
        elif cfg.boost_from_average and self.objective is not None:
            for k in range(K):
                s = self.objective.boost_from_score(k)
                self.init_scores[k] = s
                init[k] += s
        self._train_score = init
        self._grower_cfg = self._make_grower_cfg()

        self.stream_stats = PipelineStats()
        self._matrix = train_data.host_bin_matrix(plan)
        meta = {k: np.asarray(getattr(self._dd, k)) for k in
                ("num_bins", "default_bins", "nan_bins", "is_categorical",
                 "monotone")}
        self._stream_grower = StreamTreeGrower(
            make_shards([self._matrix], plan.prefetch, self.stream_stats),
            meta, self._grower_cfg)
        Log.info(
            "out-of-core streaming: %.1f MB bin matrix vs %s budget -> "
            "%d blocks of %d rows (prefetch %d, ~%.1f MB device-resident)",
            plan.total_bytes / 1e6,
            ("%.1f MB" % (plan.budget_bytes / 1e6) if plan.budget_bytes
             else "stream_rows"),
            plan.num_blocks, plan.block_rows, plan.prefetch,
            (plan.prefetch + 1) * self._matrix.block_nbytes / 1e6)

    @staticmethod
    def _check_supported(cfg: Config) -> None:
        bad = []
        if cfg.linear_tree:
            bad.append("linear_tree")
        if cfg.tree_learner != "serial":
            bad.append("tree_learner=%s (single-process streaming is "
                       "serial; multi-process goes through "
                       "parallel.train_distributed)" % cfg.tree_learner)
        if cfg.interaction_constraints:
            bad.append("interaction_constraints")
        if cfg.forcedsplits_filename:
            bad.append("forcedsplits_filename")
        if (cfg.cegb_tradeoff * cfg.cegb_penalty_split > 0
                or cfg.cegb_penalty_feature_lazy
                or cfg.cegb_penalty_feature_coupled):
            bad.append("cegb penalties")
        if (any(v != 0 for v in cfg.monotone_constraints)
                and cfg.monotone_constraints_method != "basic"):
            bad.append("monotone_constraints_method="
                       + cfg.monotone_constraints_method)
        if bad:
            raise LightGBMError(
                "out-of-core streaming does not support: " + ", ".join(bad))

    # ------------------------------------------------------------------
    def add_valid_data(self, valid_data: Dataset, name: str) -> None:
        super().add_valid_data(valid_data, name)
        # host scores (the base stored a device array; np.asarray of a jax
        # array is a read-only view — copy for in-place updates)
        self._valid_scores[-1] = np.array(self._valid_scores[-1],
                                          np.float32)

    # ------------------------------------------------------------------
    def _compute_gradients_stream(self):
        """Per-block objective gradients from the host-resident scores
        (``stream_gradients``, shared with the distributed trainer)."""
        return stream_gradients(self.objective, self._train_score,
                                self._label_np, self._weight_np,
                                self._plan.block_rows)

    def _stream_row_sample(self, iteration: int, g, h):
        """Bagging mask + amplified gradients, host-side; the uniform draw
        and mask formula are byte-identical to the in-HBM path
        (``stream_bag_mask``, shared with the distributed trainer)."""
        cfg = self.config
        n = self.train_data.num_data
        need = cfg.bagging_freq > 0 and (cfg.bagging_fraction < 1.0 or
                                         cfg.pos_bagging_fraction < 1.0 or
                                         cfg.neg_bagging_fraction < 1.0)
        if not need:
            return None, g, h
        if iteration % cfg.bagging_freq == 0 or \
                getattr(self, "_bag_mask_np", None) is None:
            self._bag_mask_np = stream_bag_mask(cfg, iteration, n,
                                                self._label_np)
        mask = self._bag_mask_np
        return mask, g * mask[None, :], h * mask[None, :]

    # ------------------------------------------------------------------
    def _valid_leaf_stream(self, vi: int, tree_arrays):
        """Leaf index of every validation row — streamed block-wise when the
        valid set itself is over budget, device-resident otherwise."""
        import jax
        import jax.numpy as jnp
        from ..ops.predict import predict_leaf_binned

        if not hasattr(self, "_valid_stream"):
            self._valid_stream = {}
            self._vpredict = jax.jit(
                lambda ta, b: predict_leaf_binned(ta, b, self._dd.nan_bins))
        if vi not in self._valid_stream:
            vset = self.valid_sets[vi]
            vplan = vset.stream_plan()
            if vplan is None:
                self._valid_stream[vi] = ("device",
                                          jnp.asarray(vset.bins))
            else:
                self._valid_stream[vi] = ("host",
                                          vset.host_bin_matrix(vplan))
        kind, store = self._valid_stream[vi]
        ta_dev = jax.tree.map(jnp.asarray, tree_arrays)
        if kind == "device":
            return np.asarray(self._vpredict(ta_dev, store))
        return predict_leaf_blocks(
            lambda blk: self._vpredict(ta_dev, jnp.asarray(blk)), store)

    # ------------------------------------------------------------------
    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        cfg = self.config
        K = self.num_tree_per_iteration
        n = self.train_data.num_data
        it = self.iter_

        if self._stop_flag:
            Log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            return True

        obs = self._obs
        if obs is not None:
            obs.phase_mark()
            obs.tracer.begin("train/iteration", step=it)

        with global_timer.scope("StreamGBDT::gradients"):
            if grad is None or hess is None:
                g, h = self._compute_gradients_stream()
            else:
                g = np.asarray(grad, np.float32).reshape(K, n)
                h = np.asarray(hess, np.float32).reshape(K, n)

        mask, g, h = self._stream_row_sample(it, g, h)
        rw = mask if mask is not None else np.ones(n, np.float32)
        fmask = np.asarray(self._feature_mask(it), np.float32)
        self._prev_scores = (self._train_score.copy(),
                             [v.copy() for v in self._valid_scores])

        should_stop = True
        for k in range(K):
            with global_timer.scope("StreamGBDT::grow_tree"):
                tree_arrays, node_assign = self._stream_grower.grow(
                    g[k], h[k], rw, fmask,
                    key_for_iteration(cfg.seed, it, salt=k + 1))
            nl = int(tree_arrays.num_leaves)
            if self._health_due(it, k):
                # streaming gradients/leaves are already host numpy —
                # check in line (no device round-trip to ride)
                obs_health.check_numeric(
                    {"grad": _finite_stats(g[k]),
                     "hess": _finite_stats(h[k]),
                     "leaf_value": _finite_stats(tree_arrays.leaf_value)},
                    iteration=it, kind="stream",
                    log=obs.log if obs is not None else None)
            if nl > 1:
                should_stop = False
            if obs is not None:
                obs.tree_event(
                    it, num_leaves=nl,
                    split_gains=[float(v) for v in np.asarray(
                        tree_arrays.split_gain)[:max(0, nl - 1)]])
            tree = Tree.from_arrays(tree_arrays, self.train_data,
                                    learning_rate=1.0)

            # leaf renewal for L1-style objectives (host state is already
            # exactly what renew wants: per-row leaf ids + scores)
            if (self.objective is not None
                    and self.objective.need_renew_tree_output() and nl > 1):
                new_vals = self.objective.renew_leaf_values(
                    node_assign, self._train_score[k].astype(np.float64),
                    tree.leaf_value.copy(), nl)
                tree.leaf_value = np.asarray(new_vals, np.float64)
                tree_arrays = tree_arrays._replace(
                    leaf_value=np.asarray(tree.leaf_value, np.float32))

            tree.shrink(self.shrinkage_rate)
            if it == 0 and self.init_scores[k] != 0.0:
                if nl > 1:
                    tree.add_bias(self.init_scores[k])
                else:
                    tree.leaf_value = np.full_like(tree.leaf_value,
                                                   self.init_scores[k])

            with global_timer.scope("StreamGBDT::update_score"):
                if nl > 1:
                    delta = (np.asarray(tree_arrays.leaf_value, np.float32)
                             * np.float32(self.shrinkage_rate))
                    self._train_score[k] += delta[node_assign]
                    for vi in range(len(self.valid_sets)):
                        vleaf = self._valid_leaf_stream(vi, tree_arrays)
                        self._valid_scores[vi][k] += delta[vleaf]
            self.models.append(tree)
            self._tree_weights.append(self.shrinkage_rate)

        self.iter_ += 1
        if obs is not None:
            obs.tracer.end("train/iteration")
            obs.iteration_event(it, trees=K)
        elif self._health_enabled:
            obs_health.set_status(stage="stream", iteration=it)
        if should_stop:
            Log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            self._stop_flag = True
        return should_stop

    # ------------------------------------------------------------------
    def continue_from(self, prev: "GBDT") -> None:
        super().continue_from(prev)
        # the base warms scores into device arrays; streaming keeps host f32
        # (np.array, not asarray: jax arrays view as read-only)
        self._train_score = np.array(self._train_score, np.float32)
        self._valid_scores = [np.array(v, np.float32)
                              for v in self._valid_scores]

    def rollback_one_iter(self) -> None:
        # base pops _device_trees too; streaming never fills it, so guard
        if self.iter_ <= 0:
            return
        if self._prev_scores is None:
            raise LightGBMError(
                "rollback history exhausted (only one step kept)")
        K = self.num_tree_per_iteration
        self.models = self.models[:-K]
        self._tree_weights = self._tree_weights[:-K]
        self._ens_cache = None
        self.iter_ -= 1
        self._empty_by_iter.pop(self.iter_, None)
        self._stop_flag = False
        self._train_score, self._valid_scores = self._prev_scores
        self._prev_scores = None


class StreamGOSS(StreamGBDT):
    """GOSS sampling over the streaming engine: the top-rate cut and
    random-tail draw reuse ``goss_mask_from_importance`` with the same
    iteration keying as the in-HBM GOSS, so sampled row sets match."""

    def _stream_row_sample(self, iteration: int, g, h):
        cfg = self.config
        if cfg.top_rate + cfg.other_rate >= 1.0:
            return None, g, h
        imp = np.sum(np.abs(g * h), axis=0)
        mask, amplify = stream_goss_sample(cfg, iteration, imp)
        amplify = amplify[None, :]
        return mask, g * amplify, h * amplify
