"""Host-resident bin matrix + the streaming budget decision.

The budget model: with prefetch depth ``d``, at most ``d + 1`` row blocks
are device-resident at once (the block being consumed plus the in-flight
prefetches), so the block size is chosen as

    block_rows = budget // ((prefetch + 1) * bytes_per_row)

rounded down to a 128-multiple (row blocks tile the TPU sublane grid).
``STREAM_FAKE_HBM_BYTES`` overrides the configured budget so CPU tier-1
tests exercise real eviction/prefetch behavior without hardware — the same
fake-backend seam pattern that made the TPU-window watcher testable
(docs/WATCHER.md).
"""
from __future__ import annotations

import os
from typing import NamedTuple, Optional

import numpy as np

FAKE_HBM_ENV = "STREAM_FAKE_HBM_BYTES"

# floor on the auto-chosen block: blocks below this thrash dispatch
# overhead without saving meaningful HBM
MIN_BLOCK_ROWS = 128

# per-row device bytes riding alongside each bins block: gradients,
# hessians, row weights, leaf-index vector (4 x f32/i32).  Folded into the
# block-size math so the STREAMED residency — not just the bins — stays
# under the budget (for Criteo-wide rows this is noise; for the narrow
# synthetic test matrices it is not)
SIDECAR_BYTES_PER_ROW = 16


class StreamPlan(NamedTuple):
    """Decision record of the out-of-core budget check."""
    block_rows: int          # rows per streamed block (128-multiple)
    num_blocks: int
    budget_bytes: int        # effective budget (0 = none configured)
    prefetch: int            # blocks in flight beyond the consumed one
    total_bytes: int         # full bin-matrix footprint
    reason: str              # 'stream_rows' | 'budget' — what triggered


def effective_budget_bytes(config) -> int:
    """Configured device budget for the bin matrix; the fake-HBM env var
    (testing seam) wins over the config knob.  0 = unbudgeted;
    ``STREAM_FAKE_HBM_BYTES=0`` disables the seam and the config knob
    governs again (a 0->1-byte clamp here would silently force every run
    to the 128-row block floor)."""
    env = os.environ.get(FAKE_HBM_ENV, "").strip()
    if env and int(env) > 0:
        return int(env)
    return int(getattr(config, "max_bin_matrix_bytes", 0) or 0)


def plan_streaming(num_data: int, num_cols: int, itemsize: int,
                   config) -> Optional[StreamPlan]:
    """Decide whether (and how) training should stream; None = fits.

    NOTE for distributed use: the decision depends on the LOCAL row count,
    so ranks may legitimately differ (the trainer chooses streaming
    per-rank) — but anything affecting cross-rank layout (EFB bundling,
    histogram shape) must gate on config alone, never on this plan.
    """
    if num_data <= 0 or num_cols <= 0:
        return None
    prefetch = max(1, int(getattr(config, "stream_prefetch", 2)))
    row_bytes = num_cols * itemsize
    total = num_data * row_bytes
    forced = int(getattr(config, "stream_rows", 0) or 0)
    budget = effective_budget_bytes(config)
    if forced:
        block = min(_floor128(forced), _ceil128(num_data))
        return StreamPlan(block_rows=block,
                          num_blocks=-(-num_data // block),
                          budget_bytes=budget, prefetch=prefetch,
                          total_bytes=total, reason="stream_rows")
    if not budget or total <= budget:
        return None
    # best-effort floor: a budget smaller than (prefetch+1) MIN_BLOCK_ROWS
    # rows cannot be honored (blocks below 128 rows thrash dispatch); the
    # plan still streams at the floor and the peak accounting reports the
    # true residency, so the overshoot is visible, not silent
    block = _floor128(budget // ((prefetch + 1)
                                 * (row_bytes + SIDECAR_BYTES_PER_ROW)))
    block = max(MIN_BLOCK_ROWS, block)
    block = min(block, _ceil128(num_data))
    return StreamPlan(block_rows=block, num_blocks=-(-num_data // block),
                      budget_bytes=budget, prefetch=prefetch,
                      total_bytes=total, reason="budget")


def _floor128(v: int) -> int:
    return max(MIN_BLOCK_ROWS, (v // 128) * 128)


def _ceil128(v: int) -> int:
    return -(-v // 128) * 128


class HostBinMatrix:
    """Row-block-chunked view of a host numpy bin matrix.

    Blocks are VIEWS into the backing array (no copy); the final partial
    block reports its true row count and the pipeline pads it to the
    uniform ``block_rows`` shape at device-put time so every block compiles
    to one program shape.
    """

    def __init__(self, bins: np.ndarray, block_rows: int) -> None:
        if bins.ndim != 2:
            raise ValueError("HostBinMatrix wants a [num_data, num_cols] "
                             f"matrix, got shape {bins.shape}")
        self.bins = bins
        self.block_rows = int(block_rows)
        if self.block_rows < 1:
            raise ValueError("block_rows must be >= 1")
        self.num_data, self.num_cols = bins.shape
        self.num_blocks = max(1, -(-self.num_data // self.block_rows))

    @property
    def block_nbytes(self) -> int:
        """Device footprint of ONE (padded) block."""
        return self.block_rows * self.num_cols * self.bins.dtype.itemsize

    def block_slice(self, i: int) -> slice:
        s = i * self.block_rows
        return slice(s, min(s + self.block_rows, self.num_data))

    def block(self, i: int) -> np.ndarray:
        """Host view of block ``i`` (unpadded)."""
        return self.bins[self.block_slice(i)]

    def block_rows_actual(self, i: int) -> int:
        sl = self.block_slice(i)
        return sl.stop - sl.start
